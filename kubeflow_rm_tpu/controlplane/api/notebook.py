"""Notebook resource: the platform's primary API object.

Shape (mirrors the reference CRD — a Notebook wraps a full pod template,
``notebook-controller/api/v1beta1/notebook_types.go:27-34`` — plus the
TPU-native ``spec.tpu`` block that is this framework's reason to exist):

    apiVersion: kubeflow.org/v1
    kind: Notebook
    metadata: {name, namespace, labels, annotations}
    spec:
      template:
        spec:            # pod spec: containers[], volumes[], ...
      tpu:               # optional — absent means a CPU notebook
        acceleratorType: v5p-16
    status:
      conditions: [...]
      readyReplicas: N
      containerState: {...}

Behavior annotations keep the reference's names (the *annotations* are
the real control API — SURVEY.md §2.7), with TPU additions under the
``notebooks.kubeflow.org/`` prefix.
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api.meta import deep_get, make_object

API_VERSION = "kubeflow.org/v1"
KIND = "Notebook"

# --- behavior annotations (reference names, pkg/culler/culler.go:40-41,
# notebook_controller.go:51-53, jupyter .../form.py:10) ----------------
STOP_ANNOTATION = "kubeflow-resource-stopped"
LAST_ACTIVITY_ANNOTATION = "notebooks.kubeflow.org/last-activity"
REWRITE_URI_ANNOTATION = "notebooks.kubeflow.org/http-rewrite-uri"
HEADERS_ANNOTATION = "notebooks.kubeflow.org/http-headers-request-set"
RESTART_ANNOTATION = "notebooks.kubeflow.org/notebook-restart"
SERVER_TYPE_ANNOTATION = "notebooks.kubeflow.org/server-type"
CULLING_EXCLUDE_ANNOTATION = "kubeflow-resource-culling-excluded"

# TPU-native additions
TPU_INJECT_EXCLUDE_ANNOTATION = "notebooks.kubeflow.org/tpu-inject-exclude"

# --- suspend/resume lifecycle (controlplane/suspend.py) ---------------
# Distinct from STOP_ANNOTATION: a *stopped* notebook stays down until a
# user restarts it; a *suspended* one released its chips to the pool and
# transparently resumes on the next incoming request. Value = ISO
# timestamp of the suspend decision (drives per-phase latency metrics).
SUSPEND_ANNOTATION = "notebooks.kubeflow.org/suspended"
# why the slice was parked: "idle" | "preempted" | "api"
SUSPEND_REASON_ANNOTATION = "notebooks.kubeflow.org/suspend-reason"
# JSON token from the Checkpointer-backed state store, written at
# suspend time; resume restores against it and stamps restored-step
SUSPEND_CHECKPOINT_ANNOTATION = "notebooks.kubeflow.org/suspend-checkpoint"
# ISO timestamp the slice finished draining (set once per suspend cycle)
SUSPEND_DRAINED_ANNOTATION = "notebooks.kubeflow.org/suspend-drained"
# ISO timestamp of the first resume-triggering request (earliest wins —
# the suspend→resume latency clock starts here)
RESUME_REQUESTED_ANNOTATION = "notebooks.kubeflow.org/resume-requested"
# step the state store restored on the last resume (proof of exactness)
RESTORED_STEP_ANNOTATION = "notebooks.kubeflow.org/restored-step"
# the workload's durable training step (maintained by the in-notebook
# launcher agent; the state store snapshots it at suspend time)
TRAINING_STEP_ANNOTATION = "notebooks.kubeflow.org/training-step"
# pin: never suspend, never select as a preemption victim, never cull
PIN_ANNOTATION = "tpu.kubeflow.org/do-not-suspend"

# --- replicated kernels (NotebookOS): spec.replicas standbys ----------
# With ``spec.replicas: R`` > 1 one replica is *active* (holds the
# chips); R-1 are parked CPU-only standbys kept warm through the
# checkpoint state store. The failover controller owns these:
# which replica id currently holds the chips (int as str)
ACTIVE_REPLICA_ANNOTATION = "tpu.kubeflow.org/active-replica"
# JSON {replica_id: "active" | "standby" | "promoting"}
REPLICA_STATES_ANNOTATION = "tpu.kubeflow.org/replica-states"
# JSON checkpoint token standbys keep warm (refreshed as the active
# replica's durable training step advances — what a promotion restores)
WARM_CHECKPOINT_ANNOTATION = "tpu.kubeflow.org/warm-checkpoint"
# ISO timestamp the active replica's death was detected (failover
# latency clock; popped when the promotion completes)
FAILOVER_T0_ANNOTATION = "tpu.kubeflow.org/failover-t0"

# --- live migration (checkpoint -> drain -> re-bind elsewhere) --------
# JSON list of node names the rebind must avoid (the nodes the slice
# occupied when the migration was initiated)
MIGRATE_EXCLUDE_ANNOTATION = "tpu.kubeflow.org/migrate-exclude-nodes"
# ISO timestamp of the migration request; while present the drain
# auto-resumes instead of parking (popped when the re-bind completes)
MIGRATE_REQUESTED_ANNOTATION = "tpu.kubeflow.org/migrate-requested"

#: the lifecycle phase a drained suspended notebook reports
SUSPENDED_PHASE = "Suspended"

#: named priority classes for spec.priorityClassName; higher wins.
#: Absent spec → "default", so pre-oversubscription notebooks neither
#: preempt nor outrank anything they didn't before.
PRIORITY_CLASSES = {"low": 0, "default": 100, "high": 1000}
DEFAULT_PRIORITY = PRIORITY_CLASSES["default"]

# label the controller stamps on everything it renders
NOTEBOOK_NAME_LABEL = "notebook-name"
# pod label carrying the slice's accelerator type (webhook + web apps read it)
TPU_ACCELERATOR_LABEL = "notebooks.kubeflow.org/tpu-accelerator-type"
# pod label carrying the multislice width (>1 ⇒ DCN job; webhook
# injects MEGASCALE_* rendezvous from it)
TPU_NUM_SLICES_LABEL = "notebooks.kubeflow.org/tpu-num-slices"


def make_notebook(name: str, namespace: str, *,
                  image: str = "jupyter-jax:latest",
                  accelerator_type: str | None = None,
                  num_slices: int = 1,
                  priority_class: str | None = None,
                  replicas: int | None = None,
                  labels: dict | None = None,
                  annotations: dict | None = None,
                  pod_spec_extra: dict | None = None,
                  container_extra: dict | None = None) -> dict:
    """Convenience constructor used by tests and the spawner backend."""
    container = {
        "name": name,
        "image": image,
        "ports": [{"containerPort": 8888, "name": "notebook-port",
                   "protocol": "TCP"}],
    }
    if container_extra:
        container.update(container_extra)
    pod_spec: dict = {"containers": [container]}
    if pod_spec_extra:
        pod_spec.update(pod_spec_extra)
    spec: dict = {"template": {"spec": pod_spec}}
    if accelerator_type is not None:
        spec["tpu"] = {"acceleratorType": accelerator_type}
        if num_slices != 1:
            spec["tpu"]["numSlices"] = num_slices
    if priority_class is not None:
        spec["priorityClassName"] = priority_class
    if replicas is not None:
        spec["replicas"] = replicas
    return make_object(API_VERSION, KIND, name, namespace,
                       labels=labels, annotations=annotations, spec=spec)


def tpu_spec(notebook: dict) -> tpu_api.SliceTopology | None:
    """Resolve spec.tpu to a SliceTopology (None for CPU notebooks)."""
    t = deep_get(notebook, "spec", "tpu")
    if not t:
        return None
    return tpu_api.lookup(t["acceleratorType"])


#: schema-level cap on multislice width — one request may render at most
#: hosts-per-slice × MAX_SLICES pods, so an unbounded value would let a
#: single authenticated POST fan the controller out arbitrarily wide
MAX_SLICES = 64


def num_slices(notebook: dict) -> int:
    """Multislice width (1 = a single ICI-connected slice; >1 = a DCN
    job of identical slices, rendered as one gang-scheduled pool)."""
    return int(deep_get(notebook, "spec", "tpu", "numSlices", default=1))


def total_hosts(notebook: dict) -> int:
    """Pods the notebook renders to: hosts-per-slice × numSlices."""
    topo = tpu_spec(notebook)
    if topo is None:
        return 1
    return topo.hosts * num_slices(notebook)


#: schema-level cap on kernel replication width — each extra replica is
#: one parked CPU-only standby pod; past a handful the marginal
#: availability gain is zero while the pod fan-out is linear
MAX_REPLICAS = 8


def replicas_of(notebook: dict) -> int:
    """Scheduling-replica count (NotebookOS ``R``): 1 means the classic
    single-kernel notebook; R > 1 keeps R-1 warm CPU standbys."""
    try:
        return max(1, int(deep_get(notebook, "spec", "replicas",
                                   default=1)))
    except (TypeError, ValueError):
        return 1


def priority_of(notebook: dict) -> int:
    """Effective scheduling priority: an explicit integer
    ``spec.priority`` wins; else ``spec.priorityClassName`` resolved
    through PRIORITY_CLASSES; else DEFAULT_PRIORITY. Preemption only
    ever displaces a *strictly lower* priority, so all-default fleets
    keep today's first-come-first-served behavior."""
    p = deep_get(notebook, "spec", "priority")
    if p is not None:
        try:
            return int(p)
        except (TypeError, ValueError):
            return DEFAULT_PRIORITY
    cls = deep_get(notebook, "spec", "priorityClassName")
    return PRIORITY_CLASSES.get(cls, DEFAULT_PRIORITY)


def is_pinned(notebook: dict) -> bool:
    """Pinned notebooks hold their slice for the notebook's lifetime:
    skipped by idle culling, idle suspension, and preemption victim
    selection. Presence-based like the stop annotation (any value but
    an explicit \"false\")."""
    ann = (notebook["metadata"].get("annotations") or {})
    if PIN_ANNOTATION not in ann:
        return False
    return str(ann.get(PIN_ANNOTATION)).lower() != "false"


def validate(notebook: dict) -> None:
    """Structural validation (the CRD schema's job in the reference)."""
    containers = deep_get(notebook, "spec", "template", "spec", "containers")
    if not containers:
        raise ValueError("notebook spec.template.spec.containers must be "
                         "non-empty")
    t = deep_get(notebook, "spec", "tpu")
    if t is not None:
        if "acceleratorType" not in t:
            raise ValueError("spec.tpu requires acceleratorType")
        tpu_api.lookup(t["acceleratorType"])  # raises on unknown
        ns = t.get("numSlices", 1)
        if not isinstance(ns, int) or ns < 1 or ns > MAX_SLICES:
            raise ValueError(
                f"spec.tpu.numSlices must be an int in [1, {MAX_SLICES}]")
    cls = deep_get(notebook, "spec", "priorityClassName")
    if cls is not None and cls not in PRIORITY_CLASSES:
        raise ValueError(
            f"spec.priorityClassName must be one of "
            f"{sorted(PRIORITY_CLASSES)}, got {cls!r}")
    p = deep_get(notebook, "spec", "priority")
    if p is not None and not isinstance(p, int):
        raise ValueError("spec.priority must be an integer")
    r = deep_get(notebook, "spec", "replicas")
    if r is not None and (not isinstance(r, int) or r < 1
                          or r > MAX_REPLICAS):
        raise ValueError(
            f"spec.replicas must be an int in [1, {MAX_REPLICAS}]")
