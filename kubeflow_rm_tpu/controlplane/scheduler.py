"""Incremental scheduler cache: kube-scheduler assume/bind for TPU slices.

The fake kubelet in ``controllers/statefulset.py`` used to recompute
per-node chip usage by scanning EVERY Pod in the cluster under one
global bind lock on every StatefulSet reconcile — O(pods) per reconcile,
O(pods²) aggregate under the 20-way spawn storm. This module replaces
that with what kube-scheduler actually does (``scheduler/cache/cache.go``):

- an informer-fed usage map, updated O(Δ) from Pod/Node watch events,
  with per-pod resourceVersion guards so stale events can't unwind a
  newer accounting state;
- **assume/bind**: a bind is charged to the cache synchronously at
  decision time (before the apiserver write), then *confirmed* with the
  write's resourceVersion or *forgotten* on failure — so two concurrent
  reconciles can never double-commit the same chips no matter how far
  the watch stream lags;
- **gang-bind**: a whole slice's pods are placed all-or-nothing under
  per-node locks (sorted acquisition), the scheduling unit a TPU slice
  actually is — no rump slices holding chips while the jax rendezvous
  waits forever;
- **relist rebuild**: a ``TOO_OLD`` overflow sentinel marks the cache
  stale and the next scheduling attempt rebuilds it from a fresh
  snapshot, preserving in-flight assumed pods (kube-scheduler keeps its
  assumed set across relists for the same reason).

Terminal pods (``Succeeded``/``Failed``) hold no capacity — a failed
host frees its chips the moment its status event lands, where the old
full scan leaked them forever (the r10 satellite bugfix).

The cache is **mixed-resource** (r11, multi-role gang jobs): every node
tracks chips AND CPU, and ``gang_bind`` places heterogeneous gangs — a
learner slice's chip pods co-bound with CPU-only actor pods in one
assume transaction. CPU-only pods never touch chip accounting, chip
pods without CPU requests never touch CPU accounting, and a partial
fit still rolls back to zero assumed binds across BOTH resources.
"""

from __future__ import annotations

import threading
import time
import weakref

from kubeflow_rm_tpu.controlplane.api.meta import (
    deep_get,
    labels_of,
    matches_selector,
    name_of,
    namespace_of,
    parse_quantity,
)
from kubeflow_rm_tpu.controlplane.api.tpu import (
    GOOGLE_TPU_HBM_RESOURCE,
    GOOGLE_TPU_RESOURCE,
    PREDICTED_FLOPS_ANNOTATION,
    PREDICTED_HBM_ANNOTATION,
)
from kubeflow_rm_tpu.analysis.lockgraph import make_lock

#: phases whose pods no longer occupy their node's chips (a kubelet
#: frees the device plugin allocation when the pod reaches a terminal
#: phase; only the DELETE frees the name)
TERMINAL_PHASES = ("Succeeded", "Failed")

#: the hermetic fallback node for selector-less CPU pods (tests with no
#: Node inventory); never capacity-tracked
VIRTUAL_NODE = "virtual-node"

#: entry.rv sentinel while a bind is assumed but its write's rv is not
#: yet known — compares newer than every real resourceVersion
_ASSUMED = float("inf")


#: the second tracked resource (mixed-resource gangs): CPU cores,
#: parsed with millicore support ("500m" → 0.5)
CPU_RESOURCE = "cpu"

#: bounded chip overcommit under ``--hbm-packing``: a pod that DECLARED
#: its workload (so the jaxcheck walker priced its HBM) may share a
#: node's chips up to this multiple of the physical chip count — the
#: HBM axis, which is what actually OOMs, is never overcommitted.
#: Undeclared chip pods stay strictly chip-bounded AND charge their
#: full per-chip HBM share, so the two populations can't starve each
#: other invisibly.
CHIP_OVERCOMMIT = 4.0

#: float-sum slack on the HBM axis (GiB): 64 pods × a 4-decimal
#: annotation round each way stays far under this
_HBM_EPS = 1e-4

_hbm_packing = False


def set_hbm_packing(enabled: bool) -> None:
    """Enable predicted-HBM as the second gang-packing axis (the
    ``--hbm-packing`` conformance arm). Off (default) = chip-count-only
    admission, the A/B baseline."""
    global _hbm_packing
    _hbm_packing = bool(enabled)


def hbm_packing() -> bool:
    return _hbm_packing


def _pod_resource(pod: dict, resource: str) -> float:
    """Amount of ``resource`` a pod occupies: requests defaulting to
    limits (the kube quota convention — mirrors
    ``statefulset._pod_tpu_request``)."""
    total = 0.0
    for c in deep_get(pod, "spec", "containers", default=[]) or []:
        amount = deep_get(c, "resources", "requests", resource)
        if amount is None:
            amount = deep_get(c, "resources", "limits", resource)
        if amount is not None:
            total += parse_quantity(amount)
    return total


def _pod_chips(pod: dict) -> float:
    return _pod_resource(pod, GOOGLE_TPU_RESOURCE)


def _pod_cpu(pod: dict) -> float:
    return _pod_resource(pod, CPU_RESOURCE)


def _pod_declared_hbm_gib(pod: dict) -> float | None:
    """The webhook-priced per-pod HBM share (decimal GB annotation →
    GiB), or None when the pod carries no declaration."""
    raw = deep_get(pod, "metadata", "annotations",
                   PREDICTED_HBM_ANNOTATION)
    if raw is None:
        return None
    try:
        gb = float(raw)
    except (TypeError, ValueError):
        return None
    if gb < 0:
        return None
    return gb * 1e9 / 2**30


def _pod_flops(pod: dict) -> float:
    """Predicted FLOPs/step (the packing tiebreak); 0 when undeclared."""
    raw = deep_get(pod, "metadata", "annotations",
                   PREDICTED_FLOPS_ANNOTATION)
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return 0.0


def _hbm_charge(declared: float | None, chips: float,
                hbm_capacity: float, capacity: float) -> float:
    """What a pod charges on a node's HBM axis: its declared share, or
    — undeclared chip pod — the full per-chip HBM slice of that node
    (it may use every byte, so it must be accounted as if it will)."""
    if declared is not None:
        return declared
    if chips > 0 and capacity > 0 and hbm_capacity > 0:
        return chips * hbm_capacity / capacity
    return 0.0


class _Node:
    """One node's slice of the usage map — both resources under one
    lock so a mixed bind is atomic per node. ``used``/``cpu_used`` are
    guarded by the node's own lock — binds against different nodes
    never contend."""

    __slots__ = ("name", "labels", "capacity", "used",
                 "cpu_capacity", "cpu_used",
                 "hbm_capacity", "hbm_used", "flops_used", "lock")

    def __init__(self, name: str, labels: dict, capacity: float,
                 cpu_capacity: float = 0.0, hbm_capacity: float = 0.0):
        self.name = name
        self.labels = labels
        self.capacity = capacity        # chips
        self.used = 0.0                 # chips
        self.cpu_capacity = cpu_capacity
        self.cpu_used = 0.0
        self.hbm_capacity = hbm_capacity   # GiB, never overcommitted
        self.hbm_used = 0.0
        self.flops_used = 0.0           # predicted FLOPs/step (tiebreak)
        # one ranked family: _commit acquires gang members sorted by
        # node name, which is exactly the rank the analyser verifies
        self.lock = make_lock("scheduler.node", rank=name)


class _Entry:
    __slots__ = ("node", "chips", "cpu", "hbm", "flops", "rv",
                 "harvested")

    def __init__(self, node: str, chips: float, cpu: float, rv: float,
                 hbm: float = 0.0, flops: float = 0.0):
        self.node = node
        self.chips = chips
        self.cpu = cpu
        self.hbm = hbm                  # GiB actually charged
        self.flops = flops
        self.rv = rv
        # harvest lease (r20): a harvested charge is serving work
        # squatting on idle notebook chips — instantly reclaimable,
        # it NEVER blocks a notebook bind the way a real charge does
        self.harvested = False


class SchedulerCache:
    """Informer-fed per-node chip accounting with assume/bind.

    Lock order (held-simultaneously pairs only): ``_relist_lock`` →
    ``_nlock`` → node locks (sorted by name) → ``_plock``. The event
    path takes ``_plock`` and node locks sequentially, never nested.
    The canonical cross-module hierarchy lives in
    :mod:`kubeflow_rm_tpu.analysis.hierarchy`; the lockgraph storm arm
    verifies the measured acquisition graph embeds into it.
    """

    def __init__(self, backend=None):
        self._nodes: dict[str, _Node] = {}
        self._pods: dict[tuple[str | None, str], _Entry] = {}
        self._plock = make_lock("scheduler.pods_map")
        self._nlock = make_lock("scheduler.nodes_map")
        self._relist_lock = make_lock("scheduler.relist")
        self._stale = True                   # prime on first use
        self._assumed = 0
        self._backend = (weakref.ref(backend)
                         if backend is not None else None)
        #: the ChipHarvestController's synchronous give-back: called
        #: with an optional node-name filter, drains the harvest
        #: replicas charged there and releases their leases, returning
        #: the chips freed. None when no harvester is attached.
        self.harvest_reclaimer = None

    # -- the informer feed (one dispatch thread per backend) -----------
    def observe(self, etype: str, obj: dict, old: dict | None = None) -> None:
        if etype == "TOO_OLD":
            # the fanout queue overflowed: the dropped window can't be
            # replayed, so the next scheduling attempt rebuilds from a
            # fresh snapshot (kube-scheduler's 410 relist)
            self._stale = True
            return
        kind = obj.get("kind")
        if kind == "Node":
            self._apply_node(etype, obj)
        elif kind == "Pod":
            self._apply_pod(etype, obj)

    def _apply_node(self, etype: str, obj: dict) -> None:
        from kubeflow_rm_tpu.controlplane import metrics
        metrics.SCHEDULER_CACHE_EVENTS_TOTAL.labels(kind="Node").inc()
        name = name_of(obj)
        with self._nlock:
            if etype == "DELETED":
                self._nodes.pop(name, None)
                return
            node = self._nodes.get(name)
            cap = parse_quantity(deep_get(
                obj, "status", "allocatable", GOOGLE_TPU_RESOURCE,
                default=0))
            cpu_cap = parse_quantity(deep_get(
                obj, "status", "allocatable", CPU_RESOURCE, default=0))
            hbm_cap = parse_quantity(deep_get(
                obj, "status", "allocatable", GOOGLE_TPU_HBM_RESOURCE,
                default=0))
            if node is None:
                self._nodes[name] = _Node(name, labels_of(obj), cap,
                                          cpu_cap, hbm_cap)
            else:
                # keep the object (its lock + used survive relabels)
                node.labels = labels_of(obj)
                node.capacity = cap
                node.cpu_capacity = cpu_cap
                node.hbm_capacity = hbm_cap

    def _apply_pod(self, etype: str, obj: dict) -> None:
        from kubeflow_rm_tpu.controlplane import metrics
        metrics.SCHEDULER_CACHE_EVENTS_TOTAL.labels(kind="Pod").inc()
        key = (namespace_of(obj), name_of(obj))
        try:
            rv = float(obj["metadata"].get("resourceVersion") or 0)
        except (TypeError, ValueError):
            rv = 0.0
        gone = (etype == "DELETED"
                or deep_get(obj, "status", "phase") in TERMINAL_PHASES)
        node_name = None if gone else deep_get(obj, "spec", "nodeName")
        chips = _pod_chips(obj)
        cpu = _pod_cpu(obj)
        # the HBM charge depends on the landing node's shape (an
        # undeclared pod charges that node's per-chip share), so it is
        # resolved here — BEFORE _plock, respecting the _nlock order
        hbm = flops = 0.0
        if node_name:
            with self._nlock:
                node = self._nodes.get(node_name)
            if node is not None:
                hbm = _hbm_charge(_pod_declared_hbm_gib(obj), chips,
                                  node.hbm_capacity, node.capacity)
            flops = _pod_flops(obj)
        with self._plock:
            cur = self._pods.get(key)
            if cur is not None and rv < cur.rv:
                # stale event (assumed entries compare newest): a bind
                # already charged this pod at a later version — applying
                # the older view would free chips that are still held
                return
            dec = (cur.node, cur.chips, cur.cpu, cur.hbm, cur.flops) \
                if cur is not None else None
            if node_name:
                self._pods[key] = _Entry(node_name, chips, cpu, rv,
                                         hbm, flops)
                inc = (node_name, chips, cpu, hbm, flops)
            else:
                self._pods.pop(key, None)
                inc = None
        self._adjust(dec, inc)

    def _adjust(self, dec: tuple[str, float, float, float, float] | None,
                inc: tuple[str, float, float, float, float] | None
                ) -> None:
        if dec == inc:
            return
        for charge, delta in ((dec, -1), (inc, +1)):
            if charge is None:
                continue
            name, chips, cpu, hbm, flops = charge
            if not chips and not cpu and not hbm:
                continue
            with self._nlock:
                node = self._nodes.get(name)
            if node is None:
                continue  # virtual node / node gone: untracked capacity
            with node.lock:
                node.used = max(0.0, node.used + delta * chips)
                node.cpu_used = max(0.0, node.cpu_used + delta * cpu)
                node.hbm_used = max(0.0, node.hbm_used + delta * hbm)
                node.flops_used = max(0.0,
                                      node.flops_used + delta * flops)

    # -- snapshot rebuild (prime + TOO_OLD recovery) -------------------
    def rebuild(self, api) -> None:
        """Replace the accounting with a fresh snapshot, keeping
        in-flight assumed binds (their writes are racing this relist)."""
        from kubeflow_rm_tpu.controlplane import metrics
        scan = getattr(api, "scan", api.list)
        with self._relist_lock:
            self._stale = False
            nodes = list(scan("Node"))
            pods = list(scan("Pod"))
            with self._nlock:
                seen = set()
                for n in nodes:
                    name = name_of(n)
                    seen.add(name)
                    cap = parse_quantity(deep_get(
                        n, "status", "allocatable", GOOGLE_TPU_RESOURCE,
                        default=0))
                    cpu_cap = parse_quantity(deep_get(
                        n, "status", "allocatable", CPU_RESOURCE,
                        default=0))
                    hbm_cap = parse_quantity(deep_get(
                        n, "status", "allocatable",
                        GOOGLE_TPU_HBM_RESOURCE, default=0))
                    node = self._nodes.get(name)
                    if node is None:
                        self._nodes[name] = _Node(name, labels_of(n),
                                                  cap, cpu_cap, hbm_cap)
                    else:
                        node.labels = labels_of(n)
                        node.capacity = cap
                        node.cpu_capacity = cpu_cap
                        node.hbm_capacity = hbm_cap
                for name in list(self._nodes):
                    if name not in seen:
                        del self._nodes[name]
                live_nodes = dict(self._nodes)
            with self._plock:
                fresh: dict = {}
                for p in pods:
                    if deep_get(p, "status", "phase") in TERMINAL_PHASES:
                        continue
                    node_name = deep_get(p, "spec", "nodeName")
                    if not node_name:
                        continue
                    key = (namespace_of(p), name_of(p))
                    try:
                        rv = float(p["metadata"].get(
                            "resourceVersion") or 0)
                    except (TypeError, ValueError):
                        rv = 0.0
                    chips = _pod_chips(p)
                    lnode = live_nodes.get(node_name)
                    hbm = _hbm_charge(
                        _pod_declared_hbm_gib(p), chips,
                        lnode.hbm_capacity if lnode else 0.0,
                        lnode.capacity if lnode else 0.0)
                    fresh[key] = _Entry(node_name, chips,
                                        _pod_cpu(p), rv, hbm,
                                        _pod_flops(p))
                for key, e in self._pods.items():
                    if e.rv is _ASSUMED and key not in fresh:
                        fresh[key] = e
                self._pods = fresh
                per_node: dict[str, list[float]] = {}
                for e in fresh.values():
                    acc = per_node.setdefault(
                        e.node, [0.0, 0.0, 0.0, 0.0])
                    acc[0] += e.chips
                    acc[1] += e.cpu
                    acc[2] += e.hbm
                    acc[3] += e.flops
            for node in live_nodes.values():
                with node.lock:
                    (node.used, node.cpu_used, node.hbm_used,
                     node.flops_used) = per_node.get(
                        node.name, (0.0, 0.0, 0.0, 0.0))
        metrics.SCHEDULER_CACHE_REBUILDS_TOTAL.inc()

    def _ensure_fresh(self) -> None:
        if not self._stale:
            return
        backend = self._backend() if self._backend is not None else None
        if backend is not None:
            self.rebuild(backend)

    # -- assume / confirm / forget (the bind protocol) -----------------
    def gang_bind(self, pods: list[dict], *,
                  allow_virtual: bool,
                  exclude_nodes: set[str] | None = None,
                  prefer_whole_nodes: bool = False
                  ) -> dict[tuple, str] | None:
        """Place a whole gang all-or-nothing. Returns ``{(ns, name):
        node_name}`` with every placement *assumed* in the cache, or
        None (nothing charged) when the gang doesn't fit. The caller
        must ``confirm`` each bind after its apiserver write lands, or
        ``forget`` it on failure. ``exclude_nodes`` bars named nodes
        from the plan — live migration's re-bind passes the nodes the
        slice just drained off so it genuinely moves.
        ``prefer_whole_nodes`` inverts the fragmentation tiebreak:
        harvest gangs take ENTIRELY free nodes first (the slices their
        notebooks just vacated), so a lease returns a whole slice and
        never pins a remainder under a half-used node."""
        from kubeflow_rm_tpu.controlplane import metrics, tracing
        self._ensure_fresh()
        with tracing.start_span_if_active(
                "gang_bind", attrs={"pods": len(pods),
                                    "allow_virtual": allow_virtual}) as sp:
            t0 = time.perf_counter()
            plan = self._try_gang(pods, allow_virtual,
                                  exclude_nodes=exclude_nodes,
                                  prefer_whole_nodes=prefer_whole_nodes)
            result = "bound" if plan is not None else "unschedulable"
            metrics.SCHEDULE_LATENCY_SECONDS.labels(
                result=result).observe(time.perf_counter() - t0)
            sp.set_attr("result", result)
        return plan

    def _try_gang(self, pods: list[dict], allow_virtual: bool,
                  exclude_nodes: set[str] | None = None,
                  prefer_whole_nodes: bool = False
                  ) -> dict[tuple, str] | None:
        # pick first (selection without locks), then verify-and-commit
        # under the chosen nodes' locks; capacity taken by a concurrent
        # gang between the two phases fails verification and retries
        for _ in range(4):
            with self._nlock:
                nodes = list(self._nodes.values())
            # best-fragmentation-fit ordering (ParvaGPU's allocation
            # tiebreak): try the nodes with the LEAST free capacity
            # first, so a gang soaks up already-fragmented remainders
            # and the emptiest nodes stay whole for future large gangs
            # — first-fit in arrival order eroded largest_free_gang by
            # carving every new gang out of the freest node. Free is
            # snapshotted once per attempt; name breaks ties so plans
            # are deterministic.
            free0: dict[str, float] = {}
            flops0: dict[str, float] = {}
            for node in nodes:
                with node.lock:
                    free0[node.name] = node.capacity - node.used
                    flops0[node.name] = node.flops_used
            # predicted FLOPs/step is the SECOND sort key: among
            # equally-fragmented nodes, land on the computationally
            # coolest one — declared heavy trainers spread out instead
            # of stacking behind one oversubscribed systolic array
            if prefer_whole_nodes:
                # harvest gangs: wholly-free nodes first (free ==
                # capacity), then the usual least-free-first remainder
                nodes.sort(key=lambda n: (
                    0 if (n.capacity > 0
                          and free0[n.name] >= n.capacity) else 1,
                    free0[n.name], flops0[n.name], n.name))
            else:
                nodes.sort(key=lambda n: (free0[n.name],
                                          flops0[n.name], n.name))
            plan: dict[tuple, str] = {}
            # per-node tentative [chips, cpu, hbm, relaxed] charged by
            # THIS gang — heterogeneous pods share the map so a learner
            # host and an actor landing on the same node both count;
            # ``relaxed`` records that a declared-HBM pod was admitted
            # past the physical chip count (hbm-packing overcommit)
            tentative: dict[str, list] = {}
            packing = hbm_packing()
            for pod in sorted(pods, key=name_of):
                key = (namespace_of(pod), name_of(pod))
                selector = deep_get(pod, "spec", "nodeSelector",
                                    default={}) or {}
                need = _pod_chips(pod)
                need_cpu = _pod_cpu(pod)
                declared = _pod_declared_hbm_gib(pod)
                chosen = None
                chosen_hbm = 0.0
                relax = False
                for node in nodes:
                    if exclude_nodes and node.name in exclude_nodes:
                        continue
                    if selector and not matches_selector(
                            node.labels, {"matchLabels": selector}):
                        continue
                    need_hbm = _hbm_charge(declared, need,
                                           node.hbm_capacity,
                                           node.capacity)
                    if need or need_cpu:
                        with node.lock:
                            used, cpu_used = node.used, node.cpu_used
                            hbm_used = node.hbm_used
                        t = tentative.get(node.name)
                        t_chips, t_cpu, t_hbm = (
                            (t[0], t[1], t[2]) if t else
                            (0.0, 0.0, 0.0))
                        # a priced pod on a priced node may pack past
                        # the chip count (bounded) — the HBM check
                        # below is then the real admission gate
                        relax = (packing and declared is not None
                                 and node.hbm_capacity > 0)
                        limit = node.capacity * (
                            CHIP_OVERCOMMIT if relax else 1.0)
                        if need and (used + t_chips + need > limit):
                            continue
                        if need_cpu and (cpu_used + t_cpu + need_cpu
                                         > node.cpu_capacity):
                            continue
                        # the HBM axis is NEVER overcommitted — this
                        # is what makes the chip relaxation safe
                        if need_hbm and node.hbm_capacity > 0 and (
                                hbm_used + t_hbm + need_hbm
                                > node.hbm_capacity + _HBM_EPS):
                            continue
                    chosen = node.name
                    chosen_hbm = need_hbm if (need or need_cpu) else 0.0
                    break
                if chosen is None:
                    if allow_virtual and not selector and not need \
                            and not need_cpu:
                        plan[key] = VIRTUAL_NODE
                        continue
                    return None  # gang is all-or-nothing
                plan[key] = chosen
                if need or need_cpu:
                    t = tentative.setdefault(
                        chosen, [0.0, 0.0, 0.0, False, 0.0])
                    t[0] += need
                    t[1] += need_cpu
                    t[2] += chosen_hbm
                    t[3] = t[3] or relax
                    t[4] += _pod_flops(pod)
            if self._commit(pods, plan, tentative):
                return plan
        return None

    def _commit(self, pods: list[dict], plan: dict[tuple, str],
                tentative: dict[str, list]) -> bool:
        """Re-verify EVERY axis and charge the gang under its
        nodes' locks (sorted acquisition — deadlock-free against
        sibling gangs), then record the assumed entries. Verification
        failure on any axis rejects the whole gang with nothing
        charged."""
        with self._nlock:
            locked = [self._nodes[n] for n in sorted(tentative)
                      if n in self._nodes]
        if len(locked) != len(tentative):
            return False  # a chosen node vanished mid-flight
        with self._relist_lock:
            for node in locked:
                node.lock.acquire()
            try:
                for node in locked:
                    (t_chips, t_cpu, t_hbm, relax,
                     _t_flops) = tentative[node.name]
                    limit = node.capacity * (
                        CHIP_OVERCOMMIT if relax else 1.0)
                    if node.used + t_chips > limit:
                        return False
                    if node.cpu_used + t_cpu > node.cpu_capacity:
                        return False
                    if t_hbm and node.hbm_capacity > 0 and (
                            node.hbm_used + t_hbm
                            > node.hbm_capacity + _HBM_EPS):
                        return False
                for node in locked:
                    (t_chips, t_cpu, t_hbm, _,
                     t_flops) = tentative[node.name]
                    node.used += t_chips
                    node.cpu_used += t_cpu
                    node.hbm_used += t_hbm
                    node.flops_used += t_flops
            finally:
                for node in locked:
                    node.lock.release()
            from kubeflow_rm_tpu.controlplane import metrics
            stale: list[tuple[str, float, float, float, float]] = []
            node_shapes = {n.name: (n.hbm_capacity, n.capacity)
                           for n in locked}
            with self._plock:
                for pod in pods:
                    key = (namespace_of(pod), name_of(pod))
                    cur = self._pods.get(key)
                    if cur is not None:
                        # re-bind over an existing entry (a stale cached
                        # list raced a prior bind): release the old
                        # charge so the gang's doesn't double-count
                        if cur.rv is _ASSUMED:
                            self._assumed -= 1
                        stale.append((cur.node, cur.chips, cur.cpu,
                                      cur.hbm, cur.flops))
                    chips = _pod_chips(pod)
                    hbm_cap, cap = node_shapes.get(plan[key],
                                                   (0.0, 0.0))
                    hbm = _hbm_charge(_pod_declared_hbm_gib(pod),
                                      chips, hbm_cap, cap)
                    flops = _pod_flops(pod)
                    self._pods[key] = _Entry(
                        plan[key], chips, _pod_cpu(pod),
                        _ASSUMED, hbm, flops)
                    self._assumed += 1
                metrics.SCHEDULER_ASSUMED_PODS.set(self._assumed)
            for dec in stale:
                self._adjust(dec, None)
        return True

    def confirm(self, key: tuple, rv) -> None:
        """The bind write landed: pin the entry at its resourceVersion
        so the echo event (and anything older) folds in idempotently."""
        from kubeflow_rm_tpu.controlplane import metrics
        try:
            rv = float(rv)
        except (TypeError, ValueError):
            rv = 0.0
        with self._plock:
            e = self._pods.get(key)
            if e is not None and e.rv is _ASSUMED:
                e.rv = rv
                self._assumed -= 1
                metrics.SCHEDULER_ASSUMED_PODS.set(self._assumed)

    def forget(self, key: tuple) -> None:
        """The bind write failed: release the assumed charge."""
        from kubeflow_rm_tpu.controlplane import metrics
        with self._plock:
            e = self._pods.get(key)
            if e is None or e.rv is not _ASSUMED:
                return
            del self._pods[key]
            self._assumed -= 1
            metrics.SCHEDULER_ASSUMED_PODS.set(self._assumed)
        self._adjust((e.node, e.chips, e.cpu, e.hbm, e.flops), None)

    def release(self, key: tuple) -> None:
        """Out-of-band eviction for suspend/preemption teardown: the
        caller just deleted the pod and needs its chips free NOW, not
        after the DELETE event clears the async fanout — a preemptive
        gang-bind retries synchronously in the same reconcile. Unlike
        ``forget`` this drops confirmed entries too. The later DELETE
        echo folds in as a no-op; a stale pre-delete UPDATE still in
        the queue can transiently re-charge until its DELETE lands —
        that converges and can only under-admit, never over-commit."""
        from kubeflow_rm_tpu.controlplane import metrics
        with self._plock:
            e = self._pods.pop(key, None)
            if e is None:
                return
            if e.rv is _ASSUMED:
                self._assumed -= 1
                metrics.SCHEDULER_ASSUMED_PODS.set(self._assumed)
        self._adjust((e.node, e.chips, e.cpu, e.hbm, e.flops), None)

    # -- harvest leases (r20) ------------------------------------------
    def mark_harvested(self, key: tuple) -> None:
        """Tag a charge as a harvest lease: serving work on idle
        notebook chips, instantly reclaimable by ANY notebook bind.
        Harvest charges stay ``_ASSUMED`` forever (there is no
        apiserver pod behind them), which is exactly what lets them
        survive a relist rebuild."""
        from kubeflow_rm_tpu.controlplane import metrics
        with self._plock:
            e = self._pods.get(key)
            if e is not None:
                e.harvested = True
            metrics.HARVESTED_CHIPS.set(sum(
                x.chips for x in self._pods.values() if x.harvested))

    def harvested_entries(self) -> dict[tuple, tuple[str, float]]:
        """``{(ns, name): (node, chips)}`` for every live harvest
        lease."""
        with self._plock:
            return {k: (e.node, e.chips)
                    for k, e in self._pods.items() if e.harvested}

    def harvested_chips(self) -> float:
        with self._plock:
            return sum(e.chips for e in self._pods.values()
                       if e.harvested)

    def release_harvested(self, key: tuple) -> None:
        """Release one harvest lease (give-back)."""
        from kubeflow_rm_tpu.controlplane import metrics
        self.release(key)
        with self._plock:
            metrics.HARVESTED_CHIPS.set(sum(
                e.chips for e in self._pods.values() if e.harvested))

    def reclaim_harvested(self, nodes: set[str] | None = None, *,
                          trigger: str = "preempt") -> float:
        """Synchronous give-back: ask the attached harvester to drain
        and release its leases (optionally only those charged on
        ``nodes``). Returns chips freed; 0.0 when no harvester is
        attached or nothing was harvested there. Notebook resume and
        preemption call this FIRST — notebook demand always outranks
        harvested serving."""
        fn = self.harvest_reclaimer
        if fn is None:
            return 0.0
        try:
            return float(fn(nodes, trigger) or 0.0)
        except Exception:
            from kubeflow_rm_tpu.controlplane import metrics
            metrics.swallowed("scheduler", "harvest reclaim")
            return 0.0

    # -- read-side helpers ---------------------------------------------
    def total_used(self) -> float:
        """Chips currently charged across the fleet — O(nodes), serves
        the ``tpu_chips_requested`` gauge without a Pod scan."""
        with self._nlock:
            nodes = list(self._nodes.values())
        total = 0.0
        for node in nodes:
            with node.lock:
                total += node.used
        return total

    def node_used(self, name: str) -> float:
        with self._nlock:
            node = self._nodes.get(name)
        if node is None:
            return 0.0
        with node.lock:
            return node.used

    def node_cpu_used(self, name: str) -> float:
        with self._nlock:
            node = self._nodes.get(name)
        if node is None:
            return 0.0
        with node.lock:
            return node.cpu_used

    def free_by_node(self) -> dict[str, tuple[float, dict]]:
        """Snapshot of ``{node: (free_chips, labels)}`` — the read side
        preemption simulates victim teardown against."""
        with self._nlock:
            nodes = list(self._nodes.values())
        out: dict[str, tuple[float, dict]] = {}
        for node in nodes:
            with node.lock:
                free = max(0.0, node.capacity - node.used)
            out[node.name] = (free, node.labels)
        return out

    def hbm_by_node(self) -> dict[str, tuple[float, float]]:
        """``{node: (hbm_used_gib, hbm_capacity_gib)}`` — the
        conformance harness's zero-overcommit assertion reads this
        after every bind wave."""
        with self._nlock:
            nodes = list(self._nodes.values())
        out: dict[str, tuple[float, float]] = {}
        for node in nodes:
            with node.lock:
                out[node.name] = (node.hbm_used, node.hbm_capacity)
        return out

    def stats(self) -> dict:
        """Cache counters plus the bin-packing view: ``free_chips``
        (total unclaimed capacity), ``largest_free_gang`` (the biggest
        slice placeable as a gang of identical hosts — max over c of
        c × |{nodes with ≥ c chips free}|, ParvaGPU's "largest
        allocatable unit"), and ``fragmentation`` = 1 − largest/free
        (0 when free chips are gang-placeable whole, → 1 as free
        capacity shatters into unusable crumbs). Refreshes the
        matching Prometheus gauges as a side effect."""
        from kubeflow_rm_tpu.controlplane import metrics
        with self._plock:
            pods, assumed = len(self._pods), self._assumed
            harvested = sum(e.chips for e in self._pods.values()
                            if e.harvested)
        with self._nlock:
            nodes = list(self._nodes.values())
        free: list[float] = []
        free_cpu = 0.0
        free_hbm = 0.0
        for node in nodes:
            with node.lock:
                free.append(max(0.0, node.capacity - node.used))
                free_cpu += max(0.0, node.cpu_capacity - node.cpu_used)
                free_hbm += max(0.0, node.hbm_capacity - node.hbm_used)
        free_chips = sum(free)
        largest = 0.0
        for i, f in enumerate(sorted(free, reverse=True)):
            if f <= 0:
                break
            largest = max(largest, f * (i + 1))
        frag = 0.0 if free_chips <= 0 else 1.0 - largest / free_chips
        metrics.SCHEDULER_FREE_CHIPS.set(free_chips)
        metrics.SCHEDULER_LARGEST_FREE_GANG.set(largest)
        metrics.SCHEDULER_FRAGMENTATION.set(frag)
        metrics.SCHEDULER_FREE_HBM_GIB.set(free_hbm)
        metrics.HARVESTED_CHIPS.set(harvested)
        return {"nodes": len(nodes), "pods": pods, "assumed": assumed,
                "stale": self._stale, "free_chips": free_chips,
                "free_cpu": free_cpu, "free_hbm_gib": free_hbm,
                "largest_free_gang": largest, "fragmentation": frag,
                "harvested_chips": harvested}


# ---- per-backend cache registry + the legacy A/B switch --------------

_caches: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_caches_lock = make_lock("scheduler.registry")

_legacy_scan = False


def set_legacy_scan(enabled: bool) -> None:
    """Restore the pre-r10 scheduling path: full Pod scan under the
    global bind lock per reconcile (the ``--legacy-schedule``
    conformance arm)."""
    global _legacy_scan
    _legacy_scan = bool(enabled)


def legacy_scan() -> bool:
    return _legacy_scan


def refresh_gauges() -> None:
    """Recompute the free-chips/fragmentation gauges for every live
    cache — called by text-scrape endpoints (``deploy/restserver.py``
    ``/metrics``) so the exposition reflects now, not the last bind."""
    with _caches_lock:
        caches = list(_caches.values())
    for cache in caches:
        cache.stats()


def cache_for(api) -> SchedulerCache:
    """The one SchedulerCache per apiserver backend, informer-fed from
    registration time and primed from a snapshot on first use. Accepts
    a CachedAPI and unwraps it — accounting must feed from the
    authoritative event stream, not a read cache."""
    backend = getattr(api, "api", api)
    with _caches_lock:
        cache = _caches.get(backend)
        if cache is None:
            cache = SchedulerCache(backend)
            # subscribe BEFORE the first rebuild: an event raced between
            # snapshot and subscription would be lost forever, while one
            # arriving twice is absorbed by the rv guards
            backend.add_watcher(cache.observe, name="scheduler")
            _caches[backend] = cache
    return cache
