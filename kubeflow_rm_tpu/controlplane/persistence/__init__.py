"""Durable persistence for one apiserver shard: WAL + snapshots.

``Persistence`` is the single object the apiserver talks to. Boot
sequence (``recover``): load the newest snapshot, replay every WAL
record past its ``seq`` horizon as a blind upsert (records carry the
complete post-write object, so replay is idempotent and convergent),
and hand back the reconstructed store plus the counters the apiserver
must resume from — the rv counter continues where it left off, so a
restarted shard never re-issues resourceVersions and its watch stream
never emits duplicates.

Steady state (``log``): every acked write appends one group-committed
record. Every ``snapshot_every`` records a compacting snapshot runs on
a background thread: the apiserver cuts a consistent view under its
write lock, the WAL rotates inside the same critical section (so all
records at-or-below the cut live in closed segments), and the closed
segments are unlinked once the snapshot file is durable.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

from kubeflow_rm_tpu.analysis.lockgraph import make_lock
from kubeflow_rm_tpu.controlplane.persistence import snapshot as snap_mod
from kubeflow_rm_tpu.controlplane.persistence.wal import (
    WALCorruption,
    WriteAheadLog,
    iter_records,
    segment_paths,
)

__all__ = ["Persistence", "RecoveredState", "WALCorruption",
           "read_state", "tail_records"]

log = logging.getLogger("kubeflow_rm_tpu.persistence")


@dataclass
class RecoveredState:
    """What a booting shard gets back: objects keyed the way the
    apiserver stores them, plus every counter that must resume."""
    objects: dict = field(default_factory=dict)  # (kind, ns, name) -> obj
    rv: int = 0
    seq: int = 0
    records_replayed: int = 0
    snapshot_seq: int = 0


def _key_of(obj: dict, cluster_scoped: set[str]) -> tuple:
    kind = obj["kind"]
    meta = obj.get("metadata") or {}
    if kind in cluster_scoped:
        return (kind, None, meta.get("name"))
    return (kind, meta.get("namespace"), meta.get("name"))


def read_state(dirpath: str, cluster_scoped: set[str]) -> RecoveredState:
    """Read-only recovery: rebuild a shard's state from its WAL
    directory WITHOUT opening the log for append. The elastic-shard
    handoff coordinator runs this against a LIVE donor (the donor keeps
    appending; we read snapshot + whatever closed-and-current segments
    exist at this instant) — blind-upsert replay makes the torn tail
    and any in-flight record harmless, and ``tail_records`` later
    catches everything past ``rec.seq``."""
    rec = RecoveredState()
    doc = snap_mod.load_latest_snapshot(dirpath)
    if doc:
        rec.snapshot_seq = rec.seq = int(doc["seq"])
        rec.rv = int(doc["rv"])
        for obj in doc["objects"]:
            rec.objects[_key_of(obj, cluster_scoped)] = obj
    for seg in segment_paths(dirpath):
        for record in iter_records(seg):
            seq = int(record.get("seq", 0))
            if seq <= rec.snapshot_seq:
                continue
            rec.seq = max(rec.seq, seq)
            rec.rv = max(rec.rv, int(record.get("rv", 0)))
            obj = record.get("obj")
            if obj is None:
                continue
            key = _key_of(obj, cluster_scoped)
            if record.get("verb") == "DELETE":
                rec.objects.pop(key, None)
            else:
                rec.objects[key] = obj
            rec.records_replayed += 1
    return rec


def tail_records(dirpath: str, after_seq: int) -> list[dict]:
    """Every WAL record with ``seq > after_seq``, in seq order — the
    tail-replay feed for a live handoff. Re-reads the segment files on
    every call (the donor appends concurrently); a torn tail ends a
    segment silently, exactly like boot replay.

    Compaction race: a snapshot the donor takes BETWEEN passes unlinks
    segments, folding their records into the snapshot file — records
    in ``(after_seq, snapshot_seq]`` are then invisible here. The
    handoff coordinator guards against this by checking the donor's
    ``snapshot_seq`` (``load_latest_snapshot``) each pass and falling
    back to a full :func:`read_state` + state diff when it advanced
    past its replay horizon."""
    out: list[dict] = []
    for seg in segment_paths(dirpath):
        for record in iter_records(seg):
            if int(record.get("seq", 0)) > after_seq:
                out.append(record)
    out.sort(key=lambda r: int(r.get("seq", 0)))
    return out


class Persistence:
    def __init__(self, dirpath: str, *, fsync: bool = True,
                 snapshot_every: int = 4096, shard: str | None = None):
        self.dir = dirpath
        self.shard = shard
        self._snapshot_every = snapshot_every
        self._since_snapshot = 0
        self._snapshotting = False
        self._guard = make_lock("persistence.snapshot_guard")
        self.wal = WriteAheadLog(dirpath, fsync=fsync, shard=shard)

    # ---- boot --------------------------------------------------------
    def recover(self, cluster_scoped: set[str]) -> RecoveredState:
        """Rebuild state from snapshot + WAL tail. Raises
        ``WALCorruption`` on a mid-log CRC failure (a torn tail record
        is tolerated — it was never acked)."""
        rec = RecoveredState()
        doc = snap_mod.load_latest_snapshot(self.dir)
        if doc:
            rec.snapshot_seq = rec.seq = int(doc["seq"])
            rec.rv = int(doc["rv"])
            for obj in doc["objects"]:
                rec.objects[_key_of(obj, cluster_scoped)] = obj
        for seg in segment_paths(self.dir):
            for record in iter_records(seg):
                seq = int(record.get("seq", 0))
                if seq <= rec.snapshot_seq:
                    continue  # the snapshot already reflects it
                rec.seq = max(rec.seq, seq)
                rec.rv = max(rec.rv, int(record.get("rv", 0)))
                obj = record.get("obj")
                if obj is None:
                    continue
                key = _key_of(obj, cluster_scoped)
                if record.get("verb") == "DELETE":
                    rec.objects.pop(key, None)
                else:
                    rec.objects[key] = obj
                rec.records_replayed += 1
        if rec.records_replayed or rec.objects:
            log.info("recovered %d objects (snapshot seq %d + %d WAL "
                     "records) from %s", len(rec.objects),
                     rec.snapshot_seq, rec.records_replayed, self.dir)
        return rec

    # ---- steady state ------------------------------------------------
    def log(self, *, seq: int, rv: int, verb: str, obj: dict,
            wait: bool = True) -> int:
        """Append one write record; return its commit ticket. With
        ``wait`` the call returns only once the record is fsync-durable
        (group commit); without it, the caller must later ``flush``
        up to the returned ticket before acking the write."""
        ticket = self.wal.append(
            {"seq": seq, "rv": rv, "verb": verb, "obj": obj}, wait=wait)
        self._since_snapshot += 1
        return ticket

    def flush(self, upto: int | None = None) -> None:
        self.wal.flush(upto=upto)

    def snapshot_due(self) -> bool:
        return self._since_snapshot >= self._snapshot_every \
            and not self._snapshotting

    def begin_snapshot(self) -> bool:
        """Claim the (single) snapshot slot; False if one is running."""
        with self._guard:
            if self._snapshotting:
                return False
            self._snapshotting = True
            return True

    def complete_snapshot(self, *, seq: int, rv: int,
                          objects: list[dict]) -> None:
        """Persist the cut the apiserver captured (its write lock held
        during capture + ``wal.rotate()``) and unlink compacted
        segments. Runs off the write path."""
        try:
            snap_mod.write_snapshot(self.dir, seq=seq, rv=rv,
                                    objects=objects, shard=self.shard)
            self.wal.compact()
            self._since_snapshot = 0
        finally:
            with self._guard:
                self._snapshotting = False

    def close(self) -> None:
        self.wal.close()
