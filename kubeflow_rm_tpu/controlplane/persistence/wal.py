"""Append-only write-ahead log with fsync-batched group commit.

The durability half of the sharded control plane: every acked write is
a CRC-framed record on disk before the verb returns, so a SIGKILLed
shard replays to exactly the state its clients observed. The recipe is
etcd's (``wal/wal.go``): length+CRC framing, group commit (one fsync
covers every record buffered while the previous fsync was in flight),
segment files rotated at snapshot time so compaction is a file unlink,
a torn tail tolerated on replay, and anything else corrupt a loud
refusal to serve.

Frame layout (little-endian)::

    [u32 payload_len][u32 crc32(payload)][payload bytes]

The payload is one JSON record. Records carry the apiserver's write
sequence number (``seq``, total order across kinds) and the object's
resourceVersion (``rv``); replay filters on ``seq`` against the
snapshot horizon and applies records as blind upserts, so re-applying
a record that the snapshot already reflects is harmless.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import Iterator

from kubeflow_rm_tpu.controlplane import metrics
from kubeflow_rm_tpu.analysis.lockgraph import make_condition, make_lock

_FRAME = struct.Struct("<II")  # payload length, crc32(payload)

SEGMENT_PREFIX = "wal-"
SEGMENT_SUFFIX = ".log"


class WALCorruption(Exception):
    """A full-length record failed its CRC check: the log is damaged in
    the middle, not merely torn at the tail — replaying past it could
    silently resurrect or lose acked writes, so recovery must stop and
    a human (or the chaos harness) must decide."""


def encode_frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def iter_frames(path: str) -> Iterator[bytes]:
    """Yield record payloads from one segment. A truncated tail (torn
    final write from a crash mid-append) ends iteration silently — the
    record was never acked, losing it is correct. A CRC mismatch on a
    full-length record raises ``WALCorruption``."""
    with open(path, "rb") as f:
        data = f.read()
    off, total = 0, len(data)
    while off < total:
        if total - off < _FRAME.size:
            return  # torn header at the tail
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        if total - start < length:
            return  # torn payload at the tail
        payload = data[start:start + length]
        if zlib.crc32(payload) != crc:
            raise WALCorruption(
                f"{os.path.basename(path)}: CRC mismatch at byte {off} "
                f"(stored {crc:#010x}, computed {zlib.crc32(payload):#010x})"
                " — refusing to replay past corruption")
        yield payload
        off = start + length


def iter_records(path: str) -> Iterator[dict]:
    for payload in iter_frames(path):
        yield json.loads(payload)


def segment_paths(dirpath: str) -> list[str]:
    """Segment files in creation (= replay) order."""
    names = [n for n in os.listdir(dirpath)
             if n.startswith(SEGMENT_PREFIX) and n.endswith(SEGMENT_SUFFIX)]
    return [os.path.join(dirpath, n) for n in sorted(names)]


class WriteAheadLog:
    """One shard's log: segmented, CRC-framed, group-committed.

    ``append`` buffers the frame under the lock and (by default) blocks
    until an fsync covers it. Only one thread runs the write+fsync at a
    time; everything buffered while it ran rides the next flush — so N
    concurrent writers pay ~2 fsyncs, not N (group commit). ``fsync``
    can be disabled for tests/benchmarks that only need crash-ordering,
    not power-loss durability.
    """

    def __init__(self, dirpath: str, *, fsync: bool = True,
                 shard: str | None = None):
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self._fsync = fsync
        self._cv = make_condition("wal.cv", lock=make_lock("wal.cv"))
        self._pending: list[bytes] = []
        self._submitted = 0   # frames accepted
        self._durable = 0     # frames flushed (+fsynced)
        self._flushing = False
        existing = segment_paths(dirpath)
        self._seg_index = len(existing) + 1
        if existing:
            # never append to a segment that may end in a torn record:
            # a fresh segment keeps "torn tail" a per-crash, tail-only
            # phenomenon instead of a mid-file one
            self._seg_index = 1 + max(
                int(os.path.basename(p)[len(SEGMENT_PREFIX):
                                        -len(SEGMENT_SUFFIX)])
                for p in existing)
        shard_l = shard if shard is not None else metrics.shard_label()
        self._m_fsync = metrics.WAL_FSYNC_SECONDS.labels(shard=shard_l)
        self._m_bytes = metrics.WAL_BYTES_TOTAL.labels(shard=shard_l)
        self._f = open(self._segment_path(self._seg_index), "ab")
        self.appends = 0

    def _segment_path(self, index: int) -> str:
        return os.path.join(
            self.dir, f"{SEGMENT_PREFIX}{index:06d}{SEGMENT_SUFFIX}")

    # ---- append / group commit --------------------------------------
    def append(self, record: dict, *, wait: bool = True) -> int:
        """Buffer one record; return its commit ticket. With ``wait``
        the call returns only after the record is durable (possibly
        fsynced by another thread's batch)."""
        frame = encode_frame(json.dumps(
            record, separators=(",", ":")).encode())
        with self._cv:
            self._pending.append(frame)
            self._submitted += 1
            ticket = self._submitted
            self.appends += 1
        if wait:
            self.flush(upto=ticket)
        return ticket

    def flush(self, upto: int | None = None) -> None:
        """Make every record up to ticket ``upto`` (default: all
        submitted) durable. One caller at a time becomes the flusher
        and commits the whole buffer; the rest wait on its fsync."""
        while True:
            with self._cv:
                if upto is None:
                    upto = self._submitted
                if self._durable >= upto:
                    return
                if self._flushing:
                    self._cv.wait(0.5)
                    continue
                batch = b"".join(self._pending)
                self._pending.clear()
                target = self._submitted
                self._flushing = True
            t0 = time.perf_counter()
            try:
                if batch:
                    self._f.write(batch)
                self._f.flush()
                if self._fsync:
                    os.fsync(self._f.fileno())
            finally:
                with self._cv:
                    self._durable = max(self._durable, target)
                    self._flushing = False
                    self._cv.notify_all()
            self._m_fsync.observe(time.perf_counter() - t0)
            if batch:
                self._m_bytes.inc(len(batch))

    def rotate(self) -> None:
        """Flush + fsync the open segment, then start a new one. The
        snapshot path calls this under the apiserver's write lock so
        every record at-or-below the snapshot's seq horizon lives in a
        now-closed segment (making compaction a plain unlink).

        The write+fsync+reopen run OUTSIDE the condvar, made exclusive
        by the same ``_flushing`` flag group commit uses — appends keep
        buffering during the fsync (they only touch ``_pending``), and
        anything buffered while we rotate simply lands in the new
        segment on its own flush."""
        with self._cv:
            while self._flushing:  # let an in-flight group commit land
                self._cv.wait(0.5)
            batch = b"".join(self._pending)
            self._pending.clear()
            target = self._submitted
            self._flushing = True
        ok = False
        try:
            if batch:
                self._f.write(batch)
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
            self._f.close()
            self._seg_index += 1
            self._f = open(self._segment_path(self._seg_index), "ab")
            ok = True
        finally:
            with self._cv:
                if ok:
                    self._durable = max(self._durable, target)
                    if batch:
                        self._m_bytes.inc(len(batch))
                self._flushing = False
                self._cv.notify_all()

    def compact(self, keep_from_index: int | None = None) -> int:
        """Unlink closed segments older than the open one (or than
        ``keep_from_index``). Returns the number removed."""
        limit = self._seg_index if keep_from_index is None \
            else keep_from_index
        removed = 0
        for path in segment_paths(self.dir):
            name = os.path.basename(path)
            idx = int(name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)])
            if idx < limit:
                os.unlink(path)
                removed += 1
        return removed

    def close(self) -> None:
        try:
            self.flush()
        finally:
            self._f.close()
