"""Compacting snapshots for the durable apiserver store.

A snapshot is one JSON file holding every stored object plus the write
``seq`` and resourceVersion counters at the cut. Written atomically
(tmp + fsync + rename, etcd's snap/ recipe), so a crash mid-snapshot
leaves the previous snapshot intact and replay simply walks more WAL.
After a successful snapshot the WAL segments at-or-below the cut are
unlinked — the log stays O(writes since last snapshot), not O(history).
"""

from __future__ import annotations

import json
import os
import time

from kubeflow_rm_tpu.controlplane import metrics

SNAP_PREFIX = "snap-"
SNAP_SUFFIX = ".json"


def snapshot_paths(dirpath: str) -> list[str]:
    names = [n for n in os.listdir(dirpath)
             if n.startswith(SNAP_PREFIX) and n.endswith(SNAP_SUFFIX)]
    return [os.path.join(dirpath, n) for n in sorted(names)]


def write_snapshot(dirpath: str, *, seq: int, rv: int,
                   objects: list[dict], shard: str | None = None) -> str:
    """Atomically persist one cut. Returns the snapshot path."""
    t0 = time.perf_counter()
    path = os.path.join(dirpath, f"{SNAP_PREFIX}{seq:012d}{SNAP_SUFFIX}")
    tmp = path + ".tmp"
    doc = {"seq": seq, "rv": rv, "objects": objects}
    with open(tmp, "w") as f:
        json.dump(doc, f, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # older snapshots are fully superseded
    for old in snapshot_paths(dirpath):
        if old != path:
            os.unlink(old)
    shard_l = shard if shard is not None else metrics.shard_label()
    metrics.SNAPSHOT_DURATION_SECONDS.labels(shard=shard_l).observe(
        time.perf_counter() - t0)
    return path


def load_latest_snapshot(dirpath: str) -> dict | None:
    """The newest parseable snapshot, or None. A half-written ``.tmp``
    is never considered (rename is the commit point); an unparseable
    committed snapshot falls back to the previous one if present."""
    for path in reversed(snapshot_paths(dirpath)):
        try:
            with open(path) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and "seq" in doc:
                return doc
        except (OSError, ValueError):
            continue
    return None
