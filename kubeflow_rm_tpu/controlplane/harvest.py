"""Chip harvesting: the serving fleet borrows idle notebook chips.

The platform's two big consumers pull in opposite directions on the
same pool: notebooks hold slices interactively (bursty, latency-
sensitive, suspend/resume makes them *elastic donors*), while the
serving fleet wants every chip it can get the moment decode queues
deepen. r13-r18 built each side separately; this module closes the
loop — a :class:`ChipHarvestController` watches serving pressure (the
r12 SLO burn engine + decode queue depth) and, when it sustains,
*harvests*: it parks an idle notebook through the exact
checkpoint→drain→release lifecycle idle culling uses, binds a serving
replica gang onto the freed slice, and registers the replica with the
fleet.

The contract that makes this safe to run against interactive users:

- **Notebook demand ALWAYS outranks harvested serving.** Every chip a
  harvest gang holds is charged in the scheduler cache with a
  ``harvested`` mark, and the cache exposes ``reclaim_harvested`` —
  the FIRST thing ``suspend.try_preempt`` tries when any gang fails to
  bind. A resuming donor (or any other notebook that needs chips)
  drains the serving replica, migrates its in-flight requests to the
  rest of the fleet (the GlobalBlockStore keeps the prefix blocks, so
  continuations stay bit-exact), and re-gangs on the returned slice —
  inside the same reconcile that failed to bind.
- **No pinned or culling-excluded notebook is ever harvested**, and a
  running notebook must sit idle past a threshold before it is a
  donor; already-Suspended notebooks are preferred (their chips are
  free — harvesting them suspends nobody).
- **Harvest gangs prefer whole freed slices** (``prefer_whole_nodes``)
  so a reclaim returns an intact slice instead of scattering the
  donor's re-bind across fragmented remainders.
- **Give-back is autonomous**: sustained calm (no burn, shallow
  queues) returns the oldest lease without waiting for demand.

Harvest charges are *synthetic*: no apiserver pods back them (the
serving fleet is not a Kubernetes workload here), so they live as
assumed entries in the scheduler cache — ``rebuild()`` preserves
assumed entries precisely so a relist cannot wipe a lease and
double-book the chips.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from kubeflow_rm_tpu.analysis.lockgraph import make_lock
from kubeflow_rm_tpu.controlplane import metrics, scheduler, suspend
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of,
    deep_get,
    name_of,
    namespace_of,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer

#: namespace the synthetic harvest-gang charges live under in the
#: scheduler cache — never a real apiserver namespace, so no controller
#: or relist can ever collide with a lease key
HARVEST_NAMESPACE = "serving-harvest"

#: suspend reason stamped on donors the controller parks itself
HARVEST_REASON = "harvest"

#: the r15 failover SLO budget (seconds): a warm-standby promotion —
#: detection to fully-ready — must land inside this envelope
#: (``notebook_failover_seconds`` bucket bound; measured p50 sits ~3
#: orders of magnitude under it). A harvest reclaim rides the SAME
#: demand-resume path, so its p95 must fit the same budget — the
#: conformance storm and test suite assert against this constant.
FAILOVER_SLO_S = 2.5


@dataclass
class HarvestLease:
    """One serving replica running on one donor notebook's chips."""
    replica: str                      # fleet replica name
    donor: tuple[str, str]            # (namespace, name) of the notebook
    keys: tuple[tuple[str, str], ...]  # scheduler charge keys
    nodes: tuple[str, ...]            # nodes the gang landed on
    chips: float                      # total chips held
    granted_at: float                 # time.monotonic() at grant

    def spec(self) -> dict:
        return {"replica": self.replica,
                "donor": "/".join(self.donor),
                "nodes": list(self.nodes),
                "chips": self.chips}


class ChipHarvestController:
    """Tick-driven: measure pressure, grant leases, reclaim on demand.

    Drive :meth:`tick` from a harness loop (the conformance storms) or
    a background thread; :meth:`reclaim` is also invoked synchronously
    by the scheduler (via ``sched.harvest_reclaimer``) when a notebook
    gang fails to bind — that path is what bounds resume latency by
    the failover SLO instead of a tick period.

    ``gateway_factory(name) -> ServingGateway`` supplies the replica
    the controller binds onto freed chips; the harness builds it
    against the shared model params. ``observer`` (an
    :class:`~kubeflow_rm_tpu.controlplane.obs.Observer`) is optional —
    without it, pressure falls back to decode queue depth alone.
    """

    def __init__(self, api: APIServer, fleet, *, gateway_factory,
                 observer=None, sched=None,
                 idle_minutes: float = 15.0,
                 pressure_depth: float = 4.0,
                 burn_slos: tuple = ("serving-victim-p95",),
                 sustain: int = 2,
                 give_back_after: int = 4,
                 max_leases: int = 4,
                 reclaim_grace_s: float = 0.05,
                 store=None):
        self.api = api
        self.fleet = fleet
        self.gateway_factory = gateway_factory
        self.observer = observer
        self.sched = (sched if sched is not None
                      else scheduler.cache_for(api))
        self.idle_minutes = float(idle_minutes)
        self.pressure_depth = float(pressure_depth)
        self.burn_slos = tuple(burn_slos)
        self.sustain = int(sustain)
        self.give_back_after = int(give_back_after)
        self.max_leases = int(max_leases)
        self.reclaim_grace_s = float(reclaim_grace_s)
        self.store = store
        # ordering: harvest -> fleet(435)/scheduler is the only
        # direction — nothing under those locks calls back into us
        self._lock = make_lock("harvest.controller")
        self._leases: dict[str, HarvestLease] = {}
        #: donors we suspended ourselves, awaiting SUSPEND_DRAINED
        self._pending: dict[tuple[str, str], float] = {}
        self._seq = 0
        self._hot = 0
        self._calm = 0
        # the reclaim hook is how "notebook outranks serving" reaches
        # the bind path without the scheduler importing this module
        self.sched.harvest_reclaimer = self.reclaim

    # ---- introspection ---------------------------------------------------

    def leases(self) -> list[dict]:
        with self._lock:
            return [ls.spec() for ls in self._leases.values()]

    def lease_count(self) -> int:
        with self._lock:
            return len(self._leases)

    # ---- the tick --------------------------------------------------------

    def tick(self) -> str:
        """One pass: reclaim resumed donors, finish pending grants,
        then act on the pressure signal. Returns the decision taken
        ("reclaim" | "grant" | "suspend" | "give_back" | "hold")."""
        if self._reclaim_resumed_donors():
            return "reclaim"
        acted = self._complete_pending()
        hot = self._pressure()
        if hot:
            self._hot += 1
            self._calm = 0
        else:
            self._calm += 1
            self._hot = 0
        if acted:
            return "grant"
        if hot and self._hot >= self.sustain:
            with self._lock:
                outstanding = len(self._leases) + len(self._pending)
            if outstanding < self.max_leases:
                return self._start_harvest()
            return "hold"
        if (not hot and self._calm >= self.give_back_after
                and self.lease_count() > 0):
            self._give_back_oldest()
            return "give_back"
        return "hold"

    # ---- pressure signal -------------------------------------------------

    def _pressure(self) -> bool:
        """Serving wants more chips: any watched SLO burning past ok,
        or the mean ready-replica decode queue deeper than the
        threshold."""
        if self.observer is not None:
            for slo in self.burn_slos:
                try:
                    if self.observer.engine.state_of(slo) != "ok":
                        return True
                except KeyError:
                    pass
        snap = self.fleet.snapshot()
        depths = [r["queue_depth"] for r in snap["replicas"].values()
                  if r["state"] == "ready"
                  and (r["role"] in (None, "decode"))]
        if not depths:
            return False
        return sum(depths) / len(depths) >= self.pressure_depth

    # ---- donor selection -------------------------------------------------

    def _harvestable(self) -> list[dict]:
        """Donor candidates, best first: already-drained Suspended
        notebooks (free chips, nobody to suspend), then running
        notebooks idle past the threshold. Pinned, culling-excluded,
        CPU-only, and mid-lifecycle notebooks are never donors."""
        drained, idle = [], []
        now = self.api.clock()
        with self._lock:
            pending = set(self._pending)
            donors = {ls.donor for ls in self._leases.values()}
        for nb in self.api.list(nb_api.KIND):
            key = (namespace_of(nb), name_of(nb))
            if key in pending or key in donors:
                continue
            if nb_api.tpu_spec(nb) is None:
                continue
            ann = annotations_of(nb)
            if (nb_api.is_pinned(nb)
                    or ann.get(nb_api.CULLING_EXCLUDE_ANNOTATION)
                    == "true"):
                continue
            if nb_api.RESUME_REQUESTED_ANNOTATION in ann:
                continue  # being resumed: the worst possible donor
            if nb_api.SUSPEND_ANNOTATION in ann:
                if nb_api.SUSPEND_DRAINED_ANNOTATION in ann:
                    drained.append(nb)
                continue  # suspending but not drained yet: wait
            last = suspend._parse_ts(
                ann.get(nb_api.LAST_ACTIVITY_ANNOTATION))
            if last is None:
                last = suspend._parse_ts(
                    nb["metadata"].get("creationTimestamp"))
            if last is None:
                continue
            if (now - last).total_seconds() >= self.idle_minutes * 60.0:
                idle.append(nb)
        # smallest slice first: harvest the cheapest donor that
        # satisfies pressure, keep big slices for their owners
        drained.sort(key=nb_api.total_hosts)
        idle.sort(key=nb_api.total_hosts)
        return drained + idle

    def _start_harvest(self) -> str:
        for nb in self._harvestable():
            ann = annotations_of(nb)
            if nb_api.SUSPEND_DRAINED_ANNOTATION in ann:
                if self._bind_lease(nb) is not None:
                    return "grant"
                continue  # freed slice got taken; try the next donor
            # running but idle: park it through the normal lifecycle,
            # bind once the SuspendController stamps the drain
            live = suspend.initiate_suspend(
                self.api, nb, reason=HARVEST_REASON, store=self.store)
            if (nb_api.SUSPEND_REASON_ANNOTATION in annotations_of(live)
                    and annotations_of(live).get(
                        nb_api.SUSPEND_REASON_ANNOTATION)
                    == HARVEST_REASON):
                with self._lock:
                    self._pending[(namespace_of(live), name_of(live))] \
                        = time.monotonic()
                return "suspend"
        return "hold"

    def _complete_pending(self) -> bool:
        """Bind leases for donors we parked once their drain lands."""
        with self._lock:
            pending = list(self._pending)
        acted = False
        for key in pending:
            ns, name = key
            nb = self.api.try_get(nb_api.KIND, name, ns)
            if nb is None:
                with self._lock:
                    self._pending.pop(key, None)
                continue
            ann = annotations_of(nb)
            if nb_api.SUSPEND_ANNOTATION not in ann:
                # resumed before we ever bound: lease never existed
                with self._lock:
                    self._pending.pop(key, None)
                continue
            if nb_api.SUSPEND_DRAINED_ANNOTATION not in ann:
                continue  # still draining
            with self._lock:
                self._pending.pop(key, None)
            if self._bind_lease(nb) is not None:
                acted = True
        return acted

    # ---- grant -----------------------------------------------------------

    def _gang_pods(self, replica: str, topo, hosts: int) -> list[dict]:
        """Synthetic pods shaped like the donor's: same per-host chip
        request, same accelerator selector — the gang lands only on
        nodes the donor could have."""
        selector = {tpu_api.NODE_LABEL_ACCELERATOR: topo.gke_accelerator}
        if topo.multihost:
            selector[tpu_api.NODE_LABEL_TOPOLOGY] = topo.topology
        return [{
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"{replica}-{i}",
                         "namespace": HARVEST_NAMESPACE},
            "spec": {
                "nodeSelector": dict(selector),
                "containers": [{
                    "name": "serve",
                    "resources": {"limits": {
                        tpu_api.GOOGLE_TPU_RESOURCE:
                            str(topo.chips_per_host)}},
                }],
            },
        } for i in range(hosts)]

    def _bind_lease(self, notebook: dict):
        """Charge a harvest gang for the donor's slice shape, spin the
        replica, register it. Returns the lease or None (didn't fit —
        the freed chips were taken by real notebook demand, which is
        the priority order working as intended)."""
        topo = nb_api.tpu_spec(notebook)
        if topo is None:
            return None
        hosts = nb_api.total_hosts(notebook)
        with self._lock:
            self._seq += 1
            replica = f"harvest-{self._seq}"
        pods = self._gang_pods(replica, topo, hosts)
        plan = self.sched.gang_bind(pods, allow_virtual=False,
                                    prefer_whole_nodes=True)
        if plan is None:
            return None
        # leases stay ASSUMED on purpose: no apiserver pod will ever
        # confirm them, and rebuild() preserves assumed entries
        for key in plan:
            self.sched.mark_harvested(key)
        try:
            gw = self.gateway_factory(replica)
            role = "decode" if self.fleet.roles is not None else None
            self.fleet.add_replica(replica, gw, role)
        except Exception:
            for key in plan:
                self.sched.release_harvested(key)
            metrics.swallowed("harvest", "replica spin-up")
            return None
        lease = HarvestLease(
            replica=replica,
            donor=(namespace_of(notebook), name_of(notebook)),
            keys=tuple(sorted(plan)),
            nodes=tuple(sorted(set(plan.values()))),
            chips=float(hosts * topo.chips_per_host),
            granted_at=time.monotonic())
        with self._lock:
            self._leases[replica] = lease
        metrics.HARVEST_GRANTS_TOTAL.inc()
        self.api.record_event(
            notebook, "Normal", "Harvested",
            f"serving replica {replica} borrowing the idle slice "
            f"({lease.chips:.0f} chip(s) on {list(lease.nodes)}); "
            "returns instantly on any resume")
        return lease

    # ---- reclaim ---------------------------------------------------------

    def _reclaim_resumed_donors(self) -> bool:
        """A donor with a resume in flight (or already running, or
        deleted) gets its chips back NOW — this is the tick-side
        mirror of the synchronous ``try_preempt`` path, covering
        resumes whose re-bind succeeded elsewhere or whose notebook
        vanished entirely."""
        with self._lock:
            leases = list(self._leases.values())
        reclaimed = False
        for ls in leases:
            ns, name = ls.donor
            nb = self.api.try_get(nb_api.KIND, name, ns)
            if nb is not None:
                ann = annotations_of(nb)
                if (nb_api.SUSPEND_ANNOTATION in ann
                        and nb_api.RESUME_REQUESTED_ANNOTATION
                        not in ann):
                    continue  # still parked: lease stands
            self._release_lease(ls, trigger="resume")
            reclaimed = True
        if reclaimed:
            # freed chips emit no event any controller watches;
            # requeue waiting gangs exactly like a drain does
            suspend.kick_pending_pods(
                self.api, now=self.api.clock().isoformat())
        return reclaimed

    def reclaim(self, nodes=None, trigger: str = "preempt") -> float:
        """The ``sched.harvest_reclaimer`` hook: give back every lease
        touching ``nodes`` (all leases when None) and return the chips
        freed. Called with no scheduler locks held."""
        with self._lock:
            leases = [ls for ls in self._leases.values()
                      if nodes is None or set(ls.nodes) & set(nodes)]
        freed = 0.0
        for ls in leases:
            freed += self._release_lease(ls, trigger=trigger)
        return freed

    def _release_lease(self, lease: HarvestLease, *,
                       trigger: str) -> float:
        with self._lock:
            if self._leases.pop(lease.replica, None) is None:
                return 0.0  # raced another reclaimer; already gone
        t0 = time.perf_counter()
        try:
            # drain-first: queued + mid-decode requests migrate to the
            # rest of the fleet (store-held prefixes keep them exact)
            self.fleet.remove_replica(lease.replica,
                                      grace_s=self.reclaim_grace_s)
        except ValueError:
            # last (or last-decode) replica: the fleet would rather
            # die than the notebook wait — kill keeps the chips' side
            # of the contract even when serving loses its quorum
            self.fleet.kill(lease.replica)
        except KeyError:
            pass  # replica already gone (chaos killed it): chips still ours to free
        for key in lease.keys:
            self.sched.release_harvested(key)
        dt = time.perf_counter() - t0
        metrics.HARVEST_RECLAIMS_TOTAL.labels(trigger=trigger).inc()
        metrics.HARVEST_RECLAIM_SECONDS.observe(dt)
        nb = self.api.try_get(nb_api.KIND, lease.donor[1],
                              lease.donor[0])
        if nb is not None:
            self.api.record_event(
                nb, "Normal", "HarvestReturned",
                f"serving replica {lease.replica} drained off the "
                f"borrowed slice in {dt * 1e3:.1f}ms ({trigger}); "
                f"{lease.chips:.0f} chip(s) back in the pool")
        return lease.chips

    def _give_back_oldest(self) -> None:
        with self._lock:
            if not self._leases:
                return
            oldest = min(self._leases.values(),
                         key=lambda ls: ls.granted_at)
        self._release_lease(oldest, trigger="idle_giveback")

    # ---- teardown --------------------------------------------------------

    def close(self) -> None:
        """Return every lease (shutdown path) and detach the hook."""
        self.reclaim(trigger="idle_giveback")
        if self.sched.harvest_reclaimer is self.reclaim:
            self.sched.harvest_reclaimer = None
