"""torch_xla (PJRT) bootstrap from the platform's rendezvous contract.

The webhook injects the same env into every pod regardless of framework
(``controlplane/webhook/tpu_inject.py``): ``TPU_WORKER_ID``,
``TPU_WORKER_HOSTNAMES``, ``TPU_ACCELERATOR_TYPE``, ``TPU_TOPOLOGY``
(+ ``MEGASCALE_*`` on multislice). jax consumes it via
``parallel.distributed``; this module is the torch_xla consumer, used
by the ``jupyter-pytorch-xla`` image (BASELINE.md eval config
"torch_xla v5litepod-4"; reference seam:
``example-notebook-servers/jupyter-pytorch-cuda/Dockerfile:14-23``,
whose NVIDIA_* env plays the role PJRT_DEVICE plays here).

Two layers, mirroring how torch_xla actually rendezvouses:

- **libtpu layer**: ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` are
  read by libtpu itself for ICI rendezvous — torch_xla's PJRT client
  consumes them exactly as jax's does, so the webhook's contract needs
  no translation there.
- **torch.distributed layer**: collectives through
  ``torch.distributed`` need a process group; ``pjrt://`` handles the
  single-host case, while multi-host needs MASTER_ADDR/MASTER_PORT +
  rank/world. ``torchxla_env`` derives those from the same contract
  (worker 0 is the master — pod ordinals are stable because the slice
  is a StatefulSet behind a headless Service).
"""

from __future__ import annotations

import os

from kubeflow_rm_tpu.parallel.distributed import TpuEnv, tpu_env

#: the conventional torch.distributed master port (init_method env://)
DEFAULT_MASTER_PORT = 12355


def torchxla_env(environ=None, *, master_port: int = DEFAULT_MASTER_PORT,
                 device: str = "TPU") -> dict[str, str]:
    """Map the webhook contract to the env a torch_xla process needs.

    Returns the variables to merge into the process environment before
    ``import torch_xla`` (PJRT reads them at client construction):

    - ``PJRT_DEVICE`` — selects the TPU PJRT plugin (or CPU in tests);
    - ``MASTER_ADDR``/``MASTER_PORT``/``RANK``/``WORLD_SIZE``/
      ``LOCAL_RANK`` — the torch.distributed env:// rendezvous, derived
      slice-major exactly like the jax process ids so a hybrid job
      numbers both worlds identically.

    Raises ``ValueError`` on a contract violation (ordinal outside the
    slice) — the platform injecting inconsistent env is a bug worth
    failing loudly on, not a condition to limp through.
    """
    env: TpuEnv = tpu_env(environ)
    if env.worker_hostnames and env.worker_id >= env.hosts_per_slice:
        raise ValueError(
            f"TPU_WORKER_ID={env.worker_id} outside the "
            f"{env.hosts_per_slice}-host slice "
            f"(TPU_WORKER_HOSTNAMES={','.join(env.worker_hostnames)})")
    master = env.worker_hostnames[0] if env.worker_hostnames else "localhost"
    if env.is_multislice and env.coordinator:
        master = env.coordinator.split(":")[0]
    return {
        "PJRT_DEVICE": device,
        "MASTER_ADDR": master,
        "MASTER_PORT": str(master_port),
        "RANK": str(env.process_id),
        "LOCAL_RANK": "0",
        "WORLD_SIZE": str(env.num_hosts),
    }


def apply_env(environ=None, **kw) -> dict[str, str]:
    """Merge ``torchxla_env`` into ``os.environ`` (idempotent; explicit
    user overrides win). Returns the mapping that was applied."""
    mapping = torchxla_env(environ, **kw)
    for k, v in mapping.items():
        os.environ.setdefault(k, v)
    return mapping


def init_distributed(environ=None, *, backend: str | None = None,
                     master_port: int = DEFAULT_MASTER_PORT,
                     device: str = "TPU"):
    """Initialize ``torch.distributed`` from the platform contract.

    On a TPU image the backend is ``xla`` (torch_xla registers it on
    import); tests pass ``backend="gloo"`` to prove the same rendezvous
    env drives a real process-group init without TPU hardware. No-op
    returning None when torch.distributed is already initialized.
    """
    import torch.distributed as dist

    if dist.is_initialized():
        return None
    mapping = apply_env(environ, master_port=master_port, device=device)
    if backend is None:
        import torch_xla  # noqa: F401  (registers the xla backend)
        backend = "xla"
    dist.init_process_group(
        backend,
        init_method="env://",
        rank=int(mapping["RANK"]),
        world_size=int(mapping["WORLD_SIZE"]),
    )
    return dist
