"""SPMD launcher — the in-image process layer for multi-host slices.

Worker 0 runs JupyterLab (s6 service, as in the reference's jupyter
image); ordinals > 0 run the worker agent (``agent.py``), which joins
``jax.distributed`` and idles until the notebook kernel on worker 0
drives an SPMD program across the slice. The reference has no
equivalent — its servers are single-pod (SURVEY.md §2.6)."""

from kubeflow_rm_tpu.launcher.agent import WorkerAgent

__all__ = ["WorkerAgent"]
