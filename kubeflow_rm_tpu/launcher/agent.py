"""Worker agent for slice ordinals > 0.

On a multi-host slice the platform starts the same image on every host
(StatefulSet, Parallel pod management). JupyterLab must run exactly
once (worker 0: the UI Service routes there), but **every** host must
run a jax process for SPMD programs to span the slice. This agent is
that process for ordinals > 0:

1. read the webhook-injected rendezvous env (``TPU_WORKER_ID`` /
   ``TPU_WORKER_HOSTNAMES`` — ``parallel/distributed.py``),
2. join ``jax.distributed`` with worker 0 as coordinator,
3. serve ``/healthz`` (the kubelet readiness probe for peer pods —
   the reference probes JupyterLab; peers have no Lab to probe),
4. block until the process is terminated (slice teardown).

The jax runtime handles the actual work: once initialized, worker 0's
kernel executing a jitted computation over the full mesh makes libtpu
run this host's shard — there is no work queue to poll. This is the
SPMD model, not a task-dispatch model, which is why the agent is this
small.
"""

from __future__ import annotations

import dataclasses
import http.server
import json
import logging
import os
import threading

log = logging.getLogger("kubeflow_rm_tpu.launcher")

HEALTH_PORT = 8080


@dataclasses.dataclass(frozen=True)
class RoleEnv:
    """The TPUJob half of the rendezvous contract (webhook → agent).

    Parsed from the ``TPU_JOB_*`` vars the tpu_inject webhook stamps on
    every gang member — chip pods and CPU actors alike. The TPU-scoped
    vars (``TPU_WORKER_*``) remain a separate, slice-local contract:
    an actor pod has the role env but NOT the TPU env, which is how the
    agent tells the two apart.
    """
    job: str
    role: str
    role_index: int
    role_hostnames: tuple[str, ...]
    #: every role's hostname list, keyed by the role name as it appears
    #: in the job spec (lowercased back from the env-var suffix)
    peers: dict[str, tuple[str, ...]]
    learner_address: str

    @property
    def in_gang(self) -> bool:
        return bool(self.job)


def role_env(environ=None) -> RoleEnv:
    """Parse the ``TPU_JOB_*`` rendezvous env; never raises — absent
    vars yield an empty ``RoleEnv`` (``in_gang`` False)."""
    from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api
    e = os.environ if environ is None else environ
    try:
        idx = int(e.get(tj_api.ENV_JOB_ROLE_INDEX, "0"))
    except ValueError:
        idx = 0
    peers: dict[str, tuple[str, ...]] = {}
    for key, val in e.items():
        if not key.startswith(tj_api.ENV_JOB_HOSTNAMES_PREFIX):
            continue
        rname = key[len(tj_api.ENV_JOB_HOSTNAMES_PREFIX):]
        peers[rname.lower().replace("_", "-")] = tuple(
            h for h in val.split(",") if h)
    own = tuple(h for h in e.get(
        tj_api.ENV_JOB_ROLE_HOSTNAMES, "").split(",") if h)
    return RoleEnv(
        job=e.get(tj_api.ENV_JOB_NAME, ""),
        role=e.get(tj_api.ENV_JOB_ROLE, ""),
        role_index=idx,
        role_hostnames=own,
        peers=peers,
        learner_address=e.get(tj_api.ENV_LEARNER_ADDRESS, ""),
    )


class WorkerAgent:
    def __init__(self, environ=None, *, health_port: int = HEALTH_PORT):
        from kubeflow_rm_tpu.parallel.distributed import tpu_env
        self.env = tpu_env(environ)
        self.role = role_env(environ)
        self.health_port = health_port
        self._httpd = None
        self._ready = False

    @property
    def is_actor(self) -> bool:
        """A CPU-only gang member: role rendezvous env but no TPU env.

        Actors never join ``jax.distributed`` — the learner slice is
        its own SPMD world; actors talk to it over the learner address
        (``TPU_JOB_LEARNER_ADDRESS``) at the application layer."""
        return self.role.in_gang and not self.env.accelerator_type

    @property
    def is_worker_zero(self) -> bool:
        """True only for the GLOBAL process 0 (slice 0, worker 0).

        On a multislice notebook every slice has a local worker 0, but
        JupyterLab must run exactly once in the whole job — gating on
        the per-slice ``TPU_WORKER_ID`` alone would start a second Lab
        on each slice and strand the global rendezvous.
        """
        return self.env.process_id == 0

    def start_health_server(self) -> int:
        """Serve /healthz; returns the bound port (ephemeral if 0)."""
        agent = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                body = json.dumps({
                    "ready": agent._ready,
                    "worker_id": agent.env.worker_id,
                    "hosts": agent.env.num_hosts,
                    **({"job": agent.role.job,
                        "role": agent.role.role,
                        "role_index": agent.role.role_index}
                       if agent.role.in_gang else {}),
                }).encode()
                self.send_response(200 if agent._ready else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("0.0.0.0", self.health_port), Handler)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self._httpd.server_address[1]

    def join_slice(self, *, retry_interval_s: float = 15.0,
                   max_attempts: int | None = None) -> None:
        """Initialize jax.distributed from the injected env (no-op on
        single-host).

        Retries until the coordinator appears: worker 0 only starts the
        jax coordinator when the user's notebook kernel initializes,
        which can be minutes-to-hours after peer pods boot — a single
        timed-out attempt would crash the agent and leave slice
        assembly to luck (whether an s6 restart overlaps the kernel's
        init window). ``max_attempts`` bounds the loop for tests.
        """
        from kubeflow_rm_tpu.parallel.distributed import initialize
        attempt = 0
        while True:
            attempt += 1
            try:
                initialize(dict_env(self.env))
                break
            except (ValueError, TypeError):
                # malformed rendezvous env: no amount of waiting fixes
                # it — crash so s6/kubernetes surface the misconfig
                raise
            except Exception as e:
                if max_attempts is not None and attempt >= max_attempts:
                    raise
                # transient (coordinator not up, DNS settling): retry,
                # but escalate to WARNING once it stops looking like a
                # normal kernel-start delay so a wedged slice is loud
                level = logging.INFO if attempt <= 8 else logging.WARNING
                coordinator = (self.env.coordinator
                               or self.env.worker_hostnames[:1])
                log.log(
                    level,
                    "process %d (slice %d worker %d): coordinator %s "
                    "not up yet (attempt %d: %s); retrying in %.0fs",
                    self.env.process_id, self.env.slice_id,
                    self.env.worker_id, coordinator, attempt, e,
                    retry_interval_s)
                import time
                time.sleep(retry_interval_s)
        self._ready = True
        log.info("worker %d/%d joined the slice", self.env.worker_id,
                 self.env.num_hosts)

    def run_forever(self) -> None:
        import signal
        stop = threading.Event()
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *a: stop.set())
        stop.wait()
        if self._httpd:
            self._httpd.shutdown()


def dict_env(env) -> dict:
    """Round-trip a ``TpuEnv`` back to the webhook's env contract.

    Must carry the MEGASCALE_* multislice vars: dropping them would
    make ``initialize`` compute a slice-local world (num_processes =
    hosts_per_slice, coordinator = this slice's worker 0) and the
    global job could never assemble.
    """
    return {
        "TPU_WORKER_ID": str(env.worker_id),
        "TPU_WORKER_HOSTNAMES": ",".join(env.worker_hostnames),
        **({"TPU_ACCELERATOR_TYPE": env.accelerator_type}
           if env.accelerator_type else {}),
        **({"TPU_TOPOLOGY": env.topology} if env.topology else {}),
        **({"MEGASCALE_NUM_SLICES": str(env.num_slices),
            "MEGASCALE_SLICE_ID": str(env.slice_id)}
           if env.num_slices > 1 else {}),
        **({"MEGASCALE_COORDINATOR_ADDRESS": env.coordinator}
           if env.coordinator else {}),
    }


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    agent = WorkerAgent()
    if agent.is_actor:
        # CPU actor in a TPUJob gang: nothing to rendezvous at the jax
        # layer — serve readiness and idle; the actor program (the
        # container's own command) does the trajectory work against
        # TPU_JOB_LEARNER_ADDRESS
        log.info("actor %s[%d] of job %s: learner at %s",
                 agent.role.role, agent.role.role_index,
                 agent.role.job, agent.role.learner_address or "<none>")
        agent.start_health_server()
        agent._ready = True
        agent.run_forever()
        return
    if agent.is_worker_zero:
        # worker 0 runs JupyterLab (notebooks) or the learner program
        # (TPUJob chip roles) as a separate s6 service; the agent has
        # nothing to do — exit cleanly so s6 doesn't restart-loop it
        log.info("worker 0: the primary program owns this host; "
                 "agent exiting")
        return
    agent.start_health_server()
    agent.join_slice()
    agent.run_forever()


if __name__ == "__main__":
    main()
