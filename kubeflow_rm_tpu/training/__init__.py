"""Training package: lazy exports (PEP 562).

``kubeflow_rm_tpu.training.checkpoint`` must be importable on a plain
CPU host with only jax+orbax (the control plane's Checkpointer-backed
suspend state store and its tests live there); eagerly importing the
model/parallelism stack here would drag the whole compute dependency
chain into every control-plane process.
"""

_EXPORTS = {
    "Checkpointer": ("kubeflow_rm_tpu.training.checkpoint", "Checkpointer"),
    "abstract_state": ("kubeflow_rm_tpu.training.checkpoint",
                       "abstract_state"),
    "LoopConfig": ("kubeflow_rm_tpu.training.loop", "LoopConfig"),
    "LoopMetrics": ("kubeflow_rm_tpu.training.loop", "LoopMetrics"),
    "fit": ("kubeflow_rm_tpu.training.loop", "fit"),
    "TrainConfig": ("kubeflow_rm_tpu.training.train", "TrainConfig"),
    "TrainState": ("kubeflow_rm_tpu.training.train", "TrainState"),
    "init_train_state": ("kubeflow_rm_tpu.training.train",
                         "init_train_state"),
    "make_train_step": ("kubeflow_rm_tpu.training.train",
                        "make_train_step"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        module, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    return getattr(importlib.import_module(module), attr)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
