from kubeflow_rm_tpu.training.train import (
    TrainConfig,
    TrainState,
    init_train_state,
    make_train_step,
)

__all__ = ["TrainConfig", "TrainState", "init_train_state", "make_train_step"]
