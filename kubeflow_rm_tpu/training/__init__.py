from kubeflow_rm_tpu.training.checkpoint import Checkpointer, abstract_state
from kubeflow_rm_tpu.training.loop import LoopConfig, LoopMetrics, fit
from kubeflow_rm_tpu.training.train import (
    TrainConfig,
    TrainState,
    init_train_state,
    make_train_step,
)

__all__ = [
    "Checkpointer",
    "LoopConfig",
    "LoopMetrics",
    "TrainConfig",
    "TrainState",
    "abstract_state",
    "fit",
    "init_train_state",
    "make_train_step",
]
