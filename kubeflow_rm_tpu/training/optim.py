"""Optimizer construction (optax).

AdamW with a no-decay mask on norms/embeddings, warmup+cosine schedule,
global-norm clipping. ``mu_dtype`` defaults to bf16: on a 16 GiB v5e
chip the first-moment buffer is the difference between fitting a ~1B
model and not; the second moment stays fp32 for stability.

``factored=True`` swaps adam's per-parameter moments for adafactor's
factored second moment (row/col RMS vectors, ~O(in+out) per matrix
instead of O(in*out)) with no first moment — the optimizer that was
built for exactly this hardware constraint (TPU HBM; Shazeer & Stern
2018). Optimizer state drops from ~6 bytes/param to ~0, which is what
lets a ~3B model FULL-fine-tune on one 16 GiB v5e
(params 2B + transient grads 2B ≈ 4 bytes/param); see bench.py
--optim adafactor and BENCH_SWEEP_r05.json's mfu-vs-scale table.

``offload="optimizer"`` is the next rung past that wall (MEMPLAN_r01):
optimizer state lives in HOST memory and the update itself runs on the
host, so the chip holds only params + the grad-accum carry + one
microbatch's workspace. The policy here is the *optimizer half* of the
design: :func:`make_offload_optimizer` decomposes the exact
``make_optimizer`` chain into per-leaf chains (everything after the
global-norm clip is leaf-local; the clip itself needs one scalar — the
global norm — which the train step computes on device and threads
through), so the streamed update is arithmetically identical to the
on-chip one, leaf for leaf. Host placement uses ``pinned_host``
memory-kind staging where the runtime supports it and plain CPU-backend
arrays (which *are* host RAM) everywhere else, so the mechanism is
testable on the CPU CI host.
"""

from dataclasses import dataclass

import jax.numpy as jnp
import optax


@dataclass(frozen=True)
class OptimConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10_000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    mu_dtype: str = "bfloat16"
    # factored second moment (adafactor), no first moment: near-zero
    # optimizer state for the multi-billion-single-chip memory shape
    factored: bool = False
    # dims below this stay unfactored (optax default; tests lower it —
    # every real model dim here is >= 2048)
    factored_min_dim: int = 128
    # "lora": train only adapter leaves (models.lora); the train step
    # then neither computes gradients nor stores moments for the frozen
    # base — the memory shape that fits 7B fine-tuning on one chip
    train_only: str | None = None
    # "optimizer": moments/stats live in host memory and the update is
    # streamed (training.train's offload arm) — the MEMPLAN_r01 recipe
    # that fits 2.7B full-FT on the chip that OOMs at 18.34 GB today
    offload: str = "none"
    # layer-group size for the streamed transfer chunks: stacked
    # (L, ...) leaves move device->host in slices of this many layers,
    # double-buffered, so the on-chip stream slot stays bounded
    offload_chunk_layers: int = 4


def _decay_mask(params):
    import jax

    def mask(path, leaf):
        name = "/".join(p.key for p in path if hasattr(p, "key"))
        return not ("norm" in name or name.startswith("embed"))

    return jax.tree_util.tree_map_with_path(mask, params)


def _make_schedule(cfg: OptimConfig):
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
        end_value=cfg.learning_rate * 0.1,
    )


def _make_scaler(cfg: OptimConfig) -> optax.GradientTransformation:
    if cfg.factored:
        # the full adafactor update rule (optax.adafactor's chain):
        # factored RMS normalization, block-RMS update clipping, and
        # the relative (parameter-scale) step size — without the last
        # two the RMS-normalized update is O(1) per element and walks
        # small-init weights straight out of their basin
        return optax.chain(
            optax.scale_by_factored_rms(
                decay_rate=cfg.b2,
                min_dim_size_to_factor=cfg.factored_min_dim),
            optax.clip_by_block_rms(1.0),
            optax.scale_by_param_block_rms(),
        )
    return optax.scale_by_adam(
        b1=cfg.b1, b2=cfg.b2, mu_dtype=jnp.dtype(cfg.mu_dtype))


def make_optimizer(cfg: OptimConfig) -> optax.GradientTransformation:
    schedule = _make_schedule(cfg)
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        _make_scaler(cfg),
        optax.add_decayed_weights(cfg.weight_decay, mask=_decay_mask),
        optax.scale_by_schedule(lambda step: -schedule(step)),
    )


# ---------------------------------------------------------------------------
# host-offload policy: per-leaf chains + host placement
# ---------------------------------------------------------------------------

_HOST_DEVICE = None


def host_device():
    """The device whose memory is host RAM: the CPU backend's device
    (present alongside TPU/GPU backends, and the only device on the CI
    host). Optimizer state committed here is host-resident on every
    platform."""
    global _HOST_DEVICE
    if _HOST_DEVICE is None:
        import jax
        try:
            _HOST_DEVICE = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            _HOST_DEVICE = jax.devices()[0]
    return _HOST_DEVICE


_PINNED = None  # lazily resolved: SingleDeviceSharding | False


def pinned_host_sharding():
    """A ``pinned_host`` memory-kind sharding for transfer staging, or
    None where the runtime has no such memory space (CPU backends
    expose only ``unpinned_host``; the device_get path below is the
    fallback and the mechanism the CI host tests)."""
    global _PINNED
    if _PINNED is None:
        import jax
        from jax.sharding import SingleDeviceSharding
        try:
            s = SingleDeviceSharding(jax.devices()[0],
                                     memory_kind="pinned_host")
            jax.device_put(jnp.zeros((1,)), s)
            _PINNED = s
        except (ValueError, RuntimeError):
            # backend has no pinned_host memory space (CPU exposes
            # only unpinned_host) — cache the miss, use device_get
            _PINNED = False
    return _PINNED or None


def host_put(x):
    """Commit a concrete array to host memory (CPU backend); abstract
    values (eval_shape tracers) pass through so the offload state
    layout stays shape-traceable for memplan and checkpoint targets."""
    import jax
    if isinstance(x, jax.core.Tracer) or not hasattr(x, "dtype"):
        return x
    return jax.device_put(x, host_device())


def _leaf_name(path) -> str:
    # "." join (orbax-safe): params are nested dicts, so every path
    # entry is a DictKey; indices cover registered-dataclass fields
    return ".".join(str(getattr(p, "key", getattr(p, "idx", "?")))
                    for p in path)


class OffloadOptimizer:
    """The ``make_optimizer`` chain, decomposed for streaming.

    Everything after the global-norm clip is leaf-local (adam moments,
    adafactor's factored stats and its block-RMS clips, the decay mask,
    the schedule), so each param leaf gets its own optax chain over a
    one-entry ``{"leaf": x}`` subtree and its own state, updateable the
    moment that leaf's gradient lands on host. The global-norm clip is
    the one cross-leaf coupling: its only input beyond the leaf is the
    scalar global norm, which the device grad phase computes and the
    train step threads into :meth:`update_leaf` — the arithmetic there
    mirrors ``optax.clip_by_global_norm`` operation for operation, so
    the composition is the on-chip update exactly.
    """

    def __init__(self, cfg: OptimConfig, params):
        import jax
        self.cfg = cfg
        flat, self.treedef = jax.tree_util.tree_flatten_with_path(params)
        self.keys = tuple(_leaf_name(p) for p, _ in flat)
        if len(set(self.keys)) != len(self.keys):
            raise ValueError("param leaf paths do not join uniquely")
        decay = jax.tree_util.tree_leaves(_decay_mask(params))
        schedule = _make_schedule(cfg)
        self._chains = {
            k: optax.chain(
                _make_scaler(cfg),
                optax.add_decayed_weights(cfg.weight_decay,
                                          mask={"leaf": d}),
                optax.scale_by_schedule(
                    lambda step, _s=schedule: -_s(step)),
            )
            for k, d in zip(self.keys, decay)
        }

    def chain(self, key: str) -> optax.GradientTransformation:
        return self._chains[key]

    def init(self, params) -> dict:
        """Host-resident state: ``{leaf_key: per-leaf chain state}`` in
        param flatten order (concrete leaves are committed to host
        memory; abstract ones trace through for eval_shape)."""
        import jax
        leaves = jax.tree_util.tree_leaves(params)
        return {k: self._chains[k].init({"leaf": host_put(p)})
                for k, p in zip(self.keys, leaves)}

    def update_leaf(self, key: str, leaf_state, grad, param, gnorm):
        """One leaf's full update: global-norm clip (mirroring
        ``optax.clip_by_global_norm``'s exact arithmetic against the
        precomputed ``gnorm``), then the leaf's chain, then
        ``apply_updates``. Returns ``(new_param, new_leaf_state)``."""
        import jax
        max_norm = self.cfg.grad_clip
        trigger = jnp.squeeze(gnorm < max_norm)
        clipped = jax.lax.select(
            trigger, grad, (grad / gnorm.astype(grad.dtype)) * max_norm)
        updates, new_state = self._chains[key].update(
            {"leaf": clipped}, leaf_state, {"leaf": param})
        new_param = optax.apply_updates({"leaf": param}, updates)["leaf"]
        return new_param, new_state


def make_offload_optimizer(cfg: OptimConfig, params) -> OffloadOptimizer:
    if cfg.offload != "optimizer":
        raise ValueError(f"offload policy is {cfg.offload!r}, expected "
                         "'optimizer'")
    if cfg.train_only is not None:
        raise ValueError("offload='optimizer' does not compose with "
                         "train_only (LoRA states are small enough to "
                         "stay on-chip)")
    return OffloadOptimizer(cfg, params)
