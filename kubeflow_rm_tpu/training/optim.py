"""Optimizer construction (optax).

AdamW with a no-decay mask on norms/embeddings, warmup+cosine schedule,
global-norm clipping. ``mu_dtype`` defaults to bf16: on a 16 GiB v5e
chip the first-moment buffer is the difference between fitting a ~1B
model and not; the second moment stays fp32 for stability.

``factored=True`` swaps adam's per-parameter moments for adafactor's
factored second moment (row/col RMS vectors, ~O(in+out) per matrix
instead of O(in*out)) with no first moment — the optimizer that was
built for exactly this hardware constraint (TPU HBM; Shazeer & Stern
2018). Optimizer state drops from ~6 bytes/param to ~0, which is what
lets a ~3B model FULL-fine-tune on one 16 GiB v5e
(params 2B + transient grads 2B ≈ 4 bytes/param); see bench.py
--optim adafactor and BENCH_SWEEP_r05.json's mfu-vs-scale table.
"""

from dataclasses import dataclass

import jax.numpy as jnp
import optax


@dataclass(frozen=True)
class OptimConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10_000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    mu_dtype: str = "bfloat16"
    # factored second moment (adafactor), no first moment: near-zero
    # optimizer state for the multi-billion-single-chip memory shape
    factored: bool = False
    # dims below this stay unfactored (optax default; tests lower it —
    # every real model dim here is >= 2048)
    factored_min_dim: int = 128
    # "lora": train only adapter leaves (models.lora); the train step
    # then neither computes gradients nor stores moments for the frozen
    # base — the memory shape that fits 7B fine-tuning on one chip
    train_only: str | None = None


def _decay_mask(params):
    import jax

    def mask(path, leaf):
        name = "/".join(p.key for p in path if hasattr(p, "key"))
        return not ("norm" in name or name.startswith("embed"))

    return jax.tree_util.tree_map_with_path(mask, params)


def make_optimizer(cfg: OptimConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
        end_value=cfg.learning_rate * 0.1,
    )
    if cfg.factored:
        # the full adafactor update rule (optax.adafactor's chain):
        # factored RMS normalization, block-RMS update clipping, and
        # the relative (parameter-scale) step size — without the last
        # two the RMS-normalized update is O(1) per element and walks
        # small-init weights straight out of their basin
        scaler = optax.chain(
            optax.scale_by_factored_rms(
                decay_rate=cfg.b2,
                min_dim_size_to_factor=cfg.factored_min_dim),
            optax.clip_by_block_rms(1.0),
            optax.scale_by_param_block_rms(),
        )
    else:
        scaler = optax.scale_by_adam(
            b1=cfg.b1, b2=cfg.b2, mu_dtype=jnp.dtype(cfg.mu_dtype))
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        scaler,
        optax.add_decayed_weights(cfg.weight_decay, mask=_decay_mask),
        optax.scale_by_schedule(lambda step: -schedule(step)),
    )
