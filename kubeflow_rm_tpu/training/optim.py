"""Optimizer construction (optax).

AdamW with a no-decay mask on norms/embeddings, warmup+cosine schedule,
global-norm clipping. ``mu_dtype`` defaults to bf16: on a 16 GiB v5e
chip the first-moment buffer is the difference between fitting a ~1B
model and not; the second moment stays fp32 for stability.
"""

from dataclasses import dataclass

import jax.numpy as jnp
import optax


@dataclass(frozen=True)
class OptimConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10_000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    mu_dtype: str = "bfloat16"
    # "lora": train only adapter leaves (models.lora); the train step
    # then neither computes gradients nor stores moments for the frozen
    # base — the memory shape that fits 7B fine-tuning on one chip
    train_only: str | None = None


def _decay_mask(params):
    import jax

    def mask(path, leaf):
        name = "/".join(p.key for p in path if hasattr(p, "key"))
        return not ("norm" in name or name.startswith("embed"))

    return jax.tree_util.tree_map_with_path(mask, params)


def make_optimizer(cfg: OptimConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
        end_value=cfg.learning_rate * 0.1,
    )
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.scale_by_adam(
            b1=cfg.b1, b2=cfg.b2, mu_dtype=jnp.dtype(cfg.mu_dtype)
        ),
        optax.add_decayed_weights(cfg.weight_decay, mask=_decay_mask),
        optax.scale_by_schedule(lambda step: -schedule(step)),
    )
