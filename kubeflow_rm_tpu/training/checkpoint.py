"""Sharding-aware checkpoint save/restore (orbax).

The platform half of the checkpoint story is the PVC-backed ``$HOME``
workspace the notebook controller mounts (reference:
``crud-web-apps/jupyter/backend/apps/default/routes/post.py:42-70``) and
GCS paths for tensorboard logs (``tensorboard_controller.go:234-249``).
This module is the in-image half the reference never had: orbax
checkpoints of the ``TrainState``, written asynchronously so the TPU
keeps stepping, restored **directly into the training shardings** — each
host reads only its shards, which is what makes restore scale on a
multi-host slice instead of replaying a full copy through host 0.

Directory convention: ``{workspace}/checkpoints/{step}/`` — a PVC path
inside a notebook, a ``gs://`` bucket on GKE with workload identity.
"""

from typing import Any

import jax


def _ocp():
    # lazy: bench.py and the train step must not require orbax — an
    # image without it still benchmarks, it just can't checkpoint
    import orbax.checkpoint as ocp
    return ocp


def abstract_state(cfg, mesh) -> Any:
    """TrainState of ShapeDtypeStructs carrying NamedShardings — the
    restore target layout, computed without allocating anything.

    Under ``cfg.optim.offload == "optimizer"`` the optimizer sub-tree
    is host-resident (``{leaf_key: per-leaf chain state}`` committed to
    the CPU backend), so its restore target carries a host
    SingleDeviceSharding instead of a mesh sharding: a resumed 2.7B
    run never stages adam moments through HBM, and resume stays
    bit-exact because the restored leaves land exactly where the
    streamed step keeps them."""
    # lazy: this module must import on a plain CPU control-plane host
    # (the suspend state store uses latest_step/save/restore on dict
    # pytrees); only model-state restores pull in the train stack
    from kubeflow_rm_tpu.training.train import (
        init_train_state, state_shardings,
    )
    shapes = jax.eval_shape(
        lambda: init_train_state(cfg, jax.random.key(0)))
    shardings = state_shardings(cfg, shapes, mesh)
    if getattr(cfg.optim, "offload", "none") == "optimizer":
        from jax.sharding import SingleDeviceSharding

        from kubeflow_rm_tpu.training.optim import host_device
        host = SingleDeviceSharding(host_device())
        shardings.opt_state = jax.tree.map(lambda _: host,
                                           shardings.opt_state)
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)


class Checkpointer:
    """Async train-state checkpointing with retention.

    ``save`` returns immediately (orbax finalizes in the background);
    ``restore`` blocks and returns state laid out on the mesh.
    """

    def __init__(self, directory, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        import os
        ocp = _ocp()
        self._mngr = ocp.CheckpointManager(
            os.path.abspath(os.fspath(directory))
            if "://" not in str(directory) else str(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
            ),
        )

    @property
    def directory(self):
        return self._mngr.directory

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def save(self, state, *, force: bool = False) -> bool:
        # state is a TrainState or any pytree with a "step" leaf — the
        # suspend state store checkpoints plain dicts
        raw = state["step"] if isinstance(state, dict) else state.step
        step = int(jax.device_get(raw))
        if step in self._mngr.all_steps():
            return False
        # chaos-engine checkpoint-write fault, sys.modules-guarded so
        # the training layer never pulls in the control plane itself —
        # the hook only exists once a control-plane process imported it
        import sys
        _chaos = sys.modules.get("kubeflow_rm_tpu.controlplane.chaos")
        if _chaos is not None:
            _chaos.checkpoint_write_fault(f"checkpointer:{step}")
        return self._mngr.save(step, args=_ocp().args.StandardSave(state),
                               force=force)

    def restore(self, cfg=None, mesh=None,
                step: int | None = None) -> Any | None:
        """Restore the latest (or given) step, or None when the
        directory holds no checkpoint yet. With ``cfg``/``mesh`` the
        target is the TrainState layout on that mesh (each host reads
        its shards); without them orbax restores the saved tree as-is
        (the dict-pytree path the suspend state store uses)."""
        if step is None:
            step = self._mngr.latest_step()
        if step is None:
            return None
        if cfg is None:
            return self._mngr.restore(
                step, args=_ocp().args.StandardRestore())
        target = abstract_state(cfg, mesh)
        return self._mngr.restore(
            step, args=_ocp().args.StandardRestore(target))

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
