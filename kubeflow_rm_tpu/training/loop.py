"""The training loop — ``fit()``.

Round 1 left a step function with no loop, no checkpointing, no metrics
and no MFU accounting (VERDICT "weak" #6); this module is the rest of
the trainer. Design points, TPU-first:

- **Async dispatch.** The loop never blocks on a step's metrics except
  at log boundaries: jax dispatches step N+1 while N runs, so host
  Python (data loading, logging) overlaps device compute. Blocking
  every step would serialize host and TPU and cap MFU far below the
  hardware ceiling.
- **MFU is computed in-loop** from ``utils.flops`` (6N + attention
  convention) against the mesh's device count — the number ``bench.py``
  reports is the same number the loop logs, so a notebook user watches
  the north-star metric live.
- **Checkpoint/resume** via ``training.checkpoint`` (orbax, async):
  ``fit`` restores the latest step if the directory has one, saves
  every ``checkpoint_every`` steps and at the end, and the step counter
  carried in ``TrainState`` makes resume exact.
"""

import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable

import jax

from kubeflow_rm_tpu.analysis.jaxcheck import hostsync as _hostsync
from kubeflow_rm_tpu.training.checkpoint import Checkpointer
from kubeflow_rm_tpu.training.train import (
    TrainConfig, TrainState, init_train_state, make_train_step, shard_batch,
)
from kubeflow_rm_tpu.utils.flops import device_peak_flops, train_flops_per_token

log = logging.getLogger("kubeflow_rm_tpu.train")


@dataclass(frozen=True)
class LoopConfig:
    total_steps: int = 100
    log_every: int = 10
    checkpoint_every: int = 0          # 0 = only final
    checkpoint_dir: str | None = None  # None = no checkpointing
    max_to_keep: int = 3
    seed: int = 0
    grad_accum: int = 1                # microbatches per optimizer step
    # "none" | "optimizer" | None (follow cfg.optim.offload): the
    # streamed host-offload arm of make_train_step — optimizer state in
    # host RAM, per-leaf updates on host, layer-group chunk transfers
    # double-buffered against them (the MEMPLAN_r01 2.7B recipe)
    offload: str | None = None


@dataclass
class LoopMetrics:
    """One log-interval record, also handed to callbacks."""
    step: int
    loss: float
    grad_norm: float
    tokens_per_sec: float
    mfu_pct: float
    step_time_ms: float
    # offload arm only (0.0 on the on-chip arm): ms the stream spent
    # blocked on device->host transfers, and the fraction of the
    # streaming phase NOT spent blocked — i.e. how much of the
    # transfer cost the double-buffering hid behind update compute
    offload_transfer_ms: float = 0.0
    offload_overlap_frac: float = 0.0


def fit(
    cfg: TrainConfig,
    mesh,
    data: Iterable[dict],
    loop: LoopConfig = LoopConfig(),
    *,
    state: TrainState | None = None,
    batch_keys: tuple | None = None,
    callbacks: tuple[Callable[[LoopMetrics], Any], ...] = (),
) -> tuple[TrainState, list[LoopMetrics]]:
    """Train for ``loop.total_steps`` total steps (counting restored
    progress), returning the final state and per-interval metrics.

    ``data`` yields host batches of ``{"tokens", "labels", ...}``;
    ``batch_keys`` defaults to the first batch's keys.

    On resume the iterator is fast-forwarded past the batches the
    restored steps already consumed, so a deterministic ``data`` stream
    replays exactly the sequence an uninterrupted run would have seen
    (non-deterministic streams get fresh batches — no worse than the
    reference's stop/start semantics).
    """
    if loop.log_every < 1:
        raise ValueError(f"log_every must be >= 1, got {loop.log_every}")
    ckpt = (Checkpointer(loop.checkpoint_dir, max_to_keep=loop.max_to_keep)
            if loop.checkpoint_dir else None)

    resumed = False
    if state is None:
        state = ckpt.restore(cfg, mesh) if ckpt else None
        if state is not None:
            resumed = True
            log.info("resumed from step %d", int(state.step))
        else:
            state = init_train_state(cfg, jax.random.key(loop.seed))

    data = iter(data)
    if resumed:
        skip = min(int(jax.device_get(state.step)), loop.total_steps)
        for _ in range(skip):
            try:
                next(data)
            except StopIteration:
                break
    try:
        first = next(data)
    except StopIteration:
        # stream exhausted by the fast-forward (e.g. fit() re-invoked
        # after a completed run on an epoch-sized stream): nothing left
        # to train on — return the restored state instead of crashing
        log.warning("data exhausted before step %d; nothing to do",
                    int(jax.device_get(state.step)))
        if ckpt:
            ckpt.close()
        return state, []
    if batch_keys is None:
        batch_keys = tuple(first.keys())
    step_fn = make_train_step(cfg, mesh, state, batch_keys=batch_keys,
                              grad_accum=loop.grad_accum,
                              offload=loop.offload)

    n_dev = mesh.devices.size
    peak = device_peak_flops(jax.tree_util.tree_leaves(mesh.devices)[0])

    history: list[LoopMetrics] = []
    start = int(jax.device_get(state.step))
    total = loop.total_steps
    t0 = time.perf_counter()
    interval_start = start
    batch = first
    try:
        for i in range(start, total):
            dev_batch = shard_batch({k: batch[k] for k in batch_keys}, mesh)
            # hot region: dispatch must stay async — the deliberate
            # metric syncs below run OUTSIDE it (KFRM_HOSTSYNC_PROBE
            # records any implicit sync in here as a witness)
            with _hostsync.region("train.step"):
                state, metrics = step_fn(state, dev_batch)

            now = i + 1
            if now == start + 1:
                # sync once after the first step so jit trace+compile
                # never pollutes the interval throughput/MFU numbers
                jax.device_get(metrics["loss"])
                t0 = time.perf_counter()
                interval_start = now
            if now < total:
                try:
                    batch = next(data)
                except StopIteration:
                    log.warning("data exhausted at step %d (< total_steps "
                                "%d); stopping", now, total)
                    total = now
            if now % loop.log_every == 0 or now == total:
                m = jax.device_get(metrics)  # blocks: one sync per interval
                dt = time.perf_counter() - t0
                steps_done = now - interval_start
                tokens = steps_done * dev_batch["tokens"].size
                tps = tokens / dt if dt > 0 else 0.0
                flops = tps * train_flops_per_token(
                    cfg.model, dev_batch["tokens"].shape[-1],
                    frozen_base=cfg.optim.train_only is not None)
                rec = LoopMetrics(
                    step=now,
                    loss=float(m["loss"]),
                    grad_norm=float(m["grad_norm"]),
                    tokens_per_sec=tps,
                    mfu_pct=100.0 * flops / (n_dev * peak) if peak else 0.0,
                    step_time_ms=1e3 * dt / max(steps_done, 1),
                    offload_transfer_ms=float(
                        m.get("offload_transfer_ms", 0.0)),
                    offload_overlap_frac=float(
                        m.get("offload_overlap_frac", 0.0)),
                )
                history.append(rec)
                log.info("step %d loss %.4f %.0f tok/s mfu %.1f%%",
                         rec.step, rec.loss, rec.tokens_per_sec, rec.mfu_pct)
                for cb in callbacks:
                    cb(rec)
                t0 = time.perf_counter()
                interval_start = now
            if (ckpt and loop.checkpoint_every
                    and now % loop.checkpoint_every == 0):
                ckpt.save(state)
            if now >= total:
                break
    finally:
        if ckpt:
            ckpt.save(state, force=True)
            ckpt.close()
    return state, history
