"""Data pipeline.

For benchmarking and smoke tests: a deterministic synthetic LM stream.
For real fine-tuning inside the notebook image: a packed-sequence
iterator over tokenized documents (next-token labels, prompt masking via
IGNORE_INDEX), which is all the input machinery a Llama SFT run needs.
"""

import numpy as np

from kubeflow_rm_tpu.ops.losses import IGNORE_INDEX


def synthetic_batches(batch_size: int, seq_len: int, vocab_size: int,
                      seed: int = 0):
    """Infinite iterator of {"tokens", "labels"} int32 batches."""
    rng = np.random.default_rng(seed)
    while True:
        tok = rng.integers(0, vocab_size, (batch_size, seq_len), dtype=np.int32)
        labels = np.roll(tok, -1, axis=1)
        labels[:, -1] = IGNORE_INDEX
        yield {"tokens": tok, "labels": labels.astype(np.int32)}


def pack_documents(docs: list[list[int]], seq_len: int,
                   pad_id: int = 0) -> dict:
    """Pack token lists into fixed-length rows with positions + segments.

    Documents are concatenated greedily; each row carries ``positions``
    restarting at 0 per document (correct RoPE) and ``segments`` — a
    per-row document id starting at 1, with padding as segment 0 — which
    the segment-aware mask in ``ops.attention`` ANDs into the causal mask
    so packed documents are fully independent and pad tokens are never
    attended. Positions alone are NOT sufficient: a later document's
    positions restart at 0, which a position-only causal mask would read
    as "in the past" of every other document.
    """
    rows, row, pos_rows, pos = [], [], [], []
    label_rows, labels = [], []
    seg_rows, segs = [], []
    next_seg = 1
    for doc in docs:
        i = 0
        while i < len(doc):
            space = seq_len - len(row)
            take = doc[i:i + space]
            row.extend(take)
            pos.extend(range(i, i + len(take)))
            segs.extend([next_seg] * len(take))
            labels.extend(doc[i + 1:i + len(take) + 1])
            if len(labels) < len(row):
                labels.append(IGNORE_INDEX)
            i += len(take)
            if len(row) == seq_len:
                rows.append(row); pos_rows.append(pos)
                label_rows.append(labels); seg_rows.append(segs)
                row, pos, labels, segs = [], [], [], []
        next_seg += 1
    if row:
        n = seq_len - len(row)
        rows.append(row + [pad_id] * n)
        pos_rows.append(pos + list(range(n)))
        label_rows.append(labels + [IGNORE_INDEX] * n)
        seg_rows.append(segs + [0] * n)  # pad = segment 0, attends nothing real
    return {
        "tokens": np.asarray(rows, np.int32).reshape(-1, seq_len),
        "labels": np.asarray(label_rows, np.int32).reshape(-1, seq_len),
        "positions": np.asarray(pos_rows, np.int32).reshape(-1, seq_len),
        "segments": np.asarray(seg_rows, np.int32).reshape(-1, seq_len),
    }
