"""Data pipeline.

For benchmarking and smoke tests: a deterministic synthetic LM stream.
For real fine-tuning inside the notebook image: a packed-sequence
iterator over tokenized documents (next-token labels, prompt masking via
IGNORE_INDEX), which is all the input machinery a Llama SFT run needs.
"""

import numpy as np

from kubeflow_rm_tpu.ops.losses import IGNORE_INDEX


def synthetic_batches(batch_size: int, seq_len: int, vocab_size: int,
                      seed: int = 0):
    """Infinite iterator of {"tokens", "labels"} int32 batches."""
    rng = np.random.default_rng(seed)
    while True:
        tok = rng.integers(0, vocab_size, (batch_size, seq_len), dtype=np.int32)
        labels = np.roll(tok, -1, axis=1)
        labels[:, -1] = IGNORE_INDEX
        yield {"tokens": tok, "labels": labels.astype(np.int32)}


def pack_documents(docs: list[list[int]], seq_len: int,
                   pad_id: int = 0) -> dict:
    """Pack token lists into fixed-length rows with per-row positions.

    Documents are concatenated greedily; each row carries ``positions``
    restarting at 0 per document so RoPE and the positions-aware causal
    mask in ``ops.attention`` keep packed documents independent.
    """
    rows, row, pos_rows, pos = [], [], [], []
    label_rows, labels = [], []
    for doc in docs:
        i = 0
        while i < len(doc):
            space = seq_len - len(row)
            take = doc[i:i + space]
            row.extend(take)
            pos.extend(range(i, i + len(take)))
            labels.extend(doc[i + 1:i + len(take) + 1])
            if len(labels) < len(row):
                labels.append(IGNORE_INDEX)
            i += len(take)
            if len(row) == seq_len:
                rows.append(row); pos_rows.append(pos); label_rows.append(labels)
                row, pos, labels = [], [], []
    if row:
        n = seq_len - len(row)
        rows.append(row + [pad_id] * n)
        pos_rows.append(pos + list(range(n)))
        label_rows.append(labels + [IGNORE_INDEX] * n)
    return {
        "tokens": np.asarray(rows, np.int32).reshape(-1, seq_len),
        "labels": np.asarray(label_rows, np.int32).reshape(-1, seq_len),
        "positions": np.asarray(pos_rows, np.int32).reshape(-1, seq_len),
    }
