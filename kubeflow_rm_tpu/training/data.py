"""Data pipeline.

For benchmarking and smoke tests: a deterministic synthetic LM stream.
For real fine-tuning inside the notebook image: a packed-sequence
iterator over tokenized documents (next-token labels, prompt masking via
IGNORE_INDEX), which is all the input machinery a Llama SFT run needs.
"""

import numpy as np

from kubeflow_rm_tpu.ops.losses import IGNORE_INDEX


def synthetic_batches(batch_size: int, seq_len: int, vocab_size: int,
                      seed: int = 0):
    """Infinite iterator of {"tokens", "labels"} int32 batches."""
    rng = np.random.default_rng(seed)
    while True:
        tok = rng.integers(0, vocab_size, (batch_size, seq_len), dtype=np.int32)
        labels = np.roll(tok, -1, axis=1)
        labels[:, -1] = IGNORE_INDEX
        yield {"tokens": tok, "labels": labels.astype(np.int32)}


def pack_documents(docs: list[list[int]], seq_len: int,
                   pad_id: int = 0) -> dict:
    """Pack token lists into fixed-length rows with positions + segments.

    Documents are concatenated greedily; each row carries ``positions``
    restarting at 0 per document (correct RoPE) and ``segments`` — a
    per-row document id starting at 1, with padding as segment 0 — which
    the segment-aware mask in ``ops.attention`` ANDs into the causal mask
    so packed documents are fully independent and pad tokens are never
    attended. Positions alone are NOT sufficient: a later document's
    positions restart at 0, which a position-only causal mask would read
    as "in the past" of every other document.
    """
    rows, row, pos_rows, pos = [], [], [], []
    label_rows, labels = [], []
    seg_rows, segs = [], []
    next_seg = 1
    for doc in docs:
        i = 0
        while i < len(doc):
            space = seq_len - len(row)
            take = doc[i:i + space]
            row.extend(take)
            pos.extend(range(i, i + len(take)))
            segs.extend([next_seg] * len(take))
            labels.extend(doc[i + 1:i + len(take) + 1])
            if len(labels) < len(row):
                labels.append(IGNORE_INDEX)
            i += len(take)
            if len(row) == seq_len:
                rows.append(row); pos_rows.append(pos)
                label_rows.append(labels); seg_rows.append(segs)
                row, pos, labels, segs = [], [], [], []
        next_seg += 1
    if row:
        n = seq_len - len(row)
        rows.append(row + [pad_id] * n)
        pos_rows.append(pos + list(range(n)))
        label_rows.append(labels + [IGNORE_INDEX] * n)
        seg_rows.append(segs + [0] * n)  # pad = segment 0, attends nothing real
    return {
        "tokens": np.asarray(rows, np.int32).reshape(-1, seq_len),
        "labels": np.asarray(label_rows, np.int32).reshape(-1, seq_len),
        "positions": np.asarray(pos_rows, np.int32).reshape(-1, seq_len),
        "segments": np.asarray(seg_rows, np.int32).reshape(-1, seq_len),
    }


def jsonl_documents(paths, *, process_id: int = 0, num_processes: int = 1,
                    field: str = "tokens", tokenize=None,
                    seed: int | None = None, epoch: int = 0):
    """Yield token lists from jsonl shards, multi-host disjoint.

    The file-backed input path for real fine-tunes: every process reads
    the SAME globally-shuffled order (seeded per epoch, so shuffling is
    reproducible and advances between epochs) and keeps rows where
    ``row_index % num_processes == process_id`` — disjoint and jointly
    exhaustive without any coordination traffic, the property multi-host
    input needs (each host feeds its own slice of the dp×fsdp batch;
    defaults come straight from ``parallel.distributed.tpu_env``).

    Records carry either pre-tokenized ``field`` (a token list) or raw
    text that ``tokenize`` maps to one.
    """
    import json as _json

    paths = sorted(str(p) for p in paths)
    index = []  # (path_i, byte offset) per record
    for pi, path in enumerate(paths):
        off = 0
        with open(path, "rb") as f:
            for line in f:
                if line.strip():
                    index.append((pi, off))
                off += len(line)
    order = np.arange(len(index))
    if seed is not None:
        np.random.default_rng(seed + epoch).shuffle(order)

    handles = [open(p, "rb") for p in paths]
    try:
        for j in order[process_id::num_processes]:
            pi, off = index[j]
            handles[pi].seek(off)
            rec = _json.loads(handles[pi].readline())
            if field in rec:
                yield list(rec[field])
            elif tokenize is not None:
                yield list(tokenize(rec["text"]))
            else:
                raise KeyError(
                    f"record has no {field!r} and no tokenizer given "
                    f"(keys: {sorted(rec)})")
    finally:
        for h in handles:
            h.close()


def packed_batches(docs, batch_size: int, seq_len: int, *,
                   pad_id: int = 0, drop_remainder: bool = True):
    """Stream ``pack_documents`` rows in fixed-size batches, O(batch)
    memory for arbitrarily large corpora.

    Row-for-row identical to a one-shot ``pack_documents`` over the
    same document stream (asserted by tests/test_data.py): the partial
    row in flight carries ACROSS batch boundaries instead of being
    padded at each flush, so streaming inserts no extra padding.
    """
    keys = ("tokens", "labels", "positions", "segments")
    ready = {k: [] for k in keys}
    row, pos, labels, segs = [], [], [], []
    next_seg = 1

    def flush_row():
        nonlocal row, pos, labels, segs
        ready["tokens"].append(row)
        ready["labels"].append(labels)
        ready["positions"].append(pos)
        ready["segments"].append(segs)
        row, pos, labels, segs = [], [], [], []

    def take_batch():
        batch = {k: np.asarray(ready[k][:batch_size], np.int32)
                 for k in keys}
        for k in keys:
            del ready[k][:batch_size]
        return batch

    for doc in docs:
        i = 0
        while i < len(doc):
            space = seq_len - len(row)
            take = doc[i:i + space]
            row.extend(take)
            pos.extend(range(i, i + len(take)))
            segs.extend([next_seg] * len(take))
            labels.extend(doc[i + 1:i + len(take) + 1])
            if len(labels) < len(row):
                labels.append(IGNORE_INDEX)
            i += len(take)
            if len(row) == seq_len:
                flush_row()
                if len(ready["tokens"]) == batch_size:
                    yield take_batch()
        next_seg += 1
    if row:
        n = seq_len - len(row)
        row += [pad_id] * n
        pos += list(range(n))
        labels += [IGNORE_INDEX] * n
        segs += [0] * n  # pad = segment 0, attends nothing real
        flush_row()
    if not drop_remainder and ready["tokens"]:
        yield {k: np.asarray(ready[k], np.int32) for k in keys}


def device_prefetch(batches, mesh, depth: int = 2):
    """Overlap host→device transfer with compute: keep ``depth`` batches
    already device_put on ``mesh`` (the standard double-buffering that
    hides PCIe/tunnel latency behind the train step)."""
    from collections import deque

    from kubeflow_rm_tpu.training.train import shard_batch

    queue = deque()
    for batch in batches:
        queue.append(shard_batch(batch, mesh))
        if len(queue) >= depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
