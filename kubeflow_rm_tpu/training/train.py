"""Sharded training step.

``make_train_step`` builds a single jitted SPMD step: params and
optimizer state carry NamedShardings from ``parallel.sharding``, the
batch arrives sharded over (dp, fsdp) x sp, and XLA's partitioner
inserts the FSDP all-gathers, TP psums and gradient reduce-scatters.
Buffers are donated so the step runs in-place in HBM.

There is no hand-rolled gradient-sync code anywhere — on TPU the
collective schedule is the compiler's job (scaling-book recipe); the
framework's job is the shardings.
"""

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_rm_tpu.analysis.jaxcheck import hostsync as _hostsync
from kubeflow_rm_tpu.models import (
    LlamaConfig,
    forward_with_aux,
    init_params,
)
from kubeflow_rm_tpu.ops.losses import softmax_cross_entropy
from kubeflow_rm_tpu.parallel.sharding import batch_pspec, param_shardings
from kubeflow_rm_tpu.training.optim import (
    OptimConfig,
    host_device,
    host_put,
    make_offload_optimizer,
    make_optimizer,
)


@dataclass(frozen=True)
class TrainConfig:
    model: LlamaConfig = field(default_factory=LlamaConfig.tiny)
    optim: OptimConfig = field(default_factory=OptimConfig)
    z_loss: float = 1e-4


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


class _Partition:
    """Split a param tree into trainable/frozen leaf lists by a mask
    (``optim.train_only``): the train step differentiates ONLY the
    trainable list, so frozen weights get neither gradient buffers nor
    optimizer moments — the memory shape LoRA fine-tuning needs."""

    def __init__(self, params, mask_tree):
        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.mask = jax.tree_util.tree_leaves(mask_tree)
        assert len(self.mask) == len(leaves)
        if not any(self.mask):
            raise ValueError("train_only matched no parameters")

    def split(self, params):
        leaves = jax.tree_util.tree_leaves(params)
        train = [p for p, m in zip(leaves, self.mask) if m]
        frozen = [p for p, m in zip(leaves, self.mask) if not m]
        return train, frozen

    def combine(self, train, frozen):
        it_t, it_f = iter(train), iter(frozen)
        leaves = [next(it_t) if m else next(it_f) for m in self.mask]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def _partition_for(cfg: TrainConfig, params) -> _Partition | None:
    if cfg.optim.train_only is None:
        return None
    if cfg.optim.train_only != "lora":
        raise ValueError(
            f"unknown train_only={cfg.optim.train_only!r} (only 'lora')")
    from kubeflow_rm_tpu.models.lora import lora_mask
    return _Partition(params, lora_mask(params))


def init_train_state(cfg: TrainConfig, key: jax.Array,
                     params=None) -> TrainState:
    """Fresh state; pass ``params`` to seed from existing weights (an
    HF conversion, or ``models.lora.add_lora`` output for adapter
    training)."""
    if params is None:
        params = init_params(cfg.model, key)
    part = _partition_for(cfg, params)
    if cfg.optim.offload == "optimizer":
        # host-resident layout: {leaf_key: per-leaf chain state}, built
        # leaf-by-leaf on the host device so a 2.7B adam init never
        # materializes mu/nu in HBM (make_offload_optimizer rejects
        # the train_only combination)
        opt_state = make_offload_optimizer(cfg.optim, params).init(params)
    else:
        opt = make_optimizer(cfg.optim)
        if part is None:
            opt_state = opt.init(params)
        else:
            opt_state = opt.init(part.split(params)[0])
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt_state)


def state_shardings(cfg: TrainConfig, state: TrainState, mesh: Mesh) -> TrainState:
    """NamedSharding pytree for a TrainState: any optimizer sub-tree with
    the params' structure (adam moments, decayed-weights masks) inherits
    the param shardings; scalars (step counts) are replicated."""
    pshard = param_shardings(state.params, mesh)
    replicated = NamedSharding(mesh, P())
    param_treedef = jax.tree_util.tree_structure(state.params)
    param_leaves = jax.tree_util.tree_leaves(state.params)

    def map_node(node):
        try:
            if jax.tree_util.tree_structure(node) == param_treedef:
                # params-shaped state (adam moments) inherits the param
                # shardings leaf-for-leaf — but only where shapes match:
                # adafactor's factored stats share the STRUCTURE while
                # holding row/col vectors, which must stay replicated
                node_leaves = jax.tree_util.tree_leaves(node)
                shard_leaves = [
                    s if getattr(n, "shape", None) == p.shape else replicated
                    for n, p, s in zip(node_leaves, param_leaves,
                                       jax.tree_util.tree_leaves(pshard))
                ]
                return jax.tree_util.tree_unflatten(param_treedef,
                                                    shard_leaves)
        except Exception:
            # fall through to the structural recursion below — but
            # leave a trace, since a silently-unsharded optimizer
            # state is exactly the kind of fault that only shows up
            # as an OOM three steps later
            logging.getLogger("kubeflow_rm_tpu.training").debug(
                "state sharding fast path failed; recursing node "
                "structurally", exc_info=True)
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(map_node(c) for c in node))
        if isinstance(node, (list, tuple)):
            return type(node)(map_node(c) for c in node)
        if isinstance(node, dict):
            return {k: map_node(v) for k, v in node.items()}
        return replicated

    return TrainState(
        step=replicated,
        params=pshard,
        opt_state=map_node(state.opt_state),
    )


def loss_fn(params, batch, cfg: TrainConfig,
            mesh: Mesh | None = None, n_microbatches: int | None = None):
    # batches come from training.data (pack_documents layout: per-doc
    # restarting positions), so the packed fast path is sound here
    kwargs = dict(positions=batch.get("positions"),
                  segments=batch.get("segments"),
                  packed=batch.get("segments") is not None)
    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        from kubeflow_rm_tpu.parallel.pipeline import (
            pipeline_forward_with_aux,
        )
        logits, router_aux = pipeline_forward_with_aux(
            params, batch["tokens"], cfg.model, mesh,
            n_microbatches=n_microbatches, **kwargs)
    else:
        logits, router_aux = forward_with_aux(params, batch["tokens"],
                                              cfg.model, mesh=mesh,
                                              **kwargs)
    loss, aux = softmax_cross_entropy(logits, batch["labels"],
                                      z_loss=cfg.z_loss)
    if router_aux is not None:
        aux = dict(aux, router_aux=router_aux)
        loss = loss + cfg.model.moe.router_aux_weight * router_aux
    return loss, aux


def make_train_step(cfg: TrainConfig, mesh: Mesh, state: TrainState,
                    batch_keys: tuple = ("tokens", "labels"),
                    n_microbatches: int | None = None,
                    grad_accum: int = 1,
                    offload: str | None = None) -> Callable:
    """Return jitted ``step(state, batch) -> (state, metrics)``.

    ``batch`` maps each of ``batch_keys`` to a (B, T) int32 array laid
    out with ``batch_pspec`` on ``mesh`` — "tokens" and "labels" always,
    plus "positions" and "segments" when training on packed documents
    (see ``training.data.pack_documents``).

    On a mesh with pp > 1 the forward runs the GPipe schedule
    (``parallel.pipeline``); ``n_microbatches`` (default: pp) sets the
    bubble fraction (pp-1)/(n_microbatches+pp-1).

    ``grad_accum`` > 1 splits the global batch into that many
    sequential microbatches under ``lax.scan``, accumulating gradients
    before ONE optimizer update. Two reasons to use it: effective batch
    beyond what HBM fits, and amortizing the optimizer update — on a
    ~1B-param single chip the adam step is pure HBM traffic worth a
    double-digit share of step time, and accumulation divides it by K.
    The per-step loss/grads equal the full-batch computation up to
    accumulation-order rounding (asserted by tests/test_train.py).

    ``offload="optimizer"`` (default: ``cfg.optim.offload``) returns
    the streamed host-offload arm instead: the device runs ONLY the
    grad-accum phase, then gradients stream host-ward in layer-group
    chunks double-buffered against the per-leaf optimizer update on
    the host, and updated params stream back (see
    ``_build_offload_step``). Loss/params match the on-chip arm
    bit-for-bit on one backend (tests/test_offload.py).
    """
    if offload is None:
        offload = cfg.optim.offload
    if offload not in ("none", "optimizer"):
        raise ValueError(f"unknown offload={offload!r} "
                         "(expected 'none' or 'optimizer')")
    if mesh.shape.get("pp", 1) > 1 and n_microbatches is None:
        n_microbatches = mesh.shape["pp"]
    sshard = state_shardings(cfg, state, mesh)
    bshard = {k: NamedSharding(mesh, batch_pspec()) for k in batch_keys}
    mshard = NamedSharding(mesh, P())
    part = _partition_for(cfg, state.params)

    if part is None:
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    else:
        # differentiate ONLY the trainable leaves: the backward never
        # materializes base-weight gradients (dW = h^T g outer products
        # are the dominant bwd memory/flops for a frozen 7B)
        def _loss_trainable(train, frozen, batch, cfg, mesh, n_mb):
            return loss_fn(part.combine(train, frozen), batch, cfg,
                           mesh, n_mb)

        _grad_trainable = jax.value_and_grad(_loss_trainable,
                                             has_aux=True)

        def grad_fn(params, batch, cfg, mesh, n_mb):
            train, frozen = part.split(params)
            return _grad_trainable(train, frozen, batch, cfg, mesh, n_mb)

    def fold(a):
        # interleaved: microbatch m takes rows m, K+m, ... so the fold
        # keeps K replicated and the microbatch dim on the batch
        # sharding with zero resharding traffic (same reasoning as
        # parallel.pipeline's fold)
        if a.shape[0] % grad_accum:
            raise ValueError(
                f"batch {a.shape[0]} not divisible by "
                f"grad_accum={grad_accum}")
        mb = a.shape[0] // grad_accum
        a = a.reshape(mb, grad_accum, *a.shape[1:]).swapaxes(0, 1)
        spec = P(None, *batch_pspec())
        return jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, spec))

    def accumulate(params, batch):
        folded = {k: fold(v) for k, v in batch.items()}

        def body(acc, mbatch):
            (loss, aux), g = grad_fn(params, mbatch, cfg, mesh,
                                     n_microbatches)
            return jax.tree_util.tree_map(jnp.add, acc, g), (loss, aux)

        grad_target = params if part is None else part.split(params)[0]
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), grad_target)
        summed, (losses, auxes) = jax.lax.scan(body, zeros, folded)
        grads = jax.tree_util.tree_map(lambda g: g / grad_accum, summed)
        loss = jnp.mean(losses)
        aux = jax.tree_util.tree_map(lambda a: jnp.mean(a, axis=0), auxes)
        return (loss, aux), grads

    def compute_grads(params, batch):
        if grad_accum > 1:
            return accumulate(params, batch)
        return grad_fn(params, batch, cfg, mesh, n_microbatches)

    if offload == "optimizer":
        return _build_offload_step(cfg, mesh, state, part, compute_grads,
                                   bshard, mshard)

    opt = make_optimizer(cfg.optim)

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        (loss, aux), grads = compute_grads(state.params, batch)
        if part is None:
            target, frozen = state.params, None
        else:
            target, frozen = part.split(state.params)
        updates, opt_state = opt.update(grads, state.opt_state, target)
        target = optax.apply_updates(target, updates)
        params = target if part is None else part.combine(target, frozen)
        gnorm = optax.global_norm(grads)
        metrics = {"loss": loss, "grad_norm": gnorm, **aux}
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state), metrics

    return jax.jit(
        step,
        in_shardings=(sshard, bshard),
        out_shardings=(sshard, mshard),
        donate_argnums=(0,),
    )


#: transfer chunks dispatched beyond the one being consumed — the
#: double-buffer depth of the stream (chunk k updates while k+1..k+2
#: are in flight), and the multiplier in the on-chip stream-slot
#: accounting that memplan's native offload walk reuses
_STREAM_LOOKAHEAD = 2


def _build_offload_step(cfg: TrainConfig, mesh: Mesh, state: TrainState,
                        part, compute_grads, bshard, mshard) -> Callable:
    """The streamed host-offload arm of ``make_train_step``.

    Two phases per step instead of one fused jit:

    1. **Grad phase (device, one jit).** The grad-accum scan plus the
       global grad norm. ``state.params`` is donated and passed
       through, so the scan carry accumulates in place (no
       double-buffered grads tree — the other half of MEMPLAN_r01's
       2.7B diagnosis) and the caller's param buffers alias the
       outputs instead of copying.
    2. **Streaming phase (host).** Gradient and param leaves stream
       host-ward in layer-group chunks (``lax.slice_in_dim`` along the
       stacked-layer axis, ``copy_to_host_async``), double-buffered
       ``_STREAM_LOOKAHEAD`` chunks deep so chunk k+1's transfer rides
       under chunk k's work; when a leaf is assembled on host, its
       per-leaf optimizer update (``OffloadOptimizer.update_leaf`` —
       arithmetically the on-chip chain) runs on the host device and
       the updated leaf is dispatched straight back with the param
       sharding (async H2D). Device-side grad/param leaves are deleted
       as their last chunk dispatches, so on-chip residency beyond the
       grad phase stays bounded by the stream slot.

    The update is leaf-granular while transfers are chunk-granular:
    adafactor's block-RMS clips reduce over whole leaves, so per-chunk
    updates would change the arithmetic — per-leaf updates keep the
    offload arm bit-identical to the on-chip arm on a given backend.

    The step donates ``state`` in the same sense the on-chip jit does:
    param and optimizer buffers are consumed (donated into the grad
    phase / deleted after streaming), so the caller must rebind
    ``state`` from the return value.
    """
    from collections import deque

    if part is not None:
        raise ValueError("offload='optimizer' does not compose with "
                         "train_only — see make_offload_optimizer")
    if mesh.shape.get("pp", 1) > 1:
        raise ValueError("offload='optimizer' targets the single-chip "
                         "memory wall; pp meshes keep the update "
                         "on-chip (state is already sharded)")
    opt = make_offload_optimizer(cfg.optim, state.params)
    keys = opt.keys
    if not (isinstance(state.opt_state, dict)
            and set(state.opt_state) == set(keys)):
        raise ValueError(
            "state.opt_state is not the host-offload layout; build the "
            "state with OptimConfig(offload='optimizer') so "
            "init_train_state lays it out host-resident")

    flat, ptreedef = jax.tree_util.tree_flatten(state.params)
    shapes = [tuple(p.shape) for p in flat]
    dtypes = [jnp.dtype(p.dtype) for p in flat]
    pshard = param_shardings(state.params, mesh)
    pshard_leaves = jax.tree_util.tree_leaves(pshard)

    # layer-group chunk plan: stacked (L, ...) leaves stream in slices
    # of offload_chunk_layers along axis 0; flat leaves (embedding,
    # norms) stream whole
    chunk_layers = max(1, cfg.optim.offload_chunk_layers)
    chunks: list[list[tuple[int, int]] | None] = []
    for shp in shapes:
        if len(shp) >= 3 and shp[0] > 1:
            chunks.append([(a, min(a + chunk_layers, shp[0]))
                           for a in range(0, shp[0], chunk_layers)])
        else:
            chunks.append(None)

    def _chunk_bytes(i, r) -> int:
        shp, item = shapes[i], dtypes[i].itemsize
        rows = shp[0] if r is None else (r[1] - r[0])
        per_row = item
        for d in shp[1:]:
            per_row *= d
        return rows * per_row if shp else item

    work: list[tuple[int, tuple[int, int] | None, bool]] = []
    for i in range(len(flat)):
        if chunks[i] is None:
            work.append((i, None, True))
        else:
            for j, r in enumerate(chunks[i]):
                work.append((i, r, j == len(chunks[i]) - 1))
    max_pair = max((2 * _chunk_bytes(i, r) for i, r, _ in work), default=0)
    # grad + param slices per chunk, one consumed + LOOKAHEAD in flight
    stream_slot_bytes = (1 + _STREAM_LOOKAHEAD) * max_pair

    def grad_phase(params, batch):
        (loss, aux), grads = compute_grads(params, batch)
        gnorm = optax.global_norm(grads)
        return params, grads, loss, gnorm, aux

    grad_phase_j = jax.jit(
        grad_phase,
        in_shardings=(pshard, bshard),
        out_shardings=(pshard, pshard, mshard, mshard, mshard),
        donate_argnums=(0,),
    )

    @partial(jax.jit, static_argnames=("key",), donate_argnums=(0,))
    def _leaf_update(opt_leaf_state, grad, param, gnorm, *, key):
        return opt.update_leaf(key, opt_leaf_state, grad, param, gnorm)

    host = host_device()

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        params_thru, grads, loss, gnorm, aux = grad_phase_j(
            state.params, batch)
        new_step = state.step + 1
        g_leaves = jax.tree_util.tree_leaves(grads)
        p_leaves = jax.tree_util.tree_leaves(params_thru)
        new_p_leaves: list = [None] * len(p_leaves)
        new_opt: dict = {}
        blocked = 0.0
        t_stream = time.perf_counter()
        with _hostsync.sanctioned("train.offload_stream"):
            inflight: deque = deque()
            pos = 0

            def dispatch_next():
                nonlocal pos
                i, r, last = work[pos]
                pos += 1
                g, p = g_leaves[i], p_leaves[i]
                if r is None:
                    gsl, psl = g, p
                else:
                    gsl = jax.lax.slice_in_dim(g, r[0], r[1])
                    psl = jax.lax.slice_in_dim(p, r[0], r[1])
                gsl.copy_to_host_async()
                psl.copy_to_host_async()
                if r is not None and last:
                    # the slices carry the data from here on: free the
                    # device-resident source leaves so on-chip residency
                    # past the grad phase is just the stream slot
                    g.delete()
                    p.delete()
                return gsl, psl

            for _ in range(min(1 + _STREAM_LOOKAHEAD, len(work))):
                inflight.append(dispatch_next())

            t1 = time.perf_counter()
            gnorm_host = jax.device_put(np.asarray(gnorm), host)
            blocked += time.perf_counter() - t1

            for i, key in enumerate(keys):
                n_chunks = 1 if chunks[i] is None else len(chunks[i])
                parts_g, parts_p = [], []
                for _ in range(n_chunks):
                    gsl, psl = inflight.popleft()
                    t1 = time.perf_counter()
                    parts_g.append(np.asarray(gsl))
                    parts_p.append(np.asarray(psl))
                    blocked += time.perf_counter() - t1
                    if pos < len(work):
                        inflight.append(dispatch_next())
                gh = (parts_g[0] if n_chunks == 1
                      else np.concatenate(parts_g, axis=0))
                ph = (parts_p[0] if n_chunks == 1
                      else np.concatenate(parts_p, axis=0))
                leaf_state = jax.tree_util.tree_map(
                    host_put, state.opt_state[key])
                new_p_host, new_opt[key] = _leaf_update(
                    leaf_state,
                    jax.device_put(gh, host),
                    jax.device_put(ph, host),
                    gnorm_host, key=key)
                # async H2D: the next leaf's transfers and update
                # overlap this dispatch
                new_p_leaves[i] = jax.device_put(new_p_host,
                                                 pshard_leaves[i])
                if chunks[i] is None:
                    # whole-leaf transfers: the host copy exists, free
                    # the device source now rather than at step exit
                    g_leaves[i].delete()
                    p_leaves[i].delete()
        stream_wall = time.perf_counter() - t_stream
        params = jax.tree_util.tree_unflatten(ptreedef, new_p_leaves)
        metrics = {
            "loss": loss, "grad_norm": gnorm, **aux,
            "offload_transfer_ms": blocked * 1e3,
            "offload_overlap_frac": (max(0.0, 1.0 - blocked / stream_wall)
                                     if stream_wall > 0 else 0.0),
        }
        return TrainState(step=new_step, params=params,
                          opt_state=new_opt), metrics

    # introspection surface: memplan's native offload walk estimates
    # the grad phase and adds the stream slot; tests assert the plan
    step.grad_phase = grad_phase_j
    step.stream_slot_bytes = stream_slot_bytes
    step.chunk_plan = dict(zip(keys, chunks))
    step.offload = "optimizer"
    return step


def shard_batch(batch: dict, mesh: Mesh) -> dict:
    """Device-put a host batch onto the mesh with the standard layout."""
    s = NamedSharding(mesh, batch_pspec())
    return {k: jax.device_put(v, s) for k, v in batch.items()}
