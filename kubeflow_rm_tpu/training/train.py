"""Sharded training step.

``make_train_step`` builds a single jitted SPMD step: params and
optimizer state carry NamedShardings from ``parallel.sharding``, the
batch arrives sharded over (dp, fsdp) x sp, and XLA's partitioner
inserts the FSDP all-gathers, TP psums and gradient reduce-scatters.
Buffers are donated so the step runs in-place in HBM.

There is no hand-rolled gradient-sync code anywhere — on TPU the
collective schedule is the compiler's job (scaling-book recipe); the
framework's job is the shardings.
"""

from dataclasses import dataclass, field
from typing import Any, Callable

import logging

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_rm_tpu.models import (
    LlamaConfig,
    forward_with_aux,
    init_params,
)
from kubeflow_rm_tpu.ops.losses import softmax_cross_entropy
from kubeflow_rm_tpu.parallel.sharding import batch_pspec, param_shardings
from kubeflow_rm_tpu.training.optim import OptimConfig, make_optimizer


@dataclass(frozen=True)
class TrainConfig:
    model: LlamaConfig = field(default_factory=LlamaConfig.tiny)
    optim: OptimConfig = field(default_factory=OptimConfig)
    z_loss: float = 1e-4


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


class _Partition:
    """Split a param tree into trainable/frozen leaf lists by a mask
    (``optim.train_only``): the train step differentiates ONLY the
    trainable list, so frozen weights get neither gradient buffers nor
    optimizer moments — the memory shape LoRA fine-tuning needs."""

    def __init__(self, params, mask_tree):
        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.mask = jax.tree_util.tree_leaves(mask_tree)
        assert len(self.mask) == len(leaves)
        if not any(self.mask):
            raise ValueError("train_only matched no parameters")

    def split(self, params):
        leaves = jax.tree_util.tree_leaves(params)
        train = [p for p, m in zip(leaves, self.mask) if m]
        frozen = [p for p, m in zip(leaves, self.mask) if not m]
        return train, frozen

    def combine(self, train, frozen):
        it_t, it_f = iter(train), iter(frozen)
        leaves = [next(it_t) if m else next(it_f) for m in self.mask]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def _partition_for(cfg: TrainConfig, params) -> _Partition | None:
    if cfg.optim.train_only is None:
        return None
    if cfg.optim.train_only != "lora":
        raise ValueError(
            f"unknown train_only={cfg.optim.train_only!r} (only 'lora')")
    from kubeflow_rm_tpu.models.lora import lora_mask
    return _Partition(params, lora_mask(params))


def init_train_state(cfg: TrainConfig, key: jax.Array,
                     params=None) -> TrainState:
    """Fresh state; pass ``params`` to seed from existing weights (an
    HF conversion, or ``models.lora.add_lora`` output for adapter
    training)."""
    if params is None:
        params = init_params(cfg.model, key)
    part = _partition_for(cfg, params)
    opt = make_optimizer(cfg.optim)
    if part is None:
        opt_state = opt.init(params)
    else:
        opt_state = opt.init(part.split(params)[0])
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt_state)


def state_shardings(cfg: TrainConfig, state: TrainState, mesh: Mesh) -> TrainState:
    """NamedSharding pytree for a TrainState: any optimizer sub-tree with
    the params' structure (adam moments, decayed-weights masks) inherits
    the param shardings; scalars (step counts) are replicated."""
    pshard = param_shardings(state.params, mesh)
    replicated = NamedSharding(mesh, P())
    param_treedef = jax.tree_util.tree_structure(state.params)
    param_leaves = jax.tree_util.tree_leaves(state.params)

    def map_node(node):
        try:
            if jax.tree_util.tree_structure(node) == param_treedef:
                # params-shaped state (adam moments) inherits the param
                # shardings leaf-for-leaf — but only where shapes match:
                # adafactor's factored stats share the STRUCTURE while
                # holding row/col vectors, which must stay replicated
                node_leaves = jax.tree_util.tree_leaves(node)
                shard_leaves = [
                    s if getattr(n, "shape", None) == p.shape else replicated
                    for n, p, s in zip(node_leaves, param_leaves,
                                       jax.tree_util.tree_leaves(pshard))
                ]
                return jax.tree_util.tree_unflatten(param_treedef,
                                                    shard_leaves)
        except Exception:
            # fall through to the structural recursion below — but
            # leave a trace, since a silently-unsharded optimizer
            # state is exactly the kind of fault that only shows up
            # as an OOM three steps later
            logging.getLogger("kubeflow_rm_tpu.training").debug(
                "state sharding fast path failed; recursing node "
                "structurally", exc_info=True)
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(map_node(c) for c in node))
        if isinstance(node, (list, tuple)):
            return type(node)(map_node(c) for c in node)
        if isinstance(node, dict):
            return {k: map_node(v) for k, v in node.items()}
        return replicated

    return TrainState(
        step=replicated,
        params=pshard,
        opt_state=map_node(state.opt_state),
    )


def loss_fn(params, batch, cfg: TrainConfig,
            mesh: Mesh | None = None, n_microbatches: int | None = None):
    # batches come from training.data (pack_documents layout: per-doc
    # restarting positions), so the packed fast path is sound here
    kwargs = dict(positions=batch.get("positions"),
                  segments=batch.get("segments"),
                  packed=batch.get("segments") is not None)
    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        from kubeflow_rm_tpu.parallel.pipeline import (
            pipeline_forward_with_aux,
        )
        logits, router_aux = pipeline_forward_with_aux(
            params, batch["tokens"], cfg.model, mesh,
            n_microbatches=n_microbatches, **kwargs)
    else:
        logits, router_aux = forward_with_aux(params, batch["tokens"],
                                              cfg.model, mesh=mesh,
                                              **kwargs)
    loss, aux = softmax_cross_entropy(logits, batch["labels"],
                                      z_loss=cfg.z_loss)
    if router_aux is not None:
        aux = dict(aux, router_aux=router_aux)
        loss = loss + cfg.model.moe.router_aux_weight * router_aux
    return loss, aux


def make_train_step(cfg: TrainConfig, mesh: Mesh, state: TrainState,
                    batch_keys: tuple = ("tokens", "labels"),
                    n_microbatches: int | None = None,
                    grad_accum: int = 1) -> Callable:
    """Return jitted ``step(state, batch) -> (state, metrics)``.

    ``batch`` maps each of ``batch_keys`` to a (B, T) int32 array laid
    out with ``batch_pspec`` on ``mesh`` — "tokens" and "labels" always,
    plus "positions" and "segments" when training on packed documents
    (see ``training.data.pack_documents``).

    On a mesh with pp > 1 the forward runs the GPipe schedule
    (``parallel.pipeline``); ``n_microbatches`` (default: pp) sets the
    bubble fraction (pp-1)/(n_microbatches+pp-1).

    ``grad_accum`` > 1 splits the global batch into that many
    sequential microbatches under ``lax.scan``, accumulating gradients
    before ONE optimizer update. Two reasons to use it: effective batch
    beyond what HBM fits, and amortizing the optimizer update — on a
    ~1B-param single chip the adam step is pure HBM traffic worth a
    double-digit share of step time, and accumulation divides it by K.
    The per-step loss/grads equal the full-batch computation up to
    accumulation-order rounding (asserted by tests/test_train.py).
    """
    if mesh.shape.get("pp", 1) > 1 and n_microbatches is None:
        n_microbatches = mesh.shape["pp"]
    opt = make_optimizer(cfg.optim)
    sshard = state_shardings(cfg, state, mesh)
    bshard = {k: NamedSharding(mesh, batch_pspec()) for k in batch_keys}
    mshard = NamedSharding(mesh, P())
    part = _partition_for(cfg, state.params)

    if part is None:
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    else:
        # differentiate ONLY the trainable leaves: the backward never
        # materializes base-weight gradients (dW = h^T g outer products
        # are the dominant bwd memory/flops for a frozen 7B)
        def _loss_trainable(train, frozen, batch, cfg, mesh, n_mb):
            return loss_fn(part.combine(train, frozen), batch, cfg,
                           mesh, n_mb)

        _grad_trainable = jax.value_and_grad(_loss_trainable,
                                             has_aux=True)

        def grad_fn(params, batch, cfg, mesh, n_mb):
            train, frozen = part.split(params)
            return _grad_trainable(train, frozen, batch, cfg, mesh, n_mb)

    def fold(a):
        # interleaved: microbatch m takes rows m, K+m, ... so the fold
        # keeps K replicated and the microbatch dim on the batch
        # sharding with zero resharding traffic (same reasoning as
        # parallel.pipeline's fold)
        if a.shape[0] % grad_accum:
            raise ValueError(
                f"batch {a.shape[0]} not divisible by "
                f"grad_accum={grad_accum}")
        mb = a.shape[0] // grad_accum
        a = a.reshape(mb, grad_accum, *a.shape[1:]).swapaxes(0, 1)
        spec = P(None, *batch_pspec())
        return jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, spec))

    def accumulate(params, batch):
        folded = {k: fold(v) for k, v in batch.items()}

        def body(acc, mbatch):
            (loss, aux), g = grad_fn(params, mbatch, cfg, mesh,
                                     n_microbatches)
            return jax.tree_util.tree_map(jnp.add, acc, g), (loss, aux)

        grad_target = params if part is None else part.split(params)[0]
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), grad_target)
        summed, (losses, auxes) = jax.lax.scan(body, zeros, folded)
        grads = jax.tree_util.tree_map(lambda g: g / grad_accum, summed)
        loss = jnp.mean(losses)
        aux = jax.tree_util.tree_map(lambda a: jnp.mean(a, axis=0), auxes)
        return (loss, aux), grads

    def step(state: TrainState, batch) -> tuple[TrainState, dict]:
        if grad_accum > 1:
            (loss, aux), grads = accumulate(state.params, batch)
        else:
            (loss, aux), grads = grad_fn(
                state.params, batch, cfg, mesh, n_microbatches)
        if part is None:
            target, frozen = state.params, None
        else:
            target, frozen = part.split(state.params)
        updates, opt_state = opt.update(grads, state.opt_state, target)
        target = optax.apply_updates(target, updates)
        params = target if part is None else part.combine(target, frozen)
        gnorm = optax.global_norm(grads)
        metrics = {"loss": loss, "grad_norm": gnorm, **aux}
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state), metrics

    return jax.jit(
        step,
        in_shardings=(sshard, bshard),
        out_shardings=(sshard, mshard),
        donate_argnums=(0,),
    )


def shard_batch(batch: dict, mesh: Mesh) -> dict:
    """Device-put a host batch onto the mesh with the standard layout."""
    s = NamedSharding(mesh, batch_pspec())
    return {k: jax.device_put(v, s) for k, v in batch.items()}
