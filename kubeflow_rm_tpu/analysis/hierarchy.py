"""The canonical lock hierarchy — ONE place, not N docstrings.

A thread may only acquire a lock whose level is **strictly greater**
than every lock it already holds, except within a ranked same-name
family (level ties with itself), where instances must be acquired in
ascending ``rank`` order. Any acquisition-order edge that runs
*downhill* is a latent deadlock even if no cycle has been observed
yet; ``tests/test_lockgraph.py`` asserts the measured graph from a
control-plane storm embeds into this table, and the table is the
review reference for every new lock.

Levels are spaced by 10 so a new lock slots in without renumbering.

Notes on the non-obvious entries:

- ``apiserver.kind`` is one *family* (one RLock per kind). Cross-kind
  nesting follows the ownerReference DAG (owner's kind lock held
  while a dependent's is taken: Notebook → StatefulSet → Pod,
  Namespace → everything at drain). The DAG is acyclic for every
  object graph the platform builds, so the family sits at one level
  and the dynamic tool watches the per-kind edges for cycles.
- ``scheduler.node`` is the ranked family: ``_commit`` acquires the
  gang's node locks sorted by node name (= the rank), under
  ``scheduler.relist``.
- The WAL condvar is at the bottom: with the r14 deferred group
  commit the fsync wait happens with NO other lock held (the verb's
  kind lock is released first), so ``wal.cv`` must never be held
  while taking anything above it.
- ``readiness.registry`` → ``readiness.key``: the hub registers and
  retires per-key waiters under the registry lock.
"""

from __future__ import annotations

#: lock-family name (the ``make_lock`` label) -> hierarchy level.
LOCK_HIERARCHY: dict[str, int] = {
    # -- coarse, outermost ---------------------------------------------
    "apiserver.global": 10,         # legacy --global-lock arm verb lock
    "scheduler.registry": 20,       # per-backend cache registry
    "scheduler.relist": 30,         # rebuild vs bind-commit exclusion
    "scheduler.nodes_map": 40,      # node-map membership
    "scheduler.node": 50,           # ranked family: sorted by node name
    "scheduler.pods_map": 60,       # pod -> entry accounting map
    # suspend's per-notebook checkpoint guard is held across the state-
    # store call AND its annotation CAS, so it must sit below every
    # apiserver verb lock; the registry hands out the per-key instances
    "suspend.store_registry": 70,
    "suspend.store": 80,            # ranked family: by "ns/name" key
    # -- apiserver write path ------------------------------------------
    "apiserver.kind": 110,          # per-kind verb locks (DAG inside)
    "apiserver.kind_locks_map": 120,
    "apiserver.event_seq": 130,     # atomic Event name counter
    "apiserver.write_log": 140,     # write audit append
    "apiserver.pod_logs": 140,      # kubelet stdout store
    # rv sits BELOW write_log: the snapshot cut reads the rv counter
    # while holding the write lock (_run_snapshot), never the reverse
    "apiserver.rv": 145,            # atomic resourceVersion counter
    "apiserver.admission_pool": 150,
    "apiserver.watch_channel": 160,  # per-watcher fanout condvar
    # chaos.plan is taken from inside publish (under watch_channel) and
    # from the kubeclient request path; it never takes anything while
    # held (flight triggers are deferred), so it slots just above the
    # deepest lock that calls into it
    "chaos.plan": 165,              # fault-plan draw/ledger mutex
    # -- controller runtime / HA ---------------------------------------
    "runtime.queue": 210,
    "runtime.child_pool": 220,
    "workqueue": 230,
    "leases.elector": 240,
    "informer.prime": 250,
    "cache.store": 260,             # ObjectStore RLock + its condvar
    # held across an ENTIRE split/merge handoff, which routes into the
    # runner watchdog (370), kubeclient transport (310+), the router,
    # and the obs stack — so it sits below the whole transport tier
    "shard.elastic": 280,
    # -- transport / web -----------------------------------------------
    "kubeclient.token_bucket": 310,
    "kubeclient.conn_pool": 320,
    "kubeclient.events_seen": 330,
    "kubeclient.router_listed": 340,
    "restserver.watch_registry": 350,
    "restserver.conns": 360,
    "shard.watchdog": 370,
    "readiness.registry": 410,
    "readiness.key": 420,           # per-notebook condvar family
    "jupyter.hub_registry": 430,
    # guards only the lease table; snapshots are taken under it and
    # every external call (gang_bind, fleet drain/remove) runs after
    # release — but it logically precedes routing into the fleet
    "harvest.controller": 433,
    "serving.fleet": 435,           # routes INTO gateways (440): uphill
    "serving.gateway": 440,
    # the global chain store is reached from the fleet routing path
    # AND from inside an engine step (promote-on-evict fires under the
    # owning gateway's lock), so it must sit above both
    "serving.store": 445,
    "metrics_service.sampler_thread": 450,  # lazy sampler-thread start
    "metrics_service.sampler": 460,         # the history ring
    # obs locks never nest with each other by design (burn rates are
    # computed before the engine lock; flight bundles are assembled
    # lock-free and only appended under obs.flight), but they sit
    # below tracing.collector so a capture reading the span ring while
    # holding one would still be uphill
    "obs.engine": 470,              # SLO alert state machine
    "obs.tsdb": 480,                # ring-buffer TSDB series map
    "obs.flight": 490,              # flight-recorder bundle ring
    "tracing.collector": 510,
    # -- persistence, innermost ----------------------------------------
    "persistence.snapshot_guard": 610,
    "wal.cv": 620,                  # group-commit condvar; leaf
    "harness.diurnal_results": 630,  # conformance audit ledger; leaf
}


def level_of(name: str) -> int | None:
    return LOCK_HIERARCHY.get(name)


def check_edges(edges) -> list[str]:
    """Validate measured acquisition-order edges (``{"from", "to"}``
    dicts from :func:`lockgraph.report`) against the hierarchy.
    Returns human-readable violations: downhill edges (held a
    higher-level lock while taking a lower-level one) and edges whose
    endpoints are unregistered (a new lock missing from the table)."""
    problems = []
    for e in edges:
        a, b = e["from"], e["to"]
        la, lb = LOCK_HIERARCHY.get(a), LOCK_HIERARCHY.get(b)
        if la is None:
            problems.append(f"unregistered lock in hierarchy: {a}")
            continue
        if lb is None:
            problems.append(f"unregistered lock in hierarchy: {b}")
            continue
        if a == b:
            continue  # ranked-family nesting is checked by rank order
        if lb <= la:
            problems.append(
                f"downhill acquisition {a} (level {la}) -> {b} "
                f"(level {lb}): violates the canonical order")
    return sorted(set(problems))
