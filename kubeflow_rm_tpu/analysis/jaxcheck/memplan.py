"""The single-chip memory plan: cost-model predictions for the
full-FT ladder, validated against BENCH_SWEEP_r05 and extrapolated to
the 7B north star.

``python -m kubeflow_rm_tpu.analysis.jaxcheck.memplan --out
MEMPLAN_r01.json`` abstractly traces every ladder rung's REAL train
step (``training.train.make_train_step`` — the same jit the bench
runs, shapes only, nothing materializes) and walks it with
:mod:`.costmodel`. Each rung row carries:

- the predicted peak HBM (donation honored) and its breakdown
  (params / grads / optimizer state / logits / workspace),
- a fit verdict against the 15.75 GiB usable budget with a 5%
  allocator margin (``HBM_MARGIN`` — XLA's reserved scratch plus
  fragmentation; the 2.1B mb2-dots rung measures OOM within ~1% of
  the raw budget, which is exactly the band the margin exists for),
- the measured BENCH_SWEEP_r05 outcome for that exact ``bench.py``
  command, and where the artifact family documents a byte figure
  (the 2.7B "state ~10.8G" note, bench_3b's 12.6 GiB docstring,
  bench.py's "~7 G bf16 state", optim.py's 4-bytes/param adafactor
  rule) an anchor with the predicted-vs-measured delta.

The **extrapolation** rows de-risked ROADMAP item 1 before
``training/loop.py`` changes: a 2.7B rung with the optimizer update
streamed through host RAM (on-chip peak = grad phase + accumulation
buffer + a double-buffered stream slot — predicted to FIT the chip
that measurably OOMs today), the same treatment at 7B (predicted
still-OOM: params+grads alone exceed the chip, so offload must pair
with sharding), and the 7B north star on a v5p-8 fsdp mesh.

Since r18 the offload arm is **modeled natively, not just
extrapolated**: ``training.train.make_train_step(offload="optimizer")``
exists, and :func:`offload_native_rows` walks its REAL device program
(the jitted grad phase the streamed step actually dispatches) and adds
the step's own stream-slot accounting
(``step.stream_slot_bytes`` — (1 + lookahead) double-buffered
layer-group chunk pairs). The plan reports both columns and their
delta, so predicted-vs-shipped disagreement is a diffable artifact
(``extrapolation.host_offload_native``); ``bench.py --offload``
reports the same delta against the priced 13.24 GB in BENCH_r06.

Validation contract (pinned by ``tests/test_jaxcheck.py``): every
anchor delta within ±10%, and the predicted fit verdict matches the
measured outcome on ALL BENCH_SWEEP_r05 scale rows — including the
mb1-vs-mb2 and dots-vs-full flips at 2.1B.
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import dataclass, field

GB = 1e9            # the artifact family quotes decimal GB ("~10.8G")
CHIP_HBM_GIB = 16.0
USABLE_GIB = 15.75  # bench.py / BENCH_SWEEP_r05 usable-HBM figure
HBM_MARGIN = 0.05   # allocator fragmentation + runtime scratch

_BUDGET_BYTES = USABLE_GIB * (2 ** 30)


@dataclass(frozen=True)
class Rung:
    name: str
    preset: str                  # LlamaConfig preset
    optim: str                   # "adamw" | "adafactor"
    batch: int
    accum: int
    remat: str
    seq: int | None = None       # None: the preset's max_seq_len
    measured: dict = field(default_factory=dict)
    anchor: dict | None = None   # measured byte figure, where one exists
    extrapolated: bool = False


#: the measured rungs mirror BENCH_SWEEP_r05's scale_rows verbatim
#: (bench.py default batch = 2*accum, i.e. mb2, unless --batch given)
LADDER: tuple[Rung, ...] = (
    Rung("1.2B full-FT adamw mb2 dots accum64", "bench_1b", "adamw",
         128, 64, "dots",
         measured={"ran": True, "mfu": 60.36},
         anchor={"kind": "bf16_state_gb", "value_gb": 7.0,
                 "source": "bench.py r4 frontier comment "
                           "('~1.2B params, bf16 state (~7 G)')"}),
    Rung("1.2B full-FT adafactor mb2 dots accum64", "bench_1b",
         "adafactor", 128, 64, "dots",
         measured={"ran": True, "mfu": 60.52,
                   "tokens_per_sec": 16881.3},
         anchor={"kind": "state_gb", "value_gb": None,  # 4 bytes/param
                 "source": "training/optim.py ('params 2B + transient "
                           "grads 2B = 4 bytes/param')"}),
    Rung("1.2B adamw mb2 dots seq4096 accum8", "bench_1b", "adamw",
         16, 8, "dots", seq=4096,
         measured={"ran": False, "oom_request_gb": 17.7,
                   "note": "bench.py frontier comment: 'mb2 dots "
                           "accum8 seq4096 OOM (17.7G)' — the request "
                           "size at failure, not a peak watermark; "
                           "the walker's no-fusion peak upper-bounds "
                           "it"}),
    Rung("2.1B full-FT adafactor mb1 dots accum64", "bench_2b",
         "adafactor", 64, 64, "dots",
         measured={"ran": True, "mfu": 59.61,
                   "tokens_per_sec": 9271.9},
         anchor={"kind": "state_gb", "value_gb": None,
                 "source": "training/optim.py 4-bytes/param rule"}),
    Rung("2.1B full-FT adafactor mb2 dots accum32", "bench_2b",
         "adafactor", 64, 32, "dots",
         measured={"ran": False}),
    Rung("2.1B full-FT adafactor mb2 full accum32", "bench_2b",
         "adafactor", 64, 32, "full",
         measured={"ran": True, "mfu": 55.84,
                   "tokens_per_sec": 8685.4}),
    Rung("2.1B full-FT adafactor mb2 attn+mlp accum32", "bench_2b",
         "adafactor", 64, 32, "attn+mlp",
         measured={"ran": False}),
    Rung("2.7B full-FT adafactor mb1 full accum32", "bench_2_7b",
         "adafactor", 32, 32, "full",
         measured={"ran": False,
                   "note": "the single-v5e wall (BENCH_SWEEP_r05): "
                           "'state ~10.8G + logits/workspace > "
                           "15.75G usable'"},
         anchor={"kind": "state_gb", "value_gb": 10.8,
                 "source": "BENCH_SWEEP_r05 2.7B OOM note"}),
    Rung("2.7B full-FT adafactor mb1 dots accum32", "bench_2_7b",
         "adafactor", 32, 32, "dots",
         measured={"ran": False}),
    Rung("3.1B full-FT adafactor mb1 full accum64", "bench_3b",
         "adafactor", 64, 64, "full",
         measured={"ran": False},
         anchor={"kind": "state_gb", "value_gb": 12.6,
                 "source": "LlamaConfig.bench_3b docstring "
                           "('params+grads = 12.6 GiB')"}),
    Rung("7B full-FT adafactor mb1 full seq2048", "llama2_7b",
         "adafactor", 32, 32, "full", seq=2048,
         extrapolated=True),
)


def _build_step(rung: Rung):
    """The rung's real jitted train step plus abstract inputs —
    everything via eval_shape, so 7B costs nothing."""
    import jax
    import jax.numpy as jnp

    from kubeflow_rm_tpu.models.llama import LlamaConfig
    from kubeflow_rm_tpu.parallel import MeshConfig, make_mesh
    from kubeflow_rm_tpu.training.optim import OptimConfig
    from kubeflow_rm_tpu.training.train import (
        TrainConfig, init_train_state, make_train_step,
    )

    kw = {"param_dtype": jnp.bfloat16, "remat_policy": rung.remat}
    if rung.seq:
        kw["max_seq_len"] = rung.seq
    model = getattr(LlamaConfig, rung.preset)(**kw)
    cfg = TrainConfig(model=model,
                      optim=OptimConfig(factored=rung.optim == "adafactor"))
    state = jax.eval_shape(
        lambda k: init_train_state(cfg, k), jax.random.PRNGKey(0))
    mesh = make_mesh(MeshConfig(), jax.devices()[:1])
    step = make_train_step(cfg, mesh, state, grad_accum=rung.accum)
    batch = {k: jax.ShapeDtypeStruct((rung.batch, model.max_seq_len),
                                     jnp.int32)
             for k in ("tokens", "labels")}
    return cfg, state, step, batch


def _tree_bytes(tree) -> int:
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "size"):
            total += leaf.size * getattr(leaf.dtype, "itemsize", 4)
    return total


def _grad_phase_peak(cfg, state, batch, accum) -> int:
    """On-chip peak with the optimizer UPDATE streamed through host
    RAM (ROADMAP item 1's design): the chip holds params, the grad
    accumulation scan and one microbatch's forward/backward; mu/nu
    (or adafactor stats), the fp32 update transient and
    ``apply_updates`` live host-side, fed by a double-buffered
    per-leaf stream slot. The on-chip residue is estimated with the
    same scan structure ``make_train_step`` uses, so the walker
    models buffer reuse identically in both columns."""
    import jax
    import jax.numpy as jnp

    from kubeflow_rm_tpu.analysis.jaxcheck.costmodel import estimate
    from kubeflow_rm_tpu.training.train import loss_fn

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def chip_phase(params, full_batch):
        mbs = {k: v.reshape(accum, v.shape[0] // accum, v.shape[1])
               for k, v in full_batch.items()}

        def body(carry, mb):
            (_, _), g = grad_fn(params, mb, cfg)
            return jax.tree_util.tree_map(jnp.add, carry, g), None

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), params)
        acc, _ = jax.lax.scan(body, zeros, mbs)
        return acc

    # jit + donation makes the scan-carry aliasing visible to the
    # walker: the accumulation adds in place instead of holding the
    # carry AND a fresh microbatch grads tree.  Params are NOT
    # discarded by this (verified: peak = params + carry + one
    # microbatch's backward workspace) — the streamed design requires
    # exactly this in-place accumulation.
    est = estimate(jax.jit(chip_phase, donate_argnums=(0,)),
                   state.params, batch)

    def _slice_bytes(leaf):
        # layer-stacked scan weights (L, d, ...) stream per layer;
        # flat leaves (embedding, norms) stream whole
        nbytes = leaf.size * getattr(leaf.dtype, "itemsize", 4)
        return nbytes // leaf.shape[0] if leaf.ndim >= 3 else nbytes

    largest_slice = max(
        (_slice_bytes(leaf)
         for leaf in jax.tree_util.tree_leaves(state.params)
         if hasattr(leaf, "size") and leaf.size), default=0)
    # accumulation-phase peak + a double-buffered host<->device
    # stream slot sized for the largest per-layer slice
    return est.peak_bytes + 2 * largest_slice


def offload_native_rows() -> list[dict]:
    """Walk the REAL streamed-offload train step (not the
    :func:`_grad_phase_peak` extrapolation): build
    ``make_train_step(offload="optimizer")`` abstractly, estimate the
    jitted grad phase it dispatches, and add its own stream-slot
    accounting. One row per offload ladder rung, each carrying the
    native-vs-priced delta the acceptance gate checks."""
    import jax
    import jax.numpy as jnp

    from kubeflow_rm_tpu.analysis.jaxcheck.costmodel import estimate
    from kubeflow_rm_tpu.models.llama import LlamaConfig
    from kubeflow_rm_tpu.parallel import MeshConfig, make_mesh
    from kubeflow_rm_tpu.training.optim import OptimConfig
    from kubeflow_rm_tpu.training.train import (
        TrainConfig, init_train_state, make_train_step,
    )

    rows = []
    for preset, batch_rows, accum, seq, label in (
            ("bench_2_7b", 32, 32, None,
             "2.7B adafactor mb1 full + streamed host-offload "
             "optimizer (native)"),
            ("llama2_7b", 32, 32, 2048,
             "7B adafactor mb1 full seq2048 + streamed host-offload "
             "optimizer (native)"),
    ):
        kw = {"param_dtype": jnp.bfloat16, "remat_policy": "full"}
        if seq:
            kw["max_seq_len"] = seq
        model = getattr(LlamaConfig, preset)(**kw)
        cfg = TrainConfig(model=model,
                          optim=OptimConfig(factored=True,
                                            offload="optimizer"))
        state = jax.eval_shape(
            lambda k, _cfg=cfg: init_train_state(_cfg, k),
            jax.random.PRNGKey(0))
        mesh = make_mesh(MeshConfig(), jax.devices()[:1])
        step = make_train_step(cfg, mesh, state, grad_accum=accum,
                               offload="optimizer")
        batch = {k: jax.ShapeDtypeStruct((batch_rows, model.max_seq_len),
                                         jnp.int32)
                 for k in ("tokens", "labels")}
        est = estimate(step.grad_phase, state.params, batch)
        peak = est.peak_bytes + step.stream_slot_bytes
        rows.append({
            "name": label,
            "preset": preset,
            "grad_phase_peak_gb": round(est.peak_bytes / GB, 2),
            "stream_slot_gb": round(step.stream_slot_bytes / GB, 3),
            "on_chip_peak_gb": round(peak / GB, 2),
            "fit": bool(peak * (1 + HBM_MARGIN) <= _BUDGET_BYTES),
            "chunk_layers": cfg.optim.offload_chunk_layers,
            "chunks": sum(len(c) if c else 1
                          for c in step.chunk_plan.values()),
        })
    return rows


def plan_rung(rung: Rung) -> dict:
    import jax

    from kubeflow_rm_tpu.analysis.jaxcheck.costmodel import estimate
    from kubeflow_rm_tpu.utils.flops import train_flops_per_token

    cfg, state, step, batch = _build_step(rung)
    est = estimate(step, state, batch)

    params_b = _tree_bytes(state.params)
    grads_b = params_b            # full FT: grads in the param dtype
    opt_b = _tree_bytes(state.opt_state)
    model = cfg.model
    seq = model.max_seq_len
    mb_rows = rung.batch // rung.accum
    logits_b = mb_rows * seq * model.vocab_size * 4
    workspace_b = max(0, est.peak_bytes - params_b - grads_b - opt_b
                      - logits_b)
    n_params = params_b // 2      # bf16
    fit = est.peak_bytes * (1 + HBM_MARGIN) <= _BUDGET_BYTES

    row = {
        "name": rung.name,
        "preset": rung.preset,
        "recipe": {"optim": rung.optim, "batch": rung.batch,
                   "grad_accum": rung.accum, "remat": rung.remat,
                   "seq": seq, "microbatch": mb_rows},
        "n_params": n_params,
        "predicted": {
            "peak_gb": round(est.peak_bytes / GB, 2),
            "peak_no_donation_gb":
                round(est.peak_bytes_no_donation / GB, 2),
            "donation_savings_gb":
                round(est.donation_savings_bytes / GB, 2),
            "params_gb": round(params_b / GB, 2),
            "grads_gb": round(grads_b / GB, 2),
            "opt_state_gb": round(opt_b / GB, 2),
            "logits_gb": round(logits_b / GB, 2),
            "workspace_gb": round(workspace_b / GB, 2),
            "flops_per_step": est.flops,
            "flops_per_token_executed":
                round(est.flops / (rung.batch * seq), 1),
            "flops_per_token_convention":
                round(train_flops_per_token(model, seq), 1),
            "fit": fit,
        },
        "extrapolated": rung.extrapolated,
    }
    if rung.measured:
        row["measured"] = dict(rung.measured)
        row["verdict_matches_measured"] = (
            fit == bool(rung.measured.get("ran")))
    if rung.anchor:
        anchor = dict(rung.anchor)
        if anchor["kind"] == "state_gb":
            predicted = (params_b + grads_b) / GB
            if anchor["value_gb"] is None:
                # the documented rule, evaluated: 4 bytes/param
                anchor["value_gb"] = round(4 * n_params / GB, 2)
        elif anchor["kind"] == "bf16_state_gb":
            # the bench.py comment counts the bf16 buffers: params,
            # grads and the adam first moment (nu stays fp32)
            predicted = 3 * params_b / GB
        else:  # pragma: no cover - defensive
            raise ValueError(f"unknown anchor kind {anchor['kind']}")
        anchor["predicted_gb"] = round(predicted, 2)
        anchor["delta_pct"] = round(
            100.0 * (predicted - anchor["value_gb"]) / anchor["value_gb"],
            1)
        row["anchor"] = anchor
    return row


def build_plan() -> dict:
    """The full MEMPLAN: every ladder rung plus the ROADMAP-item-1
    extrapolations."""
    rows = [plan_rung(r) for r in LADDER]

    # -- host-offload extrapolation columns --------------------------------
    offload = []
    for preset, optim, batch, accum, label in (
            ("bench_2_7b", "adafactor", 32, 32,
             "2.7B adafactor mb1 full + host-offloaded optimizer "
             "update"),
            ("llama2_7b", "adafactor", 32, 32,
             "7B adafactor mb1 full seq2048 + host-offloaded "
             "optimizer update"),
    ):
        rung = Rung(label, preset, optim, batch, accum, "full",
                    seq=2048 if preset == "llama2_7b" else None,
                    extrapolated=True)
        cfg, state, _, batch_sds = _build_step(rung)
        peak = _grad_phase_peak(cfg, state, batch_sds, accum)
        fit = peak * (1 + HBM_MARGIN) <= _BUDGET_BYTES
        offload.append({
            "name": label,
            "on_chip_peak_gb": round(peak / GB, 2),
            "fit": fit,
            "params_plus_grads_gb":
                round(2 * _tree_bytes(state.params) / GB, 2),
        })

    native = offload_native_rows()
    agreement = []
    for priced, nat in zip(offload, native):
        delta = (100.0 * (nat["on_chip_peak_gb"]
                          - priced["on_chip_peak_gb"])
                 / priced["on_chip_peak_gb"])
        agreement.append({
            "preset": nat["preset"],
            "priced_on_chip_peak_gb": priced["on_chip_peak_gb"],
            "native_on_chip_peak_gb": nat["on_chip_peak_gb"],
            "delta_pct": round(delta, 1),
            "verdicts_match": priced["fit"] == nat["fit"],
        })

    full = next(r for r in rows if r["preset"] == "llama2_7b")
    v5p_hbm_gb = 95.74
    per_chip = full["predicted"]["peak_gb"] / 8
    plan = {
        "artifact": "MEMPLAN_r01",
        "generated_by":
            "python -m kubeflow_rm_tpu.analysis.jaxcheck.memplan",
        "method": "jaxpr live-range walk of the real jitted train "
                  "step (analysis/jaxcheck/costmodel.py), donation "
                  "honored; traced abstractly via eval_shape — no "
                  "arrays materialize",
        "device": {"name": "TPU v5 lite, one chip",
                   "hbm_gib": CHIP_HBM_GIB,
                   "usable_gib": USABLE_GIB,
                   "allocator_margin": HBM_MARGIN},
        "validated_against": "BENCH_SWEEP_r05.json mfu_vs_scale",
        "rungs": rows,
        "oom_explanation": {
            "2.7B": "state (params + grad-accum carry, 4 bytes/param "
                    "= "
                    f"{next(r for r in rows if r['preset'] == 'bench_2_7b')['predicted']['params_gb'] * 2:.1f} GB) "
                    "stays resident through the whole step; on top "
                    "of it each scan iteration materializes the "
                    "microbatch grads tree before folding it into "
                    "the carry "
                    f"(+{next(r for r in rows if r['preset'] == 'bench_2_7b')['predicted']['params_gb']:.1f} GB) "
                    "plus backward workspace, so the walk peaks at "
                    f"{next(r for r in rows if r['preset'] == 'bench_2_7b')['predicted']['peak_gb']:.1f} GB "
                    "> 15.75 GiB usable.  Remat policy cannot save "
                    "it — full vs dots predict the SAME peak at "
                    "mb1, because the peak is grads/state-bound, "
                    "not activation-bound (why mb1/full-remat "
                    "still OOMed on the chip)",
        },
        "extrapolation": {
            "host_offload": offload,
            "host_offload_native": {
                "method": "jaxpr walk of the SHIPPED "
                          "make_train_step(offload='optimizer') grad "
                          "phase + the step's own double-buffered "
                          "stream-slot accounting "
                          "(training/train.py:_build_offload_step)",
                "rows": native,
                "agreement_vs_priced": agreement,
            },
            "conclusion_2_7b": "streaming the optimizer update "
                               "through host RAM AND accumulating "
                               "grads in place (scan-carry "
                               "aliasing) removes the transient "
                               "microbatch grads tree and the "
                               "update-phase transients: the 2.7B "
                               "rung is predicted to FIT the chip "
                               "that measurably OOMs today — "
                               "ROADMAP item 1's design is "
                               "sufficient for one rung past the "
                               "wall",
            "conclusion_7b_v5e": "params+grads alone are "
                                 f"{offload[-1]['params_plus_grads_gb']} GB "
                                 "> 15.75 GiB usable: host-offload "
                                 "alone cannot fit full-FT 7B on one "
                                 "v5e — it must pair with sharding",
            "north_star_v5p8": {
                "mesh": "v5p-8, fsdp=8",
                "per_chip_hbm_gb": v5p_hbm_gb,
                "predicted_per_chip_peak_gb": round(per_chip, 2),
                "note": "fsdp shards params/grads/opt state and the "
                        "update transient 8-way; activations shard "
                        "over batch — per-chip peak ~peak/8 leaves "
                        ">10x headroom, so the 7B north star is "
                        "HBM-safe and the binding constraint is "
                        "MFU, not memory",
            },
        },
    }
    return plan


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubeflow_rm_tpu.analysis.jaxcheck.memplan")
    ap.add_argument("--out", default=None,
                    help="write the plan JSON here (default: stdout)")
    args = ap.parse_args(argv)
    plan = build_plan()
    text = json.dumps(plan, indent=1)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
        mismatched = [r["name"] for r in plan["rungs"]
                      if r.get("verdict_matches_measured") is False]
        print(f"wrote {args.out}: {len(plan['rungs'])} rungs, "
              f"{len(mismatched)} measured-verdict mismatches")
        return 1 if mismatched else 0
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
