"""jaxcheck: jaxpr-level TPU program auditing.

The compute-side sibling of the concurrency toolkit (``analysis/lint``,
``analysis/lockgraph``): where lockgraph audits what the control plane's
threads do to each other, jaxcheck audits what the compute path does to
the chip. Four probes, one artifact:

- :mod:`.costmodel` — a jaxpr walker with a per-primitive FLOPs/bytes
  model and a live-range peak-HBM estimator that honors buffer
  donation (``donate_argnums``/``donate_argnames``), so "this config
  OOMs" becomes a prediction instead of a burned TPU-hour;
- :mod:`.memplan` — runs the cost model over the full-FT ladder and
  emits ``MEMPLAN_r01.json``, validated against the measured
  BENCH_SWEEP_r05 rungs and extrapolated to the 7B north star;
- :mod:`.pricer` — the memplan walker shaped for the control plane's
  admission path: parses+bounds a declared-workload annotation, prices
  it against the slice's HBM budget (memoized), and runs the
  auto-config advisor ladder for rejected configs;
- :mod:`.recompile` — an opt-in jit-cache sentinel
  (``KFRM_JIT_SENTINEL=1``, zero cost when off) that records
  (shape, dtype, static-arg) signatures per jitted entry point and
  flags unbounded growth — the static-shape discipline the serving
  engine's prefill buckets exist to enforce;
- :mod:`.hostsync` — probes for implicit device→host transfers
  (``bool()``, ``.item()``, ``np.asarray`` on device arrays) inside
  decode/train loops, reported with witness stacks like lockgraph's
  blocking-under-lock findings.

The static halves are lint rules KFRM006-008 in ``analysis/lint``;
``python -m kubeflow_rm_tpu.analysis.jaxcheck`` runs them plus a
cost-model self-check as the CI gate.
"""

from __future__ import annotations

from .costmodel import CostEstimate, estimate, estimate_jaxpr, selfcheck

__all__ = ["CostEstimate", "estimate", "estimate_jaxpr", "selfcheck"]
