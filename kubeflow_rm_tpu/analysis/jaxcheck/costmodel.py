"""Per-primitive FLOPs/bytes cost model and live-range peak-HBM
estimator over jaxprs.

``estimate(fn, *args, donate_argnums=...)`` traces ``fn`` abstractly
(``jax.make_jaxpr`` — shapes only, nothing materializes, so a 7B train
step costs milliseconds on a laptop) and walks the jaxpr:

- **FLOPs**: ``dot_general`` from its dimension numbers
  (2 * batch * M * N * K), elementwise/reduction primitives at one
  flop per element (transcendentals included — on TPU they are
  bandwidth-bound, not flop-bound), ``scan`` bodies multiplied by trip
  count, ``remat`` recompute counted as executed (so the model charges
  what the chip actually runs, not the 6N convention —
  ``utils.flops`` stays the MFU-accounting source of truth).
- **HBM traffic**: sum of operand+result bytes per primitive — an
  upper bound that ignores XLA fusion, useful for *relative*
  comparisons (e.g. the adam update's ~6 bytes/param/step).
- **Peak HBM**: a linear-scan liveness walk. A value is live from
  definition to last use; jaxpr invars stay resident the whole call
  *unless donated* (the caller keeps non-donated buffers), and a
  donated input's buffer is reused for outputs (XLA input/output
  aliasing), so donation shows up as a genuinely lower peak. This is
  what lets the model PROVE a non-donated train step double-buffers
  its params/optimizer state: ``peak_bytes_no_donation - peak_bytes``
  comes out to about one full TrainState.

Donation is read from two places: the ``donate_argnums`` /
``donate_argnames`` the caller passes here, and the
``donated_invars`` recorded on every ``pjit`` equation (so estimating
an already-jitted function honors the donation baked into it).

Known approximations, all conservative (over-estimating peaks):
fusion is ignored (short-lived elementwise temps count while in
scope), ``while`` bodies are costed for one trip (flagged in
``while_loops`` — FLOPs are a lower bound there), and unknown
primitives (custom/pallas calls without an inlineable jaxpr) count
bytes but zero flops, tallied in ``unknown_primitives`` rather than
silently dropped.
"""

from __future__ import annotations

import inspect
import math
from dataclasses import dataclass, field

import numpy as np

import jax
from jax import core as jax_core


# primitives that are pure data movement / bookkeeping: bytes, no flops
_MOVEMENT = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "rev",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate",
    "pad", "gather", "scatter", "scatter-add", "iota", "copy",
    "convert_element_type", "bitcast_convert_type", "device_put",
    "stop_gradient", "split", "expand_dims", "real", "imag",
    "name",  # ad_checkpoint.checkpoint_name's identity marker
    "sharding_constraint", "optimization_barrier", "select_and_scatter_add",
})

# one flop per output element (comparisons, selects, arithmetic,
# transcendentals — the table is deliberately flat; see module doc)
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "pow", "atan2",
    "and", "or", "xor", "not", "neg", "sign", "abs", "floor", "ceil",
    "round", "is_finite", "exp", "exp2", "expm1", "log", "log1p",
    "sqrt", "rsqrt", "cbrt", "logistic", "tanh", "sin", "cos", "tan",
    "asin", "acos", "atan", "sinh", "cosh", "erf", "erfc", "erf_inv",
    "eq", "ne", "lt", "le", "gt", "ge", "select_n", "clamp",
    "nextafter", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "population_count", "clz", "square",
})

# ~2 flops/element (a multiply chain or fused two-op lowering)
_TWO_FLOP = frozenset({"integer_pow", "cumsum", "cumprod", "cummax",
                       "cummin", "cumlogsumexp"})

# ops whose output can reuse a dying operand's buffer (XLA buffer
# assignment does this for elementwise lowerings; modeling it keeps a
# chained optimizer update at ~one live tree instead of one per op)
_REUSE_OK = (_ELEMENTWISE | _TWO_FLOP
             | {"convert_element_type", "copy", "reduce_precision",
                "name", "add_any"})

# one flop per INPUT element
_REDUCTION = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "reduce_precision", "sort", "top_k",
})


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    dtype = getattr(aval, "dtype", None)
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (PRNG keys etc.): jax exposes itemsize on
        # most; default to 4 rather than crash an audit
        itemsize = getattr(dtype, "itemsize", 4)
    return n * itemsize


def _dot_flops(eqn) -> float:
    (lhs_c, rhs_c), (lhs_b, _rhs_b) = eqn.params["dimension_numbers"]
    lhs, rhs = (v.aval.shape for v in eqn.invars[:2])
    batch = math.prod(int(lhs[d]) for d in lhs_b)
    contract = math.prod(int(lhs[d]) for d in lhs_c)
    lhs_free = math.prod(int(s) for d, s in enumerate(lhs)
                         if d not in lhs_c and d not in lhs_b)
    rhs_free = math.prod(int(s) for d, s in enumerate(rhs)
                         if d not in rhs_c and d not in _rhs_b)
    return 2.0 * batch * lhs_free * rhs_free * contract


def _conv_flops(eqn) -> float:
    # 2 * output elements * kernel spatial * in-features / groups
    out = math.prod(int(d) for d in eqn.outvars[0].aval.shape)
    k = eqn.invars[1].aval.shape
    spatial = math.prod(int(d) for d in k[2:])
    groups = int(eqn.params.get("feature_group_count", 1))
    return 2.0 * out * spatial * int(k[1]) * groups


@dataclass
class _Walk:
    """Accumulators threaded through one (sub)jaxpr walk."""
    flops: float = 0.0
    traffic: float = 0.0
    peak: int = 0
    unknown: dict = field(default_factory=dict)
    while_loops: int = 0


@dataclass(frozen=True)
class CostEstimate:
    """What one call of the estimated function costs the chip."""
    flops: float                    # executed flops (incl. remat recompute)
    hbm_traffic_bytes: float        # un-fused operand+result traffic
    peak_bytes: int                 # live-range peak, donation honored
    peak_bytes_no_donation: int     # same walk, donation ignored
    arg_bytes: int                  # resident input footprint
    out_bytes: int                  # result footprint
    unknown_primitives: dict        # name -> count (bytes counted, 0 flops)
    while_loops: int                # bodies costed at 1 trip (flops floor)

    @property
    def donation_savings_bytes(self) -> int:
        return self.peak_bytes_no_donation - self.peak_bytes

    def as_dict(self) -> dict:
        return {
            "flops": self.flops,
            "hbm_traffic_bytes": self.hbm_traffic_bytes,
            "peak_bytes": self.peak_bytes,
            "peak_bytes_no_donation": self.peak_bytes_no_donation,
            "arg_bytes": self.arg_bytes,
            "out_bytes": self.out_bytes,
            "unknown_primitives": dict(self.unknown_primitives),
            "while_loops": self.while_loops,
        }


def _child_jaxprs(eqn):
    """(closed_jaxpr, flop_multiplier, donated_invars) children of a
    call-like equation; empty for leaf primitives."""
    name = eqn.primitive.name
    p = eqn.params
    if name == "pjit":
        return [(p["jaxpr"], 1, p.get("donated_invars"))]
    if name == "scan":
        return [(p["jaxpr"], int(p.get("length", 1)), None)]
    if name == "while":
        return [(p["cond_jaxpr"], 1, None), (p["body_jaxpr"], 1, None)]
    if name == "cond":
        return [(b, 1, None) for b in p["branches"]]
    out = []
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = p.get(key)
        if isinstance(sub, jax_core.ClosedJaxpr):
            out.append((sub, 1, None))
        elif isinstance(sub, jax_core.Jaxpr):
            out.append((jax_core.ClosedJaxpr(sub, ()), 1, None))
    return out


def _leaf_cost(eqn) -> float:
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    out_elems = sum(
        math.prod(int(d) for d in getattr(v.aval, "shape", ()))
        for v in eqn.outvars)
    in_elems = sum(
        math.prod(int(d) for d in getattr(v.aval, "shape", ()))
        for v in eqn.invars if not isinstance(v, jax_core.Literal))
    if name in _ELEMENTWISE:
        return float(out_elems)
    if name in _TWO_FLOP:
        return 2.0 * out_elems
    if name in _REDUCTION:
        return float(in_elems)
    return 0.0


def _walk(closed: jax_core.ClosedJaxpr, donated, honor: bool,
          acc: _Walk) -> tuple[int, int, int]:
    """Liveness walk of one closed jaxpr. Returns (peak, in_bytes,
    out_bytes) for THIS jaxpr; flops/traffic/flags accumulate into
    ``acc`` (scan multipliers applied by the caller via repeated
    flop accounting below)."""
    jaxpr = closed.jaxpr
    donated = tuple(donated) if donated else (False,) * len(jaxpr.invars)

    last_use: dict = {}
    for idx, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, jax_core.Var):
                last_use[v] = idx
    for v in jaxpr.outvars:
        if isinstance(v, jax_core.Var):
            last_use[v] = len(jaxpr.eqns)  # live through the end

    live: dict = {}
    freeable: set = set()
    in_bytes = 0
    for v, const in zip(jaxpr.constvars, closed.consts):
        live[v] = _aval_bytes(v.aval)
    for i, v in enumerate(jaxpr.invars):
        live[v] = _aval_bytes(v.aval)
        in_bytes += live[v]
        if honor and i < len(donated) and donated[i]:
            freeable.add(v)

    peak = sum(live.values())
    for idx, eqn in enumerate(jaxpr.eqns):
        scratch = 0
        donated_in = 0
        children = _child_jaxprs(eqn)
        if eqn.primitive.name == "while":
            acc.while_loops += 1
        if children:
            for sub, mult, sub_donated in children:
                sub_acc = _Walk(unknown=acc.unknown)
                c_peak, c_in, c_out = _walk(sub, sub_donated, honor,
                                            sub_acc)
                acc.flops += sub_acc.flops * mult
                acc.traffic += sub_acc.traffic * mult
                acc.while_loops += sub_acc.while_loops
                scratch = max(scratch, c_peak - c_in - c_out)
            sub_donated = children[0][2]
            if honor and sub_donated:
                # a donated buffer is consumed by the call and its
                # storage reused for outputs (XLA i/o aliasing) —
                # but only when this call is the buffer's final use;
                # a later read forces XLA to copy instead of alias
                for i, v in enumerate(eqn.invars):
                    if (i < len(sub_donated) and sub_donated[i]
                            and isinstance(v, jax_core.Var)
                            and last_use.get(v) == idx and v in live):
                        donated_in += live[v]
                        freeable.add(v)
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars
                        if not isinstance(v, jax_core.DropVar))
        eqn_in_bytes = sum(
            _aval_bytes(v.aval) for v in set(
                v for v in eqn.invars if isinstance(v, jax_core.Var)))
        reused = 0
        if not children:
            acc.flops += _leaf_cost(eqn)
            if eqn.primitive.name in _REUSE_OK:
                # operand reuse is fusion modeling, not donation: it
                # applies in the no-donation walk too (temps are
                # freeable in both; donated invars only when honored)
                dying = sum(
                    live[v] for v in set(
                        v for v in eqn.invars
                        if isinstance(v, jax_core.Var))
                    if v in freeable and last_use.get(v) == idx
                    and v in live)
                reused = min(out_bytes, dying)
        acc.traffic += eqn_in_bytes + out_bytes

        out_extra = max(0, out_bytes - donated_in - reused)
        peak = max(peak, sum(live.values()) + out_extra + max(0, scratch))

        for v in eqn.outvars:
            if isinstance(v, jax_core.DropVar):
                continue
            live[v] = _aval_bytes(v.aval)
            freeable.add(v)  # temps are always reclaimable
        for v in set(v for v in eqn.invars if isinstance(v, jax_core.Var)):
            if last_use.get(v) == idx and v in freeable:
                live.pop(v, None)
    peak = max(peak, sum(live.values()))
    out_bytes_total = sum(
        _aval_bytes(v.aval) for v in jaxpr.outvars
        if isinstance(v, jax_core.Var))
    return peak, in_bytes, out_bytes_total


def estimate_jaxpr(closed: jax_core.ClosedJaxpr,
                   donated_invars=None) -> CostEstimate:
    """Cost a ClosedJaxpr directly. ``donated_invars`` is a bool per
    (flattened) invar; ``pjit`` sub-calls additionally contribute the
    donation baked into them."""
    acc = _Walk()
    peak, in_b, out_b = _walk(closed, donated_invars, True, acc)
    acc2 = _Walk()
    peak_nd, _, _ = _walk(closed, None, False, acc2)
    return CostEstimate(
        flops=acc.flops, hbm_traffic_bytes=acc.traffic,
        peak_bytes=peak, peak_bytes_no_donation=peak_nd,
        arg_bytes=in_b, out_bytes=out_b,
        unknown_primitives=_unknown_prims(closed),
        while_loops=acc.while_loops)


_KNOWN = (_MOVEMENT | _ELEMENTWISE | _TWO_FLOP | _REDUCTION
          | {"dot_general", "conv_general_dilated", "pjit", "scan",
             "while", "cond", "remat2", "checkpoint", "custom_jvp_call",
             "custom_vjp_call", "custom_vjp_call_jaxpr", "closed_call",
             "core_call", "xla_call", "random_seed", "random_wrap",
             "random_bits", "random_unwrap", "random_fold_in",
             "threefry2x32", "add_any", "select_and_gather_add",
             "erf_inv", "stop_gradient"})


def _unknown_prims(closed: jax_core.ClosedJaxpr, out=None) -> dict:
    out = {} if out is None else out
    for eqn in closed.jaxpr.eqns:
        children = _child_jaxprs(eqn)
        for sub, _, _ in children:
            _unknown_prims(sub, out)
        if not children and eqn.primitive.name not in _KNOWN:
            out[eqn.primitive.name] = out.get(eqn.primitive.name, 0) + 1
    return out


def _donated_mask(fn, args, donate_argnums, donate_argnames):
    """Flatten per-argument donation down to per-leaf invar flags, the
    layout ``jax.make_jaxpr`` presents."""
    donate = set(donate_argnums or ())
    if donate_argnames:
        try:
            params = list(inspect.signature(fn).parameters)
            donate |= {params.index(n) for n in donate_argnames}
        except (ValueError, TypeError) as exc:
            raise ValueError(
                f"cannot resolve donate_argnames={donate_argnames!r} "
                f"against {fn!r}") from exc
    mask = []
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        mask.extend([i in donate] * n)
    return tuple(mask)


def estimate(fn, *args, donate_argnums=(), donate_argnames=(),
             **kwargs) -> CostEstimate:
    """Trace ``fn(*args, **kwargs)`` abstractly and cost it. ``args``
    may be real arrays or ``jax.ShapeDtypeStruct`` trees — nothing is
    executed or materialized."""
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    mask = _donated_mask(fn, args, donate_argnums, donate_argnames)
    return estimate_jaxpr(closed, mask)


# ---------------------------------------------------------------------------
# self-check: the CI gate's smoke that the model's arithmetic is sane
# ---------------------------------------------------------------------------

def selfcheck() -> list[str]:
    """Verify the cost model against hand-computable programs. Returns
    a list of failure strings (empty = pass) so the CLI can gate on
    it without pytest."""
    import jax.numpy as jnp

    failures: list[str] = []

    def expect(label, got, want, tol=0.0):
        lo, hi = want * (1 - tol), want * (1 + tol)
        if not (lo <= got <= hi):
            failures.append(f"{label}: got {got}, want {want}"
                            + (f" ±{tol:.0%}" if tol else ""))

    # (64, 128) @ (128, 32): 2*M*N*K flops exactly
    a = jax.ShapeDtypeStruct((64, 128), jnp.float32)
    b = jax.ShapeDtypeStruct((128, 32), jnp.float32)
    est = estimate(jnp.matmul, a, b)
    expect("matmul flops", est.flops, 2 * 64 * 32 * 128)

    # donation: f(x) = x + 1 jitted with donate_argnums=(0,) must peak
    # at ~one buffer; non-donated at ~two (the double-buffer proof in
    # miniature)
    x = jax.ShapeDtypeStruct((1 << 20,), jnp.float32)
    nbytes = (1 << 20) * 4
    don = estimate(jax.jit(lambda v: v + 1.0, donate_argnums=(0,)), x)
    if not (nbytes <= don.peak_bytes < 2 * nbytes):
        failures.append(f"donated peak {don.peak_bytes} not in "
                        f"[{nbytes}, {2 * nbytes})")
    if don.peak_bytes_no_donation < 2 * nbytes:
        failures.append(f"non-donated peak {don.peak_bytes_no_donation}"
                        f" < {2 * nbytes}: double-buffer not modeled")

    # scan multiplies body flops by trip count
    def scanned(v):
        return jax.lax.scan(lambda c, _: (c * 2.0, None), v,
                            None, length=10)[0]
    est = estimate(scanned, x)
    expect("scan flops", est.flops, 10 * (1 << 20))

    return failures
