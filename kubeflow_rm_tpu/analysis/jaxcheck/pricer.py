"""Admission-time workload pricing: the memplan walker as a service.

:mod:`.memplan` walks the real jitted train step of a fixed ladder and
writes an offline artifact. This module is the same machinery shaped
for the control plane's admission path: a **declared workload** (the
JSON a user puts in ``tpu.kubeflow.org/declared-workload``) is parsed,
bounded, traced abstractly (eval_shape — nothing materializes, no
device needed) and priced against the target slice's HBM budget. The
verdict carries the full breakdown (params / grads / optimizer state /
logits / workspace), which phase binds, and the predicted FLOPs per
step the scheduler uses as a packing tiebreak.

Two things make this admissible in a webhook:

- **a memo cache** keyed by the canonical declaration + chip count:
  tracing a 2.7B step costs seconds of CPU, but every replica of a
  storm declares the same few configs, so the steady state is a dict
  lookup under a leaf lock;
- **hard schema bounds** (layer/dim/seq/batch caps) so a hostile
  declaration can't turn the webhook into a tracing DoS.

The **advisor** (:func:`advise`) answers the natural follow-up to a
rejection: walk a short ladder of progressively cheaper knob settings
(remat=full -> halve the microbatch -> offload=optimizer -> both) and
return the first rung that fits — the exact dict the user can paste
back into the declaration, priced by the same walker that rejected the
original.

Sharding model: the declared step is priced on ONE chip and divided by
the slice's chip count (fsdp shards params/grads/opt state and the
batch dimension — the same per-chip ≈ peak/chips assumption
MEMPLAN_r01's v5p-8 north-star row uses). The budget applies the bench
family's usable-HBM fraction (15.75/16) and the 5% allocator margin.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from kubeflow_rm_tpu.analysis.lockgraph import make_lock
from kubeflow_rm_tpu.analysis.jaxcheck.memplan import (
    CHIP_HBM_GIB,
    GB,
    HBM_MARGIN,
    USABLE_GIB,
)

#: fraction of raw HBM the allocator exposes (bench.py's measured
#: 15.75/16 figure, applied uniformly across generations)
USABLE_FRACTION = USABLE_GIB / CHIP_HBM_GIB

OPTIMS = ("adamw", "adafactor")
REMATS = ("dots", "full", "attn", "mlp", "attn+mlp")
OFFLOADS = (None, "optimizer")

# schema bounds: a declaration is user input reaching an abstract
# tracer — cap everything that scales trace cost
MAX_LAYERS = 200
MAX_DIM = 32768
MAX_SEQ = 65536
MAX_BATCH = 65536
MAX_VOCAB = 1_000_000

_MODEL_DIM_KEYS = ("dim", "n_layers", "n_heads", "n_kv_heads",
                   "hidden_dim", "vocab_size")


class DeclarationError(ValueError):
    """The declared-workload JSON is malformed or out of bounds."""


@dataclass(frozen=True)
class DeclaredWorkload:
    """A parsed, bounds-checked workload declaration."""
    preset: str | None           # LlamaConfig preset name, or None
    model: tuple | None          # explicit dims (sorted kv pairs)
    optim: str = "adafactor"
    batch: int = 32
    grad_accum: int = 32
    remat: str = "full"
    seq: int | None = None       # None: the preset's max_seq_len
    param_dtype: str = "bfloat16"
    offload: str | None = None
    tenant: str = "default"

    @property
    def microbatch(self) -> int:
        return self.batch // self.grad_accum

    def to_dict(self) -> dict:
        d = {"optim": self.optim, "batch": self.batch,
             "grad_accum": self.grad_accum, "remat": self.remat,
             "param_dtype": self.param_dtype}
        if self.preset:
            d["preset"] = self.preset
        if self.model:
            d["model"] = dict(self.model)
        if self.seq:
            d["seq"] = self.seq
        if self.offload:
            d["offload"] = self.offload
        if self.tenant != "default":
            d["tenant"] = self.tenant
        return d

    def key(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))


def parse(raw: str | dict) -> DeclaredWorkload:
    """Parse + validate a declaration. Raises :class:`DeclarationError`
    on anything malformed — callers degrade to chip-count-only
    admission, they do not reject."""
    if isinstance(raw, str):
        try:
            raw = json.loads(raw)
        except (TypeError, ValueError) as e:
            raise DeclarationError(f"not valid JSON: {e}") from None
    if not isinstance(raw, dict):
        raise DeclarationError("declaration must be a JSON object")

    preset = raw.get("preset")
    model_raw = raw.get("model")
    if preset is not None:
        from kubeflow_rm_tpu.models.llama import LlamaConfig
        if not isinstance(preset, str) or not hasattr(LlamaConfig,
                                                      preset) \
                or preset.startswith("_"):
            raise DeclarationError(f"unknown model preset {preset!r}")
        model = None
    elif model_raw is not None:
        if not isinstance(model_raw, dict):
            raise DeclarationError("model must be an object of dims")
        dims = {}
        for k in _MODEL_DIM_KEYS:
            v = model_raw.get(k)
            if not isinstance(v, int) or v < 1:
                raise DeclarationError(
                    f"model.{k} must be a positive int")
            dims[k] = v
        if dims["n_layers"] > MAX_LAYERS or dims["dim"] > MAX_DIM \
                or dims["vocab_size"] > MAX_VOCAB:
            raise DeclarationError("model dims exceed pricing bounds")
        if dims["dim"] % dims["n_heads"] != 0:
            raise DeclarationError("dim must divide by n_heads")
        model = tuple(sorted(dims.items()))
    else:
        raise DeclarationError(
            "declaration needs 'preset' or explicit 'model' dims")

    optim = raw.get("optim", "adafactor")
    if optim not in OPTIMS:
        raise DeclarationError(f"optim must be one of {OPTIMS}")
    remat = raw.get("remat", "full")
    if remat not in REMATS:
        raise DeclarationError(f"remat must be one of {REMATS}")
    offload = raw.get("offload")
    if offload not in OFFLOADS:
        raise DeclarationError(f"offload must be one of {OFFLOADS}")
    batch = raw.get("batch", 32)
    accum = raw.get("grad_accum", batch)
    for name, v, cap in (("batch", batch, MAX_BATCH),
                         ("grad_accum", accum, MAX_BATCH)):
        if not isinstance(v, int) or not 1 <= v <= cap:
            raise DeclarationError(
                f"{name} must be an int in [1, {cap}]")
    if batch % accum != 0:
        raise DeclarationError("batch must divide by grad_accum")
    seq = raw.get("seq")
    if seq is not None and (not isinstance(seq, int)
                            or not 16 <= seq <= MAX_SEQ):
        raise DeclarationError(f"seq must be an int in [16, {MAX_SEQ}]")
    param_dtype = raw.get("param_dtype", "bfloat16")
    if param_dtype not in ("bfloat16", "float32"):
        raise DeclarationError(
            "param_dtype must be 'bfloat16' or 'float32'")
    tenant = raw.get("tenant", "default")
    if not isinstance(tenant, str) or len(tenant) > 63:
        raise DeclarationError("tenant must be a short string")
    return DeclaredWorkload(preset=preset, model=model, optim=optim,
                            batch=batch, grad_accum=accum, remat=remat,
                            seq=seq, param_dtype=param_dtype,
                            offload=offload, tenant=tenant)


# ---- the walker ------------------------------------------------------

def _tree_bytes(tree) -> int:
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "size"):
            total += leaf.size * getattr(leaf.dtype, "itemsize", 4)
    return total


def _model_config(decl: DeclaredWorkload):
    import jax.numpy as jnp

    from kubeflow_rm_tpu.models.llama import LlamaConfig

    kw: dict = {
        "param_dtype": (jnp.bfloat16 if decl.param_dtype == "bfloat16"
                        else jnp.float32),
        "remat_policy": decl.remat,
    }
    if decl.seq:
        kw["max_seq_len"] = decl.seq
    if decl.preset:
        return getattr(LlamaConfig, decl.preset)(**kw)
    return LlamaConfig(**dict(decl.model), **kw)


def _walk(decl: DeclaredWorkload) -> dict:
    """Trace the declared step and return the raw byte/flop tallies.
    Expensive (seconds) — always reached through the memo cache."""
    import jax
    import jax.numpy as jnp

    from kubeflow_rm_tpu.analysis.jaxcheck.costmodel import estimate
    from kubeflow_rm_tpu.parallel import MeshConfig, make_mesh
    from kubeflow_rm_tpu.training.optim import OptimConfig
    from kubeflow_rm_tpu.training.train import (
        TrainConfig, init_train_state, make_train_step,
    )

    model = _model_config(decl)
    optim_kw: dict = {"factored": decl.optim == "adafactor"}
    if decl.offload:
        optim_kw["offload"] = decl.offload
    cfg = TrainConfig(model=model, optim=OptimConfig(**optim_kw))
    state = jax.eval_shape(
        lambda k: init_train_state(cfg, k), jax.random.PRNGKey(0))
    mesh = make_mesh(MeshConfig(), jax.devices()[:1])
    batch = {k: jax.ShapeDtypeStruct((decl.batch, model.max_seq_len),
                                     jnp.int32)
             for k in ("tokens", "labels")}
    params_b = _tree_bytes(state.params)
    opt_b = _tree_bytes(state.opt_state)

    if decl.offload == "optimizer":
        # the streamed step: on-chip peak = jitted grad phase + the
        # step's own double-buffered stream slot; mu/nu and the update
        # transient live host-side (memplan.offload_native_rows)
        step = make_train_step(cfg, mesh, state,
                               grad_accum=decl.grad_accum,
                               offload="optimizer")
        est = estimate(step.grad_phase, state.params, batch)
        peak = est.peak_bytes + step.stream_slot_bytes
        opt_resident_b = 0
    else:
        step = make_train_step(cfg, mesh, state,
                               grad_accum=decl.grad_accum)
        est = estimate(step, state, batch)
        peak = est.peak_bytes
        opt_resident_b = opt_b

    logits_b = decl.microbatch * model.max_seq_len * model.vocab_size * 4
    grads_b = params_b
    workspace_b = max(0, peak - params_b - grads_b - opt_resident_b
                      - logits_b)
    return {
        "peak_bytes": int(peak),
        "params_bytes": params_b,
        "grads_bytes": grads_b,
        "opt_state_bytes": opt_resident_b,
        "logits_bytes": logits_b,
        "workspace_bytes": workspace_b,
        "flops_per_step": float(est.flops),
        "seq": model.max_seq_len,
        "n_params": params_b // (2 if decl.param_dtype == "bfloat16"
                                 else 4),
    }


_cache: dict[str, dict] = {}
_cache_lock = make_lock("jaxcheck.pricer")


def _walk_cached(decl: DeclaredWorkload) -> dict:
    key = decl.key()
    with _cache_lock:
        hit = _cache.get(key)
    if hit is not None:
        return hit
    out = _walk(decl)
    with _cache_lock:
        _cache[key] = out
    return out


def cache_clear() -> None:
    with _cache_lock:
        _cache.clear()


def budget_bytes_per_chip(hbm_gib_per_chip: float) -> int:
    """Usable-HBM budget per chip, in bytes."""
    return int(hbm_gib_per_chip * USABLE_FRACTION * (2 ** 30))


def _binding_phase(walk: dict) -> str:
    """Which component binds the peak — the explanation's headline."""
    state_b = (walk["params_bytes"] + walk["grads_bytes"]
               + walk["opt_state_bytes"])
    parts = {"state (params+grads+optimizer)": state_b,
             "logits": walk["logits_bytes"],
             "backward workspace": walk["workspace_bytes"]}
    return max(parts, key=parts.get)


def price(decl: DeclaredWorkload, *, chips: int,
          hbm_gib_per_chip: float = CHIP_HBM_GIB) -> dict:
    """Price ``decl`` on a ``chips``-chip slice. Returns the admission
    verdict dict the webhook writes into the CR status."""
    walk = _walk_cached(decl)
    budget = budget_bytes_per_chip(hbm_gib_per_chip)
    per_chip = walk["peak_bytes"] / max(1, chips)
    fit = per_chip * (1 + HBM_MARGIN) <= budget
    binds = _binding_phase(walk)
    verdict = {
        "verdict": "fit" if fit else "rejected",
        "workload": decl.to_dict(),
        "chips": chips,
        "predicted_peak_gb": round(walk["peak_bytes"] / GB, 2),
        "predicted_peak_per_chip_gb": round(per_chip / GB, 2),
        "budget_per_chip_gb": round(budget / GB, 2),
        "hbm_margin": HBM_MARGIN,
        "binds": binds,
        "breakdown_gb": {
            "params": round(walk["params_bytes"] / GB, 2),
            "grads": round(walk["grads_bytes"] / GB, 2),
            "opt_state": round(walk["opt_state_bytes"] / GB, 2),
            "logits": round(walk["logits_bytes"] / GB, 2),
            "workspace": round(walk["workspace_bytes"] / GB, 2),
        },
        "flops_per_step": walk["flops_per_step"],
        "n_params": walk["n_params"],
        "tenant": decl.tenant,
    }
    verdict["explanation"] = (
        f"predicted peak {verdict['predicted_peak_per_chip_gb']} GB"
        f"/chip (x{chips} chips, {verdict['predicted_peak_gb']} GB "
        f"total) {'fits' if fit else 'exceeds'} the "
        f"{verdict['budget_per_chip_gb']} GB usable budget at a "
        f"{int(HBM_MARGIN * 100)}% allocator margin; "
        f"{binds} binds the peak")
    return verdict


# ---- the advisor -----------------------------------------------------

def _ladder(decl: DeclaredWorkload) -> list[DeclaredWorkload]:
    """Progressively cheaper rungs, least disruptive first. Each rung
    is a full declaration the user can paste back verbatim."""
    from dataclasses import replace

    rungs: list[DeclaredWorkload] = []

    def push(d: DeclaredWorkload) -> None:
        if d != decl and d not in rungs:
            rungs.append(d)

    cur = decl
    if cur.remat != "full":
        cur = replace(cur, remat="full")
        push(cur)
    # shrink the microbatch (batch stays: more accumulation steps)
    mb_rung = cur
    while mb_rung.microbatch > 1:
        mb_rung = replace(mb_rung, grad_accum=mb_rung.grad_accum * 2)
        if mb_rung.batch % mb_rung.grad_accum != 0:
            break
        push(mb_rung)
    # stream the optimizer update through host RAM
    off = replace(cur, offload="optimizer")
    push(off)
    off_mb = off
    while off_mb.microbatch > 1:
        off_mb = replace(off_mb, grad_accum=off_mb.grad_accum * 2)
        if off_mb.batch % off_mb.grad_accum != 0:
            break
        push(off_mb)
    return rungs[:8]   # bound the webhook's worst-case trace count


def advise(decl: DeclaredWorkload, *, chips: int,
           hbm_gib_per_chip: float = CHIP_HBM_GIB) -> dict | None:
    """The cheapest passing rung for a rejected declaration: the first
    ladder entry that fits, with its own priced verdict. None when no
    rung fits (the slice is simply too small)."""
    for rung in _ladder(decl):
        v = price(rung, chips=chips,
                  hbm_gib_per_chip=hbm_gib_per_chip)
        if v["verdict"] == "fit":
            return {
                "workload": rung.to_dict(),
                "predicted_peak_per_chip_gb":
                    v["predicted_peak_per_chip_gb"],
                "budget_per_chip_gb": v["budget_per_chip_gb"],
                "note": _advice_note(decl, rung),
            }
    return None


def _advice_note(decl: DeclaredWorkload, rung: DeclaredWorkload) -> str:
    changes = []
    if rung.remat != decl.remat:
        changes.append(f"remat={rung.remat}")
    if rung.grad_accum != decl.grad_accum:
        changes.append(f"grad_accum={rung.grad_accum} "
                       f"(microbatch {decl.microbatch}"
                       f"->{rung.microbatch})")
    if rung.offload != decl.offload:
        changes.append(f"offload={rung.offload}")
    return "cheapest passing rung: " + ", ".join(changes)
