"""An opt-in jit-cache sentinel: catch unbounded retracing in the
serving/training hot paths.

Every distinct (shape, dtype, static-arg) signature a jitted entry
point sees is a fresh XLA compile — seconds of latency and a cache
entry that never goes away. The serving engine's prefill buckets
(``models/generate.py _bucket_len``) exist precisely to bound this:
ragged request lengths collapse onto power-of-two buckets, so the
signature count stays at most ``log2(slot_len) + 1`` no matter what
the storm looks like. Nothing asserted that invariant — this module
does.

Same contract as :mod:`..lockgraph`: **off by default, zero cost when
off**. Enable with ``KFRM_JIT_SENTINEL=1`` (or :func:`set_enabled`)
and the instrumented call sites record each entry point's argument
signatures; :func:`over_limit` reports any entry whose signature
count exceeded its declared bucket bound, with a witness stack (first
12 frames) for the signature that crossed the line — the lockgraph
witness convention.

Instrumentation points (all no-ops when disabled):

- ``note(entry, *args, **static)`` — record the signature the entry
  point is about to be called with. Arrays contribute
  ``(shape, dtype)`` per leaf; everything else is static and
  contributes its ``repr``.
- ``set_limit(entry, n)`` — declare the expected signature bound
  (the engine declares ``log2(slot_len) + 1`` prefill buckets).
- ``track(entry, fn)`` — associate the actual jitted callable so
  :func:`report` can cross-check the recorded signature count
  against ``fn._cache_size()`` (the compiled-executable count XLA
  itself holds).
"""

from __future__ import annotations

import os
import threading
import traceback

_ENV = "KFRM_JIT_SENTINEL"
_enabled = os.environ.get(_ENV, "").strip().lower() not in (
    "", "0", "false", "no")

_STACK_LIMIT = 12

# the probe's own guard cannot come from the lockgraph factory —
# instrumentation must not recurse into the instrumented layer
# (same exemption lockgraph.py itself takes).
_lock = threading.Lock()  # kfrm: disable=KFRM001
_entries: dict[str, dict] = {}
_tracked: dict[str, object] = {}
_observers: list = []


def add_observer(fn) -> None:
    """``fn(entry, n_signatures)`` on every NEW compile signature —
    the control plane's fleet-SLO bridge hangs here (the probe itself
    stays importable without the control plane). Idempotent per
    callable; observers fire outside the probe lock."""
    with _lock:
        if fn not in _observers:
            _observers.append(fn)


def remove_observer(fn) -> None:
    with _lock:
        if fn in _observers:
            _observers.remove(fn)


def enabled() -> bool:
    """Whether the sentinel is recording."""
    return _enabled


def set_enabled(value: bool) -> None:
    """Programmatic override of the ``KFRM_JIT_SENTINEL`` gate (tests
    flip this instead of mutating the environment)."""
    global _enabled
    _enabled = bool(value)


def reset() -> None:
    """Drop all recorded signatures, limits and tracked callables."""
    with _lock:
        _entries.clear()
        _tracked.clear()


def _signature(args: tuple, static: dict) -> tuple:
    """A hashable compile signature: (shape, dtype) per array leaf,
    ``repr`` for everything else — the same partitioning jit's tracing
    cache keys on for a bucketed call site."""
    import jax

    parts = []
    for a in args:
        for leaf in jax.tree_util.tree_leaves(a):
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                parts.append((tuple(leaf.shape), str(leaf.dtype)))
            else:
                parts.append(repr(leaf))
    for k in sorted(static):
        parts.append((k, repr(static[k])))
    return tuple(parts)


def _entry(name: str) -> dict:
    e = _entries.get(name)
    if e is None:
        e = _entries[name] = {"signatures": {}, "limit": None,
                              "witnesses": []}
    return e


def set_limit(entry: str, limit: int) -> None:
    """Declare the expected signature bound for ``entry``."""
    if not _enabled:
        return
    with _lock:
        _entry(entry)["limit"] = int(limit)


def track(entry: str, fn) -> None:
    """Associate the jitted callable behind ``entry`` so ``report()``
    can read its real compile-cache size."""
    if not _enabled:
        return
    with _lock:
        _tracked[entry] = fn


def note(entry: str, *args, **static) -> None:
    """Record the signature ``entry`` is being called with.

    Call this immediately before the jitted call with the same
    positional arrays and keyword statics. No-op (one attribute read)
    when the sentinel is disabled.
    """
    if not _enabled:
        return
    sig = _signature(args, static)
    with _lock:
        e = _entry(entry)
        seen = e["signatures"]
        if sig in seen:
            seen[sig] += 1
            return
        seen[sig] = 1
        n = len(seen)
        limit = e["limit"]
        if limit is not None and n > limit:
            stack = traceback.format_list(
                traceback.extract_stack(limit=_STACK_LIMIT)[:-1])
            e["witnesses"].append({
                "entry": entry,
                "signature": sig,
                "count": n,
                "limit": limit,
                "stack": "".join(stack),
            })
        observers = list(_observers)
    for fn in observers:
        fn(entry, n)


def cache_size(entry: str) -> int | None:
    """The tracked callable's real compiled-executable count, or None
    if the entry isn't tracked / the callable doesn't expose it."""
    fn = _tracked.get(entry)
    size = getattr(fn, "_cache_size", None)
    return size() if callable(size) else None


def report() -> dict:
    """Per-entry signature counts, limits, cache sizes and witnesses."""
    with _lock:
        out = {}
        for name, e in _entries.items():
            out[name] = {
                "signatures": len(e["signatures"]),
                "calls": sum(e["signatures"].values()),
                "limit": e["limit"],
                "jit_cache_size": cache_size(name),
                "witnesses": list(e["witnesses"]),
            }
        return out


def over_limit() -> list[dict]:
    """Entries whose recorded signature count exceeds their declared
    limit — each with the witness stacks for the crossing signatures.
    Empty list == the storm stayed inside its buckets."""
    findings = []
    for name, info in report().items():
        if info["limit"] is not None and \
                info["signatures"] > info["limit"]:
            findings.append({
                "entry": name,
                "signatures": info["signatures"],
                "limit": info["limit"],
                "jit_cache_size": info["jit_cache_size"],
                "witnesses": info["witnesses"],
            })
    return findings
