"""CLI: ``python -m kubeflow_rm_tpu.analysis.jaxcheck [paths...]``.

The compute-path audit gate: runs the jaxcheck lint rules
(KFRM006-008) over the tree AND the cost model's self-check
(:func:`costmodel.selfcheck` — exact FLOPs on a known matmul, the
donation double-buffer proof, scan trip-count accounting). Exit
status: 0 clean, 1 findings or a failed self-check, 2 usage error —
the same contract as ``analysis.lint``, so CI wires both identically.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..lint import lint_paths

#: the compute-path rules this gate owns (KFRM001-005 stay with the
#: concurrency gate)
JAXCHECK_RULES = frozenset({"KFRM006", "KFRM007", "KFRM008"})


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_rm_tpu.analysis.jaxcheck",
        description="jaxpr-level TPU program audit: lint rules "
                    "KFRM006-008 + cost-model self-check")
    parser.add_argument("paths", nargs="*", default=["kubeflow_rm_tpu"],
                        help="files or directories (default: "
                             "kubeflow_rm_tpu)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable report on stdout")
    parser.add_argument("--no-selfcheck", action="store_true",
                        help="skip the cost-model self-check (lint "
                             "only; the CI gate never passes this)")
    args = parser.parse_args(argv)

    findings = lint_paths(args.paths or ["kubeflow_rm_tpu"],
                          set(JAXCHECK_RULES))

    failures: list[str] = []
    if not args.no_selfcheck:
        from .costmodel import selfcheck
        failures = selfcheck()

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in findings],
            "selfcheck_failures": failures,
        }, indent=1))
    else:
        for f in findings:
            print(f.render())
        for msg in failures:
            print(f"costmodel selfcheck: {msg}")
        if findings or failures:
            print(f"\n{len(findings)} finding(s), "
                  f"{len(failures)} selfcheck failure(s)",
                  file=sys.stderr)
    return 1 if (findings or failures) else 0


if __name__ == "__main__":
    sys.exit(main())
