"""An opt-in probe for implicit device→host transfers in hot loops.

``bool(x)``, ``x.item()``, ``int(x)``, ``float(x)`` and
``np.asarray(x)`` on a device array all block the Python thread until
the TPU stream drains and the value lands on host — one hidden
round-trip per token in a decode loop, or per step in a training
loop, is enough to serialize the accelerator behind Python. The
static half of this audit is lint rule KFRM006; this module is the
dynamic half: it patches the sync entry points on jax's array class
and records a witness (with the enclosing region and the first 12
stack frames — lockgraph's witness convention) for every implicit
sync that fires inside a declared hot region.

Same contract as :mod:`..lockgraph`: **off by default, zero cost when
off** — ``region()`` returns a shared null context manager and no
patching happens until :func:`install` runs. Enable with
``KFRM_HOSTSYNC_PROBE=1`` (or :func:`set_enabled` + :func:`install`).

Usage::

    from kubeflow_rm_tpu.analysis.jaxcheck import hostsync
    hostsync.install()                     # no-op unless enabled
    with hostsync.region("decode-step"):
        ...                                # hot loop body
    hostsync.witnesses()                   # -> [{kind, region, stack}]

Deliberate syncs are fine outside regions (a metrics fetch at a log
boundary); witnesses are only recorded while a region is open on the
calling thread, so instrumenting a loop costs nothing in reports
unless the loop actually syncs.

The host-offload train step (r18) moved a *deliberate* device→host
stream inside the ``train.step`` hot region — the transfers ARE the
feature there, not a bug. :func:`sanctioned` is the escape hatch: a
nested context naming the site (``train.offload_stream``) under which
syncs are tallied per-site (:func:`sanctioned_counts`) instead of
witnessed. An unsanctioned sync inside the same region still trips
(tests/test_jaxcheck.py pins this), so the probe keeps its teeth.
"""

from __future__ import annotations

import contextlib
import os
import threading
import traceback

_ENV = "KFRM_HOSTSYNC_PROBE"
_enabled = os.environ.get(_ENV, "").strip().lower() not in (
    "", "0", "false", "no")

_STACK_LIMIT = 12

# the probe's own guard cannot come from the lockgraph factory —
# instrumentation must not recurse into the instrumented layer
# (same exemption lockgraph.py itself takes).
_lock = threading.Lock()  # kfrm: disable=KFRM001
_observers: list = []
_witnesses: list[dict] = []
_sanctioned_counts: dict[tuple[str, str], int] = {}  # (site, kind) -> n
_installed = False
_originals: list[tuple] = []   # (owner, attr, original) for uninstall
_tls = threading.local()

#: the implicit-sync entry points on jax's concrete array class.
#: ``__array__`` is deliberately absent: numpy reaches the array's
#: buffer via the C protocol, bypassing a Python-level patch — the
#: ``np.asarray``/``np.array`` call sites are wrapped instead.
_SYNC_METHODS = ("__bool__", "__int__", "__float__", "__index__",
                 "item", "tolist")
_NUMPY_FUNCS = ("asarray", "array")


def enabled() -> bool:
    """Whether the probe is active."""
    return _enabled


def set_enabled(value: bool) -> None:
    """Programmatic override of the ``KFRM_HOSTSYNC_PROBE`` gate."""
    global _enabled
    _enabled = bool(value)


def _regions() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _sanctioned_stack() -> list:
    stack = getattr(_tls, "sanctioned", None)
    if stack is None:
        stack = _tls.sanctioned = []
    return stack


# nullcontext is reusable AND reentrant, so one shared instance
# serves every disabled region() call forever — zero allocation on
# the production path
_NULL = contextlib.nullcontext()


def region(name: str):
    """Declare a hot region: implicit syncs on this thread are
    recorded as witnesses while it is open. Returns a shared null
    context manager when the probe is disabled — safe to leave in
    production loops."""
    if not _enabled:
        return _NULL

    @contextlib.contextmanager
    def _cm():
        _regions().append(name)
        try:
            yield
        finally:
            _regions().pop()

    return _cm()


def sanctioned(site: str):
    """Declare a deliberate-sync site: syncs on this thread while the
    context is open are counted under ``site`` instead of witnessed —
    the escape hatch for transfers that ARE the feature (the offload
    step's ``train.offload_stream``). Null and free when the probe is
    disabled; syncs outside the context (even inside the same hot
    region) still trip as witnesses."""
    if not _enabled:
        return _NULL

    @contextlib.contextmanager
    def _cm():
        _sanctioned_stack().append(site)
        try:
            yield
        finally:
            _sanctioned_stack().pop()

    return _cm()


def _record(kind: str) -> None:
    stack = _regions()
    if not stack:
        return
    sanction = _sanctioned_stack()
    if sanction:
        with _lock:
            k = (sanction[-1], kind)
            _sanctioned_counts[k] = _sanctioned_counts.get(k, 0) + 1
        return
    frames = traceback.format_list(
        traceback.extract_stack(limit=_STACK_LIMIT)[:-2])
    with _lock:
        _witnesses.append({
            "kind": kind,
            "region": stack[-1],
            "stack": "".join(frames),
        })
        observers = list(_observers)
    for fn in observers:
        fn(stack[-1], kind)


def add_observer(fn) -> None:
    """``fn(region, kind)`` on every UNSANCTIONED implicit sync inside
    an open region — the control plane's fleet-SLO bridge hangs here.
    Idempotent per callable; observers fire outside the probe lock."""
    with _lock:
        if fn not in _observers:
            _observers.append(fn)


def remove_observer(fn) -> None:
    with _lock:
        if fn in _observers:
            _observers.remove(fn)


def _wrap(cls, name: str):
    orig = getattr(cls, name)

    def probe(self, *args, **kwargs):
        _record(name)
        return orig(self, *args, **kwargs)

    probe.__name__ = name
    probe.__qualname__ = f"{cls.__name__}.{name}"
    return orig, probe


def install() -> bool:
    """Patch the sync entry points on jax's concrete array class.

    Idempotent; returns True if the probe is (now) installed. No-op
    when disabled — importing jax is deferred to here, so a disabled
    probe costs nothing at import time.
    """
    global _installed
    if not _enabled:
        return False
    with _lock:
        if _installed:
            return True
        import jax
        import numpy as np

        cls = type(jax.numpy.zeros(()))
        for name in _SYNC_METHODS:
            if not hasattr(cls, name):
                continue
            orig, probe = _wrap(cls, name)
            _originals.append((cls, name, orig))
            setattr(cls, name, probe)

        def _np_wrap(label, orig):
            def probe(a, *args, **kwargs):
                if isinstance(a, cls):
                    _record(label)
                return orig(a, *args, **kwargs)

            probe.__name__ = orig.__name__
            return probe

        for fname in _NUMPY_FUNCS:
            orig = getattr(np, fname)
            _originals.append((np, fname, orig))
            setattr(np, fname, _np_wrap(f"np.{fname}", orig))
        _installed = True
        return True


def uninstall() -> None:
    """Restore the original methods (tests pair this with install)."""
    global _installed
    with _lock:
        for owner, name, orig in _originals:
            setattr(owner, name, orig)
        _originals.clear()
        _installed = False


def witnesses() -> list[dict]:
    """All recorded implicit-sync witnesses."""
    with _lock:
        return list(_witnesses)


def sanctioned_counts() -> dict:
    """``{(site, kind): count}`` for syncs under :func:`sanctioned` —
    the observability half of the escape hatch (the offload stream's
    transfer count shows up here, not in :func:`witnesses`)."""
    with _lock:
        return dict(_sanctioned_counts)


def reset() -> None:
    """Drop recorded witnesses and sanctioned-site tallies (the patch,
    if installed, remains)."""
    with _lock:
        _witnesses.clear()
        _sanctioned_counts.clear()
