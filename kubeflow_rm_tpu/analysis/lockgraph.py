"""Dynamic lock-order analysis: an instrumented lock factory.

Every control-plane module constructs its locks through
:func:`make_lock` / :func:`make_rlock` / :func:`make_condition`
instead of bare ``threading`` primitives (the KFRM001 lint rule
ratchets this). The factory has two modes:

**Off (default):** each call returns the *raw* ``threading``
primitive — not a wrapper, the actual object — so production and
test hot paths pay nothing. ``tests/test_lockgraph.py`` pins this
with an identity check.

**On** (``KFRM_LOCK_ANALYSIS=1`` in the environment at import, or
:func:`set_enabled` before the control plane is built): each call
returns an instrumented wrapper that feeds a process-global
:class:`LockAnalysis`:

- **held-sets** — a thread-local stack of (lock, acquire-time)
  entries maintained across acquire/release and ``Condition.wait``
  (which releases the lock for the duration of the wait);
- **acquisition-order graph** — on every acquire, one directed edge
  per currently-held lock name → acquired lock name, with a witness
  stack *pair* (where the held lock was taken, where the new one
  was) captured on first observation;
- **cycle detection** — :meth:`LockAnalysis.cycles` runs Tarjan SCC
  over the name graph; any non-trivial SCC is a potential deadlock,
  reported with the witness stacks of its edges;
- **ordered groups** — many-instance lock families acquired in a
  deterministic sort order (the scheduler's per-node locks) pass a
  ``rank``; acquiring a lower-ranked sibling while holding a
  higher-ranked one is an **order violation** (the intra-group
  analogue of a cycle), and clean same-name nesting is excluded
  from the cycle graph;
- **blocking-under-lock** — ``os.fsync``, ``time.sleep``,
  ``subprocess.run``-family, ``socket.create_connection`` and
  ``http.client`` request/response (the kubeclient's transport) are
  probed while analysis is on; a call with any registered lock held
  is recorded with the held-set and a witness stack;
- **held-time percentiles** — per lock name, p50/p95/p99/max of
  lock hold duration from a bounded reservoir.

:func:`report` serializes all of it (the ``LOCKGRAPH_r01.json``
artifact the spawn/oversubscription storms export); :func:`reset`
clears state between deterministic test scenarios.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback

__all__ = [
    "enabled", "set_enabled", "make_lock", "make_rlock",
    "make_condition", "analysis", "report", "reset", "dump",
]

_ENV = "KFRM_LOCK_ANALYSIS"

# how many stack frames a witness keeps (innermost last)
_STACK_LIMIT = 12
# held-time reservoir bound per lock name
_RESERVOIR = 8192

_enabled = os.environ.get(_ENV, "").strip().lower() not in (
    "", "0", "false", "no")

_tls = threading.local()


def _held_list() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Flip analysis mode. Must be called BEFORE the locks to observe
    are constructed — existing raw primitives stay raw. Turning on
    installs the blocking-call probes; turning off removes them."""
    global _enabled
    _enabled = bool(on)
    if _enabled:
        _install_probes()
    else:
        _uninstall_probes()


class _Held:
    """One entry of a thread's held-set: the wrapper, its acquire
    timestamp, the acquire stack (witness material), and a recursion
    count for reentrant locks."""

    __slots__ = ("lock", "t0", "stack", "count")

    def __init__(self, lock, t0, stack):
        self.lock = lock
        self.t0 = t0
        self.stack = stack
        self.count = 1


class _SiteStats:
    __slots__ = ("acquires", "samples", "held_max", "held_sum",
                 "held_n", "ranked")

    def __init__(self):
        self.acquires = 0
        self.samples: list[float] = []
        self.held_max = 0.0
        self.held_sum = 0.0
        self.held_n = 0
        self.ranked = False


def _pct(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    if len(samples) == 1:
        return samples[0]
    pos = q * (len(samples) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(samples) - 1)
    return samples[lo] + (samples[hi] - samples[lo]) * (pos - lo)


def _fmt_stack(stack) -> str:
    return "".join(traceback.format_list(stack)).rstrip()


class LockAnalysis:
    """Process-global accumulator behind the instrumented wrappers.

    All mutation happens under one raw guard — analysis mode trades
    some acquire-path serialization for observability, which is why
    it is opt-in and why the off path returns raw primitives."""

    def __init__(self):
        # the analyser's own guard must be a raw primitive: an
        # instrumented one would recurse into itself
        self._guard = threading.Lock()  # kfrm: disable=KFRM001
        self._sites: dict[str, _SiteStats] = {}
        # (held_name, acquired_name) -> {count, held_stack, acq_stack}
        self._edges: dict[tuple[str, str], dict] = {}
        # same-name rank inversions: name -> {count, witness...}
        self._order_violations: dict[str, dict] = {}
        # (op, held-names tuple) -> {count, stack}
        self._blocking: dict[tuple[str, tuple], dict] = {}

    # -- feed (called by the wrappers) ---------------------------------
    def on_acquired(self, lock, held: list, stack) -> None:
        with self._guard:
            st = self._sites.get(lock.name)
            if st is None:
                st = self._sites[lock.name] = _SiteStats()
            st.acquires += 1
            if lock.rank is not None:
                st.ranked = True
            for h in held:
                other = h.lock
                if other is lock:
                    continue
                if other.name == lock.name:
                    # intra-group nesting (e.g. sorted per-node locks):
                    # legal iff ranks are acquired in ascending order
                    if (other.rank is not None and lock.rank is not None
                            and other.rank > lock.rank):
                        v = self._order_violations.get(lock.name)
                        if v is None:
                            self._order_violations[lock.name] = {
                                "count": 1,
                                "held_rank": str(other.rank),
                                "acquired_rank": str(lock.rank),
                                "witness": _fmt_stack(stack),
                            }
                        else:
                            v["count"] += 1
                    continue
                edge = self._edges.get((other.name, lock.name))
                if edge is None:
                    self._edges[(other.name, lock.name)] = {
                        "count": 1,
                        "held_stack": _fmt_stack(h.stack),
                        "acquired_stack": _fmt_stack(stack),
                    }
                else:
                    edge["count"] += 1

    def on_released(self, lock, held_s: float) -> None:
        with self._guard:
            st = self._sites.get(lock.name)
            if st is None:
                st = self._sites[lock.name] = _SiteStats()
            st.held_n += 1
            st.held_sum += held_s
            if held_s > st.held_max:
                st.held_max = held_s
            if len(st.samples) < _RESERVOIR:
                st.samples.append(held_s)

    def on_blocking(self, op: str, held: list, stack) -> None:
        key = (op, tuple(sorted({h.lock.name for h in held})))
        with self._guard:
            b = self._blocking.get(key)
            if b is None:
                self._blocking[key] = {
                    "count": 1, "witness": _fmt_stack(stack)}
            else:
                b["count"] += 1

    # -- analysis ------------------------------------------------------
    def cycles(self) -> list[dict]:
        """Non-trivial SCCs of the acquisition-order name graph: each
        is a set of locks some pair of threads can acquire in opposite
        orders — a potential deadlock. Witnessed by the member edges'
        stack pairs."""
        with self._guard:
            edges = {k: dict(v) for k, v in self._edges.items()}
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        sccs = _tarjan(graph)
        out = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            members = sorted(scc)
            witness_edges = [
                {"from": a, "to": b, "count": e["count"],
                 "held_stack": e["held_stack"],
                 "acquired_stack": e["acquired_stack"]}
                for (a, b), e in sorted(edges.items())
                if a in scc and b in scc
            ]
            out.append({"locks": members, "edges": witness_edges})
        return out

    def order_violations(self) -> list[dict]:
        with self._guard:
            return [dict(v, group=name) for name, v in
                    sorted(self._order_violations.items())]

    def blocking_under_lock(self) -> list[dict]:
        with self._guard:
            return [
                {"op": op, "held": list(names), "count": b["count"],
                 "witness": b["witness"]}
                for (op, names), b in sorted(self._blocking.items())
            ]

    def report(self) -> dict:
        cycles = self.cycles()
        violations = self.order_violations()
        blocking = self.blocking_under_lock()
        with self._guard:
            locks = {}
            for name, st in sorted(self._sites.items()):
                samples = sorted(st.samples)
                locks[name] = {
                    "acquires": st.acquires,
                    "ranked_group": st.ranked,
                    "held_ms": {
                        "p50": round(_pct(samples, 0.50) * 1e3, 4),
                        "p95": round(_pct(samples, 0.95) * 1e3, 4),
                        "p99": round(_pct(samples, 0.99) * 1e3, 4),
                        "max": round(st.held_max * 1e3, 4),
                        "mean": round(
                            (st.held_sum / st.held_n if st.held_n
                             else 0.0) * 1e3, 4),
                        "samples": st.held_n,
                    },
                }
            edges = [
                {"from": a, "to": b, "count": e["count"]}
                for (a, b), e in sorted(self._edges.items())
            ]
        return {
            "enabled": _enabled,
            "locks": locks,
            "edges": edges,
            "cycles": cycles,
            "order_violations": violations,
            "blocking_under_lock": blocking,
        }

    def reset(self) -> None:
        with self._guard:
            self._sites.clear()
            self._edges.clear()
            self._order_violations.clear()
            self._blocking.clear()


def _tarjan(graph: dict[str, set[str]]) -> list[set[str]]:
    """Iterative Tarjan strongly-connected components."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[set[str]] = []

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index:
                    index[succ] = low[succ] = counter[0]
                    counter[0] += 1
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append((succ, iter(sorted(graph.get(succ,
                                                             ())))))
                    advanced = True
                    break
                if succ in on_stack:
                    low[node] = min(low[node], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)
    # a self-edge (same-name nesting never reaches the edge map, but a
    # one-node SCC with an explicit a->a edge would be a real cycle)
    return sccs


_analysis = LockAnalysis()


def analysis() -> LockAnalysis:
    return _analysis


def report() -> dict:
    return _analysis.report()


def reset() -> None:
    _analysis.reset()


def dump(path: str) -> dict:
    rep = report()
    with open(path, "w") as f:
        json.dump(rep, f, indent=1, sort_keys=True)
    return rep


# ---- instrumented wrappers -------------------------------------------


def _capture_stack():
    return traceback.extract_stack(limit=_STACK_LIMIT)[:-2]


class _InstrumentedLock:
    """Wrapper over a raw primitive that maintains the thread's
    held-set and feeds the global analysis. Reentrant acquires (the
    RLock subclass) bump the existing held entry instead of recording
    a self-edge."""

    _REENTRANT = False

    __slots__ = ("name", "rank", "_raw")

    def __init__(self, name: str, rank=None):
        self.name = name
        self.rank = rank
        if self._REENTRANT:
            self._raw = threading.RLock()  # kfrm: disable=KFRM001
        else:
            self._raw = threading.Lock()  # kfrm: disable=KFRM001

    def _entry(self):
        for h in reversed(_held_list()):
            if h.lock is self:
                return h
        return None

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            self._note_acquired()
        return ok

    def _note_acquired(self) -> None:
        held = _held_list()
        if self._REENTRANT:
            entry = self._entry()
            if entry is not None:
                entry.count += 1
                return
        stack = _capture_stack()
        _analysis.on_acquired(self, held, stack)
        held.append(_Held(self, time.perf_counter(), stack))

    def release(self) -> None:
        self._note_released()
        self._raw.release()

    def _note_released(self) -> None:
        held = _held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self:
                held[i].count -= 1
                if held[i].count == 0:
                    entry = held.pop(i)
                    _analysis.on_released(
                        self, time.perf_counter() - entry.t0)
                return

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__} {self.name!r}>"


class _InstrumentedRLock(_InstrumentedLock):
    _REENTRANT = True
    __slots__ = ()

    def _is_owned(self) -> bool:
        return self._entry() is not None


class _InstrumentedCondition:
    """Condition over an instrumented lock. ``wait`` releases the lock
    for its duration, so the held-set entry is suspended (its held
    segment recorded) and re-established on wake — without this every
    parked waiter would look like an eternal lock hold."""

    __slots__ = ("name", "_wrap", "_cond")

    def __init__(self, name: str, lock=None):
        self.name = name
        if lock is None:
            lock = _InstrumentedRLock(name)
        if not isinstance(lock, _InstrumentedLock):
            raise TypeError(
                "make_condition(lock=...) requires a factory-made lock "
                "while analysis is enabled")
        self._wrap = lock
        # the stdlib Condition manages the RAW primitive; the wrapper
        # handles held-set accounting around it
        self._cond = threading.Condition(lock._raw)  # kfrm: disable=KFRM001

    def acquire(self, *a, **kw):
        return self._wrap.acquire(*a, **kw)

    def release(self) -> None:
        self._wrap.release()

    def __enter__(self):
        self._wrap.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._wrap.release()

    def _suspend(self):
        held = _held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self._wrap:
                entry = held.pop(i)
                _analysis.on_released(
                    self._wrap, time.perf_counter() - entry.t0)
                return entry
        return None

    def _resume(self, entry) -> None:
        if entry is not None:
            entry.t0 = time.perf_counter()
            _held_list().append(entry)

    def wait(self, timeout: float | None = None) -> bool:
        entry = self._suspend()
        try:
            return self._cond.wait(timeout)
        finally:
            self._resume(entry)

    def wait_for(self, predicate, timeout: float | None = None):
        # reimplemented over self.wait so the held-set suspension
        # applies (the stdlib version would call the raw wait)
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# ---- the factory -----------------------------------------------------


def make_lock(name: str, *, rank=None):
    """A mutex. ``name`` labels the lock's site in the analysis (one
    name per lock *family* — instances of a many-instance family share
    it and pass ``rank``, the key their sorted-acquisition discipline
    orders them by, so the analyser can verify the discipline instead
    of seeing false same-name cycles)."""
    if not _enabled:
        return threading.Lock()  # kfrm: disable=KFRM001 (off path)
    return _InstrumentedLock(name, rank=rank)


def make_rlock(name: str):
    """A reentrant mutex (verbs that nest: apiserver kind locks)."""
    if not _enabled:
        return threading.RLock()  # kfrm: disable=KFRM001 (off path)
    return _InstrumentedRLock(name)


def make_condition(name: str, lock=None):
    """A condition variable, optionally over an existing factory-made
    lock (``cache.store`` shares one RLock between its mutex and its
    condvar)."""
    if not _enabled:
        return threading.Condition(lock)  # kfrm: disable=KFRM001 (off)
    return _InstrumentedCondition(name, lock=lock)


# ---- blocking-call probes --------------------------------------------

_probes: dict[tuple, object] = {}


def _check_blocking(op: str) -> None:
    held = _held_list()
    if held:
        _analysis.on_blocking(op, held, _capture_stack())


def _wrap_callable(owner, attr: str, op: str) -> None:
    fn = getattr(owner, attr, None)
    if fn is None or (owner, attr) in _probes:  # pragma: no cover
        return

    def probe(*a, **kw):
        _check_blocking(op)
        return fn(*a, **kw)

    probe.__wrapped__ = fn
    probe.__name__ = getattr(fn, "__name__", attr)
    _probes[(owner, attr)] = fn
    setattr(owner, attr, probe)


def _install_probes() -> None:
    """Patch the blocking syscall surface the control plane uses:
    fsync (WAL), sleep (polling loops), subprocess, socket dials, and
    the ``http.client`` request path (the kubeclient transport). Only
    calls made WHILE HOLDING a factory lock are recorded."""
    if _probes:
        return
    import http.client
    import socket
    import subprocess
    _wrap_callable(os, "fsync", "os.fsync")
    _wrap_callable(os, "fdatasync", "os.fdatasync")
    _wrap_callable(time, "sleep", "time.sleep")
    for name in ("run", "call", "check_call", "check_output"):
        _wrap_callable(subprocess, name, f"subprocess.{name}")
    _wrap_callable(socket, "create_connection",
                   "socket.create_connection")
    _wrap_callable(http.client.HTTPConnection, "request",
                   "http.request")
    _wrap_callable(http.client.HTTPConnection, "getresponse",
                   "http.getresponse")
    _wrap_callable(http.client.HTTPConnection, "connect",
                   "http.connect")


def _uninstall_probes() -> None:
    while _probes:
        (owner, attr), fn = _probes.popitem()
        setattr(owner, attr, fn)


if _enabled:  # pragma: no cover - env-driven boot path
    _install_probes()
