"""Concurrency correctness toolkit for the control plane.

Two prongs, both grown out of the same problem: the platform is a
sharded, multi-process, multi-threaded control plane with ~40
lock instances whose deadlock-freedom and "never block under a hot
lock" invariants were, until r14, enforced only by docstrings.

- :mod:`kubeflow_rm_tpu.analysis.lockgraph` — a dynamic, opt-in
  (``KFRM_LOCK_ANALYSIS=1``) instrumented lock factory every
  control-plane module uses in place of bare ``threading`` primitives.
  When off it hands back raw primitives (zero cost); when on it
  records per-thread held-sets, builds the global acquisition-order
  graph, detects cycles (potential deadlocks) with witness stacks,
  flags blocking syscalls executed while holding a registered lock,
  and reports per-lock held-time percentiles.

- :mod:`kubeflow_rm_tpu.analysis.lint` — a static AST lint
  (``python -m kubeflow_rm_tpu.analysis.lint kubeflow_rm_tpu/``)
  that ratchets the conventions the dynamic tool verifies: KFRM001
  no raw lock construction outside the factory, KFRM002 no blocking
  call lexically under a lock, KFRM003 manual ``.acquire()`` needs a
  ``try/finally`` release, KFRM004 no apiserver/kubeclient write
  under a kind lock, KFRM005 ``except Exception:`` must log or count.

- :mod:`kubeflow_rm_tpu.analysis.hierarchy` — the canonical lock
  hierarchy, in one importable place; tests assert the measured
  acquisition graph embeds into it.
"""
