"""Static concurrency lint: runner and file walker.

``python -m kubeflow_rm_tpu.analysis.lint kubeflow_rm_tpu/`` walks the
tree, runs every KFRM rule over each ``.py`` file, filters findings
through ``# kfrm: disable=`` comments, and exits non-zero if anything
survives — the CI gate in ``unit_tests.yaml``.
"""

from __future__ import annotations

import ast
import os

from .base import Finding, parse_disables
from .rules import ALL_RULES, Rule

__all__ = ["ALL_RULES", "Finding", "Rule", "lint_source", "lint_paths",
           "iter_python_files"]

# Files where a rule is structurally inapplicable (beyond what inline
# disable comments cover). lockgraph.py IS the factory: it must touch
# raw primitives, and its every use site carries an inline rationale —
# the allowlist is belt-and-braces so a refactor there can't wedge CI.
ALLOWLIST: dict[str, tuple[str, ...]] = {
    "KFRM001": ("kubeflow_rm_tpu/analysis/lockgraph.py",),
}


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def _allowlisted(rule_id: str, path: str) -> bool:
    return any(_norm(path).endswith(suffix)
               for suffix in ALLOWLIST.get(rule_id, ()))


def lint_source(source: str, path: str,
                rule_ids: set[str] | None = None) -> list[Finding]:
    """Lint one file's source. ``rule_ids`` restricts to a subset
    (default: all). A syntax error is reported as rule KFRM000 rather
    than aborting the run."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding("KFRM000", path, exc.lineno or 0,
                        exc.offset or 0, f"syntax error: {exc.msg}")]
    file_wide, per_line = parse_disables(source)
    findings: list[Finding] = []
    for cls in ALL_RULES:
        if rule_ids is not None and cls.rule_id not in rule_ids:
            continue
        if cls.rule_id in file_wide or _allowlisted(cls.rule_id, path):
            continue
        findings.extend(cls(path).run(tree))
    kept = [f for f in findings
            if f.rule not in per_line.get(f.line, ())]
    return sorted(kept, key=lambda f: (f.path, f.line, f.col, f.rule))


def iter_python_files(paths: list[str]):
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if not d.startswith(".")
                             and d != "__pycache__")
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def lint_paths(paths: list[str],
               rule_ids: set[str] | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_python_files(paths):
        with open(path, encoding="utf-8") as f:
            findings.extend(lint_source(f.read(), path, rule_ids))
    return findings
