"""Shared machinery for the KFRM lint rules.

Each rule is an :class:`ast.NodeVisitor` subclass (one per module
convention) that appends :class:`Finding` records. Findings are
line-addressed and machine-readable (``as_dict``); the runner filters
them through ``# kfrm: disable=RULE`` comments before reporting.

Heuristics shared by several rules:

- **lockish** — an expression reads as a lock if its terminal name
  (the last attribute/name segment, unwrapping a call) matches
  ``(?i)(lock|cond|cv|guard|mutex)``. That is deliberately broad:
  this codebase names every lock that way, and a false positive on a
  ``with`` statement is cheap to silence with a disable comment,
  while a miss silently exempts a critical section.
- **disable comments** — ``# kfrm: disable=KFRM002`` silences rules
  on that line; ``# kfrm: disable-file=KFRM001`` silences them for
  the whole file. Both accept a comma-separated list and should carry
  a rationale in the surrounding text.
"""

from __future__ import annotations

import ast
import dataclasses
import re

LOCKISH = re.compile(r"(?i)(lock|cond|cv|guard|mutex)")

_DISABLE = re.compile(
    r"#\s*kfrm:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_disables(source: str) -> tuple[set, dict]:
    """Extract ``# kfrm: disable=`` comments: (file-wide rule set,
    {lineno: rule set})."""
    file_wide: set[str] = set()
    per_line: dict[int, set[str]] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _DISABLE.search(text)
        if not m:
            continue
        rules = {r.strip().upper()
                 for r in m.group("rules").split(",") if r.strip()}
        if m.group("file"):
            file_wide |= rules
        else:
            per_line.setdefault(lineno, set()).update(rules)
    return file_wide, per_line


def terminal_name(node: ast.AST) -> str | None:
    """The last name segment of an expression: ``a.b.c`` -> ``c``,
    ``f(x).lock`` -> ``lock``, ``name`` -> ``name``."""
    while isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted(node: ast.AST) -> str | None:
    """Render a pure Name/Attribute chain as ``a.b.c``; None if the
    chain contains anything else (calls, subscripts)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def is_lockish(node: ast.AST) -> bool:
    name = terminal_name(node)
    return bool(name and LOCKISH.search(name))


class Rule(ast.NodeVisitor):
    """Base class: one instance per (rule, file) pass."""

    rule_id = ""
    synopsis = ""

    def __init__(self, path: str):
        self.path = path
        self.findings: list[Finding] = []

    def emit(self, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            self.rule_id, self.path,
            getattr(node, "lineno", 0), getattr(node, "col_offset", 0),
            message))

    def run(self, tree: ast.AST) -> list[Finding]:
        self.visit(tree)
        return self.findings


class LockScopeRule(Rule):
    """Base for rules that fire only *lexically inside* a
    ``with <lockish>:`` body. Tracks nesting depth; nested function
    and lambda bodies run later (not under the lock at definition
    time), so depth resets across them."""

    def __init__(self, path: str):
        super().__init__(path)
        self._depth = 0

    def visit_With(self, node: ast.With) -> None:
        locks = sum(1 for item in node.items
                    if is_lockish(item.context_expr))
        for item in node.items:
            self.visit(item)
        self._depth += locks
        try:
            for stmt in node.body:
                self.visit(stmt)
        finally:
            self._depth -= locks

    def _visit_scope(self, node) -> None:
        saved, self._depth = self._depth, 0
        try:
            self.generic_visit(node)
        finally:
            self._depth = saved

    def visit_FunctionDef(self, node) -> None:
        self._visit_scope(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_scope(node)

    def visit_Lambda(self, node) -> None:
        self._visit_scope(node)
