"""CLI: ``python -m kubeflow_rm_tpu.analysis.lint [paths...]``.

Exit status: 0 clean, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import lint_paths
from .rules import ALL_RULES


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_rm_tpu.analysis.lint",
        description="KFRM concurrency lint (see analysis/lint/rules.py)")
    parser.add_argument("paths", nargs="*", default=["kubeflow_rm_tpu"],
                        help="files or directories (default: "
                             "kubeflow_rm_tpu)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable findings on stdout")
    parser.add_argument("--rules", default=None,
                        help="comma-separated subset, e.g. "
                             "KFRM001,KFRM005 (default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    args = parser.parse_args(argv)

    known = {cls.rule_id: cls for cls in ALL_RULES}
    if args.list_rules:
        for rule_id, cls in sorted(known.items()):
            print(f"{rule_id}  {cls.synopsis}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = {r.strip().upper() for r in args.rules.split(",")
                    if r.strip()}
        unknown = rule_ids - set(known)
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    findings = lint_paths(args.paths or ["kubeflow_rm_tpu"], rule_ids)
    if args.as_json:
        print(json.dumps([f.as_dict() for f in findings], indent=1))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"\n{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
