"""The eight KFRM rules, one visitor class each.

| Rule    | Invariant                                               |
|---------|---------------------------------------------------------|
| KFRM001 | locks come from the ``analysis.lockgraph`` factory      |
| KFRM002 | no blocking call lexically inside ``with <lock>:``      |
| KFRM003 | manual ``.acquire()`` has a ``try/finally`` release     |
| KFRM004 | no apiserver/kubeclient write while a lock is held      |
| KFRM005 | ``except Exception:`` must log, count, or re-raise      |
| KFRM006 | no scalar host-sync on a jitted result inside a loop    |
| KFRM007 | no ``jax.jit`` construction inside a loop body          |
| KFRM008 | a jitted step must donate its state/cache argument      |

KFRM001-005 audit the control plane's locking (PR 11); KFRM006-008
are the static half of ``analysis/jaxcheck`` and audit the compute
path's TPU discipline — each one encodes a stall class the jaxcheck
dynamic probes (``hostsync``, ``recompile``, ``costmodel``) can
demonstrate at runtime.

All are heuristics biased toward catching real violations in *this*
codebase's idiom; the escape hatch for a justified exception is a
``# kfrm: disable=RULE`` comment with a rationale next to it.
"""

from __future__ import annotations

import ast

from .base import LockScopeRule, Rule, dotted, is_lockish, terminal_name

_THREADING_PRIMITIVES = ("Lock", "RLock", "Condition")

# Calls that park the thread in the kernel (or for unbounded time)
# while any lock is held. ``.wait`` is deliberately absent: a condvar
# wait RELEASES its lock for the duration.
BLOCKING_DOTTED = {
    "time.sleep",
    "os.fsync", "os.fdatasync",
    "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "urllib.request.urlopen",
}
BLOCKING_ATTRS = {
    "fsync", "fdatasync",          # durability
    "sendall", "recv", "recv_into", "accept",  # sockets
    "getresponse",                 # http.client round trip
    "result",                      # Future.result parks the caller
}

WRITE_VERBS = {
    "create", "create_many", "update", "update_status", "patch",
    "delete", "record_event",
}
# receivers that read as an apiserver/kubeclient handle; bare ``self``
# is excluded so the apiserver's own verb implementations (which run
# under their kind lock by design) don't self-flag
CLIENTISH = {
    "api", "kapi", "capi", "client", "kube", "kubeclient", "_api",
    "backend",
}

# a handler is non-silent if it raises or calls one of these
_HANDLED_CALLS = {
    "debug", "info", "warning", "warn", "error", "exception", "log",
    "critical",                   # logging
    "inc", "observe", "swallowed",  # metrics
    "print_exc",                  # traceback (tests/tools)
}


class RawLockConstruction(Rule):
    """KFRM001: construct locks through ``lockgraph.make_lock`` /
    ``make_rlock`` / ``make_condition``, never ``threading.Lock()``
    directly — otherwise the dynamic analysis is blind to them."""

    rule_id = "KFRM001"
    synopsis = ("raw threading.Lock/RLock/Condition construction "
                "outside the lockgraph factory")

    def __init__(self, path: str):
        super().__init__(path)
        self._from_imports: set[str] = set()

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "threading":
            for alias in node.names:
                if alias.name in _THREADING_PRIMITIVES:
                    self._from_imports.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted(node.func)
        hit = None
        if name and name.startswith("threading."):
            prim = name.split(".", 1)[1]
            if prim in _THREADING_PRIMITIVES:
                hit = name
        elif isinstance(node.func, ast.Name) and \
                node.func.id in self._from_imports:
            hit = node.func.id
        if hit:
            factory = {"Lock": "make_lock", "RLock": "make_rlock",
                       "Condition": "make_condition"}[hit.split(".")[-1]]
            self.emit(node, f"raw {hit}() — use "
                            f"analysis.lockgraph.{factory}(name) so the "
                            f"dynamic analysis can see this lock")
        self.generic_visit(node)


class BlockingUnderLock(LockScopeRule):
    """KFRM002: a blocking call lexically inside a ``with <lock>:``
    body serializes every other thread contending for that lock behind
    a syscall/network round trip."""

    rule_id = "KFRM002"
    synopsis = "blocking call lexically inside a with-lock body"

    def visit_Call(self, node: ast.Call) -> None:
        if self._depth > 0:
            name = dotted(node.func)
            hit = name if name in BLOCKING_DOTTED else None
            if hit is None and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in BLOCKING_ATTRS:
                hit = f".{node.func.attr}"
            if hit:
                self.emit(node, f"blocking call {hit}() while holding "
                                f"a lock — move it outside the "
                                f"critical section")
        self.generic_visit(node)


class AcquireWithoutFinally(Rule):
    """KFRM003: a manual ``<lock>.acquire()`` (the ``scheduler._commit``
    multi-lock pattern) must have a ``try/finally`` in the same
    function whose finalbody releases a matching lock — otherwise an
    exception between acquire and release leaks the lock forever."""

    rule_id = "KFRM003"
    synopsis = "manual .acquire() without a try/finally release"

    def _check_function(self, node) -> None:
        acquires: list[ast.Call] = []
        released: set[str] = set()
        stack: list[tuple[ast.AST, bool]] = [(node, False)]
        while stack:
            cur, in_finally = stack.pop()
            for child in ast.iter_child_nodes(cur):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # separate scope, checked on its own
                child_in_finally = in_finally
                if isinstance(child, ast.Call) and \
                        isinstance(child.func, ast.Attribute) and \
                        is_lockish(child.func.value):
                    if child.func.attr == "acquire" and not in_finally:
                        acquires.append(child)
                    elif child.func.attr == "release" and in_finally:
                        released.add(
                            terminal_name(child.func.value) or "")
                if isinstance(cur, ast.Try) and \
                        child in getattr(cur, "finalbody", ()):
                    child_in_finally = True
                stack.append((child, child_in_finally))
        for call in acquires:
            recv = terminal_name(call.func.value) or "<lock>"
            if recv not in released:
                self.emit(call, f"{recv}.acquire() has no matching "
                                f"release in a try/finally in this "
                                f"function — an exception leaks the "
                                f"lock")

    def visit_FunctionDef(self, node) -> None:
        self._check_function(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._check_function(node)
        self.generic_visit(node)


class WriteUnderLock(LockScopeRule):
    """KFRM004: an apiserver/kubeclient write verb issued while a lock
    is held couples the critical section to admission webhooks, WAL
    fsync, and (cluster backend) a network round trip — and a write
    re-entering the same kind lock from another thread's watch fanout
    is the classic control-plane deadlock."""

    rule_id = "KFRM004"
    synopsis = "apiserver/kubeclient write call while a lock is held"

    def visit_Call(self, node: ast.Call) -> None:
        if self._depth > 0 and isinstance(node.func, ast.Attribute) \
                and node.func.attr in WRITE_VERBS:
            recv = terminal_name(node.func.value)
            if recv in CLIENTISH:
                self.emit(node, f"{recv}.{node.func.attr}() while "
                                f"holding a lock — issue API writes "
                                f"after the critical section")
        self.generic_visit(node)


class SilentSwallow(Rule):
    """KFRM005: ``except Exception:`` that neither re-raises, logs,
    nor counts turns real faults into silence. Use
    ``metrics.swallowed(module)`` for intentional best-effort paths."""

    rule_id = "KFRM005"
    synopsis = "except Exception: swallowed without logging or counting"

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        names = t.elts if isinstance(t, ast.Tuple) else [t]
        return any(terminal_name(n) in ("Exception", "BaseException")
                   for n in names)

    @staticmethod
    def _is_handled(handler: ast.ExceptHandler) -> bool:
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call) and \
                    terminal_name(sub.func) in _HANDLED_CALLS:
                return True
            # the bound exception flowing anywhere (stored for a later
            # gather-raise, handed to a retry/record helper) is not a
            # swallow — the fault stays visible to the program
            if handler.name and isinstance(sub, ast.Name) and \
                    sub.id == handler.name and \
                    isinstance(sub.ctx, ast.Load):
                return True
        return False

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self._is_broad(node) and not self._is_handled(node):
            self.emit(node, "except Exception: swallows the error — "
                            "re-raise, log it, or count it via "
                            "metrics.swallowed(module)")
        self.generic_visit(node)


_STATEY = ("state", "cache")


def _is_statey(name: str) -> bool:
    """A parameter that names a donatable step buffer: ``state``,
    ``cache``, ``*_state``, ``*_cache``."""
    return any(name == s or name.endswith("_" + s) for s in _STATEY)


class _JitAwareRule(Rule):
    """Base for the jaxcheck rules (KFRM006-008): tracks how this
    file refers to ``jax.jit`` (dotted, or ``from jax import jit``
    aliases) and recognizes the three construction idioms — a direct
    ``jax.jit(...)`` call, a ``partial(jax.jit, ...)`` wrapper, and
    either of those as a decorator."""

    def __init__(self, path: str):
        super().__init__(path)
        self._jit_refs = {"jax.jit"}

    def _scan_imports(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "jax":
                for alias in node.names:
                    if alias.name == "jit":
                        self._jit_refs.add(alias.asname or "jit")

    def _is_jit_ref(self, node: ast.AST) -> bool:
        return dotted(node) in self._jit_refs

    def _jit_construction(self, call: ast.Call):
        """If ``call`` builds a jitted callable, return
        ``(wrapped, kwargs)`` — the wrapped function expression (None
        for the partial form, whose target arrives later) and the jit
        keyword nodes. Otherwise None."""
        if self._is_jit_ref(call.func):
            wrapped = call.args[0] if call.args else None
            return wrapped, {kw.arg: kw.value
                             for kw in call.keywords if kw.arg}
        if dotted(call.func) in ("functools.partial", "partial") and \
                call.args and self._is_jit_ref(call.args[0]):
            return None, {kw.arg: kw.value
                          for kw in call.keywords if kw.arg}
        return None

    def _decorator_jit_kwargs(self, dec: ast.AST):
        """jit kwargs if ``dec`` is a jit decorator (any idiom), else
        None."""
        if self._is_jit_ref(dec):
            return {}
        if isinstance(dec, ast.Call):
            built = self._jit_construction(dec)
            if built is not None:
                return built[1]
        return None


class _LoopScopeMixin:
    """Loop-depth tracking with the LockScopeRule scope convention:
    nested function/lambda bodies run later, not per iteration, so
    depth resets across them."""

    _loop_depth = 0

    def _visit_loop(self, node) -> None:
        self._loop_depth += 1
        try:
            self.generic_visit(node)
        finally:
            self._loop_depth -= 1

    def visit_For(self, node) -> None:
        self._visit_loop(node)

    def visit_While(self, node) -> None:
        self._visit_loop(node)

    def _visit_scope(self, node) -> None:
        saved, self._loop_depth = self._loop_depth, 0
        try:
            self.generic_visit(node)
        finally:
            self._loop_depth = saved

    def visit_FunctionDef(self, node) -> None:
        self._visit_scope(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_scope(node)

    def visit_Lambda(self, node) -> None:
        self._visit_scope(node)


class ScalarSyncInJitLoop(_LoopScopeMixin, _JitAwareRule):
    """KFRM006: ``int()``/``.item()``/``np.asarray()`` on a jitted
    call's result inside a loop blocks Python on a device→host
    round trip every iteration — the decode loop serializes the TPU
    behind the host. Batch the results and sync once outside, or keep
    the consumer on-device. The dynamic twin is
    ``jaxcheck.hostsync``."""

    rule_id = "KFRM006"
    synopsis = "scalar host-sync on a jitted result inside a loop"

    _SYNC_BUILTINS = {"int", "float", "bool"}
    _SYNC_ATTRS = {"item", "tolist"}
    _SYNC_DOTTED = {"np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "jax.device_get"}

    def run(self, tree: ast.AST) -> list:
        self._scan_imports(tree)
        # names bound to jitted callables: decorated defs and
        # ``f = jax.jit(...)`` / ``f = partial(jax.jit, ...)(...)``
        self._jitted: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._decorator_jit_kwargs(dec) is not None:
                        self._jitted.add(node.name)
            elif isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Call) and \
                    self._jit_construction(node.value) is not None:
                self._jitted.add(node.targets[0].id)
        return super().run(tree)

    def _is_jitted_call(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and \
            terminal_name(node.func) in self._jitted

    def visit_Call(self, node: ast.Call) -> None:
        if self._loop_depth > 0:
            sync = None
            if isinstance(node.func, ast.Name) and \
                    node.func.id in self._SYNC_BUILTINS and \
                    node.args and self._is_jitted_call(node.args[0]):
                sync = f"{node.func.id}()"
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in self._SYNC_ATTRS and \
                    self._is_jitted_call(node.func.value):
                sync = f".{node.func.attr}()"
            elif dotted(node.func) in self._SYNC_DOTTED and \
                    node.args and self._is_jitted_call(node.args[0]):
                sync = f"{dotted(node.func)}()"
            if sync:
                self.emit(node, f"{sync} on a jitted result inside a "
                                f"loop forces a device->host sync "
                                f"every iteration — batch the results "
                                f"and sync once outside the loop")
        self.generic_visit(node)


class JitConstructionInLoop(_LoopScopeMixin, _JitAwareRule):
    """KFRM007: ``jax.jit(...)`` constructed inside a loop body makes
    a fresh callable — and a fresh trace/compile cache — every
    iteration; nothing is ever reused. Hoist ONE jitted function out
    of the loop and key per-iteration variation on
    ``static_argnames``. The dynamic twin is
    ``jaxcheck.recompile``."""

    rule_id = "KFRM007"
    synopsis = "jax.jit constructed inside a loop body"

    def run(self, tree: ast.AST) -> list:
        self._scan_imports(tree)
        return super().run(tree)

    def visit_Call(self, node: ast.Call) -> None:
        if self._loop_depth > 0 and \
                self._jit_construction(node) is not None:
            self.emit(node, "jax.jit constructed inside a loop — a "
                            "fresh trace cache per iteration; hoist "
                            "one jitted function and pass the "
                            "varying parts via static_argnames")
        self.generic_visit(node)


class NonDonatedStateJit(_JitAwareRule):
    """KFRM008: a jitted step that takes a ``state``/``cache``
    argument and returns its successor must donate it
    (``donate_argnums``/``donate_argnames``) — otherwise XLA keeps
    the old buffer live across the call and the step double-buffers
    the largest allocation in the program (the cost model's
    ``peak_bytes_no_donation`` column prices exactly this)."""

    rule_id = "KFRM008"
    synopsis = "jitted step does not donate its state/cache argument"

    def run(self, tree: ast.AST) -> list:
        self._scan_imports(tree)
        # every def in the file (any nesting), for call-form lookup
        self._defs: dict[str, ast.arguments] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs[node.name] = node.args
        return super().run(tree)

    @staticmethod
    def _literal(node: ast.AST):
        try:
            return ast.literal_eval(node)
        except (ValueError, TypeError, SyntaxError):
            return None

    def _check(self, site: ast.AST, fn_name: str,
               args: ast.arguments, kwargs: dict) -> None:
        params = [a.arg for a in args.args]
        statey = [(i, p) for i, p in enumerate(params) if _is_statey(p)]
        if not statey:
            return
        donated_nums = self._literal(kwargs["donate_argnums"]) \
            if "donate_argnums" in kwargs else ()
        donated_names = self._literal(kwargs["donate_argnames"]) \
            if "donate_argnames" in kwargs else ()
        statics = self._literal(kwargs["static_argnames"]) \
            if "static_argnames" in kwargs else ()
        if donated_nums is None or donated_names is None or \
                statics is None:
            return  # non-literal donation spec: assume handled
        if isinstance(donated_nums, int):
            donated_nums = (donated_nums,)
        if isinstance(donated_names, str):
            donated_names = (donated_names,)
        if isinstance(statics, str):
            statics = (statics,)
        for i, p in statey:
            if i in donated_nums or p in donated_names or p in statics:
                continue
            self.emit(site, f"{fn_name} is jitted with a '{p}' "
                            f"argument (position {i}) that is not "
                            f"donated — the old buffer stays live and "
                            f"the step double-buffers it; add "
                            f"donate_argnums=({i},)")

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for dec in node.decorator_list:
            kwargs = self._decorator_jit_kwargs(dec)
            if kwargs is not None:
                self._check(node, node.name, node.args, kwargs)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        built = self._jit_construction(node)
        if built is not None:
            wrapped, kwargs = built
            if isinstance(wrapped, ast.Lambda):
                self._check(node, "<lambda>", wrapped.args, kwargs)
            elif isinstance(wrapped, ast.Name) and \
                    wrapped.id in self._defs:
                self._check(node, wrapped.id, self._defs[wrapped.id],
                            kwargs)
        self.generic_visit(node)


ALL_RULES: tuple[type[Rule], ...] = (
    RawLockConstruction,
    BlockingUnderLock,
    AcquireWithoutFinally,
    WriteUnderLock,
    SilentSwallow,
    ScalarSyncInJitLoop,
    JitConstructionInLoop,
    NonDonatedStateJit,
)
