"""The five KFRM rules, one visitor class each.

| Rule    | Invariant                                               |
|---------|---------------------------------------------------------|
| KFRM001 | locks come from the ``analysis.lockgraph`` factory      |
| KFRM002 | no blocking call lexically inside ``with <lock>:``      |
| KFRM003 | manual ``.acquire()`` has a ``try/finally`` release     |
| KFRM004 | no apiserver/kubeclient write while a lock is held      |
| KFRM005 | ``except Exception:`` must log, count, or re-raise      |

All are heuristics biased toward catching real violations in *this*
codebase's idiom; the escape hatch for a justified exception is a
``# kfrm: disable=RULE`` comment with a rationale next to it.
"""

from __future__ import annotations

import ast

from .base import LockScopeRule, Rule, dotted, is_lockish, terminal_name

_THREADING_PRIMITIVES = ("Lock", "RLock", "Condition")

# Calls that park the thread in the kernel (or for unbounded time)
# while any lock is held. ``.wait`` is deliberately absent: a condvar
# wait RELEASES its lock for the duration.
BLOCKING_DOTTED = {
    "time.sleep",
    "os.fsync", "os.fdatasync",
    "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "urllib.request.urlopen",
}
BLOCKING_ATTRS = {
    "fsync", "fdatasync",          # durability
    "sendall", "recv", "recv_into", "accept",  # sockets
    "getresponse",                 # http.client round trip
    "result",                      # Future.result parks the caller
}

WRITE_VERBS = {
    "create", "create_many", "update", "update_status", "patch",
    "delete", "record_event",
}
# receivers that read as an apiserver/kubeclient handle; bare ``self``
# is excluded so the apiserver's own verb implementations (which run
# under their kind lock by design) don't self-flag
CLIENTISH = {
    "api", "kapi", "capi", "client", "kube", "kubeclient", "_api",
    "backend",
}

# a handler is non-silent if it raises or calls one of these
_HANDLED_CALLS = {
    "debug", "info", "warning", "warn", "error", "exception", "log",
    "critical",                   # logging
    "inc", "observe", "swallowed",  # metrics
    "print_exc",                  # traceback (tests/tools)
}


class RawLockConstruction(Rule):
    """KFRM001: construct locks through ``lockgraph.make_lock`` /
    ``make_rlock`` / ``make_condition``, never ``threading.Lock()``
    directly — otherwise the dynamic analysis is blind to them."""

    rule_id = "KFRM001"
    synopsis = ("raw threading.Lock/RLock/Condition construction "
                "outside the lockgraph factory")

    def __init__(self, path: str):
        super().__init__(path)
        self._from_imports: set[str] = set()

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "threading":
            for alias in node.names:
                if alias.name in _THREADING_PRIMITIVES:
                    self._from_imports.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted(node.func)
        hit = None
        if name and name.startswith("threading."):
            prim = name.split(".", 1)[1]
            if prim in _THREADING_PRIMITIVES:
                hit = name
        elif isinstance(node.func, ast.Name) and \
                node.func.id in self._from_imports:
            hit = node.func.id
        if hit:
            factory = {"Lock": "make_lock", "RLock": "make_rlock",
                       "Condition": "make_condition"}[hit.split(".")[-1]]
            self.emit(node, f"raw {hit}() — use "
                            f"analysis.lockgraph.{factory}(name) so the "
                            f"dynamic analysis can see this lock")
        self.generic_visit(node)


class BlockingUnderLock(LockScopeRule):
    """KFRM002: a blocking call lexically inside a ``with <lock>:``
    body serializes every other thread contending for that lock behind
    a syscall/network round trip."""

    rule_id = "KFRM002"
    synopsis = "blocking call lexically inside a with-lock body"

    def visit_Call(self, node: ast.Call) -> None:
        if self._depth > 0:
            name = dotted(node.func)
            hit = name if name in BLOCKING_DOTTED else None
            if hit is None and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in BLOCKING_ATTRS:
                hit = f".{node.func.attr}"
            if hit:
                self.emit(node, f"blocking call {hit}() while holding "
                                f"a lock — move it outside the "
                                f"critical section")
        self.generic_visit(node)


class AcquireWithoutFinally(Rule):
    """KFRM003: a manual ``<lock>.acquire()`` (the ``scheduler._commit``
    multi-lock pattern) must have a ``try/finally`` in the same
    function whose finalbody releases a matching lock — otherwise an
    exception between acquire and release leaks the lock forever."""

    rule_id = "KFRM003"
    synopsis = "manual .acquire() without a try/finally release"

    def _check_function(self, node) -> None:
        acquires: list[ast.Call] = []
        released: set[str] = set()
        stack: list[tuple[ast.AST, bool]] = [(node, False)]
        while stack:
            cur, in_finally = stack.pop()
            for child in ast.iter_child_nodes(cur):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # separate scope, checked on its own
                child_in_finally = in_finally
                if isinstance(child, ast.Call) and \
                        isinstance(child.func, ast.Attribute) and \
                        is_lockish(child.func.value):
                    if child.func.attr == "acquire" and not in_finally:
                        acquires.append(child)
                    elif child.func.attr == "release" and in_finally:
                        released.add(
                            terminal_name(child.func.value) or "")
                if isinstance(cur, ast.Try) and \
                        child in getattr(cur, "finalbody", ()):
                    child_in_finally = True
                stack.append((child, child_in_finally))
        for call in acquires:
            recv = terminal_name(call.func.value) or "<lock>"
            if recv not in released:
                self.emit(call, f"{recv}.acquire() has no matching "
                                f"release in a try/finally in this "
                                f"function — an exception leaks the "
                                f"lock")

    def visit_FunctionDef(self, node) -> None:
        self._check_function(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._check_function(node)
        self.generic_visit(node)


class WriteUnderLock(LockScopeRule):
    """KFRM004: an apiserver/kubeclient write verb issued while a lock
    is held couples the critical section to admission webhooks, WAL
    fsync, and (cluster backend) a network round trip — and a write
    re-entering the same kind lock from another thread's watch fanout
    is the classic control-plane deadlock."""

    rule_id = "KFRM004"
    synopsis = "apiserver/kubeclient write call while a lock is held"

    def visit_Call(self, node: ast.Call) -> None:
        if self._depth > 0 and isinstance(node.func, ast.Attribute) \
                and node.func.attr in WRITE_VERBS:
            recv = terminal_name(node.func.value)
            if recv in CLIENTISH:
                self.emit(node, f"{recv}.{node.func.attr}() while "
                                f"holding a lock — issue API writes "
                                f"after the critical section")
        self.generic_visit(node)


class SilentSwallow(Rule):
    """KFRM005: ``except Exception:`` that neither re-raises, logs,
    nor counts turns real faults into silence. Use
    ``metrics.swallowed(module)`` for intentional best-effort paths."""

    rule_id = "KFRM005"
    synopsis = "except Exception: swallowed without logging or counting"

    @staticmethod
    def _is_broad(handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        names = t.elts if isinstance(t, ast.Tuple) else [t]
        return any(terminal_name(n) in ("Exception", "BaseException")
                   for n in names)

    @staticmethod
    def _is_handled(handler: ast.ExceptHandler) -> bool:
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call) and \
                    terminal_name(sub.func) in _HANDLED_CALLS:
                return True
            # the bound exception flowing anywhere (stored for a later
            # gather-raise, handed to a retry/record helper) is not a
            # swallow — the fault stays visible to the program
            if handler.name and isinstance(sub, ast.Name) and \
                    sub.id == handler.name and \
                    isinstance(sub.ctx, ast.Load):
                return True
        return False

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self._is_broad(node) and not self._is_handled(node):
            self.emit(node, "except Exception: swallows the error — "
                            "re-raise, log it, or count it via "
                            "metrics.swallowed(module)")
        self.generic_visit(node)


ALL_RULES: tuple[type[Rule], ...] = (
    RawLockConstruction,
    BlockingUnderLock,
    AcquireWithoutFinally,
    WriteUnderLock,
    SilentSwallow,
)
