from kubeflow_rm_tpu.utils.pytree import (
    param_count,
    tree_cast,
    tree_size_bytes,
)

__all__ = ["param_count", "tree_cast", "tree_size_bytes"]
