"""Model-FLOPs accounting and MFU.

MFU = (model FLOPs/sec achieved) / (chip peak bf16 FLOPs/sec), with
model FLOPs counted by the standard convention (PaLM appendix B /
scaling-book): 6 FLOPs per matmul parameter per trained token
(fwd 2 + bwd 4), plus the attention score/value matmuls
(12·L·H·hd·T per token, halved for causal), and **not** counting
rematerialization recompute — remat makes the hardware do more work,
it does not make the model bigger.

The reference platform has no FLOPs accounting anywhere (SURVEY.md §6:
no published benchmarks); this module is what turns the north-star
"≥40% MFU on a TPU slice" (BASELINE.md) into a measured number.
"""

from kubeflow_rm_tpu.models.llama import LlamaConfig

# chip peak dense bf16 FLOPs/sec by device kind substring (public specs)
_PEAK_BF16 = (
    ("v6", 918e12),      # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # jax device_kind for v5e
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def device_peak_flops(device) -> float | None:
    """Peak dense bf16 FLOPs/sec for a jax device, or None if unknown."""
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None


def matmul_param_count(cfg: LlamaConfig) -> int:
    """Parameters that take part in matmuls (excludes the embedding
    gather and the vector norm gains)."""
    L, D, V = cfg.n_layers, cfg.dim, cfg.vocab_size
    H, KVH, hd, F = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.hidden_dim
    per_layer = D * H * hd + 2 * D * KVH * hd + H * hd * D + 3 * D * F
    return L * per_layer + D * V  # + lm_head


def train_flops_per_token(cfg: LlamaConfig, seq_len: int,
                          causal: bool = True,
                          frozen_base: bool = False) -> float:
    """Model FLOPs per trained token for one fwd+bwd step.

    ``frozen_base=True`` (LoRA/QLoRA): the base weights take no
    weight-gradient matmuls, so each matmul param costs 4 FLOPs/token
    (fwd 2 + input-grad 2) instead of 6 — adapter weight-grads are
    O(rank/dim) and ignored. Attention (parameter-free) backward is
    unchanged. Without this, LoRA MFU reads ~1.5× too high."""
    mat = (4.0 if frozen_base else 6.0) * matmul_param_count(cfg)
    # score (QK^T) + weighted value (PV): 2·2·H·hd·T fwd, ×3 with bwd
    attn = 12.0 * cfg.n_layers * cfg.n_heads * cfg.head_dim * seq_len
    if causal:
        attn /= 2.0
    return mat + attn


def mfu(tokens_per_sec: float, cfg: LlamaConfig, seq_len: int,
        n_devices: int, peak_flops_per_device: float) -> float:
    """Model FLOPs utilization in [0, 1]."""
    achieved = tokens_per_sec * train_flops_per_token(cfg, seq_len)
    return achieved / (n_devices * peak_flops_per_device)
