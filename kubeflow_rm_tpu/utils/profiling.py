"""Profiling hooks — a capability the reference lacks entirely
(SURVEY.md §5: "Tracing / profiling: none").

Two layers:
- **In-image (device)**: ``trace()`` wraps the JAX profiler so a
  notebook user captures an XLA trace of a training interval and views
  it in xprof/tensorboard; ``annotate()`` names host-side regions in
  that trace.
- **Control plane (host)**: the web apps already expose Prometheus
  metrics; ``profile_wsgi`` adds on-demand cProfile capture around a
  WSGI app for the pprof-style "why is this request slow" question.
"""

from __future__ import annotations

import contextlib
import cProfile
import io
import pstats
import time


class PhaseRecorder:
    """Named wall-clock phases of a repeated operation, aggregated into
    per-phase percentiles — the conformance harness's breakdown of
    where provision latency goes (POST→CR, CR→StatefulSet,
    StatefulSet→Pods, Pods→Ready).

    ``record(phase, seconds)`` takes externally-measured durations
    (e.g. computed from apiserver write-log timestamps); ``phase(name)``
    times a block inline. ``summary()`` returns per-phase
    count/p50/p95/max in milliseconds."""

    def __init__(self):
        self._samples: dict[str, list[float]] = {}

    def record(self, phase: str, seconds: float) -> None:
        self._samples.setdefault(phase, []).append(float(seconds))

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def merge(self, other: "PhaseRecorder") -> None:
        for name, vals in other._samples.items():
            self._samples.setdefault(name, []).extend(vals)

    @staticmethod
    def _pct(vals: list[float], q: float) -> float:
        # linear interpolation between closest ranks (numpy's default
        # percentile method) — nearest-rank rounding made p95 of a
        # 20-sample storm report the 18th sample, off by half a rank
        s = sorted(vals)
        if len(s) == 1:
            return s[0]
        pos = min(max(q, 0.0), 1.0) * (len(s) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        return s[lo] + (s[hi] - s[lo]) * (pos - lo)

    def summary(self) -> dict[str, dict]:
        out = {}
        for name, vals in self._samples.items():
            out[name] = {
                "count": len(vals),
                "p50_ms": round(self._pct(vals, 0.5) * 1e3, 1),
                "p95_ms": round(self._pct(vals, 0.95) * 1e3, 1),
                "p99_ms": round(self._pct(vals, 0.99) * 1e3, 1),
                "max_ms": round(max(vals) * 1e3, 1),
            }
        return out


@contextlib.contextmanager
def trace(logdir: str, *, create_perfetto_link: bool = False):
    """Capture a JAX/XLA device trace for the enclosed region:

        with profiling.trace("/home/jovyan/traces"):
            state, metrics = step(state, batch)

    View with tensorboard (profile plugin) pointed at ``logdir``.
    """
    import jax
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host region inside a device trace (TraceAnnotation)."""
    import jax
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def profile_wsgi(sort: str = "cumulative", limit: int = 30):
    """cProfile a block of WSGI handling; yields a StringIO that holds
    the stats table after exit."""
    out = io.StringIO()
    prof = cProfile.Profile()
    prof.enable()
    try:
        yield out
    finally:
        prof.disable()
        pstats.Stats(prof, stream=out).sort_stats(sort).print_stats(limit)
