"""Profiling hooks — a capability the reference lacks entirely
(SURVEY.md §5: "Tracing / profiling: none").

Two layers:
- **In-image (device)**: ``trace()`` wraps the JAX profiler so a
  notebook user captures an XLA trace of a training interval and views
  it in xprof/tensorboard; ``annotate()`` names host-side regions in
  that trace.
- **Control plane (host)**: the web apps already expose Prometheus
  metrics; ``profile_wsgi`` adds on-demand cProfile capture around a
  WSGI app for the pprof-style "why is this request slow" question.
"""

from __future__ import annotations

import contextlib
import cProfile
import io
import pstats


@contextlib.contextmanager
def trace(logdir: str, *, create_perfetto_link: bool = False):
    """Capture a JAX/XLA device trace for the enclosed region:

        with profiling.trace("/home/jovyan/traces"):
            state, metrics = step(state, batch)

    View with tensorboard (profile plugin) pointed at ``logdir``.
    """
    import jax
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host region inside a device trace (TraceAnnotation)."""
    import jax
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def profile_wsgi(sort: str = "cumulative", limit: int = 30):
    """cProfile a block of WSGI handling; yields a StringIO that holds
    the stats table after exit."""
    out = io.StringIO()
    prof = cProfile.Profile()
    prof.enable()
    try:
        yield out
    finally:
        prof.disable()
        pstats.Stats(prof, stream=out).sort_stats(sort).print_stats(limit)
