"""Tensorboard logging for ``fit()`` — the training side of the
platform's TB story.

BASELINE.json's eval config 5 is "tensorboard-controller reading GCS
logs from TPU JAX run": the controller serves a Tensorboard CR over a
``gs://`` or ``pvc://`` path (``controllers/tensorboard.py``); THIS
callback is what writes those logs from inside the notebook. Point it
at the workspace PVC (``pvc://``) or a mounted GCS bucket and create a
Tensorboard CR over the same path from the tensorboards web app.

``tensorboardX`` is already in the jupyter-jax image requirements; the
import is deferred so the library stays optional elsewhere.
"""

from __future__ import annotations

from kubeflow_rm_tpu.training.loop import LoopMetrics


class TensorboardCallback:
    """``fit(callbacks=(TensorboardCallback(logdir),))`` — one scalar
    per LoopMetrics field per log interval, flushed eagerly so a
    Tensorboard server tailing the directory sees points live."""

    def __init__(self, logdir: str, *, flush_secs: int = 10):
        from tensorboardX import SummaryWriter

        self.writer = SummaryWriter(logdir, flush_secs=flush_secs)

    def __call__(self, m: LoopMetrics) -> None:
        self.writer.add_scalar("train/loss", m.loss, m.step)
        self.writer.add_scalar("train/grad_norm", m.grad_norm, m.step)
        self.writer.add_scalar("perf/tokens_per_sec", m.tokens_per_sec,
                               m.step)
        self.writer.add_scalar("perf/mfu_pct", m.mfu_pct, m.step)
        self.writer.add_scalar("perf/step_time_ms", m.step_time_ms,
                               m.step)
        self.writer.flush()

    def close(self) -> None:
        self.writer.close()
