"""kubeflow_rm_tpu — a TPU-native rebuild of the Kubeflow Notebooks stack.

Two halves, mirroring the layer map in SURVEY.md §1:

- ``controlplane``: the platform — Notebook/Profile/PodDefault/Tensorboard/
  PVCViewer resource model (``controlplane/api``), reconcilers that render
  TPU-slice StatefulSets (``controlplane/controllers``), the mutating-
  webhook merge engine with TPU rendezvous injection
  (``controlplane/webhook``), per-namespace TPU-chip quotas, and idle
  culling. Capability parity
  with /root/reference components/*, re-designed for slice-atomic TPU
  scheduling; citations in each module's docstring.

- the compute path (``models``, ``ops``, ``parallel``, ``training``): what
  runs *inside* the provisioned notebook image — a JAX/pallas Llama stack
  with FSDP/TP/SP sharding over a ``jax.sharding.Mesh``, ring attention for
  long context, and a fine-tuning trainer targeting >=40% MFU (BASELINE.md).
  The reference delegates this layer to CUDA wheels inside its images
  (SURVEY.md §2.6); here it is first-class.
"""

__version__ = "0.1.0"
