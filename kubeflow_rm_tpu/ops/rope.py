"""Rotary position embeddings (RoPE).

Angles are computed inside the jitted computation from integer positions
rather than gathered from a precomputed table: on TPU the trig is a few
cheap VPU ops that XLA fuses into the surrounding reshapes, it keeps the
op shape-polymorphic in sequence length, and — critically for sequence
parallelism — each shard can evaluate its *global* positions locally with
no gather and no replicated (max_seq, head_dim) buffer in HBM.
"""

import jax
import jax.numpy as jnp


def rope_angles(positions: jax.Array, head_dim: int, theta: float = 10000.0):
    """Return (cos, sin) of shape ``positions.shape + (head_dim // 2,)``.

    ``positions`` is an integer array of token positions (any shape,
    typically (B, T)); fractional frequencies follow the Llama convention
    ``theta ** (-2i/d)``.
    """
    half = head_dim // 2
    freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half * 1.0)
    # positions: (..., 1) * freq: (half,) -> (..., half)
    ang = positions.astype(jnp.float32)[..., None] * freq
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate ``x`` of shape (B, T, H, head_dim) by per-position angles.

    ``cos``/``sin`` have shape (B, T, head_dim//2) and broadcast over the
    head axis. Uses the split-halves convention (first half paired with
    second half), matching the neox/llama JAX implementations.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    c = cos[..., None, :]  # (B, T, 1, half)
    s = sin[..., None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
