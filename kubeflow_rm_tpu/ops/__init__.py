from kubeflow_rm_tpu.ops.norms import rms_norm
from kubeflow_rm_tpu.ops.rope import apply_rope, rope_angles
from kubeflow_rm_tpu.ops.attention import attention_mask, dot_product_attention
from kubeflow_rm_tpu.ops.losses import softmax_cross_entropy

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_angles",
    "attention_mask",
    "dot_product_attention",
    "softmax_cross_entropy",
]
