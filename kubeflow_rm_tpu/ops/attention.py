"""Multi-head attention for training.

The default implementation is plain XLA: one batched matmul for scores,
an fp32 softmax, one batched matmul for the output. On TPU this maps
directly onto the MXU and, combined with per-layer rematerialization in
the model (see ``models/llama.py``), keeps only one layer's (B, H, T, T)
score tensor live at a time — at fine-tuning sequence lengths (<= 8k)
that is both faster to compile and competitive with a hand-written
kernel. A pallas flash-attention path can be slotted in through the same
signature for long-context runs; ring attention for sequence-parallel
long context lives in ``parallel/ring_attention.py`` and reuses the same
blockwise math.

GQA (n_kv_heads < n_heads) is expressed by reshaping queries into
(kv_head, group) rather than materializing repeated K/V — the einsum
contracts over the shared kv head axis so K/V stay at their true size in
HBM.
"""

import jax
import jax.numpy as jnp

NEG_INF = -2.0**30  # large-but-finite: keeps fp32 softmax NaN-free on fully masked rows


def attention_mask(
    Tq: int,
    Tk: int,
    *,
    causal: bool = True,
    positions_q: jax.Array | None = None,
    positions_kv: jax.Array | None = None,
    segment_ids_q: jax.Array | None = None,
    segment_ids_kv: jax.Array | None = None,
) -> jax.Array | None:
    """Boolean keep-mask, (Tq, Tk) or (B, Tq, Tk), or None if unmasked.

    Causality uses global positions when given (sequence-parallel shards,
    packed sequences); segment ids — when given — additionally restrict
    attention to ``seg_q == seg_kv`` so packed documents stay independent
    and padding (its own segment) is never attended.
    """
    mask = None
    if causal:
        if positions_q is None:
            mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]  # (Tq, Tk)
        else:
            mask = positions_q[:, :, None] >= positions_kv[:, None, :]  # (B, Tq, Tk)
    if segment_ids_q is not None:
        seg = segment_ids_q[:, :, None] == segment_ids_kv[:, None, :]  # (B, Tq, Tk)
        mask = seg if mask is None else mask & seg
    return mask


def flash_eligible(q, k, *, causal, positions_q, bias) -> bool:
    """Can the pallas flash kernel handle this call exactly?

    Requires: causal self-attention over local indices (no explicit
    positions — packed sequences are covered because local-causal ∧
    same-segment ≡ position-causal ∧ same-segment, see
    ``flash_attention`` docstring), no additive bias, and shapes that
    tile the block sizes the kernel will actually pick.
    """
    from kubeflow_rm_tpu.ops.flash_attention import (
        DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, pick_block,
    )
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq = pick_block(DEFAULT_BLOCK_Q, Tq)
    bk = pick_block(DEFAULT_BLOCK_K, Tk)
    return (causal and bias is None and positions_q is None
            and Tq == Tk and bq > 0 and bk > 0
            and D % 8 == 0)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    positions_q: jax.Array | None = None,
    positions_kv: jax.Array | None = None,
    segment_ids_q: jax.Array | None = None,
    segment_ids_kv: jax.Array | None = None,
    bias: jax.Array | None = None,
    impl: str = "auto",
) -> jax.Array:
    """Scaled dot-product attention.

    Args:
      q: (B, Tq, H, D) queries.
      k, v: (B, Tk, KVH, D) keys/values; H must be a multiple of KVH.
      causal: apply a causal mask. When ``positions_q``/``positions_kv``
        are given (sequence-parallel shards, packed sequences) the mask is
        ``pos_q >= pos_kv``; otherwise it is the standard lower-triangular
        mask over local indices.
      segment_ids_q / segment_ids_kv: optional (B, T) int segment ids for
        packed sequences; attention is restricted to equal segments.
      bias: optional additive bias broadcastable to (B, H, Tq, Tk).

      impl: "auto" (flash on TPU when exactly representable, else XLA),
        "flash" (force the pallas kernel; interpreter off-TPU), or
        "xla" (always the materialized-scores path).

    Returns:
      (B, Tq, H, D) in q.dtype.
    """
    if impl not in ("auto", "flash", "xla"):
        raise ValueError(f"impl must be auto|flash|xla, got {impl!r}")
    if impl == "flash" and (bias is not None or positions_q is not None):
        raise ValueError(
            "impl='flash' cannot represent an additive bias or explicit "
            "positions; use impl='xla' (packed sequences need only "
            "segment ids — see ops/flash_attention.py)")
    use_flash = (
        impl == "flash"
        or (impl == "auto"
            and jax.default_backend() == "tpu"
            # single-device only: pallas_call has no GSPMD partitioning
            # rule, so under a multi-chip jit the compiler would
            # all-gather the FULL global q/k/v onto every device —
            # silently defeating dp/fsdp/sp sharding. Multi-chip meshes
            # keep the einsum path (partitions cleanly) or use the ring
            # schedules; shard_map-wrapping the kernel is the follow-up
            # that lifts this gate.
            and jax.device_count() == 1
            and flash_eligible(q, k, causal=causal,
                               positions_q=positions_q, bias=bias))
    )
    if use_flash:
        from kubeflow_rm_tpu.ops.flash_attention import flash_attention
        return flash_attention(
            q, k, v, causal=causal,
            segment_ids_q=segment_ids_q, segment_ids_kv=segment_ids_kv)

    B, Tq, H, D = q.shape
    _, Tk, KVH, _ = k.shape
    assert H % KVH == 0, f"n_heads {H} not divisible by n_kv_heads {KVH}"
    G = H // KVH

    scale = D ** -0.5
    qf = (q * scale).reshape(B, Tq, KVH, G, D)

    # scores: (B, KVH, G, Tq, Tk)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qf, k, preferred_element_type=jnp.float32)

    if bias is not None:
        bias = jnp.broadcast_to(bias, (B, H, Tq, Tk))
        scores = scores + bias.reshape(B, KVH, G, Tq, Tk).astype(jnp.float32)

    mask = attention_mask(
        Tq, Tk, causal=causal,
        positions_q=positions_q, positions_kv=positions_kv,
        segment_ids_q=segment_ids_q, segment_ids_kv=segment_ids_kv,
    )
    if mask is not None:
        if mask.ndim == 2:
            scores = jnp.where(mask[None, None, None], scores, NEG_INF)
        else:
            scores = jnp.where(mask[:, None, None], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs.astype(v.dtype), v)
    return out.reshape(B, Tq, H, D)
