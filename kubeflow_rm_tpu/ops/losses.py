"""Training losses.

The cross-entropy is computed from logits in fp32 with an optional z-loss
regularizer (keeps the softmax normalizer bounded — standard practice for
bf16 TPU training). Labels set to ``ignore_index`` contribute zero loss
and zero weight, which is how the data pipeline masks padding and prompt
tokens during fine-tuning.
"""

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    *,
    z_loss: float = 0.0,
    ignore_index: int = IGNORE_INDEX,
):
    """Mean token cross-entropy.

    Args:
      logits: (..., V) unnormalized log-probs (any float dtype; promoted
        to fp32 internally).
      labels: (...) int targets, with ``ignore_index`` marking tokens to
        exclude from the mean.

    Returns:
      (loss, aux) where ``loss`` is the scalar masked mean NLL
      (+ z-loss if requested) and ``aux`` has per-component terms and the
      valid-token count.
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)

    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, safe_labels[..., None], axis=-1
    ).squeeze(-1)
    nll = lse - label_logit

    weight = valid.astype(jnp.float32)
    denom = jnp.maximum(weight.sum(), 1.0)
    nll_mean = (nll * weight).sum() / denom

    aux = {"nll": nll_mean, "n_valid": weight.sum()}
    loss = nll_mean
    if z_loss:
        zl = z_loss * ((lse**2) * weight).sum() / denom
        aux["z_loss"] = zl
        loss = loss + zl
    return loss, aux
