"""Flash attention — pallas TPU kernel for the hot op.

Round 1 materialized a (B, H, T, T) score tensor per layer
(``ops/attention.py``), which caps usable context and burns HBM
bandwidth on the one tensor XLA cannot fuse away. This module is the
promised slot-in (VERDICT "weak" #5): a blockwise online-softmax
forward in pallas — scores never leave VMEM — plus a memory-efficient
blockwise backward from saved logsumexp residuals.

Design (pallas_guide.md patterns):
- grid = (batch·heads, q_blocks, kv_blocks), kv innermost and marked
  "arbitrary" so the (m, l, acc) VMEM scratch carries across kv steps;
  the output block writes once on the final kv step.
- **Causal block skipping**: fully-future kv blocks are skipped with
  ``pl.when`` — ~half the MXU work for causal training, the same
  saving the zigzag ring schedule gets at the slice level.
- GQA without repetition: q is laid out (B·KVH·G, T, D) while k/v stay
  (B·KVH, T, D); the kv index map divides by G, so repeated heads are
  a VMEM aliasing trick, not an HBM copy.
- Backward is blockwise XLA (scan over kv blocks for dq; over q blocks
  for dk/dv) using the softmax residual lse = m + log l — standard
  flash-attention calculus, O(T·block) memory, MXU-shaped matmuls.
  A hand-scheduled pallas backward can replace it behind the same
  custom_vjp without touching callers.

Semantics: causal over LOCAL indices + optional segment ids. This is
exactly the packed-documents contract (``training/data.pack_documents``):
within a row, positions rise monotonically inside each document and the
segment mask removes cross-document attention, so local-causal ∧
same-segment ≡ position-causal ∧ same-segment. Callers with truly
non-local positions (ring attention shards) use the XLA path or the
ring schedule in ``parallel/ring_attention.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.0**30

# 1024 blocks measured best on v5e for the bench1b shapes (53.4% MFU
# vs 51.1% at 512, 44.0% at 256, with the pallas backward): fewer,
# bigger MXU panels beat finer-grained causal skipping. ``pick_block``
# degrades to the largest divisor of T so sequence lengths that are
# multiples of 128 but not 1024 (1280, 1536, ...) stay on the kernel.
import os

# KFRM_FLASH_BLOCK overrides both defaults (KFRM_FLASH_BLOCK_Q/_K win
# for asymmetric grids) — the bench sweep's knob; code callers pass
# block_q/block_k explicitly.
_BLOCK_ENV = os.environ.get("KFRM_FLASH_BLOCK", 1024)
DEFAULT_BLOCK_Q = int(os.environ.get("KFRM_FLASH_BLOCK_Q", _BLOCK_ENV))
DEFAULT_BLOCK_K = int(os.environ.get("KFRM_FLASH_BLOCK_K", _BLOCK_ENV))


def pick_block(preferred: int, T: int) -> int:
    """Block size for a length-T sequence: the preferred block when it
    divides T (explicit requests, incl. sub-128 test blocks, are
    honored), else the largest 128-multiple divisor of T. Returns 0
    when no VMEM-safe block exists (long T with no such divisor) — the
    caller must reject rather than launch a full-length score block."""
    b = min(preferred, T)
    if T % b == 0:
        return b
    b = (b // 128) * 128
    while b >= 128:
        if T % b == 0:
            return b
        b -= 128
    return T if T < 128 else 0


# ---------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, segq_ref, segkv_ref,
                o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, block_q: int, block_k: int):
    i = pl.program_id(1)   # q block
    j = pl.program_id(2)   # kv block
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: a kv block strictly in the future of every query row of
    # this q block contributes nothing — skip its matmuls entirely
    run = (not causal) or (j * block_k <= i * block_q + (block_q - 1))

    @pl.when(run)
    def _step():
        q = q_ref[0]                     # (bq, D)
        k = k_ref[0]                     # (bk, D)
        v = v_ref[0]                     # (bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)

        mask = None
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = rows >= cols
        if segq_ref is not None:
            # segment blocks are (1, 8, b*): sublane-padded, row 0 live
            seg = segq_ref[0, 0][:, None] == segkv_ref[0, 0][None, :]
            mask = seg if mask is None else mask & seg
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, 0][:, None]                    # (bq, 1)
        l_prev = l_ref[:, 0][:, None]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # explicit zeroing: on a fully-masked block exp(NEG_INF - m_new)
        # underflows to 0 only when m_new is sane; when every block so
        # far was masked m_new == NEG_INF and exp(0) = 1 would leak
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)                   # (bq, 1)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finish():
        l = l_ref[:, 0][:, None]
        safe_l = jnp.where(l == 0.0, 1.0, l)             # fully-masked rows
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        m = m_ref[:, 0]
        lse = jnp.where(l[:, 0] == 0.0, NEG_INF, m + jnp.log(l[:, 0]))
        # lse block is (1, 8, bq): 8 replicated sublanes to satisfy the
        # TPU (8, 128) tiling floor; row 0 is read back
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, segq, segkv, causal, block_q, block_k, group,
           interpret):
    out, _ = _flash_call(q, k, v, segq, segkv, causal, block_q, block_k,
                         group, interpret)
    return out


def _flash_call(q, k, v, segq, segkv, causal, block_q, block_k, group,
                interpret):
    """q: (B, KVH*G, T, D); k/v: (B, KVH, T, D);
    segq/segkv: (B, T) int32 or None. Returns (out, lse)."""
    B, Hq, T, D = q.shape
    KVH = k.shape[1]
    scale = D ** -0.5
    qf = q.reshape(B * Hq, T, D)
    kf = k.reshape(B * KVH, T, D)
    vf = v.reshape(B * KVH, T, D)
    nq, nk = T // block_q, T // block_k

    def q_map(b, i, j):
        return (b, i, 0)

    def kv_map(b, i, j):
        return (b // group, j, 0)

    def segq_map(b, i, j):
        return (b // Hq, 0, i)

    def segkv_map(b, i, j):
        return (b // Hq, 0, j)

    in_specs = [
        pl.BlockSpec((1, block_q, D), q_map),
        pl.BlockSpec((1, block_k, D), kv_map),
        pl.BlockSpec((1, block_k, D), kv_map),
    ]
    args = [qf, kf, vf]
    if segq is not None:
        # sublane-pad (B, T) -> (B, 8, T) for the (8, 128) tiling floor
        segq8 = jnp.broadcast_to(segq[:, None, :], (B, 8, T))
        segkv8 = jnp.broadcast_to(segkv[:, None, :], (B, 8, T))
        in_specs += [pl.BlockSpec((1, 8, block_q), segq_map),
                     pl.BlockSpec((1, 8, block_k), segkv_map)]
        args += [segq8, segkv8]

        def kernel(q_ref, k_ref, v_ref, segq_ref, segkv_ref, o_ref,
                   lse_ref, acc_ref, m_ref, l_ref):
            return _fwd_kernel(q_ref, k_ref, v_ref, segq_ref, segkv_ref,
                               o_ref, lse_ref, acc_ref, m_ref, l_ref,
                               scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                   l_ref):
            return _fwd_kernel(q_ref, k_ref, v_ref, None, None, o_ref,
                               lse_ref, acc_ref, m_ref, l_ref,
                               scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)

    out, lse = pl.pallas_call(
        kernel,
        grid=(B * Hq, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), q_map),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * Hq, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * Hq, 8, T), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    return out.reshape(B, Hq, T, D), lse[:, 0, :].reshape(B, Hq, T)


def _flash_fwd_rule(q, k, v, segq, segkv, causal, block_q, block_k,
                    group, interpret):
    out, lse = _flash_call(q, k, v, segq, segkv, causal, block_q,
                           block_k, group, interpret)
    return out, (q, k, v, segq, segkv, out, lse)


# Backward implementation selector. The hand-scheduled pallas backward
# gets the causal 2x by SKIPPING future blocks inside the kernel grid
# (pl.when, same trick as the forward) without leaving the MXU — the
# thing the triangular XLA scan couldn't do (see _flash_bwd_xla note).
BACKWARD_IMPL = "pallas"  # "pallas" | "xla"


def _flash_bwd_rule(causal, block_q, block_k, group, interpret, res, do):
    if BACKWARD_IMPL == "pallas":
        return _flash_bwd_pallas(causal, block_q, block_k, group,
                                 interpret, res, do)
    return _flash_bwd_xla(causal, block_q, block_k, group, interpret,
                          res, do)


# ---------------------------------------------------------------------
# pallas backward: dq kernel + dk/dv kernel
# ---------------------------------------------------------------------

def _bwd_mask(i, j, block_q, block_k, causal, segq_ref, segkv_ref):
    mask = None
    if causal:
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = rows >= cols
    if segq_ref is not None:
        seg = segq_ref[0, 0][:, None] == segkv_ref[0, 0][None, :]
        mask = seg if mask is None else mask & seg
    return mask


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               segq_ref, segkv_ref, dq_ref, dq_acc,
               *, scale, causal, block_q, block_k):
    i = pl.program_id(1)   # q block (parallel)
    j = pl.program_id(2)   # kv block (arbitrary, accumulated)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (not causal) or (j * block_k <= i * block_q + (block_q - 1))

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]                     # (bq, 1)
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        p = jnp.exp(s - lse)
        mask = _bwd_mask(i, j, block_q, block_k, causal, segq_ref,
                         segkv_ref)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                segq_ref, segkv_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, block_q, block_k):
    j = pl.program_id(1)   # kv block (parallel)
    i = pl.program_id(2)   # q block (arbitrary, accumulated)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (not causal) or (j * block_k <= i * block_q + (block_q - 1))

    @pl.when(run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        p = jnp.exp(s - lse)
        mask = _bwd_mask(i, j, block_q, block_k, causal, segq_ref,
                         segkv_ref)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        # dv += P^T dO ; dk += dS^T q
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(causal, block_q, block_k, group, interpret, res,
                      do):
    """Hand-scheduled backward: two pallas kernels sharing the forward's
    layout tricks (GQA via kv index-map division, sublane-padded
    residuals, causal block skipping). dq runs on a (BH, nq, nk) grid
    with kv innermost; dk/dv on (BH, nk, nq) with q innermost, each
    accumulating its output block in VMEM across the arbitrary dim —
    future blocks never issue their matmuls, which is the causal 2x the
    rectangular XLA scan left on the table."""
    q, k, v, segq, segkv, out, lse = res
    B, Hq, T, D = q.shape
    KVH = k.shape[1]
    scale = D ** -0.5

    qf = q.reshape(B * Hq, T, D)
    kf = k.reshape(B * KVH, T, D)
    vf = v.reshape(B * KVH, T, D)
    dof = do.reshape(B * Hq, T, D)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(B * Hq, T)              # (BH, T)
    lsef = lse.reshape(B * Hq, T)
    # sublane-pad the per-row residuals to the (8, 128) tiling floor,
    # exactly as the forward stores lse
    lse8 = jnp.broadcast_to(lsef[:, None, :], (B * Hq, 8, T))
    delta8 = jnp.broadcast_to(delta[:, None, :], (B * Hq, 8, T))

    nq, nk = T // block_q, T // block_k

    def q_map_qji(b, i, j):
        return (b, i, 0)

    def kv_map_qji(b, i, j):
        return (b // group, j, 0)

    def row_map_qji(b, i, j):
        return (b, 0, i)

    def segq_map_qji(b, i, j):
        return (b // Hq, 0, i)

    def segkv_map_qji(b, i, j):
        return (b // Hq, 0, j)

    # dk/dv grid is (b, j, i): same maps with the roles swapped
    def q_map_kji(b, j, i):
        return (b, i, 0)

    def kv_map_kji(b, j, i):
        return (b // group, j, 0)

    def row_map_kji(b, j, i):
        return (b, 0, i)

    def segq_map_kji(b, j, i):
        return (b // Hq, 0, i)

    def segkv_map_kji(b, j, i):
        return (b // Hq, 0, j)

    has_seg = segq is not None
    if has_seg:
        segq8 = jnp.broadcast_to(segq[:, None, :], (B, 8, T))
        segkv8 = jnp.broadcast_to(segkv[:, None, :], (B, 8, T))

    def specs(q_map, kv_map, row_map, segq_map, segkv_map):
        in_specs = [
            pl.BlockSpec((1, block_q, D), q_map),    # q
            pl.BlockSpec((1, block_k, D), kv_map),   # k
            pl.BlockSpec((1, block_k, D), kv_map),   # v
            pl.BlockSpec((1, block_q, D), q_map),    # do
            pl.BlockSpec((1, 8, block_q), row_map),  # lse
            pl.BlockSpec((1, 8, block_q), row_map),  # delta
        ]
        if has_seg:
            in_specs += [pl.BlockSpec((1, 8, block_q), segq_map),
                         pl.BlockSpec((1, 8, block_k), segkv_map)]
        return in_specs

    args = [qf, kf, vf, dof, lse8, delta8]
    if has_seg:
        args += [segq8, segkv8]

    def wrap(kernel):
        if has_seg:
            def f(q_r, k_r, v_r, do_r, lse_r, dl_r, sq_r, skv_r, *rest):
                return kernel(q_r, k_r, v_r, do_r, lse_r, dl_r, sq_r,
                              skv_r, *rest, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k)
        else:
            def f(q_r, k_r, v_r, do_r, lse_r, dl_r, *rest):
                return kernel(q_r, k_r, v_r, do_r, lse_r, dl_r, None,
                              None, *rest, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k)
        return f

    dq = pl.pallas_call(
        wrap(_dq_kernel),
        grid=(B * Hq, nq, nk),
        in_specs=specs(q_map_qji, kv_map_qji, row_map_qji,
                       segq_map_qji, segkv_map_qji),
        out_specs=pl.BlockSpec((1, block_q, D), q_map_qji),
        out_shape=jax.ShapeDtypeStruct((B * Hq, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)

    # dk/dv per Q-HEAD (B*Hq) — grouped heads fold onto their shared kv
    # head afterwards, so no two grid rows write the same output block
    dk_h, dv_h = pl.pallas_call(
        wrap(_dkv_kernel),
        grid=(B * Hq, nk, nq),
        in_specs=specs(q_map_kji, kv_map_kji, row_map_kji,
                       segq_map_kji, segkv_map_kji),
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * Hq, T, D), k.dtype),
            jax.ShapeDtypeStruct((B * Hq, T, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)

    dq = dq.reshape(B, Hq, T, D)
    dk = dk_h.reshape(B, KVH, group, T, D).sum(axis=2)
    dv = dv_h.reshape(B, KVH, group, T, D).sum(axis=2)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None)


def _flash_bwd_xla(causal, block_q, block_k, group, interpret, res, do):
    """Blockwise backward from lse residuals — O(T·block) memory.

    dS = P ∘ (dP − δ) with P = exp(S − lse), dP = dO·Vᵀ,
    δ = rowsum(dO ∘ O); dQ = dS·K, dK = dSᵀ·Q, dV = Pᵀ·dO.

    Deliberately a RECTANGULAR scan over kv blocks (each step contracts
    the full (T × blk) panel) even though causal masking wastes ~half
    its FLOPs on future blocks. The "obvious" fix — a triangular
    (q-tile × kv-tile) scan visiting only qb ≥ jb pairs — was measured
    SLOWER on the v5e bench (36.7% vs 42.7% MFU end-to-end): it
    serializes nb(nb+1)/2 small matmuls and adds read-modify-write
    accumulator traffic, losing more to MXU underutilization than the
    skipped FLOPs save. Kept as the fallback/reference implementation
    behind ``BACKWARD_IMPL``; the pallas kernels above get the causal
    2x properly (block skipping inside the grid).
    """
    q, k, v, segq, segkv, out, lse = res
    B, Hq, T, D = q.shape
    KVH = k.shape[1]
    scale = D ** -0.5
    kr = jnp.repeat(k, group, axis=1)          # (B, Hq, T, D) — see note
    vr = jnp.repeat(v, group, axis=1)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B, Hq, T)

    nk = T // block_k
    rows = jnp.arange(T)

    def kv_block(carry, jb):
        dq_acc, dk_acc, dv_acc = carry
        k0 = jb * block_k
        ks = jax.lax.dynamic_slice_in_dim(kr, k0, block_k, 2)
        vs = jax.lax.dynamic_slice_in_dim(vr, k0, block_k, 2)
        cols = k0 + jnp.arange(block_k)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ks,
                       preferred_element_type=jnp.float32) * scale
        mask = None
        if causal:
            mask = (rows[:, None] >= cols[None, :])[None, None]
        if segq is not None:
            sk = jax.lax.dynamic_slice_in_dim(segkv, k0, block_k, 1)
            seg = (segq[:, :, None] == sk[:, None, :])[:, None]
            mask = seg if mask is None else mask & seg
        p = jnp.exp(s - lse[..., None])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vs.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds,
                                     ks.astype(jnp.float32))
        dk_b = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
        dv_b = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dk_acc = jax.lax.dynamic_update_slice_in_dim(dk_acc, dk_b, k0, 2)
        dv_acc = jax.lax.dynamic_update_slice_in_dim(dv_acc, dv_b, k0, 2)
        return (dq_acc, dk_acc, dv_acc), None

    zeros_q = jnp.zeros((B, Hq, T, D), jnp.float32)
    (dq, dk_full, dv_full), _ = jax.lax.scan(
        kv_block, (zeros_q, zeros_q, zeros_q), jnp.arange(nk))

    # fold grouped-query heads back onto their shared kv head
    dk = dk_full.reshape(B, KVH, group, T, D).sum(axis=2)
    dv = dv_full.reshape(B, KVH, group, T, D).sum(axis=2)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------
# public wrapper
# ---------------------------------------------------------------------

def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids_q: jax.Array | None = None,
    segment_ids_kv: jax.Array | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention with the ``dot_product_attention`` layout:
    q (B, T, H, D); k, v (B, T, KVH, D) → (B, T, H, D).

    Causality is over local indices; combined with segment ids this is
    exact for packed documents (module docstring). ``interpret=None``
    auto-selects the pallas interpreter off-TPU so tests run on CPU.
    """
    B, T, H, D = q.shape
    KVH = k.shape[2]
    assert H % KVH == 0
    group = H // KVH
    block_q = pick_block(block_q, T)
    block_k = pick_block(block_k, T)
    if not block_q or not block_k:
        raise ValueError(
            f"T={T} has no 128-multiple block divisor; use the XLA path")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qh = jnp.swapaxes(q, 1, 2)   # (B, H, T, D)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    segq = None if segment_ids_q is None else segment_ids_q.astype(
        jnp.int32)
    segkv = None if segment_ids_kv is None else segment_ids_kv.astype(
        jnp.int32)
    out = _flash(qh, kh, vh, segq, segkv, causal, block_q, block_k,
                 group, interpret)
    return jnp.swapaxes(out, 1, 2)
