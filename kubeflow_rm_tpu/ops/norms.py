"""Normalization ops.

TPU note: the reduction runs in float32 regardless of the compute dtype —
bf16 mean-of-squares loses enough mantissa to visibly hurt loss curves.
XLA fuses the whole thing into the neighbouring matmul's prologue, so there
is no reason to hand-write a pallas kernel for this op.
"""

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Llama-style RMSNorm: ``x * rsqrt(mean(x^2)) * weight``.

    The result is cast back to ``x.dtype`` so callers keep their compute
    dtype (bf16 on TPU) through the residual stream.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)
