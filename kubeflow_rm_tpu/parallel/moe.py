"""Mixture-of-experts layer with expert parallelism over the ``ep`` axis.

TPU-first design — dense dispatch, not gather/scatter:

- Routing produces a static-shaped dispatch tensor (tokens, E, C) and
  the expert FFN runs as one batched matmul over the expert dim. No
  ragged shapes, no data-dependent control flow: everything tiles onto
  the MXU and jit-compiles once (GShard/Switch formulation).
- Expert parallelism is pure sharding: the expert dim of the weights
  carries ``ep`` (``sharding._MIXTRAL_RULES``) and XLA's SPMD
  partitioner turns the dispatch/combine einsums into the all-to-alls
  an expert-parallel layer needs — the scaling-book recipe, in contrast
  to the reference's hand-written NCCL all-to-all (SURVEY.md §2.6 lists
  EP as an in-image capability to supply).
- Capacity-dropped tokens fall through on the residual path (standard
  Switch behavior); the auxiliary load-balancing loss keeps routing
  uniform so drops stay rare.
"""

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MoeConfig:
    n_experts: int = 8
    top_k: int = 2
    # per-expert slots = ceil(top_k * tokens * capacity_factor / E)
    capacity_factor: float = 1.25
    # weight of the load-balancing aux loss in the training objective
    router_aux_weight: float = 0.01


def expert_capacity(cfg: MoeConfig, n_tokens: int) -> int:
    import math
    cap = math.ceil(cfg.top_k * n_tokens * cfg.capacity_factor /
                    cfg.n_experts)
    return max(cap, 1)


def route(router_logits: jax.Array, cfg: MoeConfig, capacity: int):
    """Top-k routing with per-expert capacity.

    Args:
      router_logits: (N, E) fp32.
    Returns:
      dispatch: (N, E, C) 0/1 — token n occupies slot c of expert e.
      combine: (N, E, C) fp32 — dispatch weighted by the (renormalized)
        top-k gate.
      aux_loss: scalar load-balancing loss (Switch formulation,
        ``E * Σ_e fraction_routed_e * mean_prob_e``).
    """
    N, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, cfg.top_k)  # (N, k)
    gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)

    # one-hot per slot; slot 0 (the argmax choice) claims capacity
    # before slot 1 across ALL tokens, then ties break by token order —
    # priority is (slot, token), matching the GShard schedule
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # (N, k, E)
    slot_major = onehot.transpose(1, 0, 2).reshape(cfg.top_k * N, E)
    pos = jnp.cumsum(slot_major, axis=0) - 1  # position within expert
    pos = pos.reshape(cfg.top_k, N, E).transpose(1, 0, 2)  # (N, k, E)
    pos = jnp.sum(pos * onehot, axis=-1)  # (N, k) slot index
    fits = pos < capacity

    slot_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
    slot_onehot = slot_onehot * fits[..., None]
    # (N, k, E, C): expert choice x slot
    dispatch_k = onehot[..., None].astype(jnp.float32) * \
        slot_onehot[:, :, None, :]
    dispatch = jnp.sum(dispatch_k, axis=1)  # (N, E, C)
    combine = jnp.sum(
        dispatch_k * gate_vals[..., None, None], axis=1)

    # load balance: fraction of tokens whose TOP choice is e x mean
    # router prob on e (differentiable through probs)
    top1 = onehot[:, 0, :].astype(jnp.float32)
    frac_routed = jnp.mean(top1, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(frac_routed * mean_prob)
    return dispatch, combine, aux_loss


def moe_param_shapes(cfg: MoeConfig, dim: int, hidden: int) -> dict:
    E = cfg.n_experts
    return {
        "router": (dim, E),
        "moe_gate": (E, dim, hidden),
        "moe_up": (E, dim, hidden),
        "moe_down": (E, hidden, dim),
    }


def moe_ffn(params: dict, x: jax.Array, cfg: MoeConfig,
            dtype: Any = jnp.bfloat16):
    """SwiGLU expert FFN. x: (B, T, D) -> ((B, T, D), aux_loss).

    The (E, C, D) expert batch is where EP bites: with w_* sharded
    P(..., "ep", ...) the dispatch einsum becomes an all-to-all and the
    three expert matmuls run ep-parallel.
    """
    B, T, D = x.shape
    N = B * T
    xf = x.reshape(N, D)
    # router in fp32: tiny matmul, and routing decisions should not
    # flip with bf16 rounding
    logits = xf.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    capacity = expert_capacity(cfg, N)
    dispatch, combine, aux = route(logits, cfg, capacity)

    from jax.ad_checkpoint import checkpoint_name

    xc = xf.astype(dtype)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(dtype), xc)
    # tag with the same names as the dense MLP so the named remat
    # policies ("mlp", "attn+mlp") buy the same HBM/recompute trade
    # for the expert FFN
    gate = checkpoint_name(
        jnp.einsum("ecd,edf->ecf", expert_in,
                   params["moe_gate"].astype(dtype)), "mlp_gate")
    up = checkpoint_name(
        jnp.einsum("ecd,edf->ecf", expert_in,
                   params["moe_up"].astype(dtype)), "mlp_up")
    h = jax.nn.silu(gate) * up
    expert_out = jnp.einsum("ecf,efd->ecd", h,
                            params["moe_down"].astype(dtype))
    out = jnp.einsum("nec,ecd->nd", combine.astype(dtype), expert_out)
    return out.reshape(B, T, D), aux
