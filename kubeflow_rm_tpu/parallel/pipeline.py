"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` axis.

The layer stack is split into ``pp`` contiguous stages (the stacked
parameter layout makes this a pure sharding of the leading layer axis —
``sharding._LLAMA_RULES``), and microbatches flow stage-to-stage as one
``lax.scan`` over M + pp - 1 ticks. Each tick every stage runs its local
layers on the microbatch it currently holds, then hands its activation
to the next stage with a single ``ppermute`` hop. That is the whole
collective cost of PP — one point-to-point (mb, T, D) transfer per tick
— which is why ``pp`` sits on the slowest links (mesh.py axis order).

TPU-first notes:

- The schedule is data-independent (`lax.scan` over a static tick
  count), so XLA compiles ONE stage body; bubbles are the standard
  GPipe (pp-1)/(M+pp-1) fraction and shrink as microbatches grow.
- Only the ``pp`` axis is manual (``shard_map(..., axis_names={'pp'})``)
  — fsdp/tp/sp stay under GSPMD inside the stage body, so PP composes
  with the other parallelism styles without hand-written collectives.
- Stages that are "in the bubble" compute on garbage rather than
  branching: control flow under jit must be static, and predicated
  writes (`dynamic_update_index_in_dim` + `where`) keep the MXU busy
  schedule uniform across devices. Same-cost garbage beats divergent
  control flow on a systolic machine.

The reference framework ships PP via its torch/NCCL engine; SURVEY.md
§2.6 lists it as a first-class in-image capability, which this module
supplies (VERDICT r2 next-#7).
"""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_rm_tpu.models.llama import (
    LlamaConfig,
    _epilogue,
    _prologue,
    forward,
)


def pipeline_forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Mesh,
    **kw,
) -> jax.Array:
    """Causal LM forward pipelined over ``pp``; logits only (dense
    families). See ``pipeline_forward_with_aux`` for the full contract."""
    return pipeline_forward_with_aux(params, tokens, cfg, mesh, **kw)[0]


def pipeline_forward_with_aux(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Mesh,
    *,
    n_microbatches: int | None = None,
    positions: jax.Array | None = None,
    segments: jax.Array | None = None,
    packed: bool = False,
) -> tuple[jax.Array, jax.Array | None]:
    """Causal LM forward with the layer stack pipelined over ``pp``.

    Returns ``(logits, router_aux)`` — aux is the mean-per-(layer,
    microbatch) MoE load-balancing loss for Mixtral-family configs and
    ``None`` for dense ones. Semantically identical to the family's
    plain ``forward`` (same math, same remat policy per stage);
    exactness is asserted by ``tests/test_pipeline.py``. (For MoE the
    aux term is exactly equal only at ``n_microbatches=1`` — the
    load-balance statistic is nonlinear in the batch, so microbatching
    changes it slightly, same as gradient accumulation does.) Requires
    ``cfg.n_layers % pp == 0`` and ``B % n_microbatches == 0``.
    """
    from kubeflow_rm_tpu.models.mixtral import MixtralConfig, _moe_block

    is_moe = isinstance(cfg, MixtralConfig)
    pp = mesh.shape.get("pp", 1)
    if pp == 1:
        if is_moe:
            from kubeflow_rm_tpu.models.mixtral import forward as moe_fwd
            return moe_fwd(params, tokens, cfg, positions=positions,
                           segments=segments, packed=packed)
        return forward(params, tokens, cfg, positions=positions,
                       segments=segments, packed=packed), None
    if cfg.n_layers % pp:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={pp}")
    B, T = tokens.shape
    # None -> one microbatch per stage, the minimum that keeps every
    # stage busy (same default make_train_step applies).
    M = pp if n_microbatches is None else n_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    mb = B // M

    # shared prologue (embeddings + rope under GSPMD, remat-wrapped
    # block), then fold B -> (M, mb)
    x, cos, sin, attn_positions, block = _prologue(
        params, tokens, cfg, positions, segments, packed)

    # normalize the per-layer block to the (h, aux) contract so one
    # schedule serves both families
    if is_moe:
        from functools import partial

        from kubeflow_rm_tpu.models.llama import _remat_policy

        moe_block = partial(_moe_block, cfg)
        if cfg.remat:
            moe_block = jax.checkpoint(
                moe_block, policy=_remat_policy(cfg.remat_policy))
        block_aux = moe_block
    else:
        def block_aux(h, layer, *a):
            return block(h, layer, *a), jnp.zeros((), jnp.float32)

    # Interleaved fold: microbatch m takes rows m, M+m, 2M+m, ... so
    # each device's contiguous block of batch rows lands one row in
    # every microbatch. The (M, mb) layout then keeps M replicated and
    # mb carrying the batch sharding with ZERO resharding traffic — a
    # contiguous fold would split the batch axis across (M, mb), and
    # dynamic_index_in_dim over a sharded M plus the scan-carry layout
    # mismatch forces GSPMD into involuntary full rematerialization
    # (replicate + repartition every tick).
    batch_axes = ("dp", "fsdp")

    def fold(a):
        if a is None:
            return None
        a = a.reshape(mb, M, *a.shape[1:]).swapaxes(0, 1)
        spec = P(None, batch_axes, "sp", *([None] * (a.ndim - 3)))
        return jax.lax.with_sharding_constraint(
            a, jax.NamedSharding(mesh, spec))

    x_mb, cos_mb, sin_mb = fold(x), fold(cos), fold(sin)
    pos_mb, seg_mb = fold(attn_positions), fold(segments)

    stack_spec = jax.tree_util.tree_map(lambda _: P("pp"), params["blocks"])
    mb_spec = P()  # replicated over pp; other axes stay automatic

    def spmd(blocks, x_mb, cos_mb, sin_mb, pos_mb, seg_mb):
        stage = jax.lax.axis_index("pp")

        # Pin the activation layout on the auto (non-pp) axes: batch
        # rows over (dp, fsdp), sequence over sp, hidden replicated —
        # the true-FSDP pattern (gathered weights, batch-sharded
        # activations). Without this GSPMD may shard the scan carry on
        # the hidden dim instead, which conflicts with the cotangent
        # layout entering the backward scan and triggers involuntary
        # full rematerialization.
        # bare PartitionSpecs: inside the manual-pp region the ambient
        # abstract mesh carries the axis types, so a NamedSharding over
        # the outer (all-Auto) mesh would be rejected
        act_spec = P(batch_axes, "sp", None)
        outs_spec = P(None, batch_axes, "sp", None)

        def pin(a):
            return jax.lax.with_sharding_constraint(a, act_spec)

        def stage_apply(h, cos_t, sin_t, pos_t, seg_t):
            def body(carry, layer):
                h, aux = carry
                h, a = block_aux(h, layer, cos_t, sin_t, pos_t, seg_t)
                return (h, aux + a), None

            aux0 = jax.lax.pcast(jnp.zeros((), jnp.float32),
                                 ("pp",), to="varying")
            (h, aux), _ = jax.lax.scan(body, (h, aux0), blocks)
            return h, aux

        def pick(a_mb, idx):
            return None if a_mb is None else jax.lax.dynamic_index_in_dim(
                a_mb, idx, 0, keepdims=False)

        def tick(carry, t):
            recv, outputs, aux_total = carry
            # stage s holds microbatch t - s; clamp keeps bubble ticks
            # on a valid (discarded) index instead of branching
            idx = jnp.clip(t - stage, 0, M - 1)
            inp = pin(jnp.where(stage == 0, pick(x_mb, idx), recv))
            out, aux = stage_apply(inp, pick(cos_mb, idx),
                                   pick(sin_mb, idx),
                                   pick(pos_mb, idx), pick(seg_mb, idx))
            out = pin(out)
            recv_next = jax.lax.ppermute(
                out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            # bubble ticks compute on a clamped (garbage) microbatch:
            # their aux must not pollute the router loss
            valid = jnp.logical_and(t >= stage, t - stage <= M - 1)
            aux_total = aux_total + jnp.where(valid, aux, 0.0)
            # the last stage finishes microbatch t-(pp-1) at tick t
            w = jnp.clip(t - (pp - 1), 0, M - 1)
            keep = jnp.logical_and(stage == pp - 1, t >= pp - 1)
            cur = jax.lax.dynamic_index_in_dim(outputs, w, 0,
                                               keepdims=False)
            outputs = jax.lax.with_sharding_constraint(
                jax.lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(keep, out, cur), w, 0),
                outs_spec)
            return (recv_next, outputs, aux_total), None

        # the carry is stage-varying from tick 1 on; mark the initial
        # zeros varying over pp so scan's type check agrees
        carry0 = jax.lax.pcast(
            (jnp.zeros_like(x_mb[0]), jnp.zeros_like(x_mb),
             jnp.zeros((), jnp.float32)),
            ("pp",), to="varying")
        (_, outputs, aux_total), _ = jax.lax.scan(
            tick, carry0, jnp.arange(M + pp - 1))
        # broadcast the last stage's results to every pp shard; sum the
        # per-stage aux contributions (each (layer, microbatch) pair is
        # counted exactly once across stages)
        return (
            jax.lax.psum(
                jnp.where(stage == pp - 1, outputs,
                          jnp.zeros_like(outputs)), "pp"),
            jax.lax.psum(aux_total, "pp"),
        )

    in_specs = (stack_spec, mb_spec, mb_spec, mb_spec,
                None if pos_mb is None else mb_spec,
                None if seg_mb is None else mb_spec)
    h_mb, aux_total = jax.shard_map(
        spmd, mesh=mesh, in_specs=in_specs, out_specs=(mb_spec, P()),
        axis_names={"pp"},
    )(params["blocks"], x_mb, cos_mb, sin_mb, pos_mb, seg_mb)

    # inverse of the interleaved fold
    logits = _epilogue(
        params, h_mb.swapaxes(0, 1).reshape(B, T, cfg.dim), cfg)
    if not is_moe:
        return logits, None
    # mean per (layer, microbatch), matching the dense forward's
    # mean-per-layer normalization
    return logits, aux_total / (cfg.n_layers * M)
