"""Multi-host and multi-slice bootstrap from platform-injected env.

This is the in-image consumer of the control plane's rendezvous
contract. The webhook (``controlplane/webhook/tpu_inject.py``) injects
into every pod of a TPU notebook:

- ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` — the pod's ordinal
  WITHIN ITS SLICE and the headless-service DNS of its slice peers
  (ICI rendezvous);
- ``MEGASCALE_NUM_SLICES`` / ``MEGASCALE_SLICE_ID`` /
  ``MEGASCALE_COORDINATOR_ADDRESS`` — present only on multislice
  notebooks (``spec.tpu.numSlices > 1``), carrying the DCN dimension.

``initialize`` turns that env into one global ``jax.distributed``
job: process_id = slice_id·hosts_per_slice + worker_id, coordinator =
slice 0's worker 0. libtpu reads the TPU_* vars itself for ICI; jax's
megascale transport reads MEGASCALE_* for DCN. The reference platform
has no counterpart — its servers are single-pod (SURVEY.md §2.6,
``notebook_controller.go:409-412``).
"""

import os
from dataclasses import dataclass

import jax

DEFAULT_COORDINATOR_PORT = 8476


@dataclass(frozen=True)
class TpuEnv:
    worker_id: int               # ordinal within the slice
    worker_hostnames: list[str]  # this slice's peers
    accelerator_type: str | None
    topology: str | None
    num_slices: int = 1
    slice_id: int = 0
    coordinator: str | None = None  # multislice: slice 0's worker 0

    @property
    def hosts_per_slice(self) -> int:
        return max(1, len(self.worker_hostnames))

    @property
    def num_hosts(self) -> int:
        """Global process count across every slice."""
        return self.hosts_per_slice * max(1, self.num_slices)

    @property
    def process_id(self) -> int:
        """Global jax process id (slice-major, matching pod ordinals)."""
        return self.slice_id * self.hosts_per_slice + self.worker_id

    @property
    def is_multihost(self) -> bool:
        return self.num_hosts > 1

    @property
    def is_multislice(self) -> bool:
        return self.num_slices > 1


def tpu_env(environ=None) -> TpuEnv:
    """Read the rendezvous env injected by the notebook webhook."""
    env = os.environ if environ is None else environ
    hostnames = [
        h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    ]
    return TpuEnv(
        worker_id=int(env.get("TPU_WORKER_ID", "0")),
        worker_hostnames=hostnames,
        accelerator_type=env.get("TPU_ACCELERATOR_TYPE"),
        topology=env.get("TPU_TOPOLOGY"),
        num_slices=int(env.get("MEGASCALE_NUM_SLICES", "1")),
        slice_id=int(env.get("MEGASCALE_SLICE_ID", "0")),
        coordinator=env.get("MEGASCALE_COORDINATOR_ADDRESS"),
    )


def initialize(environ=None, port: int = DEFAULT_COORDINATOR_PORT) -> TpuEnv:
    """Initialize ``jax.distributed`` from the injected env (no-op on
    single-host single-slice). The coordinator is slice 0's worker 0 —
    pod ordinals are stable because the controller renders the job as a
    StatefulSet with a headless service, and multislice ordinals are
    slice-major so every process derives the same global numbering."""
    env = tpu_env(environ)
    if not env.is_multihost:
        return env
    if env.is_multislice and env.coordinator:
        coordinator = env.coordinator
    else:
        coordinator = env.worker_hostnames[0]
    jax.distributed.initialize(
        coordinator_address=f"{coordinator}:{port}",
        num_processes=env.num_hosts,
        process_id=env.process_id,
    )
    return env
