"""Multi-host bootstrap from platform-injected env.

This is the in-image consumer of the control plane's rendezvous contract:
the webhook in ``controlplane/webhook/tpu_inject.py`` injects
``TPU_WORKER_ID`` (pod ordinal) and ``TPU_WORKER_HOSTNAMES``
(headless-service DNS of every pod in the slice) into each pod of a
multi-host Notebook, and ``tests/test_notebook_controller.py`` asserts
the round-trip through this module. The reference has no equivalent —
its servers are single-pod (SURVEY.md §2.6, notebook_controller.go:409-412
replicas in {0,1}) — so this module plus the webhook is new capability.
"""

import os
from dataclasses import dataclass

import jax

DEFAULT_COORDINATOR_PORT = 8476


@dataclass(frozen=True)
class TpuEnv:
    worker_id: int
    worker_hostnames: list[str]
    accelerator_type: str | None
    topology: str | None

    @property
    def num_hosts(self) -> int:
        return max(1, len(self.worker_hostnames))

    @property
    def is_multihost(self) -> bool:
        return self.num_hosts > 1


def tpu_env(environ=None) -> TpuEnv:
    """Read the rendezvous env injected by the notebook webhook."""
    env = os.environ if environ is None else environ
    hostnames = [
        h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    ]
    return TpuEnv(
        worker_id=int(env.get("TPU_WORKER_ID", "0")),
        worker_hostnames=hostnames,
        accelerator_type=env.get("TPU_ACCELERATOR_TYPE"),
        topology=env.get("TPU_TOPOLOGY"),
    )


def initialize(environ=None, port: int = DEFAULT_COORDINATOR_PORT) -> TpuEnv:
    """Initialize ``jax.distributed`` from the injected env (no-op on
    single-host slices). Worker 0's headless DNS name is the coordinator —
    pod ordinals are stable because the controller renders the slice as a
    StatefulSet with a headless service."""
    env = tpu_env(environ)
    if env.is_multihost:
        jax.distributed.initialize(
            coordinator_address=f"{env.worker_hostnames[0]}:{port}",
            num_processes=env.num_hosts,
            process_id=env.worker_id,
        )
    return env
