"""Ulysses-style sequence parallelism: all-to-all over the ``sp`` axis.

The second of the two context-parallel schedules this framework ships
(the other being ``ring_attention``/``zigzag_ring``). Where the ring
rotates K/V chunks and keeps heads whole, Ulysses re-shards with two
all-to-alls: heads scatter across ``sp`` while the sequence gathers, so
each device runs *ordinary full-sequence attention* on H/sp heads, then
the inverse all-to-all restores the sequence layout. (Pattern from the
public DeepSpeed-Ulysses literature; implementation is jax-native over
``shard_map`` + ``lax.all_to_all``.)

Trade-off vs the ring, in ICI terms:

- **Ulysses**: 2 all-to-alls moving O(T·D·H/sp) per device, then the
  whole attention is ONE dense local call — the pallas flash kernel
  runs unmodified on (B, T, H/sp, D), so per-block softmax tricks,
  segment masks and the tuned 1024-block grid all apply.
- **Ring**: sp point-to-point hops overlapped with compute, memory
  stays O(T/sp) per device. Wins when T is too long for any device to
  hold the full sequence; Ulysses wins when heads are plentiful and
  the fused kernel beats sp smaller block matmuls.

Composes with the rest of the mesh exactly like the ring: only ``sp``
is manual; batch/head remainders stay under GSPMD.

The reference platform has no long-context story at all (SURVEY.md §5);
like the ring schedule this is TPU-native capability, not a port.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_rm_tpu.ops.attention import dot_product_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    positions_q: jax.Array | None = None,
    positions_kv: jax.Array | None = None,
    segment_ids_q: jax.Array | None = None,
    segment_ids_kv: jax.Array | None = None,
    impl: str = "auto",
) -> jax.Array:
    """Sequence-parallel attention via head/sequence all-to-all.

    Call inside ``shard_map`` with ``axis_name`` manual. Shapes are the
    local chunks: q (B, Tloc, H, D), k/v (B, Tloc, KVH, D), optional
    positions/segments (B, Tloc). Requires ``H % sp == 0``; KV heads
    that don't divide ``sp`` are broadcast up to H first (GQA loses its
    K/V memory saving across the scatter, never correctness).

    Returns the local (B, Tloc, H, D) output chunk.
    """
    n = jax.lax.axis_size(axis_name)
    B, Tloc, H, D = q.shape
    KVH = k.shape[2]
    if H % n:
        raise ValueError(
            f"ulysses needs sp ({n}) to divide n_heads ({H})")
    if KVH % n:
        reps = H // KVH
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)

    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    # scatter heads, gather sequence: (B, Tloc, H, D) -> (B, T, H/sp, D)
    qg = a2a(q, split_axis=2, concat_axis=1)
    kg = a2a(k, split_axis=2, concat_axis=1)
    vg = a2a(v, split_axis=2, concat_axis=1)

    def gather_seq(x):
        return None if x is None else jax.lax.all_gather(
            x, axis_name, axis=1, tiled=True)

    out = dot_product_attention(
        qg, kg, vg, causal=causal,
        positions_q=gather_seq(positions_q),
        positions_kv=gather_seq(positions_kv),
        segment_ids_q=gather_seq(segment_ids_q),
        segment_ids_kv=gather_seq(segment_ids_kv),
        impl=impl,
    )
    # inverse: scatter sequence, gather heads
    return a2a(out, split_axis=1, concat_axis=2)


def ulysses_self_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                           positions: jax.Array | None = None,
                           segments: jax.Array | None = None,
                           impl: str = "auto"):
    """Global-view convenience wrapper, mirror of ``ring_self_attention``:
    inputs are global (B, T, H, D) arrays on ``mesh``; only ``sp`` goes
    manual, batch/head axes stay under GSPMD."""
    spec = P(None, "sp", None, None)
    sspec = P(None, "sp")

    if positions is None and segments is None:
        fn = jax.shard_map(
            partial(ulysses_attention, axis_name="sp", causal=causal,
                    impl=impl),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            axis_names={"sp"},
        )
        return fn(q, k, v)

    B, T = q.shape[:2]
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    if segments is None:
        segments = jnp.zeros((B, T), jnp.int32)

    def local(q, k, v, pos, seg):
        return ulysses_attention(
            q, k, v, axis_name="sp", causal=causal, impl=impl,
            positions_q=pos, positions_kv=pos,
            segment_ids_q=seg, segment_ids_kv=seg)

    fn = jax.shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec, sspec, sspec),
        out_specs=spec,
        axis_names={"sp"},
    )
    return fn(q, k, v, positions, segments)
