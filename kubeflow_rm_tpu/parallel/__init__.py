from kubeflow_rm_tpu.parallel.mesh import MeshConfig, make_mesh
from kubeflow_rm_tpu.parallel.sharding import (
    batch_pspec,
    param_pspecs,
    param_shardings,
)
from kubeflow_rm_tpu.parallel.ring_attention import ring_attention

__all__ = [
    "MeshConfig",
    "make_mesh",
    "batch_pspec",
    "param_pspecs",
    "param_shardings",
    "ring_attention",
]
