from kubeflow_rm_tpu.parallel.mesh import MeshConfig, make_hybrid_mesh, make_mesh
from kubeflow_rm_tpu.parallel.pipeline import pipeline_forward
from kubeflow_rm_tpu.parallel.sharding import (
    batch_pspec,
    param_pspecs,
    param_shardings,
)
from kubeflow_rm_tpu.parallel.ring_attention import (
    ring_attention,
    ring_self_attention,
)
from kubeflow_rm_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_self_attention,
)
from kubeflow_rm_tpu.parallel.zigzag_ring import (
    zigzag_permutation,
    zigzag_positions,
    zigzag_ring_attention,
    zigzag_ring_self_attention,
)

__all__ = [
    "MeshConfig",
    "make_hybrid_mesh",
    "make_mesh",
    "pipeline_forward",
    "batch_pspec",
    "param_pspecs",
    "param_shardings",
    "ring_attention",
    "ring_self_attention",
    "ulysses_attention",
    "ulysses_self_attention",
    "zigzag_permutation",
    "zigzag_positions",
    "zigzag_ring_attention",
    "zigzag_ring_self_attention",
]
