"""Zigzag ring attention: causal sequence parallelism without waste.

The plain ring schedule (``ring_attention.py``) rotates every K/V chunk
through every device and *masks* fully-future blocks: with contiguous
chunks, device 0 needs only 1 of n visiting chunks while device n−1
needs all n, so under SPMD lockstep the ring takes n full-block steps
and ~half the block matmuls are thrown away (VERDICT weak #4).

The zigzag layout fixes the load imbalance structurally. Split the
global sequence into 2n chunks; device i holds the PAIR
``(chunk i, chunk 2n−1−i)`` — one early, one late. At ring step s the
K/V pair from device j = (i−s) mod n arrives, and causality decides
per sub-block:

  q-early(i)  × k-early(j): needed iff s ≤ i       (diagonal at s=0)
  q-early(i)  × k-late(j):  never (always future)
  q-late(i)   × k-early(j): always, fully visible
  q-late(i)   × k-late(j):  needed iff s = 0 or s > i  (diag at s=0)

Every device computes exactly 2 sub-blocks per step (±diagonals) —
2n·(T/2n)² block-matmuls total versus the plain ring's 4n·(T/2n)², the
2× causal saving, with no device idling. Skipping is real control flow
(``lax.cond``), not masking, so the MXU never sees the dead blocks.

Layout contract: callers put the whole sequence axis in zigzag order
(``zigzag_permutation``) and run the model with explicit positions
(``zigzag_positions``) so RoPE stays correct; attention then needs no
position tensors at all — causality is implied by chunk ids. Packed
segments are not supported here (use the position-aware plain ring);
long-context runs — this schedule's reason to exist — train on full
documents.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -2.0**30


# ---------------------------------------------------------------------
# layout helpers
# ---------------------------------------------------------------------

def zigzag_permutation(T: int, n: int) -> np.ndarray:
    """Natural → zigzag gather indices: result[t] = natural index held
    at zigzag position t. Device i's shard is chunks (i, 2n−1−i)."""
    assert T % (2 * n) == 0, f"T={T} must split into 2n={2 * n} chunks"
    c = T // (2 * n)
    order = []
    for i in range(n):
        order.extend(range(i * c, (i + 1) * c))
        order.extend(range((2 * n - 1 - i) * c, (2 * n - i) * c))
    return np.asarray(order, dtype=np.int32)


def inverse_permutation(perm: np.ndarray) -> np.ndarray:
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size, dtype=perm.dtype)
    return inv


def zigzag_positions(T: int, n: int) -> np.ndarray:
    """Global positions of a zigzag-ordered sequence (feed to RoPE)."""
    return zigzag_permutation(T, n)


# ---------------------------------------------------------------------
# the local collective kernel (call inside shard_map)
# ---------------------------------------------------------------------

def zigzag_ring_attention(q, k, v, *, axis_name: str = "sp",
                          causal: bool = True):
    """Local shard attention; shards are zigzag pairs (early‖late).

    q: (B, Tloc, H, D), k/v: (B, Tloc, KVH, D) with Tloc = 2·chunk.
    Returns (B, Tloc, H, D) — the exact attention output for this
    shard's tokens over the full global sequence.
    """
    n = jax.lax.axis_size(axis_name)
    i = jax.lax.axis_index(axis_name)
    B, Tloc, H, D = q.shape
    KVH = k.shape[2]
    assert H % KVH == 0
    G = H // KVH
    Tc = Tloc // 2
    scale = D ** -0.5

    if not causal:
        # no masked blocks to skip — defer to the plain ring
        from kubeflow_rm_tpu.parallel.ring_attention import ring_attention
        return ring_attention(q, k, v, axis_name=axis_name, causal=False)

    qf = (q.astype(jnp.float32) * scale).reshape(B, 2, Tc, KVH, G, D)
    kc0 = k.reshape(B, 2, Tc, KVH, D)
    vc0 = v.reshape(B, 2, Tc, KVH, D)

    local_tri = jnp.tril(jnp.ones((Tc, Tc), bool))  # diagonal-chunk mask

    def block(qc, kc, vc, o, m, l, *, diag: bool):
        """Fold one (Tc × Tc) K/V block into a q-chunk's accumulators."""
        s = jnp.einsum("bqkgd,bskd->bkgqs", qc, kc.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if diag:
            s = jnp.where(local_tri[None, None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if diag:
            p = jnp.where(local_tri[None, None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bkgqs,bskd->bkgqd", p, vc.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        return o * corr[..., None] + pv, m_new, l_new

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, s):
        (oe, me, le), (ol, ml, ll), kc, vc = carry
        ke, kl = kc[:, 0], kc[:, 1]
        ve, vl = vc[:, 0], vc[:, 1]
        qe, ql = qf[:, 0], qf[:, 1]

        # q-early × k-early: s == 0 is the diagonal; s ≤ i full
        oe, me, le = jax.lax.cond(
            s == 0,
            lambda a: block(qe, ke, ve, *a, diag=True),
            lambda a: jax.lax.cond(
                s <= i,
                lambda b: block(qe, ke, ve, *b, diag=False),
                lambda b: b, a),
            (oe, me, le))
        # q-late × k-early: always fully visible
        ol, ml, ll = block(ql, ke, ve, ol, ml, ll, diag=False)
        # q-late × k-late: diagonal at s == 0, full when s > i
        ol, ml, ll = jax.lax.cond(
            s == 0,
            lambda a: block(ql, kl, vl, *a, diag=True),
            lambda a: jax.lax.cond(
                s > i,
                lambda b: block(ql, kl, vl, *b, diag=False),
                lambda b: b, a),
            (ol, ml, ll))
        # q-early × k-late is future for every (i, j): never computed

        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return ((oe, me, le), (ol, ml, ll), kc, vc), None

    def varying(x):
        return jax.lax.pcast(x, (axis_name,), to="varying")

    def zeros():
        return (varying(jnp.zeros((B, KVH, G, Tc, D), jnp.float32)),
                varying(jnp.full((B, KVH, G, Tc), NEG_INF, jnp.float32)),
                varying(jnp.zeros((B, KVH, G, Tc), jnp.float32)))

    init = (zeros(), zeros(), kc0, vc0)
    ((oe, me, le), (ol, ml, ll), _, _), _ = jax.lax.scan(
        jax.checkpoint(step), init, jnp.arange(n))

    def finish(o, l):
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out.transpose(0, 3, 1, 2, 4).reshape(B, Tc, H, D)

    out = jnp.concatenate([finish(oe, le), finish(ol, ll)], axis=1)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------
# global-view wrapper
# ---------------------------------------------------------------------

def zigzag_ring_self_attention(q, k, v, mesh: Mesh, *,
                               causal: bool = True,
                               inputs_zigzag: bool = False):
    """shard_map wrapper over the ``sp`` axis.

    With ``inputs_zigzag=False`` the inputs are natural-order global
    arrays: they are permuted into zigzag layout, attended, and
    permuted back (two sharded gathers — fine for tests and one-off
    calls; put the whole model in zigzag order for training, see
    module docstring). With ``inputs_zigzag=True`` the caller already
    owns the layout and no permutation happens.
    """
    n = mesh.shape["sp"]
    T = q.shape[1]
    spec = P(None, "sp", None, None)

    fn = jax.shard_map(
        partial(zigzag_ring_attention, axis_name="sp", causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names={"sp"},
    )
    if inputs_zigzag:
        return fn(q, k, v)

    perm = jnp.asarray(zigzag_permutation(T, n))
    inv = jnp.asarray(inverse_permutation(np.asarray(perm)))
    out = fn(q[:, perm], k[:, perm], v[:, perm])
    return out[:, inv]
