"""Device-mesh construction.

Axis convention used throughout the framework:

- ``dp``   pure data parallelism (params replicated) — maps to DCN
           across slices in multi-slice jobs.
- ``fsdp`` data parallelism with parameter sharding (ZeRO-3 style);
           rides ICI within a slice so the per-layer all-gathers are
           cheap.
- ``sp``   sequence/context parallelism (ring attention) — also ICI.
- ``tp``   tensor (megatron-style) parallelism — innermost axis so its
           per-matmul collectives take the fastest ICI hops.

Axis order in the mesh tuple is outermost-to-innermost exactly as above:
``jax.make_mesh`` assigns the innermost mesh axis to the most-local
device neighbourhoods, which is where tp's latency-sensitive
all-reduces belong.

The platform half of this repo guarantees the env this module consumes:
the webhook injects TPU_WORKER_ID/TPU_WORKER_HOSTNAMES (SURVEY.md §2.6)
and the controller renders the slice topology into the pod.
"""

from dataclasses import dataclass

import jax
from jax.sharding import AxisType, Mesh


AXES = ("dp", "fsdp", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    fsdp: int = -1  # -1: absorb all remaining devices
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int, int, int]:
        sizes = [self.dp, self.fsdp, self.sp, self.tp]
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        n_wild = sizes.count(-1)
        if n_wild > 1:
            raise ValueError("at most one mesh axis may be -1")
        if n_wild == 1:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {known}"
                )
            sizes[sizes.index(-1)] = n_devices // known
        if sizes[0] * sizes[1] * sizes[2] * sizes[3] != n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} does not cover {n_devices} devices"
            )
        return tuple(sizes)


def make_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    """Build the framework-standard 4-axis mesh over ``devices``."""
    config = config or MeshConfig()
    devices = devices if devices is not None else jax.devices()
    shape = config.resolve(len(devices))
    # Auto axis types: shardings are annotations and XLA's SPMD
    # partitioner propagates + inserts collectives (GSPMD), rather than
    # jax 0.9's default Explicit sharding-in-types mode.
    return jax.make_mesh(
        shape, AXES, devices=devices, axis_types=(AxisType.Auto,) * len(AXES)
    )
