"""Device-mesh construction.

Axis convention used throughout the framework:

- ``dp``   pure data parallelism (params replicated) — maps to DCN
           across slices in multi-slice jobs.
- ``pp``   pipeline parallelism (layer-stack sharded into stages,
           GPipe microbatch schedule in ``parallel.pipeline``). Its
           traffic is one point-to-point activation transfer per
           microbatch — the lowest-bandwidth axis, so it sits just
           inside dp and can span DCN too.
- ``fsdp`` data parallelism with parameter sharding (ZeRO-3 style);
           rides ICI within a slice so the per-layer all-gathers are
           cheap.
- ``ep``   expert parallelism (MoE expert dim sharded; the dispatch
           einsums become XLA all-to-alls over ICI —
           ``parallel.moe``).
- ``sp``   sequence/context parallelism (ring attention) — also ICI.
- ``tp``   tensor (megatron-style) parallelism — innermost axis so its
           per-matmul collectives take the fastest ICI hops.

Axis order in the mesh tuple is outermost-to-innermost exactly as above:
``jax.make_mesh`` assigns the innermost mesh axis to the most-local
device neighbourhoods, which is where tp's latency-sensitive
all-reduces belong.

The platform half of this repo guarantees the env this module consumes:
the webhook injects TPU_WORKER_ID/TPU_WORKER_HOSTNAMES (SURVEY.md §2.6)
and the controller renders the slice topology into the pod.
"""

from dataclasses import dataclass

import jax

try:  # jax >= 0.5: sharding-in-types axis kinds
    from jax.sharding import AxisType, Mesh
except ImportError:  # older jax: every axis is implicitly Auto
    from jax.sharding import Mesh
    AxisType = None

if not hasattr(jax, "shard_map"):  # older jax: pre-promotion spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(f, *, mesh, in_specs, out_specs,
                          axis_names=None, **kw):
        # new-API ``axis_names={...}`` (manual axes) maps to the old
        # ``auto=`` complement; partial-auto needs check_rep off there
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw.setdefault("auto", auto)
                kw.setdefault("check_rep", False)

        def body(*args):
            # new jax propagates the mesh into the body; old
            # with_sharding_constraint(PartitionSpec) needs the context
            with mesh:
                return f(*args)

        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = _compat_shard_map

if not hasattr(jax.lax, "pcast"):  # older jax: no varying-type casts
    # value-identity; only the (inactive, check_rep=False) replication
    # tracker ever consumed the annotation
    jax.lax.pcast = lambda x, axes, to=None: x


def _axis_types_kwargs() -> dict:
    """``axis_types=Auto`` where the installed jax supports it.

    Older jax has no AxisType and no Explicit mode — Auto is the only
    (implicit) behaviour, so omitting the kwarg is semantically
    identical there.
    """
    if AxisType is None:
        return {}
    return {"axis_types": (AxisType.Auto,) * len(AXES)}


AXES = ("dp", "pp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    fsdp: int = -1  # -1: absorb all remaining devices
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> tuple[int, ...]:
        sizes = [self.dp, self.pp, self.fsdp, self.ep, self.sp, self.tp]
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        n_wild = sizes.count(-1)
        if n_wild > 1:
            raise ValueError("at most one mesh axis may be -1")
        if n_wild == 1:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {known}"
                )
            sizes[sizes.index(-1)] = n_devices // known
        import math
        if math.prod(sizes) != n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} does not cover {n_devices} devices"
            )
        return tuple(sizes)


def make_hybrid_mesh(config: MeshConfig | None = None, *,
                     n_slices: int, devices=None) -> Mesh:
    """Multislice mesh: ``dp`` spans slices over DCN; fsdp/sp/tp stay
    inside each slice on ICI (the scaling-book layout — parameters are
    gathered over fast links, only gradients cross the data-center
    network). ``config.dp`` must equal ``n_slices`` (or -1).

    Uses ``mesh_utils.create_hybrid_device_mesh`` so device order
    respects slice locality; under multislice the platform guarantees
    slice-major process ids (``distributed.initialize``), which is what
    makes the per-slice device blocks contiguous here.
    """
    from jax.experimental import mesh_utils

    config = config or MeshConfig()
    devices = devices if devices is not None else jax.devices()
    if config.dp == -1:
        config = MeshConfig(dp=n_slices, pp=config.pp, fsdp=config.fsdp,
                            ep=config.ep, sp=config.sp, tp=config.tp)
    shape = config.resolve(len(devices))
    if shape[0] != n_slices:
        raise ValueError(
            f"dp axis ({shape[0]}) must equal n_slices ({n_slices}) — "
            "dp is the DCN axis in a multislice job")
    per_slice = len(devices) // n_slices
    dev_mesh = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(1, *shape[1:]),
        dcn_mesh_shape=(n_slices,) + (1,) * (len(AXES) - 1),
        devices=devices,
        process_is_granule=False,
        should_sort_granules_by_key=True,
    ) if _has_slice_index(devices) else _reshape_fallback(devices, shape)
    return Mesh(dev_mesh.reshape(shape), AXES, **_axis_types_kwargs())


def _has_slice_index(devices) -> bool:
    return getattr(devices[0], "slice_index", None) is not None


def _reshape_fallback(devices, shape):
    """CPU-mesh tests have no slice_index: slice-major order is just
    the device list order (the dryrun contract)."""
    import numpy as np
    return np.asarray(devices).reshape(shape)


def make_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    """Build the framework-standard 4-axis mesh over ``devices``."""
    config = config or MeshConfig()
    devices = devices if devices is not None else jax.devices()
    shape = config.resolve(len(devices))
    # Auto axis types: shardings are annotations and XLA's SPMD
    # partitioner propagates + inserts collectives (GSPMD), rather than
    # jax 0.9's default Explicit sharding-in-types mode.
    return jax.make_mesh(
        shape, AXES, devices=devices, **_axis_types_kwargs()
    )
