"""Sharding rules: parameter pytree -> PartitionSpec pytree.

The recipe (scaling-book style): annotate shardings on params and batch,
jit the step, and let XLA's SPMD partitioner insert the collectives.

Rules for the layer-stacked Llama pytree (leading axis = layer,
sharded over ``pp`` into pipeline stages — identity when pp=1; a pp>1
mesh requires the ``parallel.pipeline`` schedule, a plain jit forward
would all-gather the stack):

- column-parallel weights (wq/wk/wv/w_gate/w_up): contract dim sharded
  on ``fsdp``, output dim on ``tp`` — forward needs an fsdp all-gather
  of the weight (prefetched by XLA) and no activation collective.
- row-parallel weights (wo/w_down): ``tp`` on the contracting dim, so
  each tp shard computes a partial product and XLA inserts the single
  psum per block that megatron TP requires.
- embed: vocab on ``tp``, model dim on ``fsdp``; lm_head transposed
  likewise. norms replicated.

The batch is sharded over (dp, fsdp) jointly — fsdp is a data-parallel
axis from the batch's point of view — and over ``sp`` along sequence.
"""

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# path (joined with '/') -> spec for the stacked-layer llama pytree
_LLAMA_RULES = {
    "embed/tokens": P("tp", "fsdp"),
    "blocks/attn_norm": P("pp", None),
    "blocks/mlp_norm": P("pp", None),
    "blocks/wq": P("pp", "fsdp", "tp"),
    "blocks/wk": P("pp", "fsdp", "tp"),
    "blocks/wv": P("pp", "fsdp", "tp"),
    "blocks/wo": P("pp", "tp", "fsdp"),
    "blocks/w_gate": P("pp", "fsdp", "tp"),
    "blocks/w_up": P("pp", "fsdp", "tp"),
    "blocks/w_down": P("pp", "tp", "fsdp"),
    "out_norm": P(None),
    "lm_head": P("fsdp", "tp"),
    # MoE (mixtral family): expert dim on ep — the dispatch/combine
    # einsums become all-to-alls, the expert matmuls run ep-parallel
    "blocks/router": P("pp", "fsdp", "ep"),
    "blocks/moe_gate": P("pp", "ep", "fsdp", "tp"),
    "blocks/moe_up": P("pp", "ep", "fsdp", "tp"),
    "blocks/moe_down": P("pp", "ep", "tp", "fsdp"),
}


def _path_str(path) -> str:
    return "/".join(
        p.key if hasattr(p, "key") else str(p.idx) for p in path
    )


def param_pspecs(params) -> dict:
    """PartitionSpec pytree for a Llama param pytree (or matching shapes)."""

    def spec_for(path, leaf):
        key = _path_str(path)
        # LoRA adapters: a carries the base weight's contract-dim
        # sharding, b its output-dim sharding; the tiny rank axis stays
        # replicated
        if key.endswith("_lora_a"):
            return P("pp", "fsdp", None)
        if key.endswith("_lora_b"):
            return P("pp", None, "tp")
        # int8-quantized weights ({"q", "s"} dicts, models.quantize):
        # q shards like the base weight; the per-output-channel scale
        # keeps the output axis and replicates the collapsed one.
        # int4 ({"q4", "s"}) splits the contraction axis into
        # (groups, packed) — the group axis inherits the contraction
        # sharding, the packed axis replicates; per-group scales have
        # one extra (singleton) axis and shard the same way.
        if key.endswith("/q") or key.endswith("/q4") or key.endswith("/s"):
            base = _LLAMA_RULES[key.rsplit("/", 1)[0]]
            if key.endswith("/q"):
                return base
            if key.endswith("/q4") or leaf.ndim == len(base) + 1:
                return P(*base[:-1], None, base[-1])
            return P(*[None if i == len(base) - 2 else ax
                       for i, ax in enumerate(base)])
        if key not in _LLAMA_RULES:
            raise KeyError(f"no sharding rule for param {key!r}")
        return _LLAMA_RULES[key]

    return jax.tree_util.tree_map_with_path(spec_for, params)


def param_shardings(params, mesh: Mesh) -> dict:
    """NamedSharding pytree for ``params`` on ``mesh``.

    Quantized leaves adapt instead of erroring: a q4 weight whose
    group-count axis does not divide the mesh moves its contraction
    sharding to the packed axis (always a multiple of typical shard
    counts — e.g. 7B w_down has G=86 groups, indivisible by tp=4, but
    g/2=64 packed rows shard fine); a weight with no dividable axis
    demotes to replicated with a warning (a silently replicated
    WEIGHT would defeat int4's capacity purpose), while scales demote
    silently — a replicated handful of scale bytes costs nothing
    worth warning about. Regular
    weights stay strict — a non-divisible real weight IS a bug worth
    raising."""
    import warnings

    specs = param_pspecs(params)

    def axis_size(ax):
        axes = ax if isinstance(ax, tuple) else (ax,)
        n = 1
        for a in axes:
            n *= mesh.shape[a]
        return n

    def mk(path, leaf, spec):
        key = _path_str(path)
        if key.endswith("/q4"):
            # trailing dims are (groups, packed, out); leading dims
            # (layer/expert stacks) pass through untouched
            st = tuple(spec)
            lead, (a_in, _, a_out) = st[:-3], st[-3:]
            G, half = leaf.shape[-3], leaf.shape[-2]
            if a_in is not None and G % axis_size(a_in):
                if half % axis_size(a_in) == 0:
                    spec = P(*lead, None, a_in, a_out)  # packed axis
                else:
                    warnings.warn(
                        f"{key}: neither group ({G}) nor packed "
                        f"({half}) axis divides the mesh — weight "
                        "replicated; consider a different group_size")
                    spec = P(*lead, None, None, a_out)
        elif key.endswith("/s"):
            def fit(dim, ax):
                if ax is None or dim % axis_size(ax) == 0:
                    return ax
                return None
            spec = P(*[fit(d, a) for d, a in zip(leaf.shape, spec)])
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(mk, params, specs)


def batch_pspec(sequence_sharded: bool = True) -> P:
    """Spec for (B, T) token batches: batch over dp+fsdp, seq over sp."""
    return P(("dp", "fsdp"), "sp" if sequence_sharded else None)
