"""Ring attention: sequence-parallel exact attention over the ``sp`` axis.

Each device holds a contiguous sequence chunk of Q, K and V. K/V chunks
rotate around the ring with ``lax.ppermute`` (one ICI hop per step) while
every device folds the visiting chunk into a running flash-style online
softmax (m, l, o accumulators in fp32). After ``sp`` steps every query
has attended to every key exactly once — memory stays O(T/sp) per device
and the per-step compute (a (Tloc x Tloc) block) overlaps with the next
chunk's transfer.

Causality is enforced with *global* positions, so the math is exact for
any contiguous sharding; fully-future chunks still rotate through (the
ring schedule is uniform) but their scores are masked. The per-step body
is wrapped in ``jax.checkpoint`` so the backward pass recomputes block
scores instead of saving n_steps score tensors.

The reference platform has no long-context story at all (SURVEY.md §5
"Long-context / sequence parallelism: absent") — this module is the
TPU-native capability that fills it, and the notebook webhook's
TPU_WORKER_* injection provides the multi-host mesh it runs on.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_rm_tpu.ops.attention import NEG_INF, attention_mask


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    positions_q: jax.Array | None = None,
    positions_kv: jax.Array | None = None,
    segment_ids_q: jax.Array | None = None,
    segment_ids_kv: jax.Array | None = None,
) -> jax.Array:
    """Attention over sequence shards. Call inside ``shard_map``.

    Args:
      q: (B, Tloc, H, D) local query chunk.
      k, v: (B, Tloc, KVH, D) local key/value chunks.
      positions_q / positions_kv: (B, Tloc) global positions of the local
        chunk; default assumes contiguous equal chunks in ring order.
      segment_ids_q / segment_ids_kv: optional (B, Tloc) segment ids for
        packed sequences; attention is restricted to equal segments.

    Returns:
      (B, Tloc, H, D) local attention output in q.dtype.

    Masked probabilities are zeroed *explicitly* (not just via NEG_INF
    scores): for a query row whose blocks so far are fully masked the
    running max ``m`` still equals the finite NEG_INF sentinel, and
    ``exp(s - m) = 1`` would silently attend to masked keys.
    """
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    _, Tk, KVH, _ = k.shape
    assert H % KVH == 0
    G = H // KVH
    scale = D ** -0.5

    if positions_q is None:
        positions_q = my * Tq + jnp.arange(Tq, dtype=jnp.int32)
        positions_q = jnp.broadcast_to(positions_q, (B, Tq))

    qf = (q.astype(jnp.float32) * scale).reshape(B, Tq, KVH, G, D)

    perm = [(j, (j + 1) % n) for j in range(n)]
    have_segments = segment_ids_q is not None or segment_ids_kv is not None
    if have_segments:
        # both-or-one: self-attention callers naturally pass only _q
        if segment_ids_q is None:
            segment_ids_q = segment_ids_kv
        if segment_ids_kv is None:
            segment_ids_kv = segment_ids_q

    def step(carry, i):
        # seg_kc rides the ring only when segments are in play — the
        # no-segments trace carries (and ppermutes) nothing extra
        if have_segments:
            o, m, l, kc, vc, pos_kc, seg_kc = carry
        else:
            o, m, l, kc, vc, pos_kc = carry
            seg_kc = None
        s = jnp.einsum(
            "bqkgd,bskd->bkgqs", qf, kc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )  # (B, KVH, G, Tq, Tk)
        mask = attention_mask(
            Tq, Tk, causal=causal,
            positions_q=positions_q, positions_kv=pos_kc,
            segment_ids_q=segment_ids_q, segment_ids_kv=seg_kc,
        )  # (B, Tq, Tk) keep-mask (positions_q is always set here)
        if mask is not None:
            s = jnp.where(mask[:, None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if mask is not None:
            p = jnp.where(mask[:, None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum(
            "bkgqs,bskd->bkgqd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        o_new = o * corr[..., None] + pv
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        pos_kc = jax.lax.ppermute(pos_kc, axis_name, perm)
        out = (o_new, m_new, l_new, kc, vc, pos_kc)
        if have_segments:
            seg_kc = jax.lax.ppermute(seg_kc, axis_name, perm)
            out = out + (seg_kc,)
        return out, None

    if positions_kv is None:
        positions_kv = my * Tk + jnp.arange(Tk, dtype=jnp.int32)
        positions_kv = jnp.broadcast_to(positions_kv, (B, Tk))

    # initial accumulators are constants — mark them varying over the ring
    # axis so the scan carry type matches its (shard-varying) outputs
    def varying(x):
        return jax.lax.pcast(x, (axis_name,), to="varying")

    o0 = varying(jnp.zeros((B, KVH, G, Tq, D), jnp.float32))
    m0 = varying(jnp.full((B, KVH, G, Tq), NEG_INF, jnp.float32))
    l0 = varying(jnp.zeros((B, KVH, G, Tq), jnp.float32))

    init = (o0, m0, l0, k, v, positions_kv)
    if have_segments:
        init = init + (segment_ids_kv,)
    carry, _ = jax.lax.scan(jax.checkpoint(step), init, jnp.arange(n))
    o, m, l = carry[0], carry[1], carry[2]
    # guard l == 0 (a query with no visible keys anywhere): emit zeros
    out = o / jnp.maximum(l, 1e-30)[..., None]
    # (B, KVH, G, Tq, D) -> (B, Tq, H, D)
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, Tq, H, D)
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                        positions: jax.Array | None = None,
                        segments: jax.Array | None = None):
    """Global-view convenience wrapper: shard_map over the ``sp`` axis only.

    Inputs are global (B, T, H, D) arrays laid out on ``mesh``; batch and
    head axes stay under automatic (GSPMD) partitioning. ``positions`` /
    ``segments`` are optional global (B, T) arrays for packed sequences.
    """
    spec = P(None, "sp", None, None)
    sspec = P(None, "sp")

    if positions is None and segments is None:
        fn = jax.shard_map(
            partial(ring_attention, axis_name="sp", causal=causal),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            axis_names={"sp"},
        )
        return fn(q, k, v)

    B, T = q.shape[:2]
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    if segments is None:
        segments = jnp.zeros((B, T), jnp.int32)

    def local(q, k, v, pos, seg):
        return ring_attention(
            q, k, v, axis_name="sp", causal=causal,
            positions_q=pos, positions_kv=pos,
            segment_ids_q=seg, segment_ids_kv=seg,
        )

    fn = jax.shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec, sspec, sspec),
        out_specs=spec,
        axis_names={"sp"},
    )
    return fn(q, k, v, positions, segments)
