"""Ring attention: sequence-parallel exact attention over the ``sp`` axis.

Each device holds a contiguous sequence chunk of Q, K and V. K/V chunks
rotate around the ring with ``lax.ppermute`` (one ICI hop per step) while
every device folds the visiting chunk into a running flash-style online
softmax (m, l, o accumulators in fp32). After ``sp`` steps every query
has attended to every key exactly once — memory stays O(T/sp) per device
and the per-step compute (a (Tloc x Tloc) block) overlaps with the next
chunk's transfer.

Causality is enforced with *global* positions, so the math is exact for
any contiguous sharding; fully-future chunks still rotate through (the
ring schedule is uniform) but their scores are masked. The per-step body
is wrapped in ``jax.checkpoint`` so the backward pass recomputes block
scores instead of saving n_steps score tensors.

The reference platform has no long-context story at all (SURVEY.md §5
"Long-context / sequence parallelism: absent") — this module is the
TPU-native capability that fills it, and the notebook webhook's
TPU_WORKER_* injection provides the multi-host mesh it runs on.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_rm_tpu.ops.attention import NEG_INF


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    positions_q: jax.Array | None = None,
    positions_kv: jax.Array | None = None,
) -> jax.Array:
    """Attention over sequence shards. Call inside ``shard_map``.

    Args:
      q: (B, Tloc, H, D) local query chunk.
      k, v: (B, Tloc, KVH, D) local key/value chunks.
      positions_q / positions_kv: (B, Tloc) global positions of the local
        chunk; default assumes contiguous equal chunks in ring order.

    Returns:
      (B, Tloc, H, D) local attention output in q.dtype.
    """
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    _, Tk, KVH, _ = k.shape
    assert H % KVH == 0
    G = H // KVH
    scale = D ** -0.5

    if positions_q is None:
        positions_q = my * Tq + jnp.arange(Tq, dtype=jnp.int32)
        positions_q = jnp.broadcast_to(positions_q, (B, Tq))

    qf = (q.astype(jnp.float32) * scale).reshape(B, Tq, KVH, G, D)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, kc, vc, pos_kc = carry
        s = jnp.einsum(
            "bqkgd,bskd->bkgqs", qf, kc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )  # (B, KVH, G, Tq, Tk)
        if causal:
            mask = positions_q[:, :, None] >= pos_kc[:, None, :]  # (B, Tq, Tk)
            s = jnp.where(mask[:, None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum(
            "bkgqs,bskd->bkgqd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        o_new = o * corr[..., None] + pv
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        pos_kc = jax.lax.ppermute(pos_kc, axis_name, perm)
        return (o_new, m_new, l_new, kc, vc, pos_kc), None

    if positions_kv is None:
        positions_kv = my * Tk + jnp.arange(Tk, dtype=jnp.int32)
        positions_kv = jnp.broadcast_to(positions_kv, (B, Tk))

    # initial accumulators are constants — mark them varying over the ring
    # axis so the scan carry type matches its (shard-varying) outputs
    def varying(x):
        return jax.lax.pcast(x, (axis_name,), to="varying")

    o0 = varying(jnp.zeros((B, KVH, G, Tq, D), jnp.float32))
    m0 = varying(jnp.full((B, KVH, G, Tq), NEG_INF, jnp.float32))
    l0 = varying(jnp.zeros((B, KVH, G, Tq), jnp.float32))

    (o, m, l, _, _, _), _ = jax.lax.scan(
        jax.checkpoint(step), (o0, m0, l0, k, v, positions_kv),
        jnp.arange(n),
    )
    out = o / l[..., None]
    # (B, KVH, G, Tq, D) -> (B, Tq, H, D)
    out = out.transpose(0, 3, 1, 2, 4).reshape(B, Tq, H, D)
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, *, causal: bool = True):
    """Global-view convenience wrapper: shard_map over the ``sp`` axis only.

    Inputs are global (B, T, H, D) arrays laid out on ``mesh``; batch and
    head axes stay under automatic (GSPMD) partitioning.
    """
    spec = P(None, "sp", None, None)
    fn = jax.shard_map(
        partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={"sp"},
    )
    return fn(q, k, v)
