"""Mixtral-family sparse MoE decoder — the Llama attention stack with
per-layer mixture-of-experts FFNs (``parallel.moe``).

Same TPU-first structure as ``models.llama`` (scan over stacked layers,
remat, bf16 compute / fp32 params); the FFN half is the dense-dispatch
MoE layer, expert-parallel over the ``ep`` mesh axis purely via
shardings (``sharding._LLAMA_RULES`` moe entries). SURVEY.md §2.6 lists
EP among the parallelism styles to supply in-image; the reference ships
it through its torch/NCCL engine, this is the XLA-collective
re-design.

``forward`` returns ``(logits, aux_loss)`` — the router load-balancing
loss must be added to the training objective
(``cfg.moe.router_aux_weight`` scales it; ``training.train.loss_fn``
does this automatically for MixtralConfig models).
"""

from dataclasses import dataclass, field, replace

import jax
import jax.numpy as jnp

from kubeflow_rm_tpu.models.llama import (
    LlamaConfig,
    _attention_half,
    _epilogue,
    _prologue,
)
from kubeflow_rm_tpu.models.llama import (
    init_params as _llama_init,
)
from kubeflow_rm_tpu.models.llama import (
    param_spec_shapes as _llama_shapes,
)
from kubeflow_rm_tpu.ops import rms_norm
from kubeflow_rm_tpu.parallel.moe import MoeConfig, moe_ffn


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    moe: MoeConfig = field(default_factory=MoeConfig)

    @staticmethod
    def mixtral_8x7b(**overrides) -> "MixtralConfig":
        return replace(
            MixtralConfig(vocab_size=32000, dim=4096, n_layers=32,
                          n_heads=32, n_kv_heads=8, hidden_dim=14336,
                          rope_theta=1e6, max_seq_len=32768,
                          moe=MoeConfig(n_experts=8, top_k=2)),
            **overrides,
        )

    @staticmethod
    def tiny_moe(**overrides) -> "MixtralConfig":
        base = LlamaConfig.tiny()
        return replace(
            MixtralConfig(
                vocab_size=base.vocab_size, dim=base.dim,
                n_layers=base.n_layers, n_heads=base.n_heads,
                n_kv_heads=base.n_kv_heads, hidden_dim=base.hidden_dim,
                max_seq_len=base.max_seq_len, dtype=base.dtype,
                moe=MoeConfig(n_experts=4, top_k=2,
                              capacity_factor=2.0)),
            **overrides,
        )


def param_spec_shapes(cfg: MixtralConfig) -> dict:
    """Llama tree with the dense MLP replaced by stacked expert FFNs."""
    shapes = _llama_shapes(cfg)
    blocks = dict(shapes["blocks"])
    for k in ("w_gate", "w_up", "w_down"):
        del blocks[k]
    L, D, F, E = cfg.n_layers, cfg.dim, cfg.hidden_dim, cfg.moe.n_experts
    blocks["router"] = (L, D, E)
    blocks["moe_gate"] = (L, E, D, F)
    blocks["moe_up"] = (L, E, D, F)
    blocks["moe_down"] = (L, E, F, D)
    return {**shapes, "blocks": blocks}


def init_params(cfg: MixtralConfig, key: jax.Array) -> dict:
    return _llama_init(cfg, key, shapes=param_spec_shapes(cfg))


def _moe_block(cfg: MixtralConfig, x, layer, cos, sin, positions,
               segments, mesh=None):
    """Attention half shared with Llama; MoE FFN half. Returns
    (x, aux_loss)."""
    x = _attention_half(cfg, x, layer, cos, sin, positions, segments,
                        mesh=mesh)
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    out, aux = moe_ffn(layer, h, cfg.moe, dtype=cfg.dtype)
    return x + out, aux


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: MixtralConfig,
    positions: jax.Array | None = None,
    segments: jax.Array | None = None,
    *,
    packed: bool = False,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Causal LM forward. Returns ((B, T, vocab) fp32 logits,
    mean-per-layer router aux loss)."""
    # the shared prologue's remat-wrapped dense block is unused here;
    # wrap the moe block with the same policy instead
    x, cos, sin, attn_positions, _ = _prologue(
        params, tokens, cfg, positions, segments, packed)

    from functools import partial

    block = partial(_moe_block, cfg, mesh=mesh)
    if cfg.remat:
        from kubeflow_rm_tpu.models.llama import _remat_policy
        block = jax.checkpoint(block, policy=_remat_policy(cfg.remat_policy))

    def scan_body(carry, layer):
        x, aux_sum = carry
        x, aux = block(x, layer, cos, sin, attn_positions, segments)
        return (x, aux_sum + aux), None

    (x, aux_sum), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), params["blocks"])
    return _epilogue(params, x, cfg), aux_sum / cfg.n_layers
