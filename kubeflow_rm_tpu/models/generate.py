"""KV-cached autoregressive generation for the Llama family.

The in-notebook inference path: prefill the prompt in one pass, then
decode a token per step against a preallocated static-shape cache —
every step is the SAME jitted computation (no data-dependent shapes),
which is what XLA wants on TPU. Exactness against the training
``forward`` is asserted by ``tests/test_generate.py``.

TPU-first choices:

- **Static cache** (B, max_len, KVH, hd) per layer, stacked on a
  leading layer axis like the weights, updated with
  ``lax.dynamic_update_slice`` — one compiled step serves the whole
  generation, prefill included (prefill is just a wider chunk).
- **Position-masked attention**: unfilled cache slots carry position
  ``INT32_MAX``, so the standard ``pos_q >= pos_kv`` causal mask of
  ``ops.dot_product_attention`` excludes them — no second mask path to
  keep in sync with training.
- **Layer scan**: the cache rides ``lax.scan`` as scanned xs/ys over
  the same stacked-parameter layout training uses, so compile time
  stays depth-independent.

The reference platform ships no model runtime at all; this module is
capability the jupyter-jax image adds on top (SURVEY.md §2.6).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from kubeflow_rm_tpu.models.llama import LlamaConfig
from kubeflow_rm_tpu.models.lora import lora_proj
from kubeflow_rm_tpu.models.quantize import maybe_dequant
from kubeflow_rm_tpu.ops import (
    apply_rope,
    dot_product_attention,
    rms_norm,
    rope_angles,
)

_UNFILLED = jnp.iinfo(jnp.int32).max


@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    k: jax.Array          # (L, B, S, KVH, hd) compute dtype
    v: jax.Array          # (L, B, S, KVH, hd)
    positions: jax.Array  # (B, S) int32; _UNFILLED marks empty slots
    offset: jax.Array     # () int32: next write index


def init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> KVCache:
    L, KVH, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    return KVCache(
        k=jnp.zeros((L, batch, max_len, KVH, hd), cfg.dtype),
        v=jnp.zeros((L, batch, max_len, KVH, hd), cfg.dtype),
        positions=jnp.full((batch, max_len), _UNFILLED, jnp.int32),
        offset=jnp.zeros((), jnp.int32),
    )


def decode_chunk(params: dict, cfg: LlamaConfig, cache: KVCache,
                 tokens: jax.Array,
                 pad_counts: jax.Array | None = None,
                 ) -> tuple[jax.Array, KVCache]:
    """Run ``tokens`` (B, Tc) through the model at the cache offset.

    One function serves prefill (Tc = prompt length) and decode
    (Tc = 1). Returns (logits (B, Tc, V) fp32, updated cache). The
    chunk must fit: offset + Tc <= cache length.

    ``pad_counts`` (B,) enables ragged batches under static shapes —
    the serving path's requirement: row *i*'s first ``pad_counts[i]``
    slots are left-padding. Pad slots get position ``_UNFILLED``, so
    the standard causal mask excludes them from every later query
    (their garbage K/V is invisible), and real tokens' positions are
    shifted down so each row's first real token sits at position 0 —
    batched left-padded output is bit-identical to running each row
    unpadded (``tests/test_generate.py``).
    """
    B, Tc = tokens.shape
    H, KVH, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    cdt = cfg.dtype

    positions = cache.offset + jnp.arange(Tc, dtype=jnp.int32)
    positions = jnp.broadcast_to(positions, (B, Tc))
    if pad_counts is not None:
        positions = positions - pad_counts[:, None]
        positions = jnp.where(positions < 0, _UNFILLED, positions)
    # rope of a ~2^31 position is finite but wild; clamp pads to 0
    # (their K is masked out by the _UNFILLED position anyway)
    rope_pos = jnp.where(positions == _UNFILLED, 0, positions)
    cos, sin = rope_angles(rope_pos, hd, cfg.rope_theta)
    kv_positions = jax.lax.dynamic_update_slice(
        cache.positions, positions, (0, cache.offset))

    x = params["embed"]["tokens"][tokens].astype(cdt)

    # family dispatch for the FFN half: dense SwiGLU or expert mixture
    # (the router aux loss is a training quantity — discarded at decode)
    from kubeflow_rm_tpu.models.mixtral import MixtralConfig

    if isinstance(cfg, MixtralConfig):
        from kubeflow_rm_tpu.parallel.moe import moe_ffn

        def ffn(layer, h):
            dq = {k: (maybe_dequant(v, cdt) if k.startswith("moe") else v)
                  for k, v in layer.items()}
            out, _aux = moe_ffn(dq, h, cfg.moe, dtype=cdt)
            return out
    else:
        def ffn(layer, h):
            proj = partial(lora_proj, layer, alpha=cfg.lora_alpha,
                           dtype=cdt)
            gate = proj("w_gate", h)
            up = proj("w_up", h)
            return proj("w_down", jax.nn.silu(gate) * up)

    def body(x, scanned):
        layer, ck, cv = scanned
        proj = partial(lora_proj, layer, alpha=cfg.lora_alpha, dtype=cdt)
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = proj("wq", h).reshape(B, Tc, H, hd)
        k = proj("wk", h).reshape(B, Tc, KVH, hd)
        v = proj("wv", h).reshape(B, Tc, KVH, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        ck = jax.lax.dynamic_update_slice(ck, k, (0, cache.offset, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v, (0, cache.offset, 0, 0))
        attn = dot_product_attention(
            q, ck, cv, causal=True,
            positions_q=positions, positions_kv=kv_positions,
        )
        x = x + proj("wo", attn.reshape(B, Tc, H * hd))
        x = x + ffn(layer, rms_norm(x, layer["mlp_norm"], cfg.norm_eps))
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v))
    x = rms_norm(x, params["out_norm"], cfg.norm_eps)
    logits = (x @ maybe_dequant(params["lm_head"], cdt)
              ).astype(jnp.float32)
    new_cache = KVCache(k=new_k, v=new_v, positions=kv_positions,
                       offset=cache.offset + Tc)
    return logits, new_cache


def cache_shardings(cfg: LlamaConfig, mesh) -> KVCache:
    """NamedSharding pytree for a KVCache on ``mesh``: batch over
    (dp, fsdp), KV heads over tp — the decode-time analogue of
    ``parallel.sharding`` (weights stay on their training shardings)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return KVCache(
        k=NamedSharding(mesh, P(None, ("dp", "fsdp"), None, "tp", None)),
        v=NamedSharding(mesh, P(None, ("dp", "fsdp"), None, "tp", None)),
        positions=NamedSharding(mesh, P(("dp", "fsdp"), None)),
        offset=NamedSharding(mesh, P()),
    )


def make_decode_step(example_params: dict, cfg: LlamaConfig, mesh):
    """Jitted sharded ``(params, cache, tokens) -> (logits, cache)``.

    Params carry their training shardings (``parallel.sharding`` rules
    — serve on an fsdp×tp mesh), the cache follows ``cache_shardings``
    and is donated so decode runs in-place in HBM; logits come back
    vocab-sharded over tp. ``example_params`` is only inspected for the
    pytree structure. Exactness vs the unsharded path is asserted by
    ``tests/test_generate.py``.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubeflow_rm_tpu.parallel.sharding import (
        batch_pspec, param_shardings,
    )

    return jax.jit(
        lambda p, cache, tokens: decode_chunk(p, cfg, cache, tokens),
        in_shardings=(param_shardings(example_params, mesh),
                      cache_shardings(cfg, mesh),
                      NamedSharding(mesh, batch_pspec(False))),
        out_shardings=(NamedSharding(mesh, P(("dp", "fsdp"), None, "tp")),
                       cache_shardings(cfg, mesh)),
        donate_argnums=(1,),
    )


def _pick(last, key, *, temperature, top_k):
    """Next-token choice from last-position logits (B, V): greedy
    argmax at temperature 0, else (top-k-truncated) categorical. The
    single source for BOTH decode paths — ``generate`` and
    ``_fused_generate`` must sample identically or the fused path's
    greedy bit-identity guarantee silently breaks."""
    if temperature <= 0:
        return jnp.argmax(last, axis=-1).astype(jnp.int32)
    scaled = last / temperature
    if top_k:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _decode_step(params, cfg, cache, tokens, pad_counts=None):
    """Module-level jitted ``decode_chunk``: one cache entry per
    (config, shapes), shared across ``generate`` calls — a per-call
    ``jax.jit(lambda ...)`` would be a fresh cache key every time and
    re-trace + re-compile on every generation."""
    return decode_chunk(params, cfg, cache, tokens, pad_counts)


def _fused_decode_loop(params, cfg, prompt, key, *, max_new_tokens,
                       temperature, top_k, eos_id, total_len,
                       cache_sharding=None, pad_counts=None):
    """Trace-time body shared by ``generate_fused`` (single device) and
    ``make_generate_step`` (sharded): prefill, then a ``lax.scan`` over
    decode steps. ``cache_sharding`` (a NamedSharding pytree) pins the
    freshly-initialized cache's layout under GSPMD."""
    B, _ = prompt.shape
    cache = init_cache(cfg, B, total_len)
    if cache_sharding is not None:
        cache = jax.lax.with_sharding_constraint(cache, cache_sharding)
    logits, cache = decode_chunk(params, cfg, cache, prompt, pad_counts)
    last = logits[:, -1, :]

    def body(carry, k_i):
        cache, last, done = carry
        nxt = _pick(last, k_i, temperature=temperature, top_k=top_k)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        logits, cache = decode_chunk(params, cfg, cache, nxt[:, None],
                                     pad_counts)
        return (cache, logits[:, -1, :], done), nxt

    keys = jax.random.split(key, max_new_tokens)
    (_, _, _), toks = jax.lax.scan(
        body, (cache, last, jnp.zeros((B,), bool)), keys)
    return jnp.concatenate([prompt, toks.T], axis=1)


@partial(jax.jit, static_argnames=(
    "cfg", "max_new_tokens", "temperature", "top_k", "eos_id",
    "total_len"))
def _fused_generate(params, prompt, key, pad_counts=None, *, cfg,
                    max_new_tokens, temperature, top_k, eos_id,
                    total_len):
    return _fused_decode_loop(
        params, cfg, prompt, key, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, eos_id=eos_id,
        total_len=total_len, pad_counts=pad_counts)


def generate_fused(params: dict, cfg: LlamaConfig, prompt: jax.Array, *,
                   max_new_tokens: int, key: jax.Array | None = None,
                   temperature: float = 0.0, top_k: int | None = None,
                   eos_id: int | None = None,
                   max_len: int | None = None,
                   pad_counts: jax.Array | None = None) -> jax.Array:
    """``generate`` as ONE compiled XLA program.

    The Python-loop ``generate`` dispatches a jitted step per token —
    ~10 ms/token of host round-trip when the chip sits behind a network
    tunnel, which dwarfs the ~1 ms of decode compute. Here the whole
    prefill + ``lax.scan`` decode loop (sampling, eos latching, cache
    updates included) lowers to a single jit, so dispatch cost is paid
    once per generation instead of once per token. Greedy output is
    bit-identical to ``generate``; at ``temperature > 0`` the PRNG
    stream differs (keys are pre-split for the scan), which is the only
    behavioral difference.

    The scan runs exactly ``max_new_tokens`` steps; the final step's
    cache write is dead work (~1/N overhead) — the price of a
    shape-static loop, which is what keeps the whole thing one program.

    ``pad_counts`` (B,) marks each row's leading slots as left-padding
    for ragged batches: masked out of attention and position-shifted
    so output rows are bit-identical to unpadded per-row calls (the
    serving batcher's correctness contract — see ``decode_chunk``).
    """
    B, Tp = prompt.shape
    S = max_len or (Tp + max_new_tokens)
    if S < Tp + max_new_tokens:
        raise ValueError(
            f"max_len={S} < prompt {Tp} + new {max_new_tokens}")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    return _fused_generate(
        params, prompt, key if key is not None else jax.random.key(0),
        pad_counts,
        cfg=cfg, max_new_tokens=max_new_tokens,
        temperature=float(temperature), top_k=top_k, eos_id=eos_id,
        total_len=S)


def rewind_cache(cache: KVCache, new_offset) -> KVCache:
    """Logically truncate the cache to ``new_offset`` filled slots.

    Slots at/after ``new_offset`` get position ``_UNFILLED`` — the
    causal mask then excludes their (stale) K/V from every future
    query, so physical K/V bytes need no clearing. O(B·S) positions
    traffic, no weight traffic. The speculative decoder uses this to
    drop rejected draft tokens."""
    idx = jnp.arange(cache.positions.shape[1], dtype=jnp.int32)
    pos = jnp.where(idx[None, :] >= new_offset, _UNFILLED,
                    cache.positions)
    return KVCache(k=cache.k, v=cache.v, positions=pos,
                   offset=jnp.asarray(new_offset, jnp.int32))


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "lookup_n",
                                   "draft_k", "eos_id", "total_len"))
def _fused_speculative(params, prompt, *, cfg, max_new_tokens,
                       lookup_n, draft_k, eos_id, total_len):
    """The whole speculative loop as ONE XLA program (batch 1).

    Decode is weights-bound, so verifying a (draft_k+1)-wide chunk
    costs roughly the same HBM traffic as a width-1 step — widening is
    nearly free ON-DEVICE. What ruins host-side speculation on a
    tunneled chip is the blocking sync every round (lookup + accept
    decisions on the host); here the n-gram match, draft gather,
    verification, cache rewind and loop all run under
    ``lax.while_loop``, so the host dispatches once per generation.
    Worst case (nothing accepts) each round still commits 1 token at
    chunk cost ≈ step cost; best case commits draft_k+1.
    """
    Tp = prompt.shape[1]
    W = draft_k + 1
    S = total_len  # buffer/cache length, incl. chunk overhang room
    V = cfg.vocab_size
    target = Tp + max_new_tokens

    buf = jnp.zeros((S,), jnp.int32).at[:Tp].set(prompt[0])
    cache = init_cache(cfg, 1, S)
    logits, cache = decode_chunk(params, cfg, cache, prompt)
    last = logits[0, -1, :]

    def cond(carry):
        buf, count, cache, last, done, rounds = carry
        return (count < target) & ~done

    def body(carry):
        buf, count, cache, last, done, rounds = carry
        nxt = jnp.argmax(last).astype(jnp.int32)
        buf = buf.at[count].set(nxt)
        count = count + 1

        # prompt-lookup on device: most recent earlier occurrence of
        # the trailing n-gram; its followers become the draft
        tail = jax.lax.dynamic_slice(buf, (count - lookup_n,),
                                     (lookup_n,))
        idx = jnp.arange(S, dtype=jnp.int32)
        windows = buf[jnp.minimum(idx[:, None]
                                  + jnp.arange(lookup_n)[None, :],
                                  S - 1)]
        hit = (windows == tail[None, :]).all(-1) & (idx < count
                                                    - lookup_n)
        has_hit = hit.any()
        p = jnp.max(jnp.where(hit, idx, -1))  # most recent match
        start = jnp.where(has_hit, p + lookup_n, 0)
        draft = jax.lax.dynamic_slice(
            jnp.pad(buf, (0, W)), (start,), (draft_k,))
        # no hit → draft vs greedy will disagree, costing nothing
        # extra: the chunk runs at width W every round regardless

        chunk = jnp.concatenate([nxt[None], draft])[None, :]  # (1, W)
        logits, cache = decode_chunk(params, cfg, cache, chunk)
        greedy = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)

        # accept the longest prefix of drafts matching greedy
        ok = jnp.cumprod((draft == greedy[:-1]).astype(jnp.int32))
        budget = jnp.clip(target - count, 0, draft_k)
        accepted = jnp.minimum(jnp.sum(ok), budget)
        wpos = count + jnp.arange(draft_k)
        wmask = jnp.arange(draft_k) < accepted
        buf = buf.at[jnp.minimum(wpos, S - 1)].set(
            jnp.where(wmask, draft, buf[jnp.minimum(wpos, S - 1)]))
        count = count + accepted

        if eos_id is not None:
            committed = jnp.concatenate([nxt[None], draft])
            cmask = jnp.arange(W) < (1 + accepted)
            is_eos = (committed == eos_id) & cmask
            done = done | is_eos.any()

        # drop the rejected tail: stale K/V is masked via positions
        cache = rewind_cache(cache, cache.offset - (W - 1 - accepted))
        last = logits[0, accepted, :]
        return (buf, count, cache, last, done, rounds + 1)

    buf, count, cache, last, done, rounds = jax.lax.while_loop(
        cond, body,
        (buf, jnp.asarray(Tp, jnp.int32), cache, last,
         jnp.asarray(False), jnp.asarray(1, jnp.int32)))
    out = buf[:target]
    if eos_id is not None:
        # latch: everything after the first generated eos (and any
        # slot past count, if the loop stopped early) becomes eos
        pos = jnp.arange(target)
        is_eos = (out == eos_id) & (pos >= Tp)
        first = jnp.min(jnp.where(is_eos, pos, target))
        out = jnp.where((pos > first) | (pos >= count), eos_id, out)
    return out[None, :], rounds, count


def generate_speculative_fused(params: dict, cfg: LlamaConfig,
                               prompt: jax.Array, *,
                               max_new_tokens: int, lookup_n: int = 3,
                               draft_k: int = 8,
                               eos_id: int | None = None,
                               stats: dict | None = None) -> jax.Array:
    """Single-program prompt-lookup speculative decoding (batch 1,
    greedy). See ``_fused_speculative``; exactness vs ``generate`` is
    asserted under fp32 in tests (bf16 chunked numerics can resolve
    near-ties differently, as with any chunked verification)."""
    B, Tp = prompt.shape
    if B != 1:
        raise ValueError("speculative decoding is batch-1 "
                         f"(got batch {B}); batched requests amortize "
                         "weights already — use generate_fused")
    if Tp <= lookup_n:
        raise ValueError(f"prompt ({Tp}) must be longer than "
                         f"lookup_n ({lookup_n})")
    total_len = Tp + max_new_tokens + draft_k + 1
    out, rounds, count = _fused_speculative(
        params, prompt, cfg=cfg, max_new_tokens=max_new_tokens,
        lookup_n=lookup_n, draft_k=draft_k, eos_id=eos_id,
        total_len=total_len)
    if stats is not None:
        stats["model_calls"] = int(rounds)
        stats["tokens_out"] = int(count) - Tp  # < max_new if eos fired
    return out


def make_generate_step(example_params: dict, cfg: LlamaConfig, mesh, *,
                       max_new_tokens: int, total_len: int,
                       temperature: float = 0.0, top_k: int | None = None,
                       eos_id: int | None = None):
    """Sharded ``generate_fused``: one compiled SPMD program per mesh.

    Returns ``(params, prompt, key=None) -> tokens`` (a jitted SPMD
    program behind a thin argument-contract check) where params
    carry their training shardings (serve on an fsdp×tp mesh, like
    ``make_decode_step``), the prompt and result tokens are
    batch-sharded over (dp, fsdp), and the KV cache lives its whole
    life inside the program on ``cache_shardings`` — it is never
    materialized on the host. Greedy output matches the single-device
    ``generate_fused`` exactly (``tests/test_generate.py``).

    ``example_params`` is only inspected for the pytree structure.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from kubeflow_rm_tpu.parallel.sharding import (
        batch_pspec, param_shardings,
    )

    def run(params, prompt, key, pad_counts):
        return _fused_decode_loop(
            params, cfg, prompt, key, max_new_tokens=max_new_tokens,
            temperature=float(temperature), top_k=top_k, eos_id=eos_id,
            total_len=total_len,
            cache_sharding=cache_shardings(cfg, mesh),
            pad_counts=pad_counts)

    batch_rows = NamedSharding(mesh, PartitionSpec(("dp", "fsdp")))
    jitted = jax.jit(
        run,
        in_shardings=(param_shardings(example_params, mesh),
                      NamedSharding(mesh, batch_pspec(False)), None,
                      batch_rows),
        out_shardings=NamedSharding(mesh, batch_pspec(False)))

    def step(params, prompt, key=None, pad_counts=None):
        # same argument contract as generate_fused: cache must fit the
        # generation (an undersized cache would silently clamp
        # dynamic_update_slice writes into the last slot), and greedy
        # decoding works without a key
        if total_len < prompt.shape[1] + max_new_tokens:
            raise ValueError(
                f"total_len={total_len} < prompt {prompt.shape[1]} + "
                f"new {max_new_tokens}")
        if temperature > 0 and key is None:
            raise ValueError(
                "sampling (temperature > 0) requires a PRNG key")
        if pad_counts is None:
            pad_counts = jnp.zeros((prompt.shape[0],), jnp.int32)
        return jitted(params, prompt,
                      key if key is not None else jax.random.key(0),
                      pad_counts)

    return step


def generate(params: dict, cfg: LlamaConfig, prompt: jax.Array, *,
             max_new_tokens: int, key: jax.Array | None = None,
             temperature: float = 0.0, top_k: int | None = None,
             eos_id: int | None = None,
             max_len: int | None = None,
             pad_counts: jax.Array | None = None) -> jax.Array:
    """Sample ``max_new_tokens`` continuations of ``prompt`` (B, Tp).

    ``temperature`` 0 (default) is greedy argmax; otherwise softmax
    sampling, optionally truncated to the ``top_k`` highest logits.
    Sequences that emit ``eos_id`` keep it and then repeat it (static
    shapes — the result is (B, Tp + max_new_tokens), pad-right).

    ``pad_counts`` (B,) marks leading left-pad slots per row (the same
    ragged-batch contract as ``generate_fused``): pads are masked out
    of attention and positions shift so padded rows match unpadded
    per-row calls — needed when the serving batcher routes padded
    batches down this loop path (int4 weights, see serve_llama).
    """
    B, Tp = prompt.shape
    S = max_len or (Tp + max_new_tokens)
    if S < Tp + max_new_tokens:
        raise ValueError(
            f"max_len={S} < prompt {Tp} + new {max_new_tokens}")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")

    # params ride as a jit ARGUMENT of the shared _decode_step, never a
    # closure: captured weights would be baked into the lowered module
    # as constants (a multi-GB HLO for real models, observed to wedge
    # remote-compile paths)
    cache = init_cache(cfg, B, S)
    logits, cache = _decode_step(params, cfg, cache, prompt, pad_counts)
    last = logits[:, -1, :]

    out = [prompt]
    done = jnp.zeros((B,), bool)
    for i in range(max_new_tokens):
        if key is not None:
            key, sub = jax.random.split(key)
        else:
            sub = None
        nxt = _pick(last, sub, temperature=temperature, top_k=top_k)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        out.append(nxt[:, None])
        if i + 1 < max_new_tokens:
            logits, cache = _decode_step(params, cfg, cache, nxt[:, None],
                                         pad_counts)
            last = logits[:, -1, :]
    return jnp.concatenate(out, axis=1)
