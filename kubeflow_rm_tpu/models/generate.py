"""KV-cached autoregressive generation for the Llama family.

The in-notebook inference path: prefill the prompt in one pass, then
decode a token per step against a preallocated static-shape cache —
every step is the SAME jitted computation (no data-dependent shapes),
which is what XLA wants on TPU. Exactness against the training
``forward`` is asserted by ``tests/test_generate.py``.

TPU-first choices:

- **Static cache** (B, max_len, KVH, hd) per layer, stacked on a
  leading layer axis like the weights, updated with
  ``lax.dynamic_update_slice`` — one compiled step serves the whole
  generation, prefill included (prefill is just a wider chunk).
- **Position-masked attention**: unfilled cache slots carry position
  ``INT32_MAX``, so the standard ``pos_q >= pos_kv`` causal mask of
  ``ops.dot_product_attention`` excludes them — no second mask path to
  keep in sync with training.
- **Layer scan**: the cache rides ``lax.scan`` as scanned xs/ys over
  the same stacked-parameter layout training uses, so compile time
  stays depth-independent.

The reference platform ships no model runtime at all; this module is
capability the jupyter-jax image adds on top (SURVEY.md §2.6).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_rm_tpu.analysis.jaxcheck import hostsync as _hostsync
from kubeflow_rm_tpu.analysis.jaxcheck import recompile as _jit_sentinel
from kubeflow_rm_tpu.models.llama import LlamaConfig
from kubeflow_rm_tpu.models.lora import lora_proj
from kubeflow_rm_tpu.models.quantize import maybe_dequant, unpack_int4_params
from kubeflow_rm_tpu.ops import (
    apply_rope,
    dot_product_attention,
    rms_norm,
    rope_angles,
)

_UNFILLED = jnp.iinfo(jnp.int32).max


@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    k: jax.Array          # (L, B, S, KVH, hd) compute dtype
    v: jax.Array          # (L, B, S, KVH, hd)
    positions: jax.Array  # (B, S) int32; _UNFILLED marks empty slots
    offset: jax.Array     # () int32: next write index


def init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> KVCache:
    L, KVH, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    return KVCache(
        k=jnp.zeros((L, batch, max_len, KVH, hd), cfg.dtype),
        v=jnp.zeros((L, batch, max_len, KVH, hd), cfg.dtype),
        positions=jnp.full((batch, max_len), _UNFILLED, jnp.int32),
        offset=jnp.zeros((), jnp.int32),
    )


def decode_chunk(params: dict, cfg: LlamaConfig, cache: KVCache,
                 tokens: jax.Array,
                 pad_counts: jax.Array | None = None,
                 ) -> tuple[jax.Array, KVCache]:
    """Run ``tokens`` (B, Tc) through the model at the cache offset.

    One function serves prefill (Tc = prompt length) and decode
    (Tc = 1). Returns (logits (B, Tc, V) fp32, updated cache). The
    chunk must fit: offset + Tc <= cache length.

    ``pad_counts`` (B,) enables ragged batches under static shapes —
    the serving path's requirement: row *i*'s first ``pad_counts[i]``
    slots are left-padding. Pad slots get position ``_UNFILLED``, so
    the standard causal mask excludes them from every later query
    (their garbage K/V is invisible), and real tokens' positions are
    shifted down so each row's first real token sits at position 0 —
    batched left-padded output is bit-identical to running each row
    unpadded (``tests/test_generate.py``).
    """
    B, Tc = tokens.shape

    positions = cache.offset + jnp.arange(Tc, dtype=jnp.int32)
    positions = jnp.broadcast_to(positions, (B, Tc))
    if pad_counts is not None:
        positions = positions - pad_counts[:, None]
        positions = jnp.where(positions < 0, _UNFILLED, positions)
    kv_positions = jax.lax.dynamic_update_slice(
        cache.positions, positions, (0, cache.offset))

    def write_kv(c, val):
        return jax.lax.dynamic_update_slice(c, val, (0, cache.offset, 0, 0))

    logits, new_k, new_v = _run_blocks(
        params, cfg, cache.k, cache.v, tokens, positions, kv_positions,
        write_kv)
    new_cache = KVCache(k=new_k, v=new_v, positions=kv_positions,
                       offset=cache.offset + Tc)
    return logits, new_cache


def _run_blocks(params, cfg, cache_k, cache_v, tokens, positions,
                kv_positions, write_kv):
    """Transformer trunk shared by the shared-offset ``decode_chunk``
    and the per-slot-offset ``slot_decode_step``: embed, layer scan
    (attention against the KV cache + FFN), final norm, lm head. The
    two callers differ ONLY in how positions are assigned and how this
    chunk's K/V lands in the cache (``write_kv``: contiguous
    ``dynamic_update_slice`` at one shared offset vs a per-row scatter
    at each slot's own offset) — the math here is identical, which is
    what makes the continuous-batching engine bit-identical to
    ``generate_fused``."""
    B, Tc = tokens.shape
    H, KVH, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    cdt = cfg.dtype

    # rope of a ~2^31 position is finite but wild; clamp pads to 0
    # (their K is masked out by the _UNFILLED position anyway)
    rope_pos = jnp.where(positions == _UNFILLED, 0, positions)
    cos, sin = rope_angles(rope_pos, hd, cfg.rope_theta)

    x = params["embed"]["tokens"][tokens].astype(cdt)

    # family dispatch for the FFN half: dense SwiGLU or expert mixture
    # (the router aux loss is a training quantity — discarded at decode)
    from kubeflow_rm_tpu.models.mixtral import MixtralConfig

    if isinstance(cfg, MixtralConfig):
        from kubeflow_rm_tpu.parallel.moe import moe_ffn

        def ffn(layer, h):
            dq = {k: (maybe_dequant(v, cdt) if k.startswith("moe") else v)
                  for k, v in layer.items()}
            out, _aux = moe_ffn(dq, h, cfg.moe, dtype=cdt)
            return out
    else:
        def ffn(layer, h):
            proj = partial(lora_proj, layer, alpha=cfg.lora_alpha,
                           dtype=cdt)
            gate = proj("w_gate", h)
            up = proj("w_up", h)
            return proj("w_down", jax.nn.silu(gate) * up)

    def body(x, scanned):
        layer, ck, cv = scanned
        proj = partial(lora_proj, layer, alpha=cfg.lora_alpha, dtype=cdt)
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = proj("wq", h).reshape(B, Tc, H, hd)
        k = proj("wk", h).reshape(B, Tc, KVH, hd)
        v = proj("wv", h).reshape(B, Tc, KVH, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        ck = write_kv(ck, k)
        cv = write_kv(cv, v)
        attn = dot_product_attention(
            q, ck, cv, causal=True,
            positions_q=positions, positions_kv=kv_positions,
        )
        x = x + proj("wo", attn.reshape(B, Tc, H * hd))
        x = x + ffn(layer, rms_norm(x, layer["mlp_norm"], cfg.norm_eps))
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache_k, cache_v))
    x = rms_norm(x, params["out_norm"], cfg.norm_eps)
    logits = (x @ maybe_dequant(params["lm_head"], cdt)
              ).astype(jnp.float32)
    return logits, new_k, new_v


def cache_shardings(cfg: LlamaConfig, mesh) -> KVCache:
    """NamedSharding pytree for a KVCache on ``mesh``: batch over
    (dp, fsdp), KV heads over tp — the decode-time analogue of
    ``parallel.sharding`` (weights stay on their training shardings)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return KVCache(
        k=NamedSharding(mesh, P(None, ("dp", "fsdp"), None, "tp", None)),
        v=NamedSharding(mesh, P(None, ("dp", "fsdp"), None, "tp", None)),
        positions=NamedSharding(mesh, P(("dp", "fsdp"), None)),
        offset=NamedSharding(mesh, P()),
    )


def make_decode_step(example_params: dict, cfg: LlamaConfig, mesh):
    """Jitted sharded ``(params, cache, tokens) -> (logits, cache)``.

    Params carry their training shardings (``parallel.sharding`` rules
    — serve on an fsdp×tp mesh), the cache follows ``cache_shardings``
    and is donated so decode runs in-place in HBM; logits come back
    vocab-sharded over tp. ``example_params`` is only inspected for the
    pytree structure. Exactness vs the unsharded path is asserted by
    ``tests/test_generate.py``.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubeflow_rm_tpu.parallel.sharding import (
        batch_pspec, param_shardings,
    )

    return jax.jit(
        lambda p, cache, tokens: decode_chunk(p, cfg, cache, tokens),
        in_shardings=(param_shardings(example_params, mesh),
                      cache_shardings(cfg, mesh),
                      NamedSharding(mesh, batch_pspec(False))),
        out_shardings=(NamedSharding(mesh, P(("dp", "fsdp"), None, "tp")),
                       cache_shardings(cfg, mesh)),
        donate_argnums=(1,),
    )


def _pick(last, key, *, temperature, top_k):
    """Next-token choice from last-position logits (B, V): greedy
    argmax at temperature 0, else (top-k-truncated) categorical. The
    single source for BOTH decode paths — ``generate`` and
    ``_fused_generate`` must sample identically or the fused path's
    greedy bit-identity guarantee silently breaks."""
    if temperature <= 0:
        return jnp.argmax(last, axis=-1).astype(jnp.int32)
    scaled = last / temperature
    if top_k:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _decode_step(params, cfg, cache, tokens, pad_counts=None):
    """Module-level jitted ``decode_chunk``: one cache entry per
    (config, shapes), shared across ``generate`` calls — a per-call
    ``jax.jit(lambda ...)`` would be a fresh cache key every time and
    re-trace + re-compile on every generation."""
    return decode_chunk(params, cfg, cache, tokens, pad_counts)


#: Hoist the int4 nibble unpack out of the fused decode scan (the
#: fix for fused int4 being 4.5x SLOWER than the per-token loop —
#: 612.77 vs 137.07 ms/tok @B8 7B, BENCH_SWEEP_r05 decode_7b: the old
#: trace re-unpacked every weight every step). False restores the
#: in-scan-unpack arm for A/B measurement only.
_UNPACK_ONCE = True


def set_unpack_once(flag: bool) -> None:
    """A/B toggle for the loop-invariant int4 unpack hoist (see
    ``_UNPACK_ONCE``). Clears the fused-path jit caches — the flag is
    read at trace time, so already-compiled programs would otherwise
    keep whichever arm they were traced under."""
    global _UNPACK_ONCE
    _UNPACK_ONCE = bool(flag)
    _fused_generate.clear_cache()
    _fused_speculative.clear_cache()


def _hoist_unpack(params):
    """Unpack packed-int4 leaves once per trace (outside any scan over
    decode steps) so every step reads loop-invariant int8 groups."""
    return unpack_int4_params(params) if _UNPACK_ONCE else params


def _fused_decode_loop(params, cfg, prompt, key, *, max_new_tokens,
                       temperature, top_k, eos_id, total_len,
                       cache_sharding=None, pad_counts=None):
    """Trace-time body shared by ``generate_fused`` (single device) and
    ``make_generate_step`` (sharded): prefill, then a ``lax.scan`` over
    decode steps. ``cache_sharding`` (a NamedSharding pytree) pins the
    freshly-initialized cache's layout under GSPMD.

    Packed-int4 params are unpacked to int8 groups HERE — before the
    scan, so the nibble unpack happens once per generation instead of
    once per token (the per-step cost drops to the int8→bf16 dequant
    prologue; dequant on the unpacked form is bit-identical to dequant
    on the packed form, see ``quantize.unpack_int4``)."""
    params = _hoist_unpack(params)
    B, _ = prompt.shape
    cache = init_cache(cfg, B, total_len)
    if cache_sharding is not None:
        cache = jax.lax.with_sharding_constraint(cache, cache_sharding)
    logits, cache = decode_chunk(params, cfg, cache, prompt, pad_counts)
    last = logits[:, -1, :]

    def body(carry, k_i):
        cache, last, done = carry
        nxt = _pick(last, k_i, temperature=temperature, top_k=top_k)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        logits, cache = decode_chunk(params, cfg, cache, nxt[:, None],
                                     pad_counts)
        return (cache, logits[:, -1, :], done), nxt

    keys = jax.random.split(key, max_new_tokens)
    (_, _, _), toks = jax.lax.scan(
        body, (cache, last, jnp.zeros((B,), bool)), keys)
    return jnp.concatenate([prompt, toks.T], axis=1)


@partial(jax.jit, static_argnames=(
    "cfg", "max_new_tokens", "temperature", "top_k", "eos_id",
    "total_len"))
def _fused_generate(params, prompt, key, pad_counts=None, *, cfg,
                    max_new_tokens, temperature, top_k, eos_id,
                    total_len):
    return _fused_decode_loop(
        params, cfg, prompt, key, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, eos_id=eos_id,
        total_len=total_len, pad_counts=pad_counts)


def generate_fused(params: dict, cfg: LlamaConfig, prompt: jax.Array, *,
                   max_new_tokens: int, key: jax.Array | None = None,
                   temperature: float = 0.0, top_k: int | None = None,
                   eos_id: int | None = None,
                   max_len: int | None = None,
                   pad_counts: jax.Array | None = None) -> jax.Array:
    """``generate`` as ONE compiled XLA program.

    The Python-loop ``generate`` dispatches a jitted step per token —
    ~10 ms/token of host round-trip when the chip sits behind a network
    tunnel, which dwarfs the ~1 ms of decode compute. Here the whole
    prefill + ``lax.scan`` decode loop (sampling, eos latching, cache
    updates included) lowers to a single jit, so dispatch cost is paid
    once per generation instead of once per token. Greedy output is
    bit-identical to ``generate``; at ``temperature > 0`` the PRNG
    stream differs (keys are pre-split for the scan), which is the only
    behavioral difference.

    The scan runs exactly ``max_new_tokens`` steps; the final step's
    cache write is dead work (~1/N overhead) — the price of a
    shape-static loop, which is what keeps the whole thing one program.

    ``pad_counts`` (B,) marks each row's leading slots as left-padding
    for ragged batches: masked out of attention and position-shifted
    so output rows are bit-identical to unpadded per-row calls (the
    serving batcher's correctness contract — see ``decode_chunk``).
    """
    B, Tp = prompt.shape
    S = max_len or (Tp + max_new_tokens)
    if S < Tp + max_new_tokens:
        raise ValueError(
            f"max_len={S} < prompt {Tp} + new {max_new_tokens}")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    return _fused_generate(
        params, prompt, key if key is not None else jax.random.key(0),
        pad_counts,
        cfg=cfg, max_new_tokens=max_new_tokens,
        temperature=float(temperature), top_k=top_k, eos_id=eos_id,
        total_len=S)


def rewind_cache(cache: KVCache, new_offset) -> KVCache:
    """Logically truncate the cache to ``new_offset`` filled slots.

    Slots at/after ``new_offset`` get position ``_UNFILLED`` — the
    causal mask then excludes their (stale) K/V from every future
    query, so physical K/V bytes need no clearing. O(B·S) positions
    traffic, no weight traffic. The speculative decoder uses this to
    drop rejected draft tokens."""
    idx = jnp.arange(cache.positions.shape[1], dtype=jnp.int32)
    pos = jnp.where(idx[None, :] >= new_offset, _UNFILLED,
                    cache.positions)
    return KVCache(k=cache.k, v=cache.v, positions=pos,
                   offset=jnp.asarray(new_offset, jnp.int32))


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "lookup_n",
                                   "draft_k", "eos_id", "total_len"))
def _fused_speculative(params, prompt, *, cfg, max_new_tokens,
                       lookup_n, draft_k, eos_id, total_len):
    """The whole speculative loop as ONE XLA program (batch 1).

    Decode is weights-bound, so verifying a (draft_k+1)-wide chunk
    costs roughly the same HBM traffic as a width-1 step — widening is
    nearly free ON-DEVICE. What ruins host-side speculation on a
    tunneled chip is the blocking sync every round (lookup + accept
    decisions on the host); here the n-gram match, draft gather,
    verification, cache rewind and loop all run under
    ``lax.while_loop``, so the host dispatches once per generation.
    Worst case (nothing accepts) each round still commits 1 token at
    chunk cost ≈ step cost; best case commits draft_k+1.
    """
    params = _hoist_unpack(params)  # unpack int4 once, not per round
    Tp = prompt.shape[1]
    W = draft_k + 1
    S = total_len  # buffer/cache length, incl. chunk overhang room
    V = cfg.vocab_size
    target = Tp + max_new_tokens

    buf = jnp.zeros((S,), jnp.int32).at[:Tp].set(prompt[0])
    cache = init_cache(cfg, 1, S)
    logits, cache = decode_chunk(params, cfg, cache, prompt)
    last = logits[0, -1, :]

    def cond(carry):
        buf, count, cache, last, done, rounds = carry
        return (count < target) & ~done

    def body(carry):
        buf, count, cache, last, done, rounds = carry
        nxt = jnp.argmax(last).astype(jnp.int32)
        buf = buf.at[count].set(nxt)
        count = count + 1

        # prompt-lookup on device: most recent earlier occurrence of
        # the trailing n-gram; its followers become the draft
        tail = jax.lax.dynamic_slice(buf, (count - lookup_n,),
                                     (lookup_n,))
        idx = jnp.arange(S, dtype=jnp.int32)
        windows = buf[jnp.minimum(idx[:, None]
                                  + jnp.arange(lookup_n)[None, :],
                                  S - 1)]
        hit = (windows == tail[None, :]).all(-1) & (idx < count
                                                    - lookup_n)
        has_hit = hit.any()
        p = jnp.max(jnp.where(hit, idx, -1))  # most recent match
        start = jnp.where(has_hit, p + lookup_n, 0)
        draft = jax.lax.dynamic_slice(
            jnp.pad(buf, (0, W)), (start,), (draft_k,))
        # no hit → draft vs greedy will disagree, costing nothing
        # extra: the chunk runs at width W every round regardless

        chunk = jnp.concatenate([nxt[None], draft])[None, :]  # (1, W)
        logits, cache = decode_chunk(params, cfg, cache, chunk)
        greedy = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)

        # accept the longest prefix of drafts matching greedy
        ok = jnp.cumprod((draft == greedy[:-1]).astype(jnp.int32))
        budget = jnp.clip(target - count, 0, draft_k)
        accepted = jnp.minimum(jnp.sum(ok), budget)
        wpos = count + jnp.arange(draft_k)
        wmask = jnp.arange(draft_k) < accepted
        buf = buf.at[jnp.minimum(wpos, S - 1)].set(
            jnp.where(wmask, draft, buf[jnp.minimum(wpos, S - 1)]))
        count = count + accepted

        if eos_id is not None:
            committed = jnp.concatenate([nxt[None], draft])
            cmask = jnp.arange(W) < (1 + accepted)
            is_eos = (committed == eos_id) & cmask
            done = done | is_eos.any()

        # drop the rejected tail: stale K/V is masked via positions
        cache = rewind_cache(cache, cache.offset - (W - 1 - accepted))
        last = logits[0, accepted, :]
        return (buf, count, cache, last, done, rounds + 1)

    buf, count, cache, last, done, rounds = jax.lax.while_loop(
        cond, body,
        (buf, jnp.asarray(Tp, jnp.int32), cache, last,
         jnp.asarray(False), jnp.asarray(1, jnp.int32)))
    out = buf[:target]
    if eos_id is not None:
        # latch: everything after the first generated eos (and any
        # slot past count, if the loop stopped early) becomes eos
        pos = jnp.arange(target)
        is_eos = (out == eos_id) & (pos >= Tp)
        first = jnp.min(jnp.where(is_eos, pos, target))
        out = jnp.where((pos > first) | (pos >= count), eos_id, out)
    return out[None, :], rounds, count


def generate_speculative_fused(params: dict, cfg: LlamaConfig,
                               prompt: jax.Array, *,
                               max_new_tokens: int, lookup_n: int = 3,
                               draft_k: int = 8,
                               eos_id: int | None = None,
                               stats: dict | None = None) -> jax.Array:
    """Single-program prompt-lookup speculative decoding (batch 1,
    greedy). See ``_fused_speculative``; exactness vs ``generate`` is
    asserted under fp32 in tests (bf16 chunked numerics can resolve
    near-ties differently, as with any chunked verification)."""
    B, Tp = prompt.shape
    if B != 1:
        raise ValueError("speculative decoding is batch-1 "
                         f"(got batch {B}); batched requests amortize "
                         "weights already — use generate_fused")
    if Tp <= lookup_n:
        raise ValueError(f"prompt ({Tp}) must be longer than "
                         f"lookup_n ({lookup_n})")
    total_len = Tp + max_new_tokens + draft_k + 1
    out, rounds, count = _fused_speculative(
        params, prompt, cfg=cfg, max_new_tokens=max_new_tokens,
        lookup_n=lookup_n, draft_k=draft_k, eos_id=eos_id,
        total_len=total_len)
    if stats is not None:
        stats["model_calls"] = int(rounds)
        stats["tokens_out"] = int(count) - Tp  # < max_new if eos fired
    return out


def make_generate_step(example_params: dict, cfg: LlamaConfig, mesh, *,
                       max_new_tokens: int, total_len: int,
                       temperature: float = 0.0, top_k: int | None = None,
                       eos_id: int | None = None):
    """Sharded ``generate_fused``: one compiled SPMD program per mesh.

    Returns ``(params, prompt, key=None) -> tokens`` (a jitted SPMD
    program behind a thin argument-contract check) where params
    carry their training shardings (serve on an fsdp×tp mesh, like
    ``make_decode_step``), the prompt and result tokens are
    batch-sharded over (dp, fsdp), and the KV cache lives its whole
    life inside the program on ``cache_shardings`` — it is never
    materialized on the host. Greedy output matches the single-device
    ``generate_fused`` exactly (``tests/test_generate.py``).

    ``example_params`` is only inspected for the pytree structure.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from kubeflow_rm_tpu.parallel.sharding import (
        batch_pspec, param_shardings,
    )

    def run(params, prompt, key, pad_counts):
        return _fused_decode_loop(
            params, cfg, prompt, key, max_new_tokens=max_new_tokens,
            temperature=float(temperature), top_k=top_k, eos_id=eos_id,
            total_len=total_len,
            cache_sharding=cache_shardings(cfg, mesh),
            pad_counts=pad_counts)

    batch_rows = NamedSharding(mesh, PartitionSpec(("dp", "fsdp")))
    jitted = jax.jit(
        run,
        in_shardings=(param_shardings(example_params, mesh),
                      NamedSharding(mesh, batch_pspec(False)), None,
                      batch_rows),
        out_shardings=NamedSharding(mesh, batch_pspec(False)))

    def step(params, prompt, key=None, pad_counts=None):
        # same argument contract as generate_fused: cache must fit the
        # generation (an undersized cache would silently clamp
        # dynamic_update_slice writes into the last slot), and greedy
        # decoding works without a key
        if total_len < prompt.shape[1] + max_new_tokens:
            raise ValueError(
                f"total_len={total_len} < prompt {prompt.shape[1]} + "
                f"new {max_new_tokens}")
        if temperature > 0 and key is None:
            raise ValueError(
                "sampling (temperature > 0) requires a PRNG key")
        if pad_counts is None:
            pad_counts = jnp.zeros((prompt.shape[0],), jnp.int32)
        return jitted(params, prompt,
                      key if key is not None else jax.random.key(0),
                      pad_counts)

    return step


def generate(params: dict, cfg: LlamaConfig, prompt: jax.Array, *,
             max_new_tokens: int, key: jax.Array | None = None,
             temperature: float = 0.0, top_k: int | None = None,
             eos_id: int | None = None,
             max_len: int | None = None,
             pad_counts: jax.Array | None = None) -> jax.Array:
    """Sample ``max_new_tokens`` continuations of ``prompt`` (B, Tp).

    ``temperature`` 0 (default) is greedy argmax; otherwise softmax
    sampling, optionally truncated to the ``top_k`` highest logits.
    Sequences that emit ``eos_id`` keep it and then repeat it (static
    shapes — the result is (B, Tp + max_new_tokens), pad-right).

    ``pad_counts`` (B,) marks leading left-pad slots per row (the same
    ragged-batch contract as ``generate_fused``): pads are masked out
    of attention and positions shift so padded rows match unpadded
    per-row calls — needed when the serving batcher routes padded
    batches down this loop path (int4 weights, see serve_llama).
    """
    B, Tp = prompt.shape
    S = max_len or (Tp + max_new_tokens)
    if S < Tp + max_new_tokens:
        raise ValueError(
            f"max_len={S} < prompt {Tp} + new {max_new_tokens}")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")

    # params ride as a jit ARGUMENT of the shared _decode_step, never a
    # closure: captured weights would be baked into the lowered module
    # as constants (a multi-GB HLO for real models, observed to wedge
    # remote-compile paths)
    cache = init_cache(cfg, B, S)
    logits, cache = _decode_step(params, cfg, cache, prompt, pad_counts)
    last = logits[:, -1, :]

    out = [prompt]
    done = jnp.zeros((B,), bool)
    for i in range(max_new_tokens):
        if key is not None:
            key, sub = jax.random.split(key)
        else:
            sub = None
        nxt = _pick(last, sub, temperature=temperature, top_k=top_k)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        out.append(nxt[:, None])
        if i + 1 < max_new_tokens:
            logits, cache = _decode_step(params, cfg, cache, nxt[:, None],
                                         pad_counts)
            last = logits[:, -1, :]
    return jnp.concatenate(out, axis=1)


# ---------------------------------------------------------------------------
# Continuous batching: fixed-capacity KV slots with PER-SLOT offsets.
#
# ``generate_fused`` runs a batch in lockstep — every row prefills
# together, decodes together, and the whole batch's HBM reservation is
# held until the LAST row finishes (a 4-token reply waits on a
# 256-token neighbour, and no new request can start until everyone is
# done). The engine below decouples rows: the cache is a pool of B
# independent slots, each with its own write offset and next-position
# counter, so requests are admitted into free slots and retired out of
# them at token boundaries while the other slots keep decoding.
# This is the serving-side analogue of what Orca-style continuous
# batching does for GPU serving, built on the same position-masked
# attention trick the ragged batcher uses: an inactive slot's query
# position is _UNFILLED, so whatever garbage it writes that step is
# invisible to every real query, and per-row output stays bit-identical
# to a one-shot ``generate_fused`` call for that row alone
# (``tests/test_generate.py``).
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclass
class SlotCache:
    """KV pool for continuous batching: like ``KVCache`` but the write
    offset and next token position are per-row vectors, so each slot
    advances independently."""
    k: jax.Array          # (L, B, S, KVH, hd) compute dtype
    v: jax.Array          # (L, B, S, KVH, hd)
    positions: jax.Array  # (B, S) int32; _UNFILLED marks empty slots
    write_idx: jax.Array  # (B,) int32: next KV write slot per row
    pos_next: jax.Array   # (B,) int32: next token position per row


def init_slot_cache(cfg: LlamaConfig, slots: int,
                    slot_len: int) -> SlotCache:
    L, KVH, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    return SlotCache(
        k=jnp.zeros((L, slots, slot_len, KVH, hd), cfg.dtype),
        v=jnp.zeros((L, slots, slot_len, KVH, hd), cfg.dtype),
        positions=jnp.full((slots, slot_len), _UNFILLED, jnp.int32),
        write_idx=jnp.zeros((slots,), jnp.int32),
        pos_next=jnp.zeros((slots,), jnp.int32),
    )


# row_cache is consumed read-only: its (B=1, S) buffers are gathered
# into the pool and cannot alias any output shape, so donating it
# would only draw an unused-donation warning; the pool itself IS
# donated.
@partial(jax.jit, donate_argnames=("cache",))
def _install_row(cache: SlotCache, row_cache: KVCache,  # kfrm: disable=KFRM008
                 row: jax.Array, n_real: jax.Array) -> SlotCache:
    """Copy a freshly-prefilled single-request cache (B=1, same S) into
    slot ``row`` of the pool. ``n_real`` is the request's REAL prompt
    length (sans left-pad): the slot resumes at position n_real while
    its writes continue at the padded offset — exactly where a fused
    left-padded batch would put them."""
    return SlotCache(
        k=cache.k.at[:, row].set(row_cache.k[:, 0]),
        v=cache.v.at[:, row].set(row_cache.v[:, 0]),
        positions=cache.positions.at[row].set(row_cache.positions[0]),
        write_idx=cache.write_idx.at[row].set(row_cache.offset),
        pos_next=cache.pos_next.at[row].set(n_real),
    )


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def slot_decode_step(params, cfg, cache: SlotCache, tokens, active):
    """One decode step over the whole slot pool.

    ``tokens`` (B,) int32 is each slot's freshly-sampled token;
    ``active`` (B,) bool masks live slots. Every row writes K/V at its
    OWN ``write_idx`` (a batched scatter — the per-slot analogue of
    ``decode_chunk``'s shared-offset ``dynamic_update_slice``) and
    attends at its OWN ``pos_next``. Inactive rows still flow through
    the matmuls (static shapes) but their query position is _UNFILLED
    and their counters don't advance, so their writes are invisible
    and harmless — the slot is fully re-initialized on the next admit.
    Returns (last-position logits (B, V) fp32, updated cache).
    """
    B = tokens.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)
    positions = jnp.where(active, cache.pos_next, _UNFILLED)[:, None]
    kv_positions = cache.positions.at[rows, cache.write_idx].set(
        positions[:, 0])

    def write_kv(c, val):
        # (B, S, KVH, hd) cache, (B, 1, KVH, hd) chunk: row i lands in
        # its own slot at its own offset
        return c.at[rows, cache.write_idx].set(val[:, 0])

    logits, new_k, new_v = _run_blocks(
        params, cfg, cache.k, cache.v, tokens[:, None], positions,
        kv_positions, write_kv)
    inc = active.astype(jnp.int32)
    new_cache = SlotCache(k=new_k, v=new_v, positions=kv_positions,
                          write_idx=cache.write_idx + inc,
                          pos_next=cache.pos_next + inc)
    return logits[:, -1, :], new_cache


@partial(jax.jit, static_argnames=("temperature", "top_k"))
def _pick_row(last, key, *, temperature, top_k):
    """Jitted single-row ``_pick`` — the engine samples per slot (each
    request has its own PRNG stream) but through the same sampling
    source as both batch decode paths."""
    return _pick(last[None, :], key, temperature=temperature,
                 top_k=top_k)[0]


def _bucket_len(n: int) -> int:
    """Next power of two ≥ n: the prefill padding buckets, so a storm
    of ragged prompts compiles O(log) prefill programs instead of one
    per distinct length (same policy as serve_llama's batcher)."""
    b = 1
    while b < n:
        b *= 2
    return b


#: in-engine SLO classes, drained by weighted share at token
#: boundaries (replacing the single FIFO between gateway and engine)
SLO_CLASSES = ("interactive", "batch", "best_effort")
DEFAULT_CLASS_WEIGHTS = {"interactive": 8, "batch": 3, "best_effort": 1}


class EngineRequest:
    """Handle returned by ``ContinuousBatchingEngine.submit``:
    ``tokens`` fills in as the request decodes; ``done`` flips when the
    slot retires (eos or max_new_tokens)."""

    _next_id = 0

    def __init__(self, prompt, *, max_new_tokens, eos_id, temperature,
                 top_k, key, slo_class="interactive",
                 speculative=False):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_k = top_k
        self.key = key
        self.slo_class = slo_class
        # per-request execution options: ``speculative`` runs the whole
        # generation as one fused prompt-lookup program at admission
        # (batch/best_effort only); ``chain`` is a serialized KV chain
        # installed in place of prefill (models.paging export format)
        self.speculative = bool(speculative)
        self.chain = None
        self.tokens: list[int] = []
        self.done = False
        self.rid = EngineRequest._next_id
        EngineRequest._next_id += 1
        # filled by the engine for latency accounting
        self.submitted_step = None
        self.admitted_step = None
        self.finished_step = None


class ContinuousBatchingEngine:
    """Slot-based continuous-batching decode engine.

    ``submit`` queues a request into its SLO class; ``step`` admits
    queued requests into free slots (one prefill each), runs ONE
    decode step for all live slots, samples each slot's next token
    host-side, and retires slots that hit eos or their token budget —
    so short requests leave (and new ones enter) mid-flight instead of
    waiting for the longest neighbour.

    Two cache arms:

    - ``paged=True`` (default): KV lives in a block pool
      (``models.paging``) with per-slot block tables, refcounted
      copy-on-write prefix sharing (a shared system prompt is
      prefilled once, later requests adopt the cached blocks), and
      LRU retention of retired prefix blocks.
    - ``paged=False``: the r12 contiguous ``SlotCache`` — kept as the
      measured A/B baseline arm (``benchmarks/serve_bench.py``).

    Admission drains three priority-weighted class queues
    (``SLO_CLASSES``) by smooth weighted round-robin at token
    boundaries — interactive requests keep jumping a best-effort
    backlog without starving it.

    Exactness contract (both arms): each request's output is
    bit-identical to ``generate_fused(prompt[None],
    max_new_tokens=..., max_len=slot_len)`` for that request alone
    (greedy; sampled requests use their own key stream) — cached
    prefix or not. Packed-int4 params are unpacked ONCE at
    construction so per-step cost is the int8→bf16 dequant prologue,
    same as the fixed fused path.
    """

    def __init__(self, params, cfg, *, slots: int = 8,
                 slot_len: int = 256, paged: bool = True,
                 block_size: int = 16, num_blocks: int | None = None,
                 class_weights: dict | None = None,
                 prefix_cache: bool = True):
        from kubeflow_rm_tpu.models import paging

        self.cfg = cfg
        self.slots = slots
        self.slot_len = slot_len
        self.paged = paged
        # unpack int4 leaves once, outside any per-step work; no-op on
        # int8/bf16 trees
        self.params = jax.jit(unpack_int4_params)(params)
        if paged:
            if slot_len % block_size:
                raise ValueError(
                    f"slot_len {slot_len} must be a multiple of "
                    f"block_size {block_size}")
            self.block_size = block_size
            maxb = slot_len // block_size
            if num_blocks is None:
                # every slot fully packed + 50% headroom so retired
                # prefix blocks can be RETAINED instead of recycled
                num_blocks = (paging.RESERVED_BLOCKS + slots * maxb
                              + max(maxb, (slots * maxb) // 2))
            self.pool = paging.BlockPool(num_blocks, block_size)
            self.prefix_cache = prefix_cache
            self.cache = paging.init_paged_cache(
                cfg, slots, slot_len, num_blocks, block_size)
        else:
            self.block_size = None
            self.pool = None
            self.prefix_cache = False
            self.cache = init_slot_cache(cfg, slots, slot_len)
        self._slot_req: list[EngineRequest | None] = [None] * slots
        self._slot_blocks: list[list | None] = [None] * slots
        self._last = [None] * slots   # (V,) logits per live slot
        self._queues = {c: [] for c in SLO_CLASSES}
        self.class_weights = dict(DEFAULT_CLASS_WEIGHTS)
        if class_weights:
            self.class_weights.update(class_weights)
        self._credits = {c: 0.0 for c in SLO_CLASSES}
        # counters surfaced by stats()
        self.decode_steps = 0
        self.prefills = 0
        self.occupancy_sum = 0
        self.admitted_total = 0
        self.finished_total = 0
        self.admitted_by_class = {c: 0 for c in SLO_CLASSES}
        self.prefix_hit_tokens = 0
        self.prompt_tokens = 0
        # disaggregation + speculative counters
        self.chain_installs = 0
        self.chains_exported = 0
        self.chains_adopted = 0
        self.speculative_requests = 0
        self.speculative_model_calls = 0
        self._spec_finished: list[EngineRequest] = []
        if _jit_sentinel.enabled():
            # prompt lengths bucket to powers of two (_bucket_len), so
            # a pow-2 slot_len admits at most log2(slot_len)+1 prefill
            # shapes; decode always runs the full (slots,) batch — ONE
            # shape, ever. The sentinel turns both into assertions.
            _jit_sentinel.set_limit("engine.prefill",
                                    slot_len.bit_length())
            _jit_sentinel.set_limit("engine.decode_step", 1)
            _jit_sentinel.track(
                "engine.prefill",
                paging.paged_prefill if paged else _decode_step)
            _jit_sentinel.track(
                "engine.decode_step",
                paging.paged_decode_step if paged else slot_decode_step)

    # -- request lifecycle -------------------------------------------------

    def submit(self, prompt, *, max_new_tokens: int,
               eos_id: int | None = None, temperature: float = 0.0,
               top_k: int | None = None,
               key: jax.Array | None = None,
               slo_class: str = "interactive",
               speculative: bool = False) -> EngineRequest:
        Tp = len(prompt)
        if Tp == 0:
            raise ValueError("empty prompt")
        if slo_class not in SLO_CLASSES:
            raise ValueError(f"unknown slo_class {slo_class!r} "
                             f"(one of {SLO_CLASSES})")
        if speculative:
            # one fused program monopolizes the device for the whole
            # generation — a latency-class request must never do that,
            # and prompt-lookup drafting is greedy by construction
            if slo_class == "interactive":
                raise ValueError(
                    "speculative decode is a batch/best_effort option "
                    "(interactive stays on the continuous-batching "
                    "path)")
            if temperature > 0:
                raise ValueError("speculative decode is greedy-only")
            if Tp <= 3:
                raise ValueError(
                    f"speculative decode needs a prompt longer than "
                    f"lookup_n=3 (got {Tp})")
        need = _bucket_len(Tp) + max_new_tokens
        if need > self.slot_len:
            raise ValueError(
                f"request needs {need} cache slots (prefill bucket "
                f"{_bucket_len(Tp)} + {max_new_tokens} new) > slot_len "
                f"{self.slot_len}")
        if self.paged:
            chunks = -(-(Tp + max_new_tokens) // self.block_size)
            if chunks > self.pool.usable_blocks:
                raise ValueError(
                    f"request needs {chunks} KV blocks > pool of "
                    f"{self.pool.usable_blocks} usable blocks")
        if temperature > 0 and key is None:
            raise ValueError("sampling (temperature > 0) requires a key")
        req = EngineRequest(prompt, max_new_tokens=max_new_tokens,
                            eos_id=eos_id, temperature=temperature,
                            top_k=top_k, key=key, slo_class=slo_class,
                            speculative=speculative)
        req.submitted_step = self.decode_steps
        self._queues[slo_class].append(req)
        return req

    def install_chain(self, chain: dict, *, max_new_tokens: int,
                      eos_id: int | None = None,
                      temperature: float = 0.0,
                      top_k: int | None = None,
                      key: jax.Array | None = None,
                      slo_class: str = "interactive") -> EngineRequest:
        """Submit a request whose prefill is REPLACED by a serialized
        KV chain (``models.paging.export_chain`` format, produced by a
        prefill replica for exactly this prompt): the chain's chunks
        seat directly in the pool and sampling starts from the carried
        last-token logits — zero prefill FLOPs on this replica.
        Verification happens here, before queueing: a corrupted chunk
        raises ``ValueError`` and nothing is enqueued."""
        from kubeflow_rm_tpu.models import paging

        if not self.paged:
            raise ValueError("install_chain requires the paged engine")
        paging.verify_chain(chain)
        if int(chain["block_size"]) != self.block_size:
            raise ValueError(
                f"chain block_size {chain['block_size']} != engine "
                f"block_size {self.block_size}")
        if chain.get("tokens") is None or chain.get("last_logits") is None:
            raise ValueError("install_chain needs a full chain "
                             "(tokens + last_logits); partial chains "
                             "go through adopt_chain")
        ck = chain["chunks_k"]
        if (ck.shape[0] != self.cache.k.shape[0]
                or ck.shape[2:] != self.cache.k.shape[2:]):
            raise ValueError("chain chunk shape does not fit this "
                             "engine's cache")
        req = self.submit(chain["tokens"],
                          max_new_tokens=max_new_tokens, eos_id=eos_id,
                          temperature=temperature, top_k=top_k,
                          key=key, slo_class=slo_class)
        req.chain = chain
        return req

    def adopt_chain(self, chain: dict) -> int:
        """Seat a foreign chain in the local pool as retained prefix
        cache — no slot, no request; the next ``submit`` for a prompt
        sharing the prefix hits it like any locally-prefilled chain.
        Returns the number of chunks adopted (0 when the chain is
        already local or the pool is transiently full)."""
        from kubeflow_rm_tpu.models import paging

        if not self.paged:
            raise ValueError("adopt_chain requires the paged engine")
        keys = list(zip(chain["covers"], chain["keys"]))
        if len(self.pool.lookup_chain(keys)) == len(keys):
            return 0
        got = paging.import_chain(self.cache, self.pool, chain)
        if got is None:
            return 0
        self.cache, blocks = got
        self.pool.decref(blocks)   # retained at ref 0 until evicted
        self.chains_adopted += 1
        return len(blocks)

    def chain_coverage(self, prompt) -> int:
        """Prompt tokens the local prefix cache already covers."""
        from kubeflow_rm_tpu.models import paging

        if not self.paged or not self.prefix_cache:
            return 0
        keys = paging.prefix_keys(prompt, self.block_size)
        chain = self.pool.lookup_chain(keys)
        return keys[len(chain) - 1][0] if chain else 0

    def _next_queued(self) -> EngineRequest | None:
        """Smooth weighted round-robin over the non-empty class
        queues: every pick tops each contender up by its weight, the
        highest credit wins and pays back the round's total — over
        time each class's share of admissions converges to its weight
        share, and no non-empty class starves."""
        live = [c for c in SLO_CLASSES if self._queues[c]]
        if not live:
            return None
        total = sum(self.class_weights[c] for c in live)
        for c in live:
            self._credits[c] += self.class_weights[c]
        chosen = max(live, key=lambda c: (self._credits[c],
                                          -SLO_CLASSES.index(c)))
        self._credits[chosen] -= total
        return self._queues[chosen].pop(0)

    def _requeue_front(self, req: EngineRequest) -> None:
        self._queues[req.slo_class].insert(0, req)

    def evict_queued(self) -> list[EngineRequest]:
        """Pull every not-yet-admitted request back out (drain path:
        the gateway re-routes them to another replica). Admitted
        slots are untouched — they finish here."""
        out: list[EngineRequest] = []
        for c in SLO_CLASSES:
            out.extend(self._queues[c])
            self._queues[c] = []
        return out

    def _admit(self) -> None:
        from kubeflow_rm_tpu.models import paging

        for i in range(self.slots):
            if self._slot_req[i] is not None:
                continue
            while True:
                req = self._next_queued()
                if req is None:
                    return
                if req.speculative:
                    # runs whole at this boundary, never holds a slot
                    self._run_speculative(req)
                    continue
                break
            if self.paged and req.chain is not None:
                keys = paging.prefix_keys(req.prompt, self.block_size)
                if len(self.pool.lookup_chain(keys)) == len(keys):
                    # full local hit: adopt the cached blocks instead
                    # of seating duplicate chunks from the payload
                    req.chain = None
            if self.paged and req.chain is not None:
                last = self._admit_chain(i, req)
                if last is None:
                    self._requeue_front(req)
                    return
            elif self.paged:
                last = self._admit_paged(i, req)
                if last is None:
                    # transient block OOM: head waits at the front of
                    # its class queue; blocks free as slots retire (or
                    # as retained prefix blocks get evicted), so this
                    # always makes progress eventually
                    self._requeue_front(req)
                    return
                self.prefills += 1
            else:
                last = self._admit_contiguous(i, req)
                self.prefills += 1
            self._last[i] = last
            self._slot_req[i] = req
            req.admitted_step = self.decode_steps
            self.admitted_total += 1
            self.admitted_by_class[req.slo_class] += 1

    def _run_speculative(self, req: EngineRequest) -> None:
        """Execute a speculative request whole: one fused prompt-lookup
        program (``generate_speculative_fused``), greedy, exactness-
        matched to ``generate_fused`` for the same prompt. The request
        finishes at this token boundary without consuming a slot."""
        stats: dict = {}
        out = generate_speculative_fused(
            self.params, self.cfg,
            jnp.asarray([req.prompt], jnp.int32),
            max_new_tokens=req.max_new_tokens, eos_id=req.eos_id,
            stats=stats)
        toks = [int(t) for t in
                jax.device_get(out)[0][len(req.prompt):]]
        if req.eos_id is not None and req.eos_id in toks:
            toks = toks[:toks.index(req.eos_id) + 1]
        req.tokens = toks
        req.done = True
        req.admitted_step = self.decode_steps
        req.finished_step = self.decode_steps
        self.admitted_total += 1
        self.admitted_by_class[req.slo_class] += 1
        self.finished_total += 1
        self.speculative_requests += 1
        self.speculative_model_calls += stats.get("model_calls", 0)
        self._spec_finished.append(req)

    def _admit_contiguous(self, i: int, req: EngineRequest):
        Tp = len(req.prompt)
        Tb = _bucket_len(Tp)
        padded = jnp.asarray([[0] * (Tb - Tp) + req.prompt], jnp.int32)
        pads = jnp.asarray([Tb - Tp], jnp.int32)
        tmp = init_cache(self.cfg, 1, self.slot_len)
        _jit_sentinel.note("engine.prefill", padded)
        with _hostsync.region("engine.prefill"):
            logits, tmp = _decode_step(self.params, self.cfg, tmp,
                                       padded, pads)
        self.cache = _install_row(
            self.cache, tmp, jnp.asarray(i, jnp.int32),
            jnp.asarray(Tp, jnp.int32))
        return logits[0, -1, :]

    def _admit_paged(self, i: int, req: EngineRequest):
        """Plan blocks, prefill the un-cached suffix, install. Returns
        the last real token's logits row, or ``None`` on transient
        block OOM (pool state untouched — clean rejection).

        Plan: the longest consecutive cached chain covers ``n_hit``
        prompt tokens (clamped to Tp-1: the last prompt token is
        always prefilled, its logits seed sampling). Chunks fully
        inside the hit are ADOPTED (incref, never written); the chunk
        containing ``n_hit`` — when mid-block — is FORKED: the request
        gets its own copy, because its own writes (suffix prefill +
        generated tokens from offset Tp) land there. That fork is the
        copy-on-write: shared blocks are immutable, first write forks.
        """
        from kubeflow_rm_tpu.models import paging

        pool, BS = self.pool, self.block_size
        maxb = self.slot_len // BS
        Tp, budget = len(req.prompt), req.max_new_tokens
        keys = (paging.prefix_keys(req.prompt, BS)
                if self.prefix_cache else [])
        chain = pool.lookup_chain(keys)
        n_hit = min(keys[len(chain) - 1][0] if chain else 0, Tp - 1)
        # fit: cached tokens + the suffix's padding bucket must fit
        # the strip; dropping back to a block boundary only costs
        # re-prefill of the dropped tokens
        while n_hit > 0 and n_hit + _bucket_len(Tp - n_hit) > self.slot_len:
            n_hit = ((n_hit - 1) // BS) * BS
        shared_full = n_hit // BS
        fork = n_hit % BS != 0
        shared = chain[:shared_full]
        needed = -(-(Tp + budget) // BS)
        owned_n = needed - shared_full

        # pin sources before alloc: alloc may EVICT ref-0 retained
        # blocks, and evicting a block we are about to read from (or
        # re-handing it out as our own fresh block) would corrupt the
        # copy. On OOM the pins roll back — no torn state.
        pins = chain[:shared_full + 1] if fork else shared
        pool.incref(pins)
        fresh = pool.alloc(owned_n)
        if fresh is None:
            pool.decref(pins)
            return None
        if fork:
            pool.cow_forks += 1

        load_row = [paging.NULL_BLOCK] * maxb
        load_row[:len(pins)] = pins
        final_row = [paging.NULL_BLOCK] * maxb
        final_row[:shared_full] = shared
        final_row[shared_full:needed] = fresh
        # owned chunks land in their blocks; shared chunks and tail
        # chunks past the allocation divert to SINK (never overwrite a
        # shared block, never touch NULL)
        dest_row = [c_blk if shared_full <= c < needed else
                    paging.SINK_BLOCK
                    for c, c_blk in enumerate(final_row)]

        suffix = req.prompt[n_hit:]
        Tc = _bucket_len(len(suffix))
        padded = jnp.asarray([suffix + [0] * (Tc - len(suffix))],
                             jnp.int32)
        _jit_sentinel.note("engine.prefill", padded)
        with _hostsync.region("engine.prefill"):
            last, tk, tv, tpos = paging.paged_prefill(
                self.params, self.cfg, self.cache,
                jnp.asarray(load_row, jnp.int32),
                jnp.asarray(n_hit, jnp.int32), padded,
                jnp.asarray(len(suffix), jnp.int32))
        self.cache = paging.paged_install(
            self.cache, tk, tv, tpos, jnp.asarray(i, jnp.int32),
            jnp.asarray(final_row, jnp.int32),
            jnp.asarray(dest_row, jnp.int32),
            jnp.asarray(Tp, jnp.int32))
        if fork:
            pool.decref([chain[shared_full]])   # unpin the fork source
        if self.prefix_cache:
            parent = None
            for covered, key in keys:
                pool.register(key, final_row[(covered - 1) // BS],
                              parent=parent, covered=covered)
                parent = key
        self._slot_blocks[i] = shared + fresh
        self.prefix_hit_tokens += n_hit
        self.prompt_tokens += Tp
        return last

    def _admit_chain(self, i: int, req: EngineRequest):
        """Seat a verified foreign chain straight into slot ``i``: the
        chain's chunks land in freshly allocated blocks, counters seat
        at the real prompt length, and sampling starts from the
        carried last-token logits — the decode replica runs ZERO
        prefill FLOPs. Returns ``None`` on transient block OOM.

        Exactness: chunk contents are the prefill replica's
        ``paged_prefill`` output for this exact prompt on the same
        weights, round-tripped through host memory bit-for-bit;
        columns past the prompt carry ``_UNFILLED`` positions so the
        causal mask hides them, and decode overwrites from offset Tp
        exactly as a local admission would."""
        from kubeflow_rm_tpu.models import paging

        pool, BS = self.pool, self.block_size
        maxb = self.slot_len // BS
        chain = req.chain
        Tp, budget = len(req.prompt), req.max_new_tokens
        nchain = len(chain["keys"])
        needed = -(-(Tp + budget) // BS)
        fresh = pool.alloc(needed)
        if fresh is None:
            return None
        cache = self.cache
        idx = jnp.asarray(fresh[:nchain], jnp.int32)
        final_row = [paging.NULL_BLOCK] * maxb
        final_row[:needed] = fresh
        positions = cache.positions.at[idx].set(
            jnp.asarray(chain["chunks_pos"], jnp.int32))
        if needed > nchain:
            # decode-budget blocks past the chain may be recycled:
            # wipe their positions so the gathered strip never shows a
            # stale row (the no-stale-reads guarantee paged_install
            # provides on the prefill path)
            tail = jnp.asarray(fresh[nchain:], jnp.int32)
            positions = positions.at[tail].set(_UNFILLED)
        self.cache = paging.PagedKVCache(
            k=cache.k.at[:, idx].set(
                jnp.asarray(chain["chunks_k"], cache.k.dtype)),
            v=cache.v.at[:, idx].set(
                jnp.asarray(chain["chunks_v"], cache.v.dtype)),
            positions=positions,
            block_tables=cache.block_tables.at[i].set(
                jnp.asarray(final_row, jnp.int32)),
            write_idx=cache.write_idx.at[i].set(Tp),
            pos_next=cache.pos_next.at[i].set(Tp),
        )
        if self.prefix_cache:
            parent = None
            for j, key in enumerate(chain["keys"]):
                pool.register(key, fresh[j], parent=parent,
                              covered=chain["covers"][j])
                parent = key
        self._slot_blocks[i] = fresh
        self.prefix_hit_tokens += Tp   # the whole prompt arrived cached
        self.prompt_tokens += Tp
        self.chain_installs += 1
        return jnp.asarray(chain["last_logits"])

    def prefill_chain(self, prompt) -> dict | None:
        """Prefill-replica entry point: compute the full prompt's KV
        chain into the local pool (adopting any cached prefix),
        register it, and export it serialized with the last real
        token's logits — so a decode replica can ``install_chain`` it
        without prefilling. No decode slot is touched; the chain stays
        behind as retained (ref-0) prefix cache, so a resumed or
        repeated prompt only prefills its new suffix. Returns ``None``
        on transient block OOM."""
        from kubeflow_rm_tpu.models import paging

        if not self.paged:
            raise ValueError("prefill_chain requires the paged engine")
        prompt = [int(t) for t in prompt]
        Tp = len(prompt)
        if Tp == 0:
            raise ValueError("empty prompt")
        if _bucket_len(Tp) > self.slot_len:
            raise ValueError(
                f"prompt bucket {_bucket_len(Tp)} > slot_len "
                f"{self.slot_len}")
        pool, BS = self.pool, self.block_size
        maxb = self.slot_len // BS
        keys = paging.prefix_keys(prompt, BS)
        chain = pool.lookup_chain(keys)
        n_hit = min(keys[len(chain) - 1][0] if chain else 0, Tp - 1)
        while n_hit > 0 and n_hit + _bucket_len(Tp - n_hit) > self.slot_len:
            n_hit = ((n_hit - 1) // BS) * BS
        shared_full = n_hit // BS
        fork = n_hit % BS != 0
        shared = chain[:shared_full]
        needed = -(-Tp // BS)          # prompt only: no decode budget
        owned_n = needed - shared_full
        pins = chain[:shared_full + 1] if fork else shared
        pool.incref(pins)
        fresh = pool.alloc(owned_n)
        if fresh is None:
            pool.decref(pins)
            return None
        if fork:
            pool.cow_forks += 1
        load_row = [paging.NULL_BLOCK] * maxb
        load_row[:len(pins)] = pins
        final_row = [paging.NULL_BLOCK] * maxb
        final_row[:shared_full] = shared
        final_row[shared_full:needed] = fresh
        suffix = prompt[n_hit:]
        Tc = _bucket_len(len(suffix))
        padded = jnp.asarray([suffix + [0] * (Tc - len(suffix))],
                             jnp.int32)
        _jit_sentinel.note("engine.prefill", padded)
        with _hostsync.region("engine.prefill"):
            last, tk, tv, tpos = paging.paged_prefill(
                self.params, self.cfg, self.cache,
                jnp.asarray(load_row, jnp.int32),
                jnp.asarray(n_hit, jnp.int32), padded,
                jnp.asarray(len(suffix), jnp.int32))
        # carve owned chunks into their blocks WITHOUT seating any
        # slot table — prefill replicas never decode, the chain lives
        # purely in the pool + prefix index
        L = self.cache.k.shape[0]
        ck = tk[:, 0].reshape(L, maxb, BS, *tk.shape[3:])
        cv = tv[:, 0].reshape(L, maxb, BS, *tv.shape[3:])
        cp = tpos[0].reshape(maxb, BS)
        idx = jnp.asarray(fresh, jnp.int32)
        self.cache = paging.PagedKVCache(
            k=self.cache.k.at[:, idx].set(ck[:, shared_full:needed]),
            v=self.cache.v.at[:, idx].set(cv[:, shared_full:needed]),
            positions=self.cache.positions.at[idx].set(
                cp[shared_full:needed]),
            block_tables=self.cache.block_tables,
            write_idx=self.cache.write_idx,
            pos_next=self.cache.pos_next,
        )
        if fork:
            pool.decref([chain[shared_full]])
        parent = None
        for covered, key in keys:
            pool.register(key, final_row[(covered - 1) // BS],
                          parent=parent, covered=covered)
            parent = key
        out = paging.export_chain(self.cache, pool, prompt)
        # logits keep their compute dtype: install-side sampling must
        # see the exact values solo prefill would produce
        out["last_logits"] = np.array(last)
        out["nbytes"] += out["last_logits"].nbytes
        # release: everything drops to ref 0 — registered blocks are
        # retained as prefix cache until evicted (or promoted)
        pool.decref(shared)
        pool.decref(fresh)
        self.prefills += 1
        self.chains_exported += 1
        self.prefix_hit_tokens += n_hit
        self.prompt_tokens += Tp
        return out

    def _retire(self, i: int) -> None:
        if self.paged and self._slot_blocks[i] is not None:
            self.pool.decref(self._slot_blocks[i])
        self._slot_blocks[i] = None
        self._slot_req[i] = None
        self._last[i] = None

    def step(self) -> list[EngineRequest]:
        """Admit, sample, retire, decode — one token boundary. Returns
        the requests that finished at this boundary."""
        self._admit()
        finished: list[EngineRequest] = []
        if self._spec_finished:
            # speculative requests ran whole inside _admit
            finished.extend(self._spec_finished)
            self._spec_finished = []
        tokens = [0] * self.slots
        active = [False] * self.slots
        for i, req in enumerate(self._slot_req):
            if req is None:
                continue
            if req.temperature > 0:
                req.key, sub = jax.random.split(req.key)
            else:
                sub = None
            # the ONE deliberate sync per token boundary: the sampled
            # token drives host-side scheduling (EOS retirement,
            # admission) and cannot stay on device.  hostsync.region
            # in callers documents the same budget dynamically.
            nxt = int(_pick_row(self._last[i], sub,  # kfrm: disable=KFRM006
                                temperature=req.temperature,
                                top_k=req.top_k))
            req.tokens.append(nxt)
            hit_eos = req.eos_id is not None and nxt == req.eos_id
            if hit_eos or len(req.tokens) >= req.max_new_tokens:
                req.done = True
                req.finished_step = self.decode_steps
                finished.append(req)
                self._retire(i)
                self.finished_total += 1
            else:
                tokens[i] = nxt
                active[i] = True
        n_active = sum(active)
        if n_active:
            tok_arr = jnp.asarray(tokens, jnp.int32)
            act_arr = jnp.asarray(active)
            _jit_sentinel.note("engine.decode_step", tok_arr, act_arr)
            if self.paged:
                from kubeflow_rm_tpu.models import paging
                with _hostsync.region("engine.decode"):
                    last, self.cache = paging.paged_decode_step(
                        self.params, self.cfg, self.cache,
                        tok_arr, act_arr)
            else:
                with _hostsync.region("engine.decode"):
                    last, self.cache = slot_decode_step(
                        self.params, self.cfg, self.cache,
                        tok_arr, act_arr)
            for i in range(self.slots):
                if active[i]:
                    self._last[i] = last[i]
            self.decode_steps += 1
            self.occupancy_sum += n_active
        return finished

    def run(self) -> list[EngineRequest]:
        """Drive ``step`` until every queued/live request retires."""
        out: list[EngineRequest] = []
        while (self.queue_depth
               or any(r is not None for r in self._slot_req)):
            out.extend(self.step())
        return out

    # -- observability -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def queue_depth_by_class(self) -> dict:
        return {c: len(self._queues[c]) for c in SLO_CLASSES}

    @property
    def active_slots(self) -> int:
        return sum(r is not None for r in self._slot_req)

    def stats(self) -> dict:
        steps = self.decode_steps
        out = {
            "slots": self.slots,
            "slot_len": self.slot_len,
            "paged": self.paged,
            "active_slots": self.active_slots,
            "queue_depth": self.queue_depth,
            "queue_depth_by_class": self.queue_depth_by_class,
            "decode_steps": steps,
            "prefills": self.prefills,
            "admitted_total": self.admitted_total,
            "admitted_by_class": dict(self.admitted_by_class),
            "finished_total": self.finished_total,
            "batch_occupancy": (self.occupancy_sum / (steps * self.slots)
                                if steps else 0.0),
            "speculative_requests": self.speculative_requests,
            "speculative_model_calls": self.speculative_model_calls,
        }
        if self.paged:
            out.update(self.pool.stats())
            out["prefix_hit_tokens"] = self.prefix_hit_tokens
            out["prompt_tokens"] = self.prompt_tokens
            out["prefix_hit_ratio"] = (
                self.prefix_hit_tokens / self.prompt_tokens
                if self.prompt_tokens else 0.0)
            out["chain_installs"] = self.chain_installs
            out["chains_exported"] = self.chains_exported
            out["chains_adopted"] = self.chains_adopted
        return out
