"""Hugging Face Llama checkpoint → this framework's parameter layout.

The bridge a user switching from the reference world needs: take any
``transformers.LlamaForCausalLM`` (or its state_dict) and produce the
layer-stacked pytree ``models.llama.forward`` consumes, plus the
matching ``LlamaConfig``. Conventions line up by construction —
``ops.rope.apply_rope`` uses the same split-halves rotation as HF's
``rotate_half``, so projections transfer as plain transposes (the
torch Linear stores (out, in); we store (in, out)) with NO head
permutation. Exactness against the HF forward is asserted by
``tests/test_convert.py``, which is also the strongest fidelity proof
of the model math itself.

Layout mapping (HF name → pytree path, per layer i stacked on axis 0):

    model.embed_tokens.weight              embed/tokens      (V, D)
    model.layers.i.input_layernorm.weight  blocks/attn_norm  (L, D)
    model.layers.i.self_attn.q_proj.weight blocks/wq         (L, D, H*hd)   [T]
    ...k_proj / v_proj                     blocks/wk, wv     (L, D, KVH*hd) [T]
    ...o_proj                              blocks/wo         (L, H*hd, D)   [T]
    model.layers.i.post_attention_layernorm.weight blocks/mlp_norm (L, D)
    model.layers.i.mlp.gate_proj.weight    blocks/w_gate     (L, D, F)      [T]
    ...up_proj / down_proj                 blocks/w_up, w_down               [T]
    model.norm.weight                      out_norm          (D,)
    lm_head.weight                         lm_head           (D, V)         [T]
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any

import jax.numpy as jnp
import numpy as np

from kubeflow_rm_tpu.models.llama import LlamaConfig


def _np(t) -> np.ndarray:
    """torch tensor / np array → float32 numpy (host)."""
    if hasattr(t, "detach"):
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t, dtype=np.float32)


def config_from_hf(hf_config: Any, **overrides) -> LlamaConfig:
    """Derive a ``LlamaConfig`` from a transformers LlamaConfig."""
    base = LlamaConfig(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        hidden_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(hf_config.rms_norm_eps),
    )
    return replace(base, **overrides)


def from_hf_llama(model_or_state: Any,
                  cfg: LlamaConfig | None = None,
                  param_dtype=jnp.float32) -> tuple[LlamaConfig, dict]:
    """Convert an HF ``LlamaForCausalLM`` (or its state_dict).

    Returns ``(cfg, params)`` ready for ``forward``/``generate``. A
    model instance also yields the config; from a bare state_dict pass
    ``cfg`` explicitly. Tied-embedding checkpoints (no ``lm_head``
    entry) reuse the embedding matrix, matching HF's tie behavior.
    """
    if hasattr(model_or_state, "state_dict"):
        state = model_or_state.state_dict()
        if cfg is None:
            cfg = config_from_hf(model_or_state.config)
    else:
        state = dict(model_or_state)
        if cfg is None:
            raise ValueError("pass cfg when converting a bare state_dict")

    def get(name):
        for key in (name, f"model.{name}"):
            if key in state:
                return _np(state[key])
        raise KeyError(f"{name} not found in state_dict "
                       f"(keys: {sorted(state)[:8]}...)")

    L = cfg.n_layers

    def stack(fmt, transpose=False):
        mats = [get(fmt.format(i=i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(np.stack(mats), param_dtype)

    embed = jnp.asarray(get("embed_tokens.weight"), param_dtype)
    try:
        lm_head = jnp.asarray(get("lm_head.weight").T, param_dtype)
    except KeyError:
        lm_head = embed.T  # tied embeddings
    params = {
        "embed": {"tokens": embed},
        "blocks": {
            "attn_norm": stack("layers.{i}.input_layernorm.weight"),
            "wq": stack("layers.{i}.self_attn.q_proj.weight", True),
            "wk": stack("layers.{i}.self_attn.k_proj.weight", True),
            "wv": stack("layers.{i}.self_attn.v_proj.weight", True),
            "wo": stack("layers.{i}.self_attn.o_proj.weight", True),
            "mlp_norm": stack("layers.{i}.post_attention_layernorm.weight"),
            "w_gate": stack("layers.{i}.mlp.gate_proj.weight", True),
            "w_up": stack("layers.{i}.mlp.up_proj.weight", True),
            "w_down": stack("layers.{i}.mlp.down_proj.weight", True),
        },
        "out_norm": jnp.asarray(get("norm.weight"), param_dtype),
        "lm_head": lm_head,
    }
    return cfg, params


def to_hf_llama(cfg: LlamaConfig, params: dict) -> dict:
    """Export the stacked pytree as an HF-keyed numpy state_dict.

    The inverse of ``from_hf_llama`` — load it into a
    ``transformers.LlamaForCausalLM`` via ``load_state_dict`` (after
    wrapping values in torch tensors) to hand a fine-tuned checkpoint
    back to the HF ecosystem. Roundtrip fidelity is asserted by
    ``tests/test_convert.py``.
    """
    blocks = params["blocks"]
    state = {
        "model.embed_tokens.weight": _np(params["embed"]["tokens"]),
        "model.norm.weight": _np(params["out_norm"]),
        "lm_head.weight": _np(params["lm_head"]).T,
    }
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}"
        state[f"{pre}.input_layernorm.weight"] = _np(blocks["attn_norm"][i])
        state[f"{pre}.post_attention_layernorm.weight"] = \
            _np(blocks["mlp_norm"][i])
        for ours, theirs in (("wq", "self_attn.q_proj"),
                             ("wk", "self_attn.k_proj"),
                             ("wv", "self_attn.v_proj"),
                             ("wo", "self_attn.o_proj"),
                             ("w_gate", "mlp.gate_proj"),
                             ("w_up", "mlp.up_proj"),
                             ("w_down", "mlp.down_proj")):
            state[f"{pre}.{theirs}.weight"] = _np(blocks[ours][i]).T
    return state
