"""Block-paged KV cache with copy-on-write prefix sharing.

The vLLM-shape upgrade to the serving engine (ROADMAP item 2): instead
of one contiguous ``slot_len`` KV strip per decode slot, the cache is a
single pool of fixed-size **blocks** (``block_size`` tokens each) and
every slot owns a **block table** — a row of block indices whose
concatenation is that slot's logical KV strip. Two consequences:

- **Prefix sharing.** Blocks are content-addressed: a chained
  token-hash over the prompt (hash of ``tokens[:block_size]``, then
  ``tokens[:2*block_size]``, ...) keys each *full* block, plus one
  trailing key for the partial last block. Identical prefixes resolve
  to the same chain, so an 80%-shared system prompt is prefilled once
  and later requests just point their tables at the cached blocks
  (refcounted). Shared blocks are never written — a request that must
  write into a partially-filled shared block (its first generated
  token lands mid-block) **forks** it first: copy-on-write at the
  first write, counted in ``BlockPool.cow_forks``.
- **Packing.** Slot capacity stops being ``slots x worst-case
  length``: short requests hold few blocks, retired blocks return to
  the free pool, and prefix blocks whose refcount hits zero are
  *retained* in an LRU and only evicted when an allocation needs them.

Exactness contract (the whole point of the design): a slot's gathered
view — ``pool[k][:, table].reshape(...)`` — is byte-for-byte the
contiguous cache a solo ``generate_fused(prompt[None],
max_len=slot_len)`` call would build, because (a) prefill right-pads
(token *t* sits at offset *t*, preserving block alignment; pad columns
carry position ``_UNFILLED`` so the causal mask hides them), and
(b) splitting prefill at a cached-prefix boundary is bit-identical to
one wide chunk under XLA (verified in ``tests/test_paging.py``). So
per-request outputs stay bit-identical to solo ``generate_fused``,
cached prefix or not.

Layout notes (CPU/TPU-portable XLA, no custom kernel): the decode step
gathers each slot's blocks into a contiguous (B, slot_len) view, runs
the same ``_run_blocks`` trunk as the contiguous engine, and scatters
back only the one written column. Two blocks are reserved: block 0 is
NULL (all-``_UNFILLED`` positions, the gather target of unassigned
table entries — never written) and block 1 is SINK (the redirect
target for writes that must go nowhere: inactive rows' decode writes
and install chunks that belong to shared blocks).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict, deque
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_rm_tpu.models.generate import _UNFILLED, _run_blocks
from kubeflow_rm_tpu.models.llama import LlamaConfig

#: reserved block ids (see module docstring)
NULL_BLOCK = 0
SINK_BLOCK = 1
RESERVED_BLOCKS = 2


# ---------------------------------------------------------------------------
# device state
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclass
class PagedKVCache:
    """Pool-of-blocks KV state. ``block_tables[i]`` concatenated is
    slot *i*'s logical strip of ``slot_len = MAXB * BS`` positions;
    ``write_idx``/``pos_next`` are the same per-slot counters
    ``SlotCache`` keeps, expressed in logical-strip offsets."""
    k: jax.Array             # (L, NB, BS, KVH, hd) compute dtype
    v: jax.Array             # (L, NB, BS, KVH, hd)
    positions: jax.Array     # (NB, BS) int32; _UNFILLED marks empty
    block_tables: jax.Array  # (SLOTS, MAXB) int32; NULL_BLOCK = unset
    write_idx: jax.Array     # (SLOTS,) int32: next logical write slot
    pos_next: jax.Array      # (SLOTS,) int32: next token position


def init_paged_cache(cfg: LlamaConfig, slots: int, slot_len: int,
                     num_blocks: int, block_size: int) -> PagedKVCache:
    if slot_len % block_size:
        raise ValueError(f"slot_len {slot_len} must be a multiple of "
                         f"block_size {block_size}")
    if num_blocks <= RESERVED_BLOCKS:
        raise ValueError(f"num_blocks {num_blocks} leaves no usable "
                         f"blocks ({RESERVED_BLOCKS} are reserved)")
    L, KVH, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    maxb = slot_len // block_size
    return PagedKVCache(
        k=jnp.zeros((L, num_blocks, block_size, KVH, hd), cfg.dtype),
        v=jnp.zeros((L, num_blocks, block_size, KVH, hd), cfg.dtype),
        positions=jnp.full((num_blocks, block_size), _UNFILLED,
                           jnp.int32),
        block_tables=jnp.full((slots, maxb), NULL_BLOCK, jnp.int32),
        write_idx=jnp.zeros((slots,), jnp.int32),
        pos_next=jnp.zeros((slots,), jnp.int32),
    )


# ---------------------------------------------------------------------------
# jitted ops
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def paged_decode_step(params, cfg, cache: PagedKVCache, tokens, active):
    """One decode step over every slot, against gathered block views.

    Mirrors ``slot_decode_step`` exactly: each active row attends at
    its own ``pos_next`` over its gathered (slot_len-long) strip and
    writes K/V at its own ``write_idx``; inactive rows flow through
    with query position ``_UNFILLED`` and their (garbage) pool write
    redirected to SINK_BLOCK — their table may reference blocks that
    other slots now own, so unlike the contiguous engine their write
    target is NOT private and must be diverted. Only the one written
    column per row is scattered back to the pool.
    """
    B, MAXB = cache.block_tables.shape
    BS = cache.positions.shape[1]
    S = MAXB * BS
    rows = jnp.arange(B, dtype=jnp.int32)

    positions = jnp.where(active, cache.pos_next, _UNFILLED)[:, None]
    wi = jnp.clip(cache.write_idx, 0, S - 1)
    blk = jnp.where(active, cache.block_tables[rows, wi // BS],
                    SINK_BLOCK)
    off = wi % BS

    # gathered per-slot contiguous views: bit-identical to the strip a
    # contiguous SlotCache would hold for the same request
    gk = cache.k[:, cache.block_tables].reshape(
        cache.k.shape[0], B, S, *cache.k.shape[3:])
    gv = cache.v[:, cache.block_tables].reshape(
        cache.v.shape[0], B, S, *cache.v.shape[3:])
    gpos = cache.positions[cache.block_tables].reshape(B, S)
    kv_positions = gpos.at[rows, wi].set(positions[:, 0])

    def write_kv(c, val):
        return c.at[rows, wi].set(val[:, 0])

    logits, new_k, new_v = _run_blocks(
        params, cfg, gk, gv, tokens[:, None], positions, kv_positions,
        write_kv)

    # scatter ONLY the written column back to the pool (inactive rows
    # land in SINK); duplicate sink hits are garbage-on-garbage
    col_k = new_k[:, rows, wi]          # (L, B, KVH, hd)
    col_v = new_v[:, rows, wi]
    inc = active.astype(jnp.int32)
    new_cache = PagedKVCache(
        k=cache.k.at[:, blk, off].set(col_k),
        v=cache.v.at[:, blk, off].set(col_v),
        positions=cache.positions.at[blk, off].set(
            jnp.where(active, cache.pos_next, _UNFILLED)),
        block_tables=cache.block_tables,
        write_idx=cache.write_idx + inc,
        pos_next=cache.pos_next + inc,
    )
    return logits[:, -1, :], new_cache


# cache is READ-ONLY here: prefill gathers the shared-prefix strip
# out of the pool and writes a fresh single-request row cache; the
# pool has no successor to alias, and donating it would free buffers
# the engine still serves other slots from.
@partial(jax.jit, static_argnames=("cfg",))
def paged_prefill(params, cfg, cache: PagedKVCache,  # kfrm: disable=KFRM008
                  load_row, n_hit, tokens, n_real):
    """Prefill one request's suffix against its cached prefix.

    ``load_row`` (MAXB,) names the SOURCE blocks of the shared prefix
    (chunks beyond it are NULL); the gathered strip is truncated to
    ``n_hit`` tokens (everything at/after ``n_hit`` reads
    ``_UNFILLED`` — a partially-reused source block may carry another
    request's live tokens past the shared region, and truncation is
    what makes borrowing it safe). ``tokens`` (1, Tc) is the
    right-pad-bucketed suffix whose first ``n_real`` columns are real;
    it runs at offsets ``n_hit .. n_hit+Tc``. Returns the last REAL
    token's logits plus the full temp strip (k, v, positions) for
    ``paged_install`` to carve into blocks.

    Split-at-``n_hit`` prefill is bit-identical to one full-width
    chunk, and right-pad columns (position ``_UNFILLED``) leave real
    columns bit-identical — both properties are what lets a cached
    prefix + suffix prefill replace solo prefill exactly.
    """
    L = cache.k.shape[0]
    MAXB, BS = load_row.shape[0], cache.positions.shape[1]
    S = MAXB * BS
    Tc = tokens.shape[1]

    gk = cache.k[:, load_row].reshape(L, 1, S, *cache.k.shape[3:])
    gv = cache.v[:, load_row].reshape(L, 1, S, *cache.v.shape[3:])
    gpos = cache.positions[load_row].reshape(1, S)
    idx = jnp.arange(S, dtype=jnp.int32)[None, :]
    gpos = jnp.where(idx < n_hit, gpos, _UNFILLED)

    positions = n_hit + jnp.arange(Tc, dtype=jnp.int32)[None, :]
    positions = jnp.where(jnp.arange(Tc)[None, :] < n_real, positions,
                          _UNFILLED)
    kv_positions = jax.lax.dynamic_update_slice(gpos, positions,
                                                (0, n_hit))

    def write_kv(c, val):
        return jax.lax.dynamic_update_slice(c, val, (0, n_hit, 0, 0))

    logits, new_k, new_v = _run_blocks(
        params, cfg, gk, gv, tokens, positions, kv_positions, write_kv)
    last = logits[0, n_real - 1, :]
    return last, new_k, new_v, kv_positions


@partial(jax.jit, donate_argnames=("cache",))
def paged_install(cache: PagedKVCache, temp_k, temp_v, temp_pos, slot,
                  final_row, dest_row, write_idx0):
    """Carve a prefilled temp strip into pool blocks and activate the
    slot. ``dest_row`` (MAXB,) maps each strip chunk to its pool
    destination: the request's OWN blocks for owned chunks, SINK for
    chunks it shares (already in the pool — never overwrite a shared
    block) and for tail chunks past its allocation. Every owned block
    is fully overwritten — positions included — which is the
    no-stale-reads guarantee for recycled blocks: whatever a block held
    before, after install its visible state is exactly the fresh
    strip's. ``write_idx0`` seats both counters at the REAL prompt
    length, so the first generated token overwrites the first pad
    column — the same offset solo ``generate_fused`` writes."""
    L = cache.k.shape[0]
    MAXB, BS = dest_row.shape[0], cache.positions.shape[1]
    chunks_k = temp_k[:, 0].reshape(L, MAXB, BS, *temp_k.shape[3:])
    chunks_v = temp_v[:, 0].reshape(L, MAXB, BS, *temp_v.shape[3:])
    chunks_p = temp_pos[0].reshape(MAXB, BS)
    return PagedKVCache(
        k=cache.k.at[:, dest_row].set(chunks_k),
        v=cache.v.at[:, dest_row].set(chunks_v),
        positions=cache.positions.at[dest_row].set(chunks_p),
        block_tables=cache.block_tables.at[slot].set(final_row),
        write_idx=cache.write_idx.at[slot].set(write_idx0),
        pos_next=cache.pos_next.at[slot].set(write_idx0),
    )


# debug/test helper: reads the pool into a contiguous strip for
# inspection — the cache must survive the call, donation would be a
# use-after-free for the engine.
@jax.jit
def gather_slot_strip(cache: PagedKVCache, slot):  # kfrm: disable=KFRM008
    """Debug/test helper: slot ``slot``'s logical strip as contiguous
    (k (L, S, KVH, hd), v, positions (S,)) arrays."""
    row = cache.block_tables[slot]
    L = cache.k.shape[0]
    MAXB, BS = row.shape[0], cache.positions.shape[1]
    k = cache.k[:, row].reshape(L, MAXB * BS, *cache.k.shape[3:])
    v = cache.v[:, row].reshape(L, MAXB * BS, *cache.v.shape[3:])
    pos = cache.positions[row].reshape(MAXB * BS)
    return k, v, pos


# ---------------------------------------------------------------------------
# host-side block accounting
# ---------------------------------------------------------------------------


def prefix_keys(tokens, block_size: int) -> list[tuple[int, bytes]]:
    """Chained content keys for a prompt: one per full-block boundary
    plus one for the trailing partial block. Key *i* digests
    ``tokens[: covered_i]`` — the whole prefix, not just the block —
    because a block's K/V depends on every token before it. Returns
    ``[(covered_tokens, key), ...]`` in chain order."""
    out: list[tuple[int, bytes]] = []
    arr = np.asarray(list(tokens), np.int32)
    h = hashlib.blake2b(digest_size=16)
    full = len(arr) // block_size
    for c in range(full):
        h.update(arr[c * block_size:(c + 1) * block_size].tobytes())
        out.append(((c + 1) * block_size, b"f" + h.digest()))
    if len(arr) % block_size:
        hp = h.copy()
        hp.update(arr[full * block_size:].tobytes())
        out.append((len(arr), b"p" + hp.digest()))
    return out


class BlockPool:
    """Refcounted free-list + content-addressed prefix index over the
    pool's block ids. Host-side only, driven by the single engine
    thread (callers serialize via the gateway lock) — no lock here.

    Lifecycle of a block: ``alloc`` (ref=1) → optionally ``register``
    under a prefix key (content-addressed, sharable) → ``incref`` per
    additional table that adopts it → ``decref`` per retiring table.
    At ref 0 an *unregistered* block returns to the free list
    immediately; a *registered* block is retained as prefix cache and
    only evicted — oldest first — when ``alloc`` runs dry. ``alloc``
    is atomic: it either returns ``n`` blocks or returns ``None``
    having changed nothing (the clean-OOM contract admission relies
    on)."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks <= RESERVED_BLOCKS:
            raise ValueError(
                f"num_blocks {num_blocks} leaves no usable blocks")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free: deque[int] = deque(range(RESERVED_BLOCKS,
                                             num_blocks))
        self._ref: dict[int, int] = {}
        self._index: OrderedDict[bytes, int] = OrderedDict()
        self._block_key: dict[int, bytes] = {}
        # chain linkage for registered keys: key -> predecessor key
        # (None at the chain head) and key -> covered-token count.
        # Export and promote-on-evict walk these to rebuild the chain
        # a key belongs to without re-hashing the prompt.
        self._parent: dict[bytes, bytes | None] = {}
        self._covered: dict[bytes, int] = {}
        #: optional ``fn(key, block)`` called just before a retained
        #: ref-0 prefix block is evicted, while its content is still
        #: in the device pool — the promote-to-global-store hook.
        self.on_evict = None
        self.cow_forks = 0
        self.evictions = 0
        self.alloc_failures = 0
        self.evict_hook_errors = 0

    # -- capacity ------------------------------------------------------

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - RESERVED_BLOCKS

    def free_count(self) -> int:
        return len(self._free)

    def evictable_count(self) -> int:
        return sum(1 for b in self._block_key
                   if self._ref.get(b, 0) == 0)

    def available(self) -> int:
        """Blocks an alloc could hand out right now: free + evictable
        retained prefix blocks."""
        return self.free_count() + self.evictable_count()

    def ref_of(self, block: int) -> int:
        return self._ref.get(block, 0)

    # -- alloc / refcount ----------------------------------------------

    def alloc(self, n: int) -> list[int] | None:
        """``n`` blocks at ref 1, or ``None`` with NO state change."""
        if n <= 0:
            return []
        if self.available() < n:
            self.alloc_failures += 1
            return None
        out: list[int] = []
        while len(out) < n:
            if self._free:
                b = self._free.popleft()
            else:
                b = self._evict_one()
            self._ref[b] = 1
            out.append(b)
        return out

    def _evict_one(self) -> int:
        for key, b in self._index.items():     # oldest entry first
            if self._ref.get(b, 0) == 0:
                if self.on_evict is not None:
                    # promotion reads the block from the device pool,
                    # so it must run BEFORE the id is handed out for
                    # reuse; a failing hook must never break alloc
                    try:
                        self.on_evict(key, b)
                    except Exception:  # kfrm: disable=KFRM005
                        # counted locally (evict_hook_errors): the
                        # models layer can't import controlplane
                        # metrics, and alloc must survive any hook
                        self.evict_hook_errors += 1
                del self._index[key]
                del self._block_key[b]
                self._parent.pop(key, None)
                self._covered.pop(key, None)
                self.evictions += 1
                return b
        raise RuntimeError("evict with no evictable block "
                           "(available() said otherwise)")

    def incref(self, blocks) -> None:
        for b in blocks:
            self._ref[b] = self._ref.get(b, 0) + 1
            key = self._block_key.get(b)
            if key is not None:                # LRU touch
                self._index.move_to_end(key)

    def decref(self, blocks) -> None:
        for b in blocks:
            r = self._ref.get(b, 0) - 1
            if r < 0:
                raise RuntimeError(f"decref of block {b} below zero")
            self._ref[b] = r
            if r == 0 and b not in self._block_key:
                self._free.append(b)

    # -- prefix index --------------------------------------------------

    def lookup(self, key: bytes) -> int | None:
        b = self._index.get(key)
        if b is not None:
            self._index.move_to_end(key)
        return b

    def register(self, key: bytes, block: int, *,
                 parent: bytes | None = None,
                 covered: int | None = None) -> int:
        """Publish ``block`` under ``key``; first writer wins (an
        identical prefix prefilled twice registers once — the second
        block simply frees on retire). ``parent``/``covered`` record
        the chain linkage used by export and promote-on-evict."""
        if parent is not None or key not in self._parent:
            self._parent[key] = parent
        if covered is not None:
            self._covered[key] = int(covered)
        existing = self._index.get(key)
        if existing is not None:
            self._index.move_to_end(key)
            return existing
        if block in self._block_key:           # one key per block
            return self._index[self._block_key[block]]
        self._index[key] = block
        self._block_key[block] = key
        return block

    def parent_of(self, key: bytes) -> bytes | None:
        return self._parent.get(key)

    def covered_of(self, key: bytes) -> int | None:
        return self._covered.get(key)

    def lookup_chain(self, keys) -> list[int]:
        """Longest CONSECUTIVE run of ``keys`` present in the index
        (a later hit without its predecessors is unusable — the table
        needs every chunk up to the hit). Returns the blocks."""
        out: list[int] = []
        for _covered, key in keys:
            b = self.lookup(key)
            if b is None:
                break
            out.append(b)
        return out

    def stats(self) -> dict:
        return {
            "blocks_total": self.usable_blocks,
            "blocks_free": self.free_count(),
            "blocks_evictable": self.evictable_count(),
            "blocks_available": self.available(),
            "free_block_fraction": (self.available()
                                    / max(1, self.usable_blocks)),
            "prefix_entries": len(self._index),
            "cow_forks": self.cow_forks,
            "evictions": self.evictions,
            "alloc_failures": self.alloc_failures,
        }


# ---------------------------------------------------------------------------
# chain export / import — replica-to-replica block transfer
# ---------------------------------------------------------------------------
# The chained ``prefix_keys`` hashes commit to the whole prefix, so a
# chain is a replica-agnostic name for its K/V content: any pool that
# prefilled the same tokens on the same weights holds bit-identical
# blocks under the same keys. A serialized chain carries the host
# copies of those blocks plus per-chunk checksums; ``import_chain``
# refuses a corrupted chunk without touching pool state, and a chain
# adopted into a foreign pool decodes bit-identically to solo
# ``generate_fused`` (tests/test_chain_transfer.py).


def _chunk_checksum(ck: np.ndarray, cv: np.ndarray,
                    cp: np.ndarray) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(np.ascontiguousarray(ck).tobytes())
    h.update(np.ascontiguousarray(cv).tobytes())
    h.update(np.ascontiguousarray(cp).tobytes())
    return h.digest()


def export_block_chunk(cache: PagedKVCache, block: int,
                       valid: int) -> dict:
    """Host copy of ONE pool block, sanitized past ``valid`` tokens
    (zero K/V, ``_UNFILLED`` positions) so the bytes — and therefore
    the checksum — depend only on the prefix the block's key names,
    never on whatever a later request generated into the tail."""
    ck = np.array(cache.k[:, block])           # (L, BS, KVH, hd)
    cv = np.array(cache.v[:, block])
    cp = np.array(cache.positions[block], np.int32)
    ck[:, valid:] = 0
    cv[:, valid:] = 0
    cp[valid:] = _UNFILLED
    return {"k": ck, "v": cv, "pos": cp,
            "sum": _chunk_checksum(ck, cv, cp)}


def export_chain(cache: PagedKVCache, pool: BlockPool,
                 tokens) -> dict | None:
    """Serialize the pool's chain for ``tokens`` — every chunk's K/V,
    positions, keys, and checksums — or ``None`` if the pool does not
    hold the full chain. Tail columns past each chunk's covered count
    are sanitized, so identical prompts export identical bytes."""
    tokens = [int(t) for t in tokens]
    keys = prefix_keys(tokens, pool.block_size)
    blocks = pool.lookup_chain(keys)
    if len(blocks) < len(keys):
        return None
    BS = pool.block_size
    idx = jnp.asarray(blocks, jnp.int32)
    ck = np.array(cache.k[:, idx])             # (L, NC, BS, KVH, hd)
    cv = np.array(cache.v[:, idx])
    cp = np.array(cache.positions[idx], np.int32)
    for i, (covered, _key) in enumerate(keys):
        valid = covered - i * BS
        ck[:, i, valid:] = 0
        cv[:, i, valid:] = 0
        cp[i, valid:] = _UNFILLED
    sums = [_chunk_checksum(ck[:, i], cv[:, i], cp[i])
            for i in range(len(keys))]
    return {
        "version": 1,
        "block_size": BS,
        "tokens": tokens,
        "covered": keys[-1][0],
        "keys": [k for _c, k in keys],
        "covers": [c for c, _k in keys],
        "chunks_k": ck,
        "chunks_v": cv,
        "chunks_pos": cp,
        "sums": sums,
        "nbytes": int(ck.nbytes + cv.nbytes + cp.nbytes),
    }


def verify_chain(chain: dict) -> None:
    """Raise ``ValueError`` unless the chain is internally consistent:
    chunk checksums match the payload, and — when the prompt rides
    along — the keys really are the chained hashes of the tokens.
    Checks mutate nothing, so a refusal leaves any pool untouched."""
    keys = list(chain.get("keys") or [])
    covers = list(chain.get("covers") or [])
    sums = list(chain.get("sums") or [])
    nc = len(keys)
    if not nc or len(covers) != nc or len(sums) != nc:
        raise ValueError("chain integrity: malformed key/cover/sum "
                         "lists")
    ck, cv, cp = (chain["chunks_k"], chain["chunks_v"],
                  chain["chunks_pos"])
    BS = int(chain["block_size"])
    if (ck.shape[1] != nc or cv.shape != ck.shape
            or cp.shape != (nc, BS) or ck.shape[2] != BS):
        raise ValueError("chain integrity: chunk shapes disagree "
                         "with the key list")
    tokens = chain.get("tokens")
    if tokens is not None:
        want = prefix_keys(tokens, BS)
        if ([k for _c, k in want] != keys
                or [c for c, _k in want] != covers):
            raise ValueError("chain integrity: keys are not the "
                             "chained hashes of the tokens")
    for i in range(nc):
        if _chunk_checksum(ck[:, i], cv[:, i], cp[i]) != sums[i]:
            raise ValueError(
                f"chain integrity: chunk {i} checksum mismatch")


def import_chain(cache: PagedKVCache, pool: BlockPool,
                 chain: dict) -> tuple[PagedKVCache, list[int]] | None:
    """Adopt a foreign chain: verify it, seat its chunks in freshly
    allocated blocks, and register every key. Returns the new cache
    plus the allocated blocks (ref 1 — the caller decrefs them to
    hand the chain to the LRU as retained prefix cache), or ``None``
    on clean OOM. Keys already registered locally keep their existing
    blocks; the redundant fresh block simply frees on decref."""
    verify_chain(chain)
    if int(chain["block_size"]) != pool.block_size:
        raise ValueError(
            f"chain block_size {chain['block_size']} != pool "
            f"block_size {pool.block_size}")
    nc = len(chain["keys"])
    if chain["chunks_k"].shape[0] != cache.k.shape[0] \
            or chain["chunks_k"].shape[2:] != cache.k.shape[2:]:
        raise ValueError("chain chunk shape does not fit this cache")
    blocks = pool.alloc(nc)
    if blocks is None:
        return None
    idx = jnp.asarray(blocks, jnp.int32)
    cache = PagedKVCache(
        k=cache.k.at[:, idx].set(
            jnp.asarray(chain["chunks_k"], cache.k.dtype)),
        v=cache.v.at[:, idx].set(
            jnp.asarray(chain["chunks_v"], cache.v.dtype)),
        positions=cache.positions.at[idx].set(
            jnp.asarray(chain["chunks_pos"], jnp.int32)),
        block_tables=cache.block_tables,
        write_idx=cache.write_idx,
        pos_next=cache.pos_next,
    )
    parent = None
    for i, key in enumerate(chain["keys"]):
        pool.register(key, blocks[i], parent=parent,
                      covered=chain["covers"][i])
        parent = key
    return cache, blocks
