"""Model zoo. ``init_params`` / ``forward_with_aux`` dispatch on the
config type so generic code (training, bench, dryrun) never branches on
model families itself."""

import jax

from kubeflow_rm_tpu.models import llama as _llama
from kubeflow_rm_tpu.models import mixtral as _mixtral
from kubeflow_rm_tpu.models.convert import config_from_hf, from_hf_llama
from kubeflow_rm_tpu.models.lora import add_lora, lora_mask, merge_lora
from kubeflow_rm_tpu.models.quantize import (
    maybe_dequant,
    quantize_params,
    unpack_int4_params,
)
from kubeflow_rm_tpu.models.generate import (
    ContinuousBatchingEngine,
    EngineRequest,
    KVCache,
    SlotCache,
    cache_shardings,
    decode_chunk,
    generate,
    generate_fused,
    generate_speculative_fused,
    init_cache,
    init_slot_cache,
    make_decode_step,
    make_generate_step,
    slot_decode_step,
)
from kubeflow_rm_tpu.models.generate import (
    DEFAULT_CLASS_WEIGHTS,
    SLO_CLASSES,
)
from kubeflow_rm_tpu.models.llama import LlamaConfig, forward
from kubeflow_rm_tpu.models.mixtral import MixtralConfig


def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Family-correct parameter init for any model config."""
    if isinstance(cfg, MixtralConfig):
        return _mixtral.init_params(cfg, key)
    return _llama.init_params(cfg, key)


def forward_with_aux(params, tokens, cfg: LlamaConfig, **kwargs):
    """Uniform forward: returns (logits, aux) where aux is the router
    load-balancing loss for MoE families and None for dense ones.
    ``mesh`` (kwarg) enables explicit sequence-parallel attention
    schedules when ``cfg.attention_backend`` asks for one."""
    if isinstance(cfg, MixtralConfig):
        return _mixtral.forward(params, tokens, cfg, **kwargs)
    return _llama.forward(params, tokens, cfg, **kwargs), None


from kubeflow_rm_tpu.models.paging import (
    BlockPool,
    PagedKVCache,
    init_paged_cache,
    paged_decode_step,
    paged_prefill,
    prefix_keys,
)

__all__ = ["BlockPool", "ContinuousBatchingEngine",
           "DEFAULT_CLASS_WEIGHTS", "EngineRequest", "KVCache",
           "PagedKVCache", "SLO_CLASSES",
           "init_paged_cache", "paged_decode_step", "paged_prefill",
           "prefix_keys",
           "LlamaConfig", "MixtralConfig", "SlotCache", "add_lora",
           "config_from_hf",
           "cache_shardings", "decode_chunk", "forward", "forward_with_aux", "from_hf_llama",
           "generate", "generate_fused", "generate_speculative_fused",
           "init_cache", "init_params", "init_slot_cache",
           "make_decode_step", "make_generate_step", "slot_decode_step",
           "lora_mask", "maybe_dequant", "merge_lora", "quantize_params",
           "unpack_int4_params"]
