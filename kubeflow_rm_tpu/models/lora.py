"""LoRA: low-rank adapter fine-tuning for the Llama family.

The practical way to fine-tune a 7B-class model on a small slice (or
ONE v5e chip): freeze the base weights, train rank-r adapters on the
attention projections. Memory drops from "params + grads + 2 adam
moments for 7B" to "frozen params + a few M adapter floats + their
moments" — ``tests/test_7b_plan.py`` proves the 7B LoRA plan fits a
single 16 GiB v5e by AOT accounting.

Design (jax-native, composes with everything already here):

- **Adapters are just extra leaves** in ``params["blocks"]``
  (``{t}_lora_a`` (L, in, r), ``{t}_lora_b`` (L, r, out), b
  zero-initialized so step 0 is exactly the base model). The stacked
  layer scan, FSDP/TP shardings, grad accumulation, checkpointing and
  the pipeline schedule all apply unchanged.
- **Freezing lives in the optimizer**: ``optax.multi_transform`` routes
  adapter leaves to adamw and everything else to ``set_to_zero`` —
  frozen leaves carry no moments, which is where the memory win is
  (``training.optim.make_optimizer(train_only="lora")``).
- **The forward applies adapters in factored form**
  (``h @ w + (h @ a) @ b * alpha/r``) — never materializing the
  (in, out) delta — and ``merge_lora`` folds them into the base for
  serving (then quantize/convert/export as usual).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: classic LoRA targets: the attention projections
DEFAULT_TARGETS = ("wq", "wv")

LORA_A = "_lora_a"
LORA_B = "_lora_b"


def is_lora_name(name: str) -> bool:
    return name.endswith(LORA_A) or name.endswith(LORA_B)


def add_lora(params: dict, rank: int, *, key: jax.Array,
             targets: tuple[str, ...] = DEFAULT_TARGETS,
             param_dtype=None) -> dict:
    """Return params extended with rank-``rank`` adapters on ``targets``.

    ``a`` gets a small normal init, ``b`` zeros — the adapted forward
    equals the base model until the first update (asserted in tests).
    """
    blocks = dict(params["blocks"])
    keys = jax.random.split(key, len(targets))
    for t, k in zip(targets, keys):
        if t not in blocks:
            raise KeyError(f"lora target {t!r} not in blocks "
                           f"({sorted(blocks)})")
        w = blocks[t]
        if isinstance(w, dict):  # quantized base (QLoRA recipe)
            if "q4" in w:        # packed nibbles: (L, G, g/2, out)
                q4 = w["q4"]
                L, d_in, d_out = (q4.shape[0],
                                  q4.shape[-3] * q4.shape[-2] * 2,
                                  q4.shape[-1])
            else:
                L, d_in, d_out = w["q"].shape
            dt = param_dtype or jnp.bfloat16
        else:
            L, d_in, d_out = w.shape
            dt = param_dtype or w.dtype
        blocks[t + LORA_A] = (
            jax.random.normal(k, (L, d_in, rank)) * 0.02).astype(dt)
        blocks[t + LORA_B] = jnp.zeros((L, rank, d_out), dt)
    return dict(params, blocks=blocks)


def lora_proj(layer: dict, name: str, h: jax.Array, *,
              alpha: float, dtype) -> jax.Array:
    """``h @ w`` plus the factored adapter delta when present.

    The base weight may be int8-quantized (``models.quantize``) — the
    QLoRA-style recipe: frozen int8 base + bf16 adapters, which is what
    fits a 7B fine-tune on one 16 GiB v5e chip."""
    from kubeflow_rm_tpu.models.quantize import maybe_dequant

    out = h @ maybe_dequant(layer[name], dtype)
    a = layer.get(name + LORA_A)
    if a is None:
        return out
    b = layer[name + LORA_B]
    rank = a.shape[-1]
    return out + (h @ a.astype(dtype)) @ b.astype(dtype) * (alpha / rank)


def merge_lora(params: dict, *, alpha: float) -> dict:
    """Fold adapters into the base weights (serving form)."""
    from kubeflow_rm_tpu.models.quantize import is_quantized

    blocks = {}
    for k, v in params["blocks"].items():
        if is_lora_name(k):
            continue
        a = params["blocks"].get(k + LORA_A)
        if a is None:
            blocks[k] = v
        elif is_quantized(v):
            raise ValueError(
                f"cannot merge adapters into int8 base weight {k!r}: "
                "dequantize first (maybe_dequant) or serve the adapted "
                "model unmerged")
        else:
            b = params["blocks"][k + LORA_B]
            rank = a.shape[-1]
            delta = jnp.einsum(
                "lir,lro->lio", a.astype(jnp.float32),
                b.astype(jnp.float32)) * (alpha / rank)
            blocks[k] = (v.astype(jnp.float32) + delta).astype(v.dtype)
    return dict(params, blocks=blocks)


def lora_mask(params: dict) -> dict:
    """True for adapter leaves — the optimizer's trainable set."""

    def mask(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else ""
        return is_lora_name(name)

    return jax.tree_util.tree_map_with_path(mask, params)
