"""Llama-family decoder, written TPU-first in functional JAX.

Design notes (why this is not a torch translation):

- **Scan over layers.** All transformer blocks share one set of stacked
  weights with a leading layer axis and run under ``lax.scan``. XLA
  compiles a single block once instead of unrolling n_layers copies —
  compile time stays flat as depth grows, and the stacked layout gives
  every layer identical sharding, which is what the FSDP all-gather
  schedule wants.

- **Rematerialization.** ``jax.checkpoint`` wraps the scanned block with
  a dots-saveable policy: matmul outputs survive, attention scores and
  softmax are recomputed in the backward pass. This trades a ~30% FLOP
  overhead in attention for O(1) live layers of activation memory — the
  standard HBM/FLOPs trade on TPU.

- **bf16 compute, fp32 params/master.** Params are stored in
  ``param_dtype`` (fp32 by default) and cast to ``dtype`` (bf16) at use;
  the final logits come back in fp32 for the loss.

- Weights use a GPT-2-style scaled init (out-projections scaled by
  1/sqrt(2 * n_layers)) so tiny test configs train stably.

This model is the flagship for the jupyter-jax notebook image; the
platform half of the repo provisions the slice it runs on
(BASELINE.json north_star).
"""

from dataclasses import dataclass, replace
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_rm_tpu.ops import (
    apply_rope,
    dot_product_attention,
    rms_norm,
    rope_angles,
)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "dots": save all matmul outputs (fastest bwd, ~L× activation
    # memory); "full": save only the scan carry and recompute the block;
    # "attn"/"mlp"/"attn+mlp": save the named activations only (the
    # HBM-vs-recompute middle ground — see _NAME_POLICIES).
    remat_policy: str = "dots"
    # LoRA scaling (alpha/rank) for adapter-carrying params — see
    # models.lora; inert when no adapter leaves are present.
    lora_alpha: float = 16.0
    # "auto": dense attention, GSPMD inserts whatever collectives the
    # sp sharding needs (all-gather of K/V). "ring"/"ulysses": run the
    # explicit sequence-parallel schedule (parallel.ring_attention /
    # parallel.ulysses) when forward() is given a mesh with sp > 1 —
    # O(T/sp) attention memory per device instead of a gathered T.
    attention_backend: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    # ---- presets -----------------------------------------------------
    @staticmethod
    def llama2_7b(**overrides) -> "LlamaConfig":
        return replace(LlamaConfig(), **overrides)

    @staticmethod
    def llama2_13b(**overrides) -> "LlamaConfig":
        return replace(
            LlamaConfig(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40,
                        hidden_dim=13824),
            **overrides,
        )

    @staticmethod
    def llama3_8b(**overrides) -> "LlamaConfig":
        return replace(
            LlamaConfig(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                        n_kv_heads=8, hidden_dim=14336, rope_theta=500000.0,
                        max_seq_len=8192),
            **overrides,
        )

    @staticmethod
    def bench_1b(**overrides) -> "LlamaConfig":
        """~1.2B-param config sized for a single v5e chip (16 GiB HBM)."""
        return replace(
            LlamaConfig(dim=2048, n_layers=20, n_heads=16, n_kv_heads=16,
                        hidden_dim=5632, max_seq_len=2048),
            **overrides,
        )

    @staticmethod
    def bench_2b(**overrides) -> "LlamaConfig":
        """~2.1B params: the mid rung of the single-chip MFU-vs-scale
        ladder (full fine-tune on one v5e with the factored optimizer —
        see bench.py --preset bench_2b --optim adafactor)."""
        return replace(
            LlamaConfig(dim=2560, n_layers=24, n_heads=20, n_kv_heads=20,
                        hidden_dim=6912, max_seq_len=2048),
            **overrides,
        )

    @staticmethod
    def bench_2_7b(**overrides) -> "LlamaConfig":
        """~2.7B params: one rung PAST the measured single-v5e wall —
        state (params+grads ≈ 10.8 GiB at 4 bytes/param) plus logits
        and recompute workspace OOMs 15.75 GiB usable HBM even at
        mb1/full remat (BENCH_SWEEP_r05 scale rows); bench_2b (~2.1B)
        is the largest full fine-tune that fits."""
        return replace(
            LlamaConfig(dim=3072, n_layers=22, n_heads=24, n_kv_heads=24,
                        hidden_dim=8192, max_seq_len=2048),
            **overrides,
        )

    @staticmethod
    def bench_3b(**overrides) -> "LlamaConfig":
        """~3.1B params: one rung PAST the single-v5e wall — state
        alone (params+grads ≈ 12.6 GiB) plus workspace/fragmentation
        exceeds 15.75 GiB usable HBM even at full remat (the OOM row
        in BENCH_SWEEP_r05); it exists to document the boundary and as
        the first multi-chip-ladder config."""
        return replace(
            LlamaConfig(dim=3072, n_layers=26, n_heads=24, n_kv_heads=24,
                        hidden_dim=8192, max_seq_len=2048),
            **overrides,
        )

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Test-sized config: runs in milliseconds on a CPU mesh."""
        return replace(
            LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=128,
                        dtype=jnp.float32),
            **overrides,
        )


#: named-tensor remat presets: save the listed activations, recompute
#: the rest in backward. Sizes per layer (B=4, T=2048, bench_1b):
#: qkv+attn 4x32 MB; mlp gate/up 92 MB each. "attn" skips recomputing
#: the attention pipeline (projections + rope + flash fwd) for ~1.9 GB;
#: "attn+mlp" also skips the two F-sized matmuls for ~3.7 GB more.
_NAME_POLICIES = {
    "attn": ("q_rope", "k_rope", "v_proj", "attn_out"),
    "attn+mlp": ("q_rope", "k_rope", "v_proj", "attn_out",
                 "mlp_gate", "mlp_up"),
    "mlp": ("mlp_gate", "mlp_up"),
}


def _remat_policy(name: str):
    if name == "full":
        return jax.checkpoint_policies.nothing_saveable
    if name == "dots":
        return jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    if name in _NAME_POLICIES:
        return jax.checkpoint_policies.save_only_these_names(
            *_NAME_POLICIES[name])
    raise ValueError(
        f"remat_policy must be one of "
        f"{sorted(['full', 'dots', *_NAME_POLICIES])}, got {name!r}")


def param_spec_shapes(cfg: LlamaConfig) -> dict:
    """Abstract shapes of the parameter pytree (layer-stacked)."""
    L, D, V = cfg.n_layers, cfg.dim, cfg.vocab_size
    H, KVH, hd, F = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.hidden_dim
    return {
        "embed": {"tokens": (V, D)},
        "blocks": {
            "attn_norm": (L, D),
            "wq": (L, D, H * hd),
            "wk": (L, D, KVH * hd),
            "wv": (L, D, KVH * hd),
            "wo": (L, H * hd, D),
            "mlp_norm": (L, D),
            "w_gate": (L, D, F),
            "w_up": (L, D, F),
            "w_down": (L, F, D),
        },
        "out_norm": (D,),
        "lm_head": (D, V),
    }


def init_params(cfg: LlamaConfig, key: jax.Array,
                shapes: dict | None = None) -> dict:
    """Random-init a parameter pytree matching ``param_spec_shapes``
    (or an explicit ``shapes`` tree — the MoE family passes its own)."""
    if shapes is None:
        shapes = param_spec_shapes(cfg)
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        shapes, is_leaf=lambda x: isinstance(x, tuple)
    )
    keys = jax.random.split(key, len(flat))
    leaves = [init_leaf(cfg, p[-1].key, s, k)
              for (p, s), k in zip(flat, keys)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def init_leaf(cfg: LlamaConfig, name: str, shape, k: jax.Array):
    """Init rule for ONE named parameter leaf — the single source of
    truth shared by ``init_params`` and the leaf-at-a-time
    ``quantize.init_params_quantized`` (which must stay bit-identical
    to materialize-then-quantize)."""
    out_scale = 0.02 / (2.0 * cfg.n_layers) ** 0.5
    if "norm" in name:
        return jnp.ones(shape, cfg.param_dtype)
    if name in ("wo", "w_down", "moe_down"):  # residual-writing projections
        return (jax.random.normal(k, shape) * out_scale).astype(cfg.param_dtype)
    return (jax.random.normal(k, shape) * 0.02).astype(cfg.param_dtype)


def _attention_half(cfg: LlamaConfig, x, layer, cos, sin, positions,
                    segments, mesh=None):
    """Pre-norm attention + residual. x: (B, T, D) in compute dtype.

    Activations are tagged with ``checkpoint_name`` so remat policies
    can save exactly the tensors whose recompute is expensive relative
    to their HBM cost (see ``LlamaConfig.remat_policy``). Shared with
    the MoE family (``models.mixtral``), whose blocks differ only in
    the FFN half."""
    from jax.ad_checkpoint import checkpoint_name

    B, T, D = x.shape
    H, KVH, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    cdt = cfg.dtype

    from kubeflow_rm_tpu.models.lora import lora_proj

    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    proj = partial(lora_proj, layer, alpha=cfg.lora_alpha, dtype=cdt)
    q = proj("wq", h).reshape(B, T, H, hd)
    k = proj("wk", h).reshape(B, T, KVH, hd)
    v = proj("wv", h).reshape(B, T, KVH, hd)
    q = checkpoint_name(apply_rope(q, cos, sin), "q_rope")
    k = checkpoint_name(apply_rope(k, cos, sin), "k_rope")
    v = checkpoint_name(v, "v_proj")
    backend = cfg.attention_backend
    if backend not in ("auto", "ring", "ulysses"):
        raise ValueError(
            f"attention_backend must be auto/ring/ulysses, got {backend!r}")
    if (backend != "auto" and mesh is not None
            and mesh.shape.get("sp", 1) > 1):
        if backend == "ring":
            from kubeflow_rm_tpu.parallel.ring_attention import (
                ring_self_attention,
            )
            attn = ring_self_attention(q, k, v, mesh, causal=True,
                                       positions=positions,
                                       segments=segments)
        else:
            from kubeflow_rm_tpu.parallel.ulysses import (
                ulysses_self_attention,
            )
            attn = ulysses_self_attention(q, k, v, mesh, causal=True,
                                          positions=positions,
                                          segments=segments)
    else:
        attn = dot_product_attention(
            q, k, v, causal=True, positions_q=positions,
            positions_kv=positions,
            segment_ids_q=segments, segment_ids_kv=segments,
        )
    attn = checkpoint_name(attn, "attn_out")
    return x + proj("wo", attn.reshape(B, T, H * hd))


def _block(cfg: LlamaConfig, x, layer, cos, sin, positions, segments,
           mesh=None):
    """One transformer block (attention + dense SwiGLU MLP)."""
    from jax.ad_checkpoint import checkpoint_name

    cdt = cfg.dtype
    from kubeflow_rm_tpu.models.lora import lora_proj

    x = _attention_half(cfg, x, layer, cos, sin, positions, segments,
                        mesh=mesh)
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    proj = partial(lora_proj, layer, alpha=cfg.lora_alpha, dtype=cdt)
    gate = checkpoint_name(proj("w_gate", h), "mlp_gate")
    up = checkpoint_name(proj("w_up", h), "mlp_up")
    x = x + proj("w_down", jax.nn.silu(gate) * up)
    return x


def _prologue(params, tokens, cfg: LlamaConfig, positions, segments,
              packed: bool, mesh=None):
    """Shared forward prologue: the positions/packed mask contract,
    embedding gather, rope tables, and the remat-wrapped block. Used by
    both the plain ``forward`` and ``parallel.pipeline`` so the two
    execution schedules cannot drift."""
    B, T = tokens.shape
    if positions is None or packed:
        attn_positions = None
    else:
        attn_positions = positions
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    # gather the (B, T, D) rows first, then cast — never materialize a
    # compute-dtype copy of the whole (V, D) table
    x = params["embed"]["tokens"][tokens].astype(cfg.dtype)
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

    block = partial(_block, cfg, mesh=mesh)
    if cfg.remat:
        block = jax.checkpoint(block, policy=_remat_policy(cfg.remat_policy))
    return x, cos, sin, attn_positions, block


def _epilogue(params, x, cfg: LlamaConfig) -> jax.Array:
    """Shared forward epilogue: final norm, lm head, fp32 logits."""
    from kubeflow_rm_tpu.models.quantize import maybe_dequant

    x = rms_norm(x, params["out_norm"], cfg.norm_eps)
    logits = x @ maybe_dequant(params["lm_head"], cfg.dtype)
    return logits.astype(jnp.float32)


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    positions: jax.Array | None = None,
    segments: jax.Array | None = None,
    *,
    packed: bool = False,
    mesh=None,
) -> jax.Array:
    """Causal LM forward pass.

    Args:
      params: pytree from ``init_params``.
      tokens: (B, T) int32 token ids.
      positions: (B, T) global positions; defaults to arange. Passing
        explicit positions is how sequence-parallel shards and packed
        sequences get correct RoPE.
      segments: (B, T) document segment ids for packed sequences (from
        ``training.data.pack_documents``); restricts attention to equal
        segments so packed documents stay independent.
      packed: assert that ``positions`` restart per document and are
        monotone within each segment (the ``pack_documents`` layout).
        Only then may the attention mask drop positions — local-causal
        ∧ same-segment is exact for that layout, and leaving
        attn_positions=None keeps the call on the pallas flash kernel.
        Without the flag, explicit positions + segments (e.g. a zigzag
        sequence-parallel shard of packed data, whose positions are
        NON-monotonic) keep the position-aware XLA path — silently
        assuming monotonicity would compute a wrong mask.

    Returns:
      (B, T, vocab) fp32 logits.
    """
    x, cos, sin, attn_positions, block = _prologue(
        params, tokens, cfg, positions, segments, packed, mesh=mesh)

    def scan_body(x, layer):
        return block(x, layer, cos, sin, attn_positions, segments), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    return _epilogue(params, x, cfg)
