"""Weight-only int8 / int4 quantization for serving.

Single-sequence decode is weights-bound: every token-step streams the
full parameter set out of HBM while the MXU idles. Cutting the bytes
(bf16 → int8, or → packed int4 + per-group scales) is therefore nearly
a linear token-rate lever, with no activation quantization and no
retraining — the standard weight-only serving recipe, implemented
jax-native.

- **int8** (``bits=8``): symmetric per-output-channel scales,
  ``scale = max|w| / 127`` over the contraction axis, stored fp32.
  Leaves are ``{"q": int8, "s": fp32}``.
- **int4** (``bits=4``): symmetric per-group scales (``group_size``
  rows of the contraction axis share one scale per output channel —
  finer granularity recovers most of the accuracy the 15-level grid
  loses), two nibbles packed per int8 byte. Leaves are
  ``{"q4": int8 packed, "s": fp32}``; a 7B model stores in
  ~3.6 GB — comfortable on one 16 GiB v5e next to its KV cache.
  Leaves carry only stacked arrays (no scalar metadata) so they ride
  ``lax.scan`` over the layer axis like every other weight.
- **int4 decode speed**: the nibble unpack is loop-invariant, so the
  fused decode path hoists it out of the per-token scan
  (``unpack_int4_params`` → ``{"q8g", "s"}`` group-shaped int8 leaves,
  unpacked ONCE per generation) and each step pays only the int8→bf16
  dequant prologue. Early revisions re-unpacked inside the scan body
  every step, which made fused int4 8x+ slower than the per-token
  loop (612.77 vs 137.07 ms/tok at B8/7B, BENCH_SWEEP_r05.json
  ``decode_7b``) and earned the docstring claim that int4 was "a
  capacity lever, not a speed lever". With the hoist that claim is
  stale: fused int4 decodes at int8-like step cost (SERVE_r01.json
  ``decode_int4`` re-measurement) while still storing a 7B in ~3.6 GB
  packed + ~6.7 GB unpacked-resident during decode — both levers now.
- The dequant multiply fuses into the matmul epilogue; XLA reads the
  narrow weights from HBM and converts in VMEM, which is exactly where
  the bandwidth win comes from. Norms (tiny) and the embedding (a
  gather, one row per token) stay in the original dtype.
- ``models.generate.decode_chunk`` consumes quantized and plain
  pytrees interchangeably (``maybe_dequant``), so ``generate`` and the
  sharded ``make_decode_step`` work unchanged.

Accuracy and the speed claim are covered by ``tests/test_quantize.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

#: weight leaves consumed by matmuls in the decode path
_MATMUL_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                  "moe_gate", "moe_up", "moe_down")


def _quant_leaf(w: jax.Array) -> dict:
    """Symmetric int8 over the contraction axis (-2 in our (in, out)
    layout; leading axes are layer/expert stacks)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def _quant_leaf4(w: jax.Array, group_size: int) -> dict:
    """Symmetric int4 with per-(group, out-channel) scales, two values
    packed per byte along the contraction axis."""
    wf = w.astype(jnp.float32)
    K = wf.shape[-2]
    if K % 2:
        raise ValueError(
            f"int4 packing needs an even contraction dim, got {K} "
            "(real transformer dims are even; pad or use int8)")
    g = min(group_size, K)
    if K % g or g % 2:
        g = K  # indivisible or odd group: fall back to one group
    gshape = wf.shape[:-2] + (K // g, g) + wf.shape[-1:]
    wg = wf.reshape(gshape)                      # (..., G, g, out)
    amax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)
    scale = jnp.where(amax == 0, 1.0, amax / 7.0)
    q = jnp.clip(jnp.round(wg / scale), -7, 7).astype(jnp.int8)
    hi, lo = q[..., 0::2, :], q[..., 1::2, :]    # (..., G, g/2, out)
    packed = ((hi << 4) | (lo & 0xF)).astype(jnp.int8)
    # NOTE: every leaf must carry the leading layer-stack axis so the
    # pytree rides lax.scan's xs unstacking — no scalar metadata here
    # (group size is recoverable as 2 * q4.shape[-2])
    return {"q4": packed, "s": scale}


def _quant_fn(bits: int, group_size: int):
    """The bits→leaf-quantizer dispatch shared by ``quantize_params``
    and ``init_params_quantized``."""
    if bits == 8:
        return _quant_leaf
    if bits == 4:
        return lambda w: _quant_leaf4(w, group_size)
    raise ValueError(f"bits must be 8 or 4, got {bits}")


def quantize_params(params: dict, bits: int = 8,
                    group_size: int = 128) -> dict:
    """Quantize every matmul weight to ``bits`` (8 or 4);
    norms/embed pass through. ``group_size`` applies to int4 only."""
    quant = _quant_fn(bits, group_size)
    blocks = {
        k: (quant(v) if k in _MATMUL_LEAVES else v)
        for k, v in params["blocks"].items()
    }
    out = dict(params, blocks=blocks)
    out["lm_head"] = quant(params["lm_head"])
    return out


@partial(jax.jit, static_argnames=("cfg", "name", "shape", "bits",
                                   "group_size"))
def _init_quant_leaf(key: jax.Array, cfg, name: str, shape: tuple,
                     bits: int, group_size: int):
    """Init one matmul leaf and quantize it inside a single jitted
    call, so the full-precision tensor is a transient. Module-level
    on purpose: the trace cache keys on the static (name, shape) —
    one compile per distinct leaf spec across ALL calls, where the
    old per-leaf ``jax.jit(lambda ...)`` built a fresh single-entry
    cache every iteration (KFRM007)."""
    from kubeflow_rm_tpu.models.llama import init_leaf

    return _quant_fn(bits, group_size)(init_leaf(cfg, name, shape, key))


def init_params_quantized(cfg, key: jax.Array, bits: int = 8,
                          group_size: int = 128) -> dict:
    """Random-init a model DIRECTLY into quantized form, one leaf at a
    time, so the full-precision copy never exists in HBM.

    ``quantize_params(init_params(cfg, key))`` needs the whole fp32/bf16
    tree resident before the first leaf quantizes — for a 7B that is
    ~13-27 GiB and OOMs a 16 GiB v5e. Here each matmul leaf runs
    init→quantize inside ONE jitted call whose full-precision tensor is
    a transient (largest: the stacked w_up, ~2.9 GiB bf16 at 7B), so
    peak HBM is the quantized model plus one leaf. Bit-identical to the
    two-step path (asserted by tests/test_quantize.py) because it
    splits keys and applies the same init/quant math in the same order.

    This is the synthetic-weights entry the 7B serving/QLoRA benches
    use; ``from_hf_llama`` + ``quantize_params`` on a big-RAM host is
    the real-checkpoint equivalent.
    """
    from kubeflow_rm_tpu.models.llama import init_leaf, param_spec_shapes

    # dispatch shapes like models.init_params does (MixtralConfig
    # reuses llama's init rules over its own shape tree)
    from kubeflow_rm_tpu.models.mixtral import MixtralConfig
    from kubeflow_rm_tpu.models.mixtral import (
        param_spec_shapes as moe_shapes,
    )
    shapes = (moe_shapes(cfg) if isinstance(cfg, MixtralConfig)
              else param_spec_shapes(cfg))
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        shapes, is_leaf=lambda x: isinstance(x, tuple)
    )
    keys = jax.random.split(key, len(flat))

    leaves = []
    for (path, shape), k in zip(flat, keys):
        name = path[-1].key
        if name in _MATMUL_LEAVES or name == "lm_head":
            leaves.append(jax.block_until_ready(
                _init_quant_leaf(k, cfg, name, tuple(shape),
                                 bits, group_size)))
        else:
            leaves.append(init_leaf(cfg, name, shape, k))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and set(leaf) in ({"q", "s"},
                                                    {"q4", "s"},
                                                    {"q8g", "s"})


def unpack_int4(leaf: dict) -> dict:
    """Unpack a packed-int4 leaf to group-shaped int8 ``{"q8g", "s"}``.

    The unpack here is byte-for-byte the ops the old in-scan q4 dequant
    performed, so ``maybe_dequant`` on the result is bit-identical to
    dequanting the packed form directly — the fused decode loop relies
    on that for loop/fused parity. Unlike plain int8 ``{"q", "s"}``,
    the group axes are kept so the per-group scales still broadcast.
    Doubles the weight bytes vs packed (int8 vs two nibbles/byte);
    intended as a transient inside a generation, not a storage format.
    """
    packed = leaf["q4"]                          # (..., G, g/2, out)
    hi = packed >> 4                             # arithmetic: sign ok
    lo = (packed << 4).astype(jnp.int8) >> 4
    q = jnp.stack([hi, lo], axis=-2)             # (..., G, g/2, 2, out)
    gshape = packed.shape[:-2] + (packed.shape[-2] * 2,) \
        + packed.shape[-1:]
    return {"q8g": q.reshape(gshape), "s": leaf["s"]}


def unpack_int4_params(params):
    """Rewrite every packed-int4 leaf in a param tree to its unpacked
    ``{"q8g", "s"}`` form; every other leaf passes through untouched.

    Called ONCE at the top of the fused decode paths (outside the
    per-token scan) so nibble unpacking is loop-invariant — the fix
    for the 612.77 ms/tok fused-int4 trap. No-op on int8/bf16 trees.
    """
    return jax.tree_util.tree_map(
        lambda x: unpack_int4(x) if isinstance(x, dict) and "q4" in x
        else x,
        params,
        is_leaf=lambda x: isinstance(x, dict) and ("q4" in x or
                                                   "q8g" in x or
                                                   "q" in x),
    )


def maybe_dequant(leaf, dtype) -> jax.Array:
    """Materialize a compute-dtype weight from any representation.
    Under jit the unpack/convert/scale fuses into the consuming
    matmul's prologue."""
    if not isinstance(leaf, dict):
        return leaf.astype(dtype)
    if "q4" in leaf:
        leaf = unpack_int4(leaf)
    if "q8g" in leaf:
        q = leaf["q8g"]                          # (..., G, g, out)
        w = q.astype(dtype) * leaf["s"].astype(dtype)
        K = q.shape[-3] * q.shape[-2]
        return w.reshape(q.shape[:-3] + (K,) + q.shape[-1:])
    return (leaf["q"].astype(dtype) * leaf["s"].astype(dtype))


def quantized_bytes(params: dict) -> int:
    """Total stored bytes — the HBM-traffic accounting behind the
    decode speedup claim."""
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params))
