"""Weight-only int8 quantization for serving.

Single-sequence decode is weights-bound: every token-step streams the
full parameter set out of HBM while the MXU idles. Halving the bytes
(bf16 → int8 + per-output-channel fp scales) is therefore nearly a 2×
token-rate lever, with no activation quantization and no retraining —
the standard weight-only serving recipe, implemented jax-native.

- **Symmetric per-output-channel scales**: ``scale = max|w| / 127``
  over the contraction axis, stored fp32. The dequant multiply fuses
  into the matmul epilogue; XLA reads int8 from HBM and converts in
  VMEM, which is exactly where the bandwidth win comes from.
- Quantized leaves are ``{"q": int8, "s": fp32}`` dicts; everything the
  decode path multiplies by (attention/MLP projections, lm_head) is
  quantized, while norms (tiny) and the embedding (a gather, already
  one row per token) stay in the original dtype.
- ``models.generate.decode_chunk`` consumes quantized and plain
  pytrees interchangeably (``maybe_dequant``), so ``generate`` and the
  sharded ``make_decode_step`` work unchanged.

Accuracy and the speed claim are covered by ``tests/test_quantize.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: weight leaves consumed by matmuls in the decode path
_MATMUL_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                  "moe_gate", "moe_up", "moe_down")


def _quant_leaf(w: jax.Array) -> dict:
    """Symmetric int8 over the contraction axis (-2 in our (in, out)
    layout; leading axes are layer/expert stacks)."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def quantize_params(params: dict) -> dict:
    """int8-quantize every matmul weight; norms/embed pass through."""
    blocks = {
        k: (_quant_leaf(v) if k in _MATMUL_LEAVES else v)
        for k, v in params["blocks"].items()
    }
    out = dict(params, blocks=blocks)
    out["lm_head"] = _quant_leaf(params["lm_head"])
    return out


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q", "s"}


def maybe_dequant(leaf, dtype) -> jax.Array:
    """Materialize a compute-dtype weight from either representation.
    Under jit the convert+scale fuses into the consuming matmul."""
    if is_quantized(leaf):
        return (leaf["q"].astype(dtype) * leaf["s"].astype(dtype))
    return leaf.astype(dtype)


def quantized_bytes(params: dict) -> int:
    """Total stored bytes — the HBM-traffic accounting behind the
    decode speedup claim."""
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params))
