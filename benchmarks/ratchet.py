#!/usr/bin/env python3
"""Perf ratchet: baseline-diff a fresh storm against checked-in
artifacts, and FAIL the build when a matched hop regresses.

Turns tracing from a debugging tool into enforcement (ROADMAP item 3):
the checked-in ``TRACE_r01.json`` / ``PROVISION_r11.json`` record what
the spawn path cost when they were cut; this tool compares a fresh
storm's trace critical-path hops and PhaseRecorder percentiles against
them and exits 3 — the repo's established gate-failure code, same as
the lockgraph gate — when any matched hop regressed more than
``--threshold`` (default 20%) AND more than ``--floor-ms`` (absolute
noise floor: a 0.1ms hop doubling is not a regression).

Hop matching normalizes per-run identifiers (``wc-14`` -> ``wc-*``,
``/namespaces/conf-p2/`` -> ``/namespaces/*/``) and sums self-time per
normalized name, so the same logical hop matches across runs. Edge
cases degrade to warnings, never spurious failures: a hop present only
in the baseline (vanished or renamed) warns, a hop present only in the
fresh run (new work) warns, and a comparison whose ``run_meta`` arm
flags disagree is REFUSED (exit 2) instead of producing garbage
deltas. Artifacts predating run_meta stamping compare with a warning.

Exit codes: 0 ok, 2 refused / unusable input, 3 regression.

Usage (the CI gate):
    python benchmarks/ratchet.py \
        --baseline-trace TRACE_r01.json --trace TRACE_ci.json \
        --baseline-provision PROVISION_r11.json \
        --provision provision_ci.json --out RATCHET_ci.json
"""

from __future__ import annotations

import argparse
import json
import re
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from kubeflow_rm_tpu.controlplane.obs.runmeta import compatible  # noqa: E402

# per-run identifier scrubbing so "the same hop" matches across storms
_NORMALIZERS = (
    (re.compile(r"\b(wc|nb|chaos|walk|conf-job)-\d+\b"), r"\1-*"),
    (re.compile(r"/namespaces/[^/\s]+/"), "/namespaces/*/"),
    (re.compile(r"/notebooks/[^/\s]+/"), "/notebooks/*/"),
    (re.compile(r"\bchaos-p\d+\b|\bconf-p\d+\b"), "ns-*"),
)


def normalize_hop(name: str) -> str:
    for rx, sub in _NORMALIZERS:
        name = rx.sub(sub, name)
    return name


def _hop_sums(trace_artifact: dict) -> dict[str, float]:
    """self_ms summed per normalized hop name over the slowest trace's
    critical path (several readiness.wait hops fold into one row)."""
    slowest = trace_artifact.get("slowest") or {}
    sums: dict[str, float] = {}
    for hop in slowest.get("critical_path") or []:
        key = normalize_hop(hop.get("name") or "")
        sums[key] = sums.get(key, 0.0) + float(hop.get("self_ms") or 0)
    return sums


def _phase_p50s(artifact: dict) -> dict[str, float]:
    """Per-phase p50 from a provision artifact. Handles both the raw
    PhaseRecorder key (``p50_ms``) and the merged-artifact key
    (``p50_ms_median_of_runs``), and finds the phases dict either at
    top level or inside a named arm section."""
    candidates = [artifact]
    candidates.extend(v for v in artifact.values()
                      if isinstance(v, dict) and "phases" in v)
    out: dict[str, float] = {}
    for c in candidates:
        phases = c.get("phases")
        if not isinstance(phases, dict):
            continue
        for phase, stats in phases.items():
            if not isinstance(stats, dict):
                continue
            p50 = stats.get("p50_ms",
                            stats.get("p50_ms_median_of_runs"))
            if p50 is not None:
                out[phase] = float(p50)
        break  # first section with phases wins (top level preferred)
    return out


def _serve_metrics(artifact: dict) -> dict[str, float]:
    """Lower-is-better rows from a serve_bench artifact's arms.
    Throughput inverts to ms per 1k useful tokens (1e6 / tok_s) so the
    shared ``_compare`` direction (bigger = worse) applies; victim p95
    passes through as-is."""
    out: dict[str, float] = {}
    for arm, sec in sorted((artifact.get("arms") or {}).items()):
        if not isinstance(sec, dict):
            continue
        tok_s = sec.get("useful_tok_per_s")
        if tok_s:
            out[f"{arm}.ms_per_1k_useful_tok"] = 1e6 / float(tok_s)
        p95 = sec.get("victim_p95_ms_worst")
        if p95 is not None:
            out[f"{arm}.victim_p95_ms"] = float(p95)
        # disagg_storm arms carry an aggregate interactive p95 (the
        # SLO-class latency the prefill/decode split is meant to
        # protect) alongside the per-tenant worst
        p95i = sec.get("interactive_p95_ms")
        if p95i is not None:
            out[f"{arm}.interactive_p95_ms"] = float(p95i)
    return out


def _top_level_p50(artifact: dict) -> float | None:
    v = artifact.get("provision_p50_ms")
    if v is not None:
        return float(v)
    for sec in artifact.values():
        if isinstance(sec, dict) and "provision_p50_ms" in sec:
            return float(sec["provision_p50_ms"])
    return None


def _compare(kind: str, base: dict[str, float], fresh: dict[str, float],
             threshold: float, floor_ms: float
             ) -> tuple[list[dict], list[str], list[dict]]:
    """(matched rows, warnings, regressions) for one metric table."""
    rows, warnings, regressions = [], [], []
    for name in sorted(set(base) | set(fresh)):
        b, f = base.get(name), fresh.get(name)
        if b is None:
            warnings.append(f"{kind} '{name}' absent from baseline "
                            f"(new hop?) — not gated")
            continue
        if f is None:
            warnings.append(f"{kind} '{name}' absent from fresh run "
                            f"(vanished or renamed?) — not gated")
            continue
        delta = f - b
        pct = (delta / b * 100.0) if b > 0 else (
            0.0 if delta <= 0 else float("inf"))
        row = {"kind": kind, "name": name, "baseline_ms": round(b, 2),
               "fresh_ms": round(f, 2), "delta_ms": round(delta, 2),
               "delta_pct": round(pct, 1) if pct != float("inf")
               else None}
        regressed = (delta > floor_ms
                     and (b <= 0 or delta / b > threshold))
        row["regressed"] = regressed
        rows.append(row)
        if regressed:
            regressions.append(row)
    return rows, warnings, regressions


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="perf ratchet: fail on >threshold regressions vs "
                    "checked-in baselines")
    ap.add_argument("--baseline-trace", default="",
                    help="checked-in trace artifact (TRACE_r01.json)")
    ap.add_argument("--trace", default="",
                    help="fresh storm's --trace-out artifact")
    ap.add_argument("--baseline-provision", default="",
                    help="checked-in provision artifact "
                         "(PROVISION_r11.json)")
    ap.add_argument("--provision", default="",
                    help="fresh storm's --out artifact")
    ap.add_argument("--baseline-serve", default="",
                    help="checked-in serve_bench artifact "
                         "(SERVE_r02.json)")
    ap.add_argument("--serve", default="",
                    help="fresh serve_bench --out artifact")
    ap.add_argument("--serve-gate", action="store_true",
                    help="fail (exit 3) on serving regressions instead "
                         "of warning — serving throughput on shared CI "
                         "hosts is noisy, so the default only warns "
                         "(the r12 convention for new sections)")
    ap.add_argument("--threshold", type=float, default=0.20,
                    help="relative regression gate (0.20 = 20%%)")
    ap.add_argument("--floor-ms", type=float, default=150.0,
                    help="absolute delta a hop must also exceed — "
                         "single-trace self_ms attribution jitters by "
                         "tens of ms run-to-run; sub-floor deltas "
                         "never fail the gate")
    ap.add_argument("--out", default="",
                    help="write the comparison report JSON here")
    args = ap.parse_args(argv)

    pairs = []
    if bool(args.baseline_trace) != bool(args.trace):
        print("ratchet: --baseline-trace and --trace go together",
              file=sys.stderr)
        return 2
    if bool(args.baseline_provision) != bool(args.provision):
        print("ratchet: --baseline-provision and --provision go "
              "together", file=sys.stderr)
        return 2
    if bool(args.baseline_serve) != bool(args.serve):
        print("ratchet: --baseline-serve and --serve go together",
              file=sys.stderr)
        return 2
    if args.trace:
        pairs.append(("trace", args.baseline_trace, args.trace))
    if args.provision:
        pairs.append(("provision", args.baseline_provision,
                      args.provision))
    if args.serve:
        pairs.append(("serve", args.baseline_serve, args.serve))
    if not pairs:
        print("ratchet: nothing to compare (pass --trace/--provision)",
              file=sys.stderr)
        return 2

    report: dict = {"threshold": args.threshold,
                    "floor_ms": args.floor_ms,
                    "comparisons": [], "warnings": [],
                    "refusals": [], "regressions": []}
    for kind, base_path, fresh_path in pairs:
        try:
            base, fresh = _load(base_path), _load(fresh_path)
        except (OSError, ValueError) as e:
            print(f"ratchet: cannot load {kind} pair: {e}",
                  file=sys.stderr)
            return 2
        refusals, warnings = compatible(base.get("run_meta"),
                                        fresh.get("run_meta"))
        report["refusals"].extend(f"{kind}: {r}" for r in refusals)
        report["warnings"].extend(f"{kind}: {w}" for w in warnings)
        if refusals:
            continue
        if kind == "serve":
            base_t, fresh_t = _serve_metrics(base), _serve_metrics(fresh)
        elif kind == "trace":
            base_t, fresh_t = _hop_sums(base), _hop_sums(fresh)
            # the whole-storm p50 rides the trace artifact: gate it as
            # a synthetic hop so a regression spread thinly over many
            # hops (or parked on a NEW hop, which only warns) still
            # trips the ratchet
            bp, fp = _top_level_p50(base), _top_level_p50(fresh)
            if bp is not None and fp is not None:
                base_t["(provision_p50_ms)"] = bp
                fresh_t["(provision_p50_ms)"] = fp
        else:
            base_t, fresh_t = _phase_p50s(base), _phase_p50s(fresh)
            bp, fp = _top_level_p50(base), _top_level_p50(fresh)
            if bp is not None and fp is not None:
                base_t["(provision_p50_ms)"] = bp
                fresh_t["(provision_p50_ms)"] = fp
        rows, warnings, regressions = _compare(
            kind, base_t, fresh_t, args.threshold, args.floor_ms)
        if kind == "serve" and not args.serve_gate and regressions:
            # warn-not-fail: serving throughput jitters with host load;
            # the rows still land in the report for eyeballing
            warnings.extend(
                f"serve '{r['name']}' regressed {r['baseline_ms']}ms "
                f"-> {r['fresh_ms']}ms (+{r['delta_pct']}%) — warn-only "
                f"(pass --serve-gate to enforce)" for r in regressions)
            regressions = []
        report["comparisons"].append(
            {"kind": kind, "baseline": base_path, "fresh": fresh_path,
             "rows": rows})
        report["warnings"].extend(warnings)
        report["regressions"].extend(regressions)

    if report["refusals"]:
        report["verdict"] = "refused"
    elif report["regressions"]:
        report["verdict"] = "regressed"
    else:
        report["verdict"] = "ok"

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    for w in report["warnings"]:
        print(f"ratchet: warn: {w}", file=sys.stderr)
    for r in report["refusals"]:
        print(f"ratchet: REFUSED: {r}", file=sys.stderr)
    if report["verdict"] == "refused":
        print("RATCHET REFUSED (mismatched arms — fix the comparison, "
              "don't trust these deltas)", file=sys.stderr)
        return 2
    if report["verdict"] == "regressed":
        print("RATCHET GATE FAILED:", file=sys.stderr)
        for r in report["regressions"]:
            print(f"  {r['kind']} '{r['name']}': "
                  f"{r['baseline_ms']}ms -> {r['fresh_ms']}ms "
                  f"(+{r['delta_pct']}%)", file=sys.stderr)
        return 3
    matched = sum(len(c["rows"]) for c in report["comparisons"])
    print(f"RATCHET OK ({matched} matched hops/phases within "
          f"{int(args.threshold * 100)}%, "
          f"{len(report['warnings'])} warnings)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
