#!/usr/bin/env python3
"""HTTP serving throughput + speculative-decode workload bench.

Two campaigns, each printing one JSON line (appended to
``BENCH_SWEEP_r05_raw.jsonl`` by the caller):

- ``serve``: boot ``examples/serve_llama.py``'s app in-process on a
  synthetic-weight model (``--preset`` / ``--quant``), fire N requests
  at C concurrency from real HTTP clients, report warm tokens/sec and
  latency percentiles — the 7B companion of r4's 1.2B ``serving_http``
  block (VERDICT r5 item 2).
- ``spec``: measure prompt-lookup speculative decoding on the workload
  it was designed for — continuation of REPETITIVE text (code/docs
  where the continuation echoes the prompt) — against plain fused
  decode, reporting acceptance and net speedup (VERDICT r5 item 8).
  The model is trained briefly on a tiny repetitive corpus so greedy
  continuations actually repeat (random weights accept nothing —
  that's r4's measured worst case, not the win case).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def serve_campaign(preset: str, quant: str | None, requests_n: int,
                   concurrency: int, max_new: int) -> dict:
    import jax
    import numpy as np
    from werkzeug.serving import make_server

    from examples.serve_llama import make_app
    from kubeflow_rm_tpu.models import LlamaConfig, init_params

    cfg = getattr(LlamaConfig, preset)(param_dtype=jax.numpy.bfloat16) \
        if jax.devices()[0].platform == "tpu" \
        else getattr(LlamaConfig, preset)()
    if quant:
        from kubeflow_rm_tpu.models.quantize import init_params_quantized
        params = init_params_quantized(cfg, jax.random.key(0),
                                       bits=4 if quant == "int4" else 8)
    else:
        params = init_params(cfg, jax.random.key(0))

    app = make_app(cfg, params, max_new_tokens=max_new, window_ms=8,
                   max_batch=16)
    httpd = make_server("127.0.0.1", 0, app, threaded=True)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_port}/generate"

    rng = np.random.default_rng(0)
    # one prompt-length bucket (96-127) like the r4 block
    prompts = [rng.integers(1, cfg.vocab_size,
                            size=int(rng.integers(96, 128))).tolist()
               for _ in range(requests_n)]

    import urllib.request

    def call(p):
        t0 = time.perf_counter()
        req = urllib.request.Request(
            url, data=json.dumps({"prompt": p}).encode(),
            headers={"Content-Type": "application/json"})
        body = json.loads(urllib.request.urlopen(req, timeout=600).read())
        assert len(body["tokens"]) == len(p) + max_new
        return time.perf_counter() - t0

    # warm: one concurrency-wide wave so the coalesced batch shapes
    # (not just batch-1) compile BEFORE the timed region
    warm_ts = [threading.Thread(target=call, args=(p,))
               for p in prompts[:concurrency]]
    for t in warm_ts:
        t.start()
    for t in warm_ts:
        t.join()
    call(prompts[0])  # and the solo shape

    lat: list[float] = []
    lock = threading.Lock()
    idx = {"i": 1}

    def worker():
        while True:
            with lock:
                i = idx["i"]
                if i >= len(prompts):
                    return
                idx["i"] = i + 1
            d = call(prompts[i])
            with lock:
                lat.append(d)

    t0 = time.perf_counter()
    ts = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    n = len(lat)
    return {
        "metric": "serving_http",
        "model": f"llama-{preset}" + (f" {quant}" if quant else " bf16"),
        "requests": n,
        "concurrency": concurrency,
        "new_tokens_per_req": max_new,
        "warm_requests_per_s": round(n / wall, 2),
        "warm_gen_tokens_per_s": round(n * max_new / wall, 1),
        "latency_p50_s": round(lat[n // 2], 2),
        "latency_p95_s": round(lat[max(0, int(n * 0.95) - 1)], 2),
        "batches": app.batcher.batches_run,
    }


def spec_campaign(preset: str, train_steps: int, max_new: int) -> dict:
    """Train a small model on repetitive text, then decode
    continuations of its own training prefixes — the prompt-lookup
    decoder's intended workload — vs plain fused decode."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_rm_tpu.models import LlamaConfig
    from kubeflow_rm_tpu.models.generate import (
        generate_fused, generate_speculative_fused,
    )
    from kubeflow_rm_tpu.parallel import MeshConfig, make_mesh
    from kubeflow_rm_tpu.training.train import (
        TrainConfig, init_train_state, make_train_step, shard_batch,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = getattr(LlamaConfig, preset)(
        **({"param_dtype": jnp.bfloat16} if on_tpu else {}))
    mesh = make_mesh(MeshConfig(), jax.devices()[:1])
    tc = TrainConfig(model=cfg)
    state = init_train_state(tc, jax.random.key(0))
    step = make_train_step(tc, mesh, state)

    # a tiny repetitive corpus: short token phrases repeated many times
    rng = np.random.default_rng(0)
    phrases = [rng.integers(2, min(cfg.vocab_size, 200), size=8).tolist()
               for _ in range(4)]
    seq_len = min(cfg.max_seq_len, 256)
    doc = []
    while len(doc) < 8 * seq_len:
        doc += phrases[rng.integers(0, len(phrases))]
    toks = np.array(doc[:8 * seq_len], np.int32).reshape(8, seq_len)
    batch = shard_batch(
        {"tokens": toks, "labels": np.roll(toks, -1, 1)}, mesh)
    for _ in range(train_steps):
        state, metrics = step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))

    # prompt = a training row prefix; greedy continuation repeats it
    prompt = jnp.asarray(toks[:1, :96])

    def timed(fn):
        out = fn()
        jax.device_get(np.asarray(out)[:, -1])
        t0 = time.perf_counter()
        out = fn()
        jax.device_get(np.asarray(out)[:, -1])
        return np.asarray(out), time.perf_counter() - t0

    plain, t_plain = timed(lambda: generate_fused(
        state.params, cfg, prompt, max_new_tokens=max_new))
    spec, t_spec = timed(lambda: generate_speculative_fused(
        state.params, cfg, prompt, max_new_tokens=max_new, lookup_n=3))
    match = bool((plain[0, :spec.shape[1]] == spec[0]).all()) \
        or bool((spec[0, :plain.shape[1]] == plain[0]).all())
    return {
        "metric": "speculative_repetitive_workload",
        "model": f"llama-{preset}",
        "train_steps": train_steps,
        "final_loss": round(loss, 3),
        "new_tokens": max_new,
        "plain_ms_per_token": round(1e3 * t_plain / max_new, 2),
        "spec_ms_per_token": round(1e3 * t_spec / max_new, 2),
        "net_speedup": round(t_plain / t_spec, 2),
        "outputs_match": match,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("campaign", choices=["serve", "spec"])
    ap.add_argument("--preset", default="bench_1b")
    ap.add_argument("--quant", choices=["int8", "int4"], default=None)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--train-steps", type=int, default=60)
    args = ap.parse_args()
    if args.campaign == "serve":
        out = serve_campaign(args.preset, args.quant, args.requests,
                             args.concurrency, args.max_new)
    else:
        out = spec_campaign(args.preset, args.train_steps, args.max_new)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
