#!/usr/bin/env python3
"""HTTP serving throughput + speculative-decode workload bench.

Five campaigns, each printing one JSON line:

- ``serve``: boot ``examples/serve_llama.py``'s app in-process on a
  synthetic-weight model (``--preset`` / ``--quant``), fire N requests
  at C concurrency from real HTTP clients, report warm tokens/sec and
  latency percentiles — the 7B companion of r4's 1.2B ``serving_http``
  block (VERDICT r5 item 2).
- ``spec``: measure prompt-lookup speculative decoding on the workload
  it was designed for — continuation of REPETITIVE text (code/docs
  where the continuation echoes the prompt) — against plain fused
  decode, reporting acceptance and net speedup (VERDICT r5 item 8).
  The model is trained briefly on a tiny repetitive corpus so greedy
  continuations actually repeat (random weights accept nothing —
  that's r4's measured worst case, not the win case).
- ``decode``: the int4 decode-path A/B behind the unpack-once fix —
  per-token-loop vs fused-with-hoist vs fused-re-unpack (the pre-fix
  trace, restored via ``set_unpack_once(False)``) on one host, ms/tok
  each. Feeds ``SERVE_r01.json`` ``decode_int4``.
- ``storm``: the many-tenant serving storm — a mixed-length,
  mixed-budget request schedule from T victim tenants plus one
  flooding tenant, replayed against three same-host arms
  (continuous batching + admission control, continuous without
  admission, and serve_llama's static batcher), reporting per-tenant
  p50/p95, aggregate USEFUL tokens/sec (tokens a request asked for —
  the static arm decodes its server-fixed budget regardless), batch
  occupancy, queue depth, and shed counts. Feeds ``SERVE_r01.json``.
- ``prefix_storm``: the r13 prefix-heavy storm — 80% of requests open
  with one long shared system prompt, replayed against the block-paged
  engine (CoW prefix sharing) and the r12 contiguous engine on the
  same host/weights, plus a ServingFleet chaos pass that hard-kills a
  replica mid-storm (every request must migrate and finish exactly).
  Feeds ``SERVE_r02.json``.
- ``disagg_storm``: the r17 disaggregated-fleet storm — interactive
  shared-prefix traffic mixed with long-prefill batch/best_effort
  traffic (some speculative), replayed against the r13 symmetric
  fleet and a prefill/decode split fleet with the fleet-wide
  GlobalBlockStore, same host/weights. Every request is checked
  token-exact against solo fused decode; both arms then take
  two-replica chaos kills plus a post-kill prefix probe. Feeds
  ``SERVE_r03.json``.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def serve_campaign(preset: str, quant: str | None, requests_n: int,
                   concurrency: int, max_new: int) -> dict:
    import jax
    import numpy as np
    from werkzeug.serving import make_server

    from examples.serve_llama import make_app
    from kubeflow_rm_tpu.models import LlamaConfig, init_params

    cfg = getattr(LlamaConfig, preset)(param_dtype=jax.numpy.bfloat16) \
        if jax.devices()[0].platform == "tpu" \
        else getattr(LlamaConfig, preset)()
    if quant:
        from kubeflow_rm_tpu.models.quantize import init_params_quantized
        params = init_params_quantized(cfg, jax.random.key(0),
                                       bits=4 if quant == "int4" else 8)
    else:
        params = init_params(cfg, jax.random.key(0))

    app = make_app(cfg, params, max_new_tokens=max_new, window_ms=8,
                   max_batch=16)
    httpd = make_server("127.0.0.1", 0, app, threaded=True)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_port}/generate"

    rng = np.random.default_rng(0)
    # one prompt-length bucket (96-127) like the r4 block
    prompts = [rng.integers(1, cfg.vocab_size,
                            size=int(rng.integers(96, 128))).tolist()
               for _ in range(requests_n)]

    import urllib.request

    def call(p):
        t0 = time.perf_counter()
        req = urllib.request.Request(
            url, data=json.dumps({"prompt": p}).encode(),
            headers={"Content-Type": "application/json"})
        body = json.loads(urllib.request.urlopen(req, timeout=600).read())
        assert len(body["tokens"]) == len(p) + max_new
        return time.perf_counter() - t0

    # warm: one concurrency-wide wave so the coalesced batch shapes
    # (not just batch-1) compile BEFORE the timed region
    warm_ts = [threading.Thread(target=call, args=(p,))
               for p in prompts[:concurrency]]
    for t in warm_ts:
        t.start()
    for t in warm_ts:
        t.join()
    call(prompts[0])  # and the solo shape

    lat: list[float] = []
    lock = threading.Lock()
    idx = {"i": 1}

    def worker():
        while True:
            with lock:
                i = idx["i"]
                if i >= len(prompts):
                    return
                idx["i"] = i + 1
            d = call(prompts[i])
            with lock:
                lat.append(d)

    t0 = time.perf_counter()
    ts = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    n = len(lat)
    return {
        "metric": "serving_http",
        "model": f"llama-{preset}" + (f" {quant}" if quant else " bf16"),
        "requests": n,
        "concurrency": concurrency,
        "new_tokens_per_req": max_new,
        "warm_requests_per_s": round(n / wall, 2),
        "warm_gen_tokens_per_s": round(n * max_new / wall, 1),
        "latency_p50_s": round(lat[n // 2], 2),
        "latency_p95_s": round(lat[max(0, int(n * 0.95) - 1)], 2),
        "batches": app.batcher.batches_run,
    }


def spec_campaign(preset: str, train_steps: int, max_new: int) -> dict:
    """Train a small model on repetitive text, then decode
    continuations of its own training prefixes — the prompt-lookup
    decoder's intended workload — vs plain fused decode."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_rm_tpu.models import LlamaConfig
    from kubeflow_rm_tpu.models.generate import (
        generate_fused, generate_speculative_fused,
    )
    from kubeflow_rm_tpu.parallel import MeshConfig, make_mesh
    from kubeflow_rm_tpu.training.train import (
        TrainConfig, init_train_state, make_train_step, shard_batch,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = getattr(LlamaConfig, preset)(
        **({"param_dtype": jnp.bfloat16} if on_tpu else {}))
    mesh = make_mesh(MeshConfig(), jax.devices()[:1])
    tc = TrainConfig(model=cfg)
    state = init_train_state(tc, jax.random.key(0))
    step = make_train_step(tc, mesh, state)

    # a tiny repetitive corpus: short token phrases repeated many times
    rng = np.random.default_rng(0)
    phrases = [rng.integers(2, min(cfg.vocab_size, 200), size=8).tolist()
               for _ in range(4)]
    seq_len = min(cfg.max_seq_len, 256)
    doc = []
    while len(doc) < 8 * seq_len:
        doc += phrases[rng.integers(0, len(phrases))]
    toks = np.array(doc[:8 * seq_len], np.int32).reshape(8, seq_len)
    batch = shard_batch(
        {"tokens": toks, "labels": np.roll(toks, -1, 1)}, mesh)
    for _ in range(train_steps):
        state, metrics = step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))

    # prompt = a training row prefix; greedy continuation repeats it
    prompt = jnp.asarray(toks[:1, :96])

    def timed(fn):
        out = fn()
        jax.device_get(np.asarray(out)[:, -1])
        t0 = time.perf_counter()
        out = fn()
        jax.device_get(np.asarray(out)[:, -1])
        return np.asarray(out), time.perf_counter() - t0

    plain, t_plain = timed(lambda: generate_fused(
        state.params, cfg, prompt, max_new_tokens=max_new))
    spec, t_spec = timed(lambda: generate_speculative_fused(
        state.params, cfg, prompt, max_new_tokens=max_new, lookup_n=3))
    match = bool((plain[0, :spec.shape[1]] == spec[0]).all()) \
        or bool((spec[0, :plain.shape[1]] == plain[0]).all())
    return {
        "metric": "speculative_repetitive_workload",
        "model": f"llama-{preset}",
        "train_steps": train_steps,
        "final_loss": round(loss, 3),
        "new_tokens": max_new,
        "plain_ms_per_token": round(1e3 * t_plain / max_new, 2),
        "spec_ms_per_token": round(1e3 * t_spec / max_new, 2),
        "net_speedup": round(t_plain / t_spec, 2),
        "outputs_match": match,
    }


def _device_tag() -> str:
    import os

    import jax
    plat = jax.devices()[0].platform
    if plat == "cpu":
        return f"cpu-{os.cpu_count()}core"
    return f"{plat}x{len(jax.devices())}"


def decode_campaign(preset: str, batch: int, prompt_len: int,
                    max_new: int, overrides: dict) -> dict:
    """Int4 decode-path A/B: per-token loop vs fused-with-hoist vs
    fused re-unpacking inside the scan (the pre-fix trace, restored
    via ``set_unpack_once(False)``). All three arms decode the SAME
    prompts greedily on the same host; the fused arms must also agree
    token-for-token with the loop (exactness is part of the claim)."""
    import jax
    import numpy as np

    from kubeflow_rm_tpu.models import LlamaConfig, generate_fused
    from kubeflow_rm_tpu.models.generate import generate, set_unpack_once
    from kubeflow_rm_tpu.models.quantize import init_params_quantized

    cfg = getattr(LlamaConfig, preset)(**overrides)
    params = init_params_quantized(cfg, jax.random.key(0), bits=4)
    rng = np.random.default_rng(0)
    ids = jax.numpy.asarray(
        rng.integers(1, cfg.vocab_size, size=(batch, prompt_len)),
        jax.numpy.int32)
    total = prompt_len + max_new

    def timed(fn, reps: int = 3):
        out = fn()                       # compile + warm
        jax.device_get(np.asarray(out)[:, -1])
        ts = []
        for _ in range(reps):            # median: CPU hosts are noisy
            t0 = time.perf_counter()
            out = fn()
            jax.device_get(np.asarray(out)[:, -1])
            ts.append(time.perf_counter() - t0)
        return np.asarray(out), sorted(ts)[len(ts) // 2]

    loop, t_loop = timed(lambda: generate(
        params, cfg, ids, max_new_tokens=max_new, max_len=total))
    set_unpack_once(True)
    fused, t_fused = timed(lambda: generate_fused(
        params, cfg, ids, max_new_tokens=max_new, max_len=total))
    set_unpack_once(False)               # pre-fix arm: unpack per step
    refused, t_reunpack = timed(lambda: generate_fused(
        params, cfg, ids, max_new_tokens=max_new, max_len=total))
    set_unpack_once(True)
    return {
        "metric": "decode_int4",
        "model": f"llama-{preset} int4"
                 + (f" {overrides}" if overrides else ""),
        "device": _device_tag(),
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": max_new,
        "loop_ms_per_tok": round(1e3 * t_loop / max_new, 2),
        "fused_ms_per_tok": round(1e3 * t_fused / max_new, 2),
        "fused_reunpack_ms_per_tok": round(1e3 * t_reunpack / max_new, 2),
        "fused_le_loop": bool(t_fused <= t_loop),
        "outputs_match": bool((loop == fused).all()
                              and (loop == refused).all()),
    }


def storm_campaign(preset: str, quant: str | None, tenants: int,
                   reqs_per_tenant: int, flood_threads: int,
                   flood_reqs: int, slots: int, slot_len: int,
                   slo_ms: float, qps: float, burst: int,
                   overrides: dict | None = None) -> dict:
    """Many-tenant serving storm over three same-host arms sharing one
    set of weights:

    - ``continuous_admission``: ContinuousBatchingEngine behind
      ServingGateway with per-tenant rate/token buckets + SLO shedding.
    - ``continuous_no_admission``: same engine, ``admission=False``
      (only the queue cap survives) — the noisy-neighbor baseline.
    - ``static``: serve_llama's window-coalescing fixed-shape batcher,
      which decodes its server-fixed budget for every request.

    T victim tenants each send a mixed-length, mixed-budget schedule
    at a polite rate; one flood tenant hammers from ``flood_threads``
    parallel connections. Useful tokens = the ``max_new`` each request
    ASKED for (the static arm decodes its fixed budget regardless, so
    its extra tokens are waste, not throughput)."""
    import logging
    import urllib.error
    import urllib.request

    import jax
    import numpy as np
    from werkzeug.serving import make_server

    # one log line per request x hundreds of storm requests = noise
    logging.getLogger("werkzeug").setLevel(logging.ERROR)

    from examples.serve_llama import make_app
    from kubeflow_rm_tpu.controlplane.webapps.serving import (
        ServingGateway, TenantPolicy, make_serving_app,
    )
    from kubeflow_rm_tpu.models import (
        ContinuousBatchingEngine, LlamaConfig, init_params,
    )

    cfg = getattr(LlamaConfig, preset)(**(overrides or {}))
    if quant:
        from kubeflow_rm_tpu.models.quantize import init_params_quantized
        params = init_params_quantized(cfg, jax.random.key(0),
                                       bits=4 if quant == "int4" else 8)
    else:
        params = init_params(cfg, jax.random.key(0))

    # Long-tail budgets: the static server must fix max_new at the tail
    # (32) and decode it for EVERY request; the engine retires each
    # request at its own ask.  avg ask ~= 14.7 vs 32 decoded is the
    # over-decode waste the continuous arm gets back.
    budgets = (4, 8, 32)
    max_budget = max(budgets)
    rng = np.random.default_rng(7)
    # (tenant, prompt, max_new, gap_s) — victims pace themselves,
    # the flood tenant does not
    schedule: dict[str, list] = {}
    for t in range(tenants):
        name = f"tenant-{t}"
        schedule[name] = [
            (rng.integers(1, cfg.vocab_size,
                          size=int(rng.integers(8, 49))).tolist(),
             int(budgets[rng.integers(0, len(budgets))]),
             0.02)
            for _ in range(reqs_per_tenant)]
    # the flood is mixed-length/mixed-budget too — a noisy tenant is
    # ordinary traffic at extraordinary volume
    flood_work = [
        (rng.integers(1, cfg.vocab_size,
                      size=int(rng.integers(8, 49))).tolist(),
         int(budgets[rng.integers(0, len(budgets))]))
        for _ in range(flood_reqs)]

    def run_storm(url: str) -> tuple[list[dict], float]:
        results: list[dict] = []
        lock = threading.Lock()

        def call(tenant, prompt, m):
            body = {"prompt": prompt, "tenant": tenant,
                    "max_new_tokens": m}
            t0 = time.perf_counter()
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json",
                         "X-Tenant": tenant})
            try:
                resp = json.loads(
                    urllib.request.urlopen(req, timeout=600).read())
                ok, reason = True, None
                # gateway arms return the continuation, the static arm
                # prompt+continuation — both non-empty on success
                assert resp["tokens"], resp
            except urllib.error.HTTPError as e:
                ok = False
                try:
                    reason = json.loads(e.read()).get("reason", str(e.code))
                except Exception:
                    reason = str(e.code)
            lat = time.perf_counter() - t0
            with lock:
                results.append({"tenant": tenant, "ok": ok,
                                "reason": reason, "useful": m if ok else 0,
                                "lat_ms": lat * 1e3})

        def victim(name):
            for prompt, m, gap in schedule[name]:
                call(name, prompt, m)
                time.sleep(gap)

        def flooder(i):
            for j in range(i, len(flood_work), flood_threads):
                call("flood", *flood_work[j])

        ts = ([threading.Thread(target=victim, args=(n,))
               for n in schedule]
              + [threading.Thread(target=flooder, args=(i,))
                 for i in range(flood_threads)])
        t0 = time.perf_counter()
        for th in ts:
            th.start()
        for th in ts:
            th.join()
        return results, time.perf_counter() - t0

    def summarize(results, wall, extra) -> dict:
        def pct(v, q):
            return round(v[min(len(v) - 1, int(q * (len(v) - 1)))], 1)

        per_tenant = {}
        for name in sorted({r["tenant"] for r in results}):
            lats = sorted(r["lat_ms"] for r in results
                          if r["tenant"] == name and r["ok"])
            per_tenant[name] = {
                "ok": len(lats),
                "shed": sum(1 for r in results
                            if r["tenant"] == name and not r["ok"]),
                "p50_ms": pct(lats, 0.50) if lats else None,
                "p95_ms": pct(lats, 0.95) if lats else None,
            }
        victim_p95 = [v["p95_ms"] for k, v in per_tenant.items()
                      if k != "flood" and v["p95_ms"] is not None]
        return {
            "wall_s": round(wall, 2),
            "ok": sum(1 for r in results if r["ok"]),
            "shed": sum(1 for r in results if not r["ok"]),
            "useful_tokens": sum(r["useful"] for r in results),
            "useful_tok_per_s": round(
                sum(r["useful"] for r in results) / wall, 1),
            "victim_p95_ms_worst": max(victim_p95) if victim_p95 else None,
            "per_tenant": per_tenant,
            **extra,
        }

    def continuous_arm(admission: bool) -> dict:
        engine = ContinuousBatchingEngine(params, cfg, slots=slots,
                                          slot_len=slot_len)
        gw = ServingGateway(
            engine,
            default_policy=TenantPolicy(qps=qps, burst=burst,
                                        tokens_per_s=qps * 16,
                                        token_burst=burst * 16,
                                        slo_p95_ms=slo_ms),
            max_queue=64, admission=admission)
        app = make_serving_app(gw, cfg)
        httpd = make_server("127.0.0.1", 0, app, threaded=True)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{httpd.server_port}/generate"
        # warm every prefill bucket (8/16/32/64) + decode/install
        for n in (8, 12, 32, 48):
            warm = urllib.request.Request(
                url, data=json.dumps(
                    {"prompt": list(range(1, n + 1)), "tenant": "warm",
                     "max_new_tokens": 4}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(warm, timeout=600).read()
        results, wall = run_storm(url)
        snap = gw.snapshot()
        httpd.shutdown()
        gw.close()
        return summarize(results, wall, {
            "admission": admission,
            "batch_occupancy": round(snap["batch_occupancy"], 3),
            "decode_steps": snap["decode_steps"],
            "shed_reasons": snap["shed"],
        })

    def static_arm() -> dict:
        app = make_app(cfg, params, max_new_tokens=max_budget,
                       window_ms=8, max_batch=slots)
        httpd = make_server("127.0.0.1", 0, app, threaded=True)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{httpd.server_port}/generate"
        # warm the static batcher's (B, T) compile grid: waves at
        # several concurrencies so the storm doesn't pay XLA compiles
        def warm_one(n):
            urllib.request.urlopen(urllib.request.Request(
                url, data=json.dumps(
                    {"prompt": list(range(1, n + 1))}).encode(),
                headers={"Content-Type": "application/json"}),
                timeout=600).read()

        for wave in ((48,), (8, 40), (8, 16, 24, 48),
                     (8, 16, 24, 32, 40, 48, 12, 20)):
            warm_ts = [threading.Thread(target=warm_one, args=(n,))
                       for n in wave]
            for t in warm_ts:
                t.start()
            for t in warm_ts:
                t.join()
        results, wall = run_storm(url)
        batches = app.batcher.batches_run
        httpd.shutdown()
        app.batcher.close()
        return summarize(results, wall, {
            "fixed_max_new": max_budget, "batches": batches})

    return {
        "metric": "serving_storm",
        "model": f"llama-{preset}" + (f" {quant}" if quant else " bf16")
                 + (f" {overrides}" if overrides else ""),
        "device": _device_tag(),
        "workload": {
            "victim_tenants": tenants,
            "reqs_per_tenant": reqs_per_tenant,
            "flood_threads": flood_threads,
            "flood_reqs": flood_reqs,
            "budgets": list(budgets),
            "slots": slots, "slot_len": slot_len,
            "slo_p95_ms": slo_ms,
        },
        "arms": {
            "continuous_admission": continuous_arm(True),
            "continuous_no_admission": continuous_arm(False),
            "static": static_arm(),
        },
    }


def prefix_storm_campaign(preset: str, quant: str | None, tenants: int,
                          reqs_per_tenant: int, flood_threads: int,
                          flood_reqs: int, slots: int, slot_len: int,
                          block_size: int, shared_len: int,
                          chaos_replicas: int,
                          overrides: dict | None = None) -> dict:
    """The r13 prefix-heavy storm: 80% of traffic opens with one long
    shared system prompt, replayed against two same-host arms sharing
    one set of weights:

    - ``paged``: the block-paged engine (``paged=True``) — the shared
      prefix is content-addressed in the block pool, so repeat prompts
      adopt the cached blocks and prefill only their short tail.
    - ``contiguous``: the r12 contiguous-slot engine (``paged=False``)
      on the SAME traffic — every request re-prefills the full prompt.

    Victims submit as ``interactive``, the flood as ``best_effort``,
    so the in-engine weighted queues (not gateway-side shedding) set
    the victim p95. Both arms run ``admission=False``: nothing sheds,
    every request completes, and useful tok/s compares the engines —
    not the admission policy. Each arm also answers one known prompt
    at the end and checks it bit-identical to solo ``generate_fused``.

    A third ``chaos`` pass runs the paged engine as a
    ``ServingFleet`` of N replicas and hard-kills the affinity owner
    mid-storm: every in-flight request must migrate and finish with
    exactly the tokens an uninterrupted run produces — zero failures.
    """
    import logging
    import urllib.error
    import urllib.request

    import jax
    import jax.numpy as jnp
    import numpy as np
    from werkzeug.serving import make_server

    logging.getLogger("werkzeug").setLevel(logging.ERROR)

    from kubeflow_rm_tpu.controlplane.serving_fleet import ServingFleet
    from kubeflow_rm_tpu.controlplane.webapps.serving import (
        ServingGateway, make_serving_app,
    )
    from kubeflow_rm_tpu.models import (
        ContinuousBatchingEngine, LlamaConfig, init_params,
    )
    from kubeflow_rm_tpu.models.generate import generate_fused

    cfg = getattr(LlamaConfig, preset)(**(overrides or {}))
    if quant:
        from kubeflow_rm_tpu.models.quantize import init_params_quantized
        params = init_params_quantized(cfg, jax.random.key(0),
                                       bits=4 if quant == "int4" else 8)
    else:
        params = init_params(cfg, jax.random.key(0))

    budgets = (4, 8)
    rng = np.random.default_rng(13)
    # the one system prompt 80% of traffic opens with; tails of 4-8
    # keep every shared request inside a single small suffix bucket
    shared_sys = rng.integers(1, cfg.vocab_size,
                              size=shared_len).tolist()

    def one_request():
        if rng.random() < 0.8:
            tail = rng.integers(1, cfg.vocab_size,
                                size=int(rng.integers(4, 9))).tolist()
            return shared_sys + tail, True
        p = rng.integers(1, cfg.vocab_size,
                         size=int(rng.integers(shared_len,
                                               shared_len + 9))).tolist()
        return p, False

    schedule: dict[str, list] = {}
    shared_n = total_n = 0
    for t in range(tenants):
        work = []
        for _ in range(reqs_per_tenant):
            p, is_shared = one_request()
            shared_n += is_shared
            total_n += 1
            work.append((p, int(budgets[rng.integers(0, len(budgets))]),
                         0.02))
        schedule[f"tenant-{t}"] = work
    flood_work = []
    for _ in range(flood_reqs):
        p, is_shared = one_request()
        shared_n += is_shared
        total_n += 1
        flood_work.append(
            (p, int(budgets[rng.integers(0, len(budgets))])))

    def run_storm(url: str) -> tuple[list[dict], float]:
        results: list[dict] = []
        lock = threading.Lock()

        def call(tenant, prompt, m, slo_class):
            body = {"prompt": prompt, "tenant": tenant,
                    "max_new_tokens": m, "slo_class": slo_class}
            t0 = time.perf_counter()
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            try:
                resp = json.loads(
                    urllib.request.urlopen(req, timeout=600).read())
                ok = bool(resp["tokens"])
            except urllib.error.HTTPError:
                ok = False
            lat = time.perf_counter() - t0
            with lock:
                results.append({"tenant": tenant, "ok": ok,
                                "useful": m if ok else 0,
                                "lat_ms": lat * 1e3})

        def victim(name):
            for prompt, m, gap in schedule[name]:
                call(name, prompt, m, "interactive")
                time.sleep(gap)

        def flooder(i):
            for j in range(i, len(flood_work), flood_threads):
                call("flood", *flood_work[j], "best_effort")

        ts = ([threading.Thread(target=victim, args=(n,))
               for n in schedule]
              + [threading.Thread(target=flooder, args=(i,))
                 for i in range(flood_threads)])
        t0 = time.perf_counter()
        for th in ts:
            th.start()
        for th in ts:
            th.join()
        return results, time.perf_counter() - t0

    def summarize(results, wall) -> dict:
        def pct(v, q):
            return round(v[min(len(v) - 1, int(q * (len(v) - 1)))], 1)

        per_tenant = {}
        for name in sorted({r["tenant"] for r in results}):
            lats = sorted(r["lat_ms"] for r in results
                          if r["tenant"] == name and r["ok"])
            per_tenant[name] = {
                "ok": len(lats),
                "p50_ms": pct(lats, 0.50) if lats else None,
                "p95_ms": pct(lats, 0.95) if lats else None,
            }
        victim_p95 = [v["p95_ms"] for k, v in per_tenant.items()
                      if k != "flood" and v["p95_ms"] is not None]
        return {
            "wall_s": round(wall, 2),
            "ok": sum(1 for r in results if r["ok"]),
            "failed": sum(1 for r in results if not r["ok"]),
            "useful_tokens": sum(r["useful"] for r in results),
            "useful_tok_per_s": round(
                sum(r["useful"] for r in results) / wall, 1),
            "victim_p95_ms_worst": max(victim_p95) if victim_p95
            else None,
            "per_tenant": per_tenant,
        }

    def solo(prompt, budget):
        ref = generate_fused(params, cfg,
                             jnp.asarray([prompt], jnp.int32),
                             max_new_tokens=budget, max_len=slot_len)
        return np.asarray(ref)[0, len(prompt):].tolist()

    check_prompt = shared_sys + [1, 2, 3, 4]
    check_want = solo(check_prompt, 8)

    def engine_arm(paged: bool) -> dict:
        engine = ContinuousBatchingEngine(params, cfg, slots=slots,
                                          slot_len=slot_len, paged=paged,
                                          block_size=block_size)
        gw = ServingGateway(engine, max_queue=100_000, admission=False)
        app = make_serving_app(gw, cfg)
        httpd = make_server("127.0.0.1", 0, app, threaded=True)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{httpd.server_port}/generate"

        def post(prompt, m):
            req = urllib.request.Request(
                url, data=json.dumps(
                    {"prompt": prompt, "tenant": "warm",
                     "max_new_tokens": m}).encode(),
                headers={"Content-Type": "application/json"})
            return json.loads(
                urllib.request.urlopen(req, timeout=600).read())

        # warm BOTH prefill paths before the timed region: a full-miss
        # prompt (big bucket, registers the shared chain) and a
        # shared-prefix sibling (small suffix bucket on the paged arm)
        post(list(shared_sys) + [9, 9, 9, 9], 4)
        post(list(shared_sys) + [9, 9, 9, 8], 4)          # 4-token tail
        post(list(shared_sys) + [9, 8, 7, 6, 5, 4, 3, 2], 4)  # 8-token
        post([1 + i % (cfg.vocab_size - 2)
              for i in range(shared_len + 3)], 4)

        results, wall = run_storm(url)
        got = post(check_prompt, 8)["tokens"]
        st = engine.stats()
        snap = gw.snapshot()
        httpd.shutdown()
        gw.close()
        out = summarize(results, wall)
        out.update({
            "paged": paged,
            "sample_exact": got == check_want,
            "batch_occupancy": round(snap["batch_occupancy"], 3),
            "decode_steps": snap["decode_steps"],
        })
        if paged:
            out.update({
                "prefix_hit_ratio": st["prefix_hit_ratio"],
                "prefix_hit_tokens": st["prefix_hit_tokens"],
                "cow_forks": st["cow_forks"],
                "block_evictions": st["evictions"],
            })
        return out

    def chaos_arm() -> dict:
        fleet = ServingFleet({
            f"r{i}": ServingGateway(
                ContinuousBatchingEngine(params, cfg, slots=slots,
                                         slot_len=slot_len,
                                         block_size=block_size),
                max_queue=100_000, admission=False)
            for i in range(chaos_replicas)})
        try:
            prompts = [shared_sys + [7, 7, 7, i] for i in range(6)] \
                + [[3 + i % (cfg.vocab_size - 4)
                    for i in range(shared_len + 4)], shared_sys[::-1]]
            want = {i: solo(p, 12) for i, p in enumerate(prompts)}
            jobs = [(i % len(prompts)) for i in range(3 * len(prompts))]
            results: list = [None] * len(jobs)

            def go(j):
                results[j] = fleet.submit_and_wait(
                    "chaos", list(prompts[jobs[j]]), max_new_tokens=12,
                    slo_class="interactive")

            victim = fleet.route(prompts[0])
            ts = [threading.Thread(target=go, args=(j,))
                  for j in range(len(jobs))]
            t0 = time.perf_counter()
            for th in ts:
                th.start()
            # hard-kill the affinity owner the moment it holds work
            gw = fleet.gateways[victim]
            deadline = time.monotonic() + 60
            while (not gw.engine.active_slots
                   and time.monotonic() < deadline):
                time.sleep(0.001)
            fleet.kill(victim)
            for th in ts:
                th.join()
            wall = time.perf_counter() - t0
            failed = sum(1 for r in results
                         if r is None or r[0] is None)
            exact = sum(1 for j, r in enumerate(results)
                        if r is not None and r[0] == want[jobs[j]])
            return {
                "replicas": chaos_replicas,
                "killed": victim,
                "requests": len(jobs),
                "failed": failed,
                "exact": exact,
                "all_exact": exact == len(jobs),
                "migrations": fleet.migrations,
                "wall_s": round(wall, 2),
            }
        finally:
            fleet.close()

    paged = engine_arm(True)
    contiguous = engine_arm(False)
    chaos = chaos_arm()
    speedup = round(paged["useful_tok_per_s"]
                    / max(1e-9, contiguous["useful_tok_per_s"]), 2)
    return {
        "metric": "serving_prefix_storm",
        "model": f"llama-{preset}" + (f" {quant}" if quant else " bf16")
                 + (f" {overrides}" if overrides else ""),
        "device": _device_tag(),
        "workload": {
            "victim_tenants": tenants,
            "reqs_per_tenant": reqs_per_tenant,
            "flood_threads": flood_threads,
            "flood_reqs": flood_reqs,
            "shared_prefix_len": shared_len,
            "shared_fraction": round(shared_n / max(1, total_n), 3),
            "budgets": list(budgets),
            "slots": slots, "slot_len": slot_len,
            "block_size": block_size,
        },
        "arms": {"paged": paged, "contiguous": contiguous},
        "paged_speedup": speedup,
        "paged_ge_2x": speedup >= 2.0,
        "chaos": chaos,
    }


def disagg_storm_campaign(preset: str, quant: str | None, tenants: int,
                          reqs_per_tenant: int, flood_threads: int,
                          flood_reqs: int, slots: int, slot_len: int,
                          block_size: int, shared_len: int,
                          replicas: int, store_mb: int,
                          long_len: int | None = None,
                          num_blocks: int | None = None,
                          overrides: dict | None = None) -> dict:
    """The r17 disaggregated-serving storm: interactive shared-prefix
    victims plus long-prefill batch/best_effort flooders (every few
    flood requests decode speculatively), replayed against two
    same-host fleet arms sharing one set of weights:

    - ``symmetric``: the r13 fleet — N identical replicas, prefix-
      affinity routing, per-replica prefix caches, no shared state.
    - ``disagg``: 1 prefill replica + N-1 decode replicas. Long
      prompts prefill on the prefill tier into block chains published
      to the fleet-wide GlobalBlockStore; decode replicas are picked
      by queue depth and adopt chains by hash, and hot ref-0 chains
      promote back to the store on local eviction.

    EVERY request — storm, chaos wave, and probe — is checked
    token-exact against solo ``generate_fused`` on the same weights;
    the throughput/latency claims are conditional on bit-identical
    output. After the timed storm each arm takes two hard kills while
    a chaos wave is in flight: the prefill replica (the shared-prefix
    affinity owner on the symmetric arm) and the decode replica
    holding the most shared-prefix blocks. Every in-flight request
    must migrate and finish exactly. A post-kill probe (shared prefix
    + fresh tail) then measures where the prefix went: the symmetric
    arm buried it with the killed owner, the disagg arm re-adopts it
    from the store."""
    import logging

    import jax
    import jax.numpy as jnp
    import numpy as np

    logging.getLogger("werkzeug").setLevel(logging.ERROR)

    from kubeflow_rm_tpu.controlplane.serving_fleet import ServingFleet
    from kubeflow_rm_tpu.controlplane.webapps.serving import ServingGateway
    from kubeflow_rm_tpu.models import (
        ContinuousBatchingEngine, LlamaConfig, init_params,
    )
    from kubeflow_rm_tpu.models.generate import generate_fused

    if replicas < 3:
        raise ValueError("disagg_storm kills two replicas mid-wave; "
                         "--replicas must be >= 3")
    cfg = getattr(LlamaConfig, preset)(**(overrides or {}))
    if quant:
        from kubeflow_rm_tpu.models.quantize import init_params_quantized
        params = init_params_quantized(cfg, jax.random.key(0),
                                       bits=4 if quant == "int4" else 8)
    else:
        params = init_params(cfg, jax.random.key(0))

    if long_len is None:
        long_len = min(2 * shared_len, slot_len - 24)
    rng = np.random.default_rng(17)
    vocab = cfg.vocab_size
    shared_sys = rng.integers(1, vocab, size=shared_len).tolist()

    # finite prompt pools so EVERY request has a precomputed greedy
    # reference — exactness is asserted for the whole storm, not for
    # one sample at the end
    victim_pool = [shared_sys
                   + rng.integers(1, vocab, size=4).tolist()
                   for _ in range(8)]
    long_pool = [rng.integers(1, vocab, size=long_len).tolist()
                 for _ in range(8)]
    chaos_pool = [rng.integers(1, vocab, size=shared_len + 6).tolist()
                  for _ in range(4)]
    probe = shared_sys + rng.integers(1, vocab, size=5).tolist()

    budgets = (4, 8)
    victim_jobs: dict[str, list] = {}
    for t in range(tenants):
        victim_jobs[f"tenant-{t}"] = [
            (victim_pool[int(rng.integers(0, len(victim_pool)))],
             int(budgets[int(rng.integers(0, len(budgets)))]), 0.02)
            for _ in range(reqs_per_tenant)]
    # long-prefill flood: batch/best_effort, every 4th speculative
    flood_jobs = [
        (long_pool[int(rng.integers(0, len(long_pool)))], 8,
         "best_effort" if j % 2 else "batch", j % 4 == 0)
        for j in range(flood_reqs)]

    def solo(prompt, budget):
        ref = generate_fused(params, cfg,
                             jnp.asarray([prompt], jnp.int32),
                             max_new_tokens=budget, max_len=slot_len)
        return np.asarray(ref)[0, len(prompt):].tolist()

    # greedy decode is prefix-stable, so one reference at the largest
    # budget a prompt is ever asked for covers every smaller ask
    want: dict[tuple, list] = {}

    def want_for(prompt, budget):
        key = tuple(prompt)
        if key not in want or len(want[key]) < budget:
            want[key] = solo(prompt, budget)
        return want[key][:budget]

    for p in victim_pool:
        want_for(p, max(budgets))
    for p in long_pool:
        want_for(p, 8)
    for p in chaos_pool:
        want_for(p, 12)
    want_for(probe, 8)

    eng_kw: dict = dict(slots=slots, slot_len=slot_len, paged=True,
                        block_size=block_size)
    if num_blocks:
        eng_kw["num_blocks"] = num_blocks

    def run_arm(disagg: bool) -> dict:
        if disagg:
            names = (["prefill-0"]
                     + [f"decode-{i}" for i in range(replicas - 1)])
            roles = {n: ("prefill" if n.startswith("prefill")
                         else "decode") for n in names}
        else:
            names = [f"r{i}" for i in range(replicas)]
            roles = None
        gws = {n: ServingGateway(
            ContinuousBatchingEngine(params, cfg, **eng_kw),
            max_queue=100_000, admission=False) for n in names}
        fleet = (ServingFleet(gws, roles=roles,
                              store_bytes=store_mb << 20)
                 if roles else ServingFleet(gws))
        try:
            results: list[dict] = []
            lock = threading.Lock()

            def call(tenant, prompt, m, slo, spec=False):
                t0 = time.perf_counter()
                toks, _info = fleet.submit_and_wait(
                    tenant, list(prompt), max_new_tokens=m,
                    slo_class=slo, speculative=spec)
                lat = (time.perf_counter() - t0) * 1e3
                ok = toks is not None
                with lock:
                    results.append({
                        "tenant": tenant, "ok": ok,
                        "exact": ok and toks == want_for(prompt, m),
                        "useful": m if ok else 0, "lat_ms": lat,
                        "interactive": slo == "interactive",
                        "speculative": spec})

            # warm the compile buckets (and each arm's prefix state)
            # before the timed region — including the speculative
            # path, whose first compile would otherwise land inside
            # whichever arm runs first
            call("warm", shared_sys + [9, 9, 9, 9], 4, "interactive")
            call("warm", long_pool[0], 4, "batch")
            call("warm", long_pool[1], 4, "best_effort", True)
            with lock:
                results.clear()

            def victim(name):
                for prompt, m, gap in victim_jobs[name]:
                    call(name, prompt, m, "interactive")
                    time.sleep(gap)

            def flooder(i):
                for j in range(i, len(flood_jobs), flood_threads):
                    p, m, slo, spec = flood_jobs[j]
                    call("flood", p, m, slo, spec)

            ts = ([threading.Thread(target=victim, args=(n,))
                   for n in victim_jobs]
                  + [threading.Thread(target=flooder, args=(i,))
                     for i in range(flood_threads)])
            t0 = time.perf_counter()
            for th in ts:
                th.start()
            for th in ts:
                th.join()
            wall = time.perf_counter() - t0

            def pct(v, q):
                return round(
                    v[min(len(v) - 1, int(q * (len(v) - 1)))], 1)

            inter = sorted(r["lat_ms"] for r in results
                           if r["interactive"] and r["ok"])
            per_tenant_p95 = []
            for name in victim_jobs:
                lats = sorted(r["lat_ms"] for r in results
                              if r["tenant"] == name and r["ok"])
                if lats:
                    per_tenant_p95.append(pct(lats, 0.95))
            arm = {
                "wall_s": round(wall, 2),
                "ok": sum(1 for r in results if r["ok"]),
                "failed": sum(1 for r in results if not r["ok"]),
                "exact": sum(1 for r in results if r["exact"]),
                "all_exact": all(r["exact"] for r in results),
                "useful_tokens": sum(r["useful"] for r in results),
                "useful_tok_per_s": round(
                    sum(r["useful"] for r in results) / wall, 1),
                "interactive_p50_ms": pct(inter, 0.50) if inter
                else None,
                "interactive_p95_ms": pct(inter, 0.95) if inter
                else None,
                "victim_p95_ms_worst": max(per_tenant_p95)
                if per_tenant_p95 else None,
                "speculative_requests": sum(
                    1 for r in results if r["speculative"]),
            }

            # --- chaos: two kills while a wave is in flight ---------
            if disagg:
                kill_first = "prefill-0"
                decs = [n for n in names if roles[n] == "decode"]
                kill_second = max(
                    decs, key=lambda n: gws[n].chain_coverage(probe))
            else:
                kill_first = fleet.route(list(probe))
                rest = [n for n in names if n != kill_first]
                kill_second = max(
                    rest, key=lambda n: gws[n].chain_coverage(probe))
            chaos_jobs = [chaos_pool[i % len(chaos_pool)]
                          for i in range(2 * len(chaos_pool))]
            chaos_res: list = [None] * len(chaos_jobs)

            def go(j):
                chaos_res[j] = fleet.submit_and_wait(
                    "chaos", list(chaos_jobs[j]), max_new_tokens=12,
                    slo_class="batch")

            cts = [threading.Thread(target=go, args=(j,))
                   for j in range(len(chaos_jobs))]
            for th in cts:
                th.start()
            deadline = time.monotonic() + 60
            while (not any(gws[n].engine.active_slots
                           or gws[n].engine.queue_depth
                           for n in names)
                   and time.monotonic() < deadline):
                time.sleep(0.001)
            fleet.kill(kill_first)
            fleet.kill(kill_second)
            for th in cts:
                th.join()
            failed = sum(1 for r in chaos_res
                         if r is None or r[0] is None)
            exact = sum(
                1 for j, r in enumerate(chaos_res)
                if r is not None
                and r[0] == want_for(chaos_jobs[j], 12))
            arm["chaos"] = {
                "killed": [kill_first, kill_second],
                "requests": len(chaos_jobs),
                "failed": failed,
                "exact": exact,
                "all_exact": exact == len(chaos_jobs),
                "migrations": fleet.migrations,
            }

            # --- post-kill probe: did the shared prefix survive? ----
            survivors = [n for n in names
                         if n not in (kill_first, kill_second)]

            def hit_tokens():
                return sum(gws[n].engine.stats()
                           .get("prefix_hit_tokens", 0) or 0
                           for n in survivors)

            before = hit_tokens()
            store_hits0 = (fleet.store.stats()["hits"]
                           if fleet.store else 0)
            t0 = time.perf_counter()
            ptoks, _ = fleet.submit_and_wait(
                "probe", list(probe), max_new_tokens=8,
                slo_class="interactive")
            probe_ms = (time.perf_counter() - t0) * 1e3
            arm["post_kill_probe"] = {
                "hit_ratio": round(max(0.0, min(1.0,
                    (hit_tokens() - before) / (len(probe) - 1))), 3),
                "exact": ptoks == want_for(probe, 8),
                "lat_ms": round(probe_ms, 1),
                "store_hits_delta": (
                    fleet.store.stats()["hits"] - store_hits0
                    if fleet.store else 0),
            }
            if disagg:
                snap = fleet.snapshot()
                arm["handoffs"] = snap["handoffs"]
                arm["store"] = snap["store"]
            return arm
        finally:
            fleet.close()

    symmetric = run_arm(False)
    disagg = run_arm(True)
    return {
        "metric": "serving_disagg_storm",
        "model": f"llama-{preset}" + (f" {quant}" if quant else " bf16")
                 + (f" {overrides}" if overrides else ""),
        "device": _device_tag(),
        "workload": {
            "victim_tenants": tenants,
            "reqs_per_tenant": reqs_per_tenant,
            "flood_threads": flood_threads,
            "flood_reqs": flood_reqs,
            "shared_prefix_len": shared_len,
            "long_prefill_len": long_len,
            "budgets": list(budgets),
            "slots": slots, "slot_len": slot_len,
            "block_size": block_size,
            "num_blocks": num_blocks,
            "replicas": replicas,
            "store_mb": store_mb,
        },
        "arms": {"symmetric": symmetric, "disagg": disagg},
        "disagg_wins_interactive_p95": bool(
            disagg["interactive_p95_ms"] is not None
            and symmetric["interactive_p95_ms"] is not None
            and disagg["interactive_p95_ms"]
            <= symmetric["interactive_p95_ms"]),
        "disagg_wins_useful_tok": bool(
            disagg["useful_tok_per_s"]
            >= symmetric["useful_tok_per_s"]),
        "prefix_survives_death": bool(
            disagg["post_kill_probe"]["hit_ratio"]
            > max(0.5, symmetric["post_kill_probe"]["hit_ratio"])),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("campaign", choices=["serve", "spec", "decode",
                                         "storm", "prefix_storm",
                                         "disagg_storm"])
    ap.add_argument("--preset", default="bench_1b")
    ap.add_argument("--quant", choices=["int8", "int4"], default=None)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--train-steps", type=int, default=60)
    # decode campaign: measurement shape + host-sized config overrides
    # (recorded in the output — a CPU host can't time 7B honestly)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--dim", type=int, default=None)
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--hidden", type=int, default=None)
    ap.add_argument("--seq-len", type=int, default=None)
    # storm campaign knobs
    ap.add_argument("--tenants", type=int, default=6)
    ap.add_argument("--reqs-per-tenant", type=int, default=8)
    ap.add_argument("--flood-threads", type=int, default=12)
    ap.add_argument("--flood-reqs", type=int, default=72)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--slot-len", type=int, default=128)
    ap.add_argument("--slo-ms", type=float, default=2000.0)
    ap.add_argument("--qps", type=float, default=25.0,
                    help="per-tenant admitted request rate (storm)")
    ap.add_argument("--burst", type=int, default=30)
    # prefix_storm campaign knobs
    ap.add_argument("--block-size", type=int, default=16,
                    help="paged-KV block size (prefix_storm)")
    ap.add_argument("--shared-len", type=int, default=88,
                    help="shared system-prompt length (prefix_storm)")
    ap.add_argument("--replicas", type=int, default=3,
                    help="fleet size for the chaos arm (prefix_storm) "
                         "/ total fleet size per arm (disagg_storm)")
    # disagg_storm campaign knobs
    ap.add_argument("--store-mb", type=int, default=64,
                    help="GlobalBlockStore byte budget in MiB "
                         "(disagg_storm)")
    ap.add_argument("--long-len", type=int, default=None,
                    help="long-prefill prompt length; default "
                         "min(2*shared_len, slot_len-24) (disagg_storm)")
    ap.add_argument("--num-blocks", type=int, default=None,
                    help="per-engine KV pool size in blocks; small "
                         "pools force eviction + store promotion "
                         "(disagg_storm)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON to this path")
    ap.add_argument("--jaxcheck-out", default=None,
                    help="enable the jit-cache sentinel for the "
                         "campaign and write its report (signature "
                         "counts, limits, witnesses) here; exits "
                         "nonzero if any entry exceeded its bucket "
                         "bound")
    args = ap.parse_args()
    if args.jaxcheck_out:
        from kubeflow_rm_tpu.analysis.jaxcheck import recompile
        recompile.set_enabled(True)
        recompile.reset()
    if args.campaign == "serve":
        out = serve_campaign(args.preset, args.quant, args.requests,
                             args.concurrency, args.max_new)
    elif args.campaign == "spec":
        out = spec_campaign(args.preset, args.train_steps, args.max_new)
    elif args.campaign == "decode":
        overrides = {k: v for k, v in {
            "dim": args.dim, "n_layers": args.layers,
            "hidden_dim": args.hidden,
            "max_seq_len": args.seq_len}.items() if v is not None}
        out = decode_campaign(args.preset, args.batch, args.prompt_len,
                              args.max_new, overrides)
    elif args.campaign == "prefix_storm":
        overrides = {k: v for k, v in {
            "dim": args.dim, "n_layers": args.layers,
            "hidden_dim": args.hidden,
            "max_seq_len": args.seq_len}.items() if v is not None}
        out = prefix_storm_campaign(
            args.preset, args.quant, args.tenants,
            args.reqs_per_tenant, args.flood_threads, args.flood_reqs,
            args.slots, args.slot_len, args.block_size,
            args.shared_len, args.replicas, overrides)
    elif args.campaign == "disagg_storm":
        overrides = {k: v for k, v in {
            "dim": args.dim, "n_layers": args.layers,
            "hidden_dim": args.hidden,
            "max_seq_len": args.seq_len}.items() if v is not None}
        out = disagg_storm_campaign(
            args.preset, args.quant, args.tenants,
            args.reqs_per_tenant, args.flood_threads, args.flood_reqs,
            args.slots, args.slot_len, args.block_size,
            args.shared_len, args.replicas, args.store_mb,
            long_len=args.long_len, num_blocks=args.num_blocks,
            overrides=overrides)
    else:
        overrides = {k: v for k, v in {
            "dim": args.dim, "n_layers": args.layers,
            "hidden_dim": args.hidden,
            "max_seq_len": args.seq_len}.items() if v is not None}
        out = storm_campaign(args.preset, args.quant, args.tenants,
                             args.reqs_per_tenant, args.flood_threads,
                             args.flood_reqs, args.slots, args.slot_len,
                             args.slo_ms, args.qps, args.burst,
                             overrides)
    # shared artifact header: ratchet.py refuses to diff storms whose
    # arm flags (campaign/preset/load shape) disagree
    import os

    from kubeflow_rm_tpu.controlplane.obs.runmeta import build_run_meta
    interleave = os.environ.get("KFRM_RUN_INTERLEAVE")
    out["run_meta"] = build_run_meta(
        "serve_bench",
        {
            "campaign": args.campaign, "preset": args.preset,
            "quant": args.quant, "tenants": args.tenants,
            "reqs_per_tenant": args.reqs_per_tenant,
            "flood_threads": args.flood_threads, "slots": args.slots,
            "slo_ms": args.slo_ms, "qps": args.qps,
            "slot_len": args.slot_len, "block_size": args.block_size,
            "shared_len": args.shared_len, "replicas": args.replicas,
            "store_mb": args.store_mb, "long_len": args.long_len,
            "num_blocks": args.num_blocks,
        },
        interleave_index=int(interleave) if interleave else None)
    print(json.dumps(out))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    if args.jaxcheck_out:
        from kubeflow_rm_tpu.analysis.jaxcheck import recompile
        findings = recompile.over_limit()
        audit = {
            "run_meta": out.get("run_meta"),
            "report": recompile.report(),
            "over_limit": findings,
        }
        with open(args.jaxcheck_out, "w") as f:
            json.dump(audit, f, indent=1)
        if findings:
            print(f"jaxcheck: {len(findings)} jit entries over their "
                  f"recompile limit (see {args.jaxcheck_out})",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
