#!/usr/bin/env python3
"""Round-5 single-chip sweep driver.

Runs each bench config in a FRESH process (leftover HBM state poisons
later configs — see .claude/skills/verify) and appends one JSON line
per config to ``BENCH_SWEEP_r05_raw.jsonl``. Two campaigns:

- ``scale``: the MFU-vs-scale ladder (full fine-tune with the factored
  optimizer at 1.2B/2.1B/3.1B) — does the 40% north-star line hold as
  params grow? (VERDICT r4 "what's weak" #1)
- ``qlora``: the 7B QLoRA recipe tuned the way the 1.2B bench was
  (microbatch/accum/remat), int8 and int4 bases.

Usage: python benchmarks/sweep_r05.py [scale|qlora|decode7b|all]
"""

import json
import subprocess
import sys
import time

SCALE = [
    # preset, args — each row one fresh process
    ("bench_1b", ["--optim", "adafactor", "--accum", "64", "--steps", "4"]),
    ("bench_2b", ["--optim", "adafactor", "--accum", "32", "--steps", "4"]),
    ("bench_2b", ["--optim", "adafactor", "--accum", "64", "--steps", "4"]),
    ("bench_2b", ["--optim", "adafactor", "--accum", "64", "--steps", "4",
                  "--batch", "64"]),           # mb1
    ("bench_3b", ["--optim", "adafactor", "--accum", "32", "--steps", "3",
                  "--batch", "32"]),           # mb1 dots
    ("bench_3b", ["--optim", "adafactor", "--accum", "32", "--steps", "3",
                  "--batch", "32", "--remat", "full"]),
    ("bench_3b", ["--optim", "adafactor", "--accum", "32", "--steps", "3",
                  "--batch", "64", "--remat", "full"]),  # mb2 full
    ("bench_3b", ["--optim", "adafactor", "--accum", "64", "--steps", "3",
                  "--batch", "64", "--remat", "full"]),  # mb1 deeper accum
]

QLORA = [
    ("llama2_7b", ["--lora-rank", "16", "--base-quant", "int8",
                   "--seq", "2048", "--steps", "3", "--remat", "full",
                   "--batch", "1", "--accum", "1"]),     # r4 repro point
    ("llama2_7b", ["--lora-rank", "16", "--base-quant", "int8",
                   "--seq", "2048", "--steps", "3", "--remat", "full",
                   "--batch", "8", "--accum", "4"]),     # mb2
    ("llama2_7b", ["--lora-rank", "16", "--base-quant", "int8",
                   "--seq", "2048", "--steps", "3", "--remat", "full",
                   "--batch", "16", "--accum", "4"]),    # mb4
    ("llama2_7b", ["--lora-rank", "16", "--base-quant", "int8",
                   "--seq", "2048", "--steps", "3", "--remat", "full",
                   "--batch", "32", "--accum", "8"]),    # mb4 deeper
    ("llama2_7b", ["--lora-rank", "16", "--base-quant", "int8",
                   "--seq", "2048", "--steps", "3", "--remat", "attn",
                   "--batch", "8", "--accum", "4"]),     # mb2 attn-save
    ("llama2_7b", ["--lora-rank", "16", "--base-quant", "int4",
                   "--seq", "2048", "--steps", "3", "--remat", "full",
                   "--batch", "8", "--accum", "4"]),     # int4 base mb2
    ("llama2_7b", ["--lora-rank", "16", "--base-quant", "int4",
                   "--seq", "2048", "--steps", "3", "--remat", "full",
                   "--batch", "16", "--accum", "4"]),    # int4 mb4
    ("llama2_7b", ["--lora-rank", "16", "--base-quant", "int8",
                   "--seq", "4096", "--steps", "3", "--remat", "full",
                   "--batch", "4", "--accum", "4"]),     # long-seq point
]

SCALE2 = [
    # follow-up after the first ladder pass: bench_2b mb2 OOMs under
    # "dots" (stacked per-layer saves + 62% fragmentation) -> try the
    # cheaper-save policies; bench_3b (3.1B) is past the single-chip
    # wall at ANY remat (state alone ~12.6G) -> bench_2_7b is the
    # largest-that-fits rung
    ("bench_2b", ["--optim", "adafactor", "--accum", "32", "--steps", "4",
                  "--remat", "full"]),               # mb2 full
    ("bench_2b", ["--optim", "adafactor", "--accum", "32", "--steps", "4",
                  "--remat", "attn+mlp"]),           # mb2 named-save
    ("bench_2_7b", ["--optim", "adafactor", "--accum", "32", "--steps", "3",
                    "--batch", "32"]),               # mb1 dots
    ("bench_2_7b", ["--optim", "adafactor", "--accum", "32", "--steps", "3",
                    "--batch", "32", "--remat", "full"]),
    ("bench_2_7b", ["--optim", "adafactor", "--accum", "32", "--steps", "3",
                    "--batch", "64", "--remat", "full"]),  # mb2 full
    ("bench_2_7b", ["--optim", "adafactor", "--accum", "64", "--steps", "3",
                    "--batch", "64", "--remat", "full"]),
]

DECODE7B = [
    ("llama2_7b", ["--decode", "--quant", "int4"]),
    ("llama2_7b", ["--decode", "--quant", "int4", "--batch", "8"]),
    ("llama2_7b", ["--decode", "--quant", "int8"]),
    ("llama2_7b", ["--decode", "--quant", "int8", "--batch", "8"]),
    ("llama2_7b", ["--decode", "--quant", "int8", "--batch", "16"]),
]


def run(campaign: str, rows, out_path: str):
    for preset, extra in rows:
        cmd = [sys.executable, "bench.py", "--preset", preset] + extra
        t0 = time.time()
        try:
            p = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=1800)
            line = p.stdout.strip().splitlines()[-1] if p.stdout.strip() \
                else ""
            rec = json.loads(line) if line.startswith("{") else {
                "error": (p.stderr or "no output")[-800:]}
        except subprocess.TimeoutExpired:
            rec = {"error": "timeout 1800s"}
        except Exception as e:  # noqa: BLE001 - log and continue sweeping
            rec = {"error": repr(e)}
        rec["campaign"] = campaign
        rec["cmd"] = " ".join(cmd[1:])
        rec["wall_s"] = round(time.time() - t0, 1)
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    out = "BENCH_SWEEP_r05_raw.jsonl"
    if which in ("scale", "all"):
        run("scale", SCALE, out)
    if which in ("qlora", "all"):
        run("qlora", QLORA, out)
    if which in ("scale2", "all2"):
        run("scale", SCALE2, out)
    if which in ("decode7b", "all"):
        run("decode7b", DECODE7B, out)


if __name__ == "__main__":
    main()
