#!/usr/bin/env python3
"""Release tooling — the ``releasing/`` machinery of the reference
(version bump + image-tag pinning + tag instructions), one script.

    python releasing/release.py prepare v0.4.0 [--dry-run]
        - validates the version string
        - writes releasing/VERSION
        - pins every kustomize image tag (manifests/default) and the
          spawner config's image tags to the release version
        - prints the git tag / push steps (never runs git itself)

    python releasing/release.py check
        - verifies VERSION, the kustomize pin, and the spawner config
          agree (CI guard; exits non-zero on drift)

The image DAG itself is built/pushed by CI on the tag
(.github/workflows/image_build.yaml) — this script only moves the
version forward consistently.
"""

from __future__ import annotations

import argparse
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
VERSION_FILE = ROOT / "releasing/VERSION"
KUSTOMIZATION = ROOT / "manifests/default/kustomization.yaml"
SPAWNER_CONFIG = (
    ROOT / "kubeflow_rm_tpu/controlplane/webapps/spawner_ui_config.yaml")

VERSION_RE = re.compile(r"^v\d+\.\d+\.\d+(-rc\.\d+)?$")


def current_version() -> str:
    return VERSION_FILE.read_text().strip()


def _pin_kustomization(version: str, dry: bool) -> None:
    text = KUSTOMIZATION.read_text()
    new = re.sub(r"newTag: \S+", f"newTag: {version}", text)
    _write(KUSTOMIZATION, new, dry)


def _pin_spawner_images(version: str, dry: bool) -> None:
    """Image options in the spawner config track the release so fresh
    deployments offer the pinned, CI-built tags."""
    text = SPAWNER_CONFIG.read_text()
    new = re.sub(r"(ghcr\.io/kubeflow-rm-tpu/[a-z0-9-]+):\S+",
                 rf"\1:{version}", text)
    _write(SPAWNER_CONFIG, new, dry)


def _write(path: pathlib.Path, content: str, dry: bool) -> None:
    import os
    rel = os.path.relpath(path, ROOT)
    if dry:
        print(f"would write {rel}")
    else:
        path.write_text(content)
        print(f"wrote {rel}")


def cmd_prepare(version: str, dry: bool) -> int:
    if not VERSION_RE.match(version):
        print(f"bad version {version!r} (want vX.Y.Z[-rc.N])",
              file=sys.stderr)
        return 2
    _write(VERSION_FILE, version + "\n", dry)
    _pin_kustomization(version, dry)
    _pin_spawner_images(version, dry)
    print(f"""
release {version} prepared. Next:
  git add -A && git commit -m "Release {version}"
  git tag {version} && git push origin main {version}
CI builds and pushes the image DAG for the tag
(.github/workflows/image_build.yaml); deploy with
  kustomize build manifests/overlays/standalone | kubectl apply -f -""")
    return 0


def cmd_check() -> int:
    version = current_version()
    problems = []
    if not VERSION_RE.match(version) and version != "latest":
        problems.append(f"VERSION {version!r} is not vX.Y.Z")
    kust = KUSTOMIZATION.read_text()
    tags = set(re.findall(r"newTag: (\S+)", kust))
    if tags - {version, "latest"}:
        problems.append(f"kustomize newTag {tags} != VERSION {version}")
    spawn_tags = set(re.findall(
        r"ghcr\.io/kubeflow-rm-tpu/[a-z0-9-]+:(\S+)",
        SPAWNER_CONFIG.read_text()))
    if spawn_tags - {version, "latest"}:
        problems.append(
            f"spawner config tags {spawn_tags} != VERSION {version}")
    for p in problems:
        print("DRIFT:", p, file=sys.stderr)
    print("ok" if not problems else f"{len(problems)} problem(s)")
    return 1 if problems else 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    prep = sub.add_parser("prepare")
    prep.add_argument("version")
    prep.add_argument("--dry-run", action="store_true")
    sub.add_parser("check")
    args = ap.parse_args()
    if args.cmd == "prepare":
        return cmd_prepare(args.version, args.dry_run)
    return cmd_check()


if __name__ == "__main__":
    sys.exit(main())
