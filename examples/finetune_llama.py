#!/usr/bin/env python3
"""Fine-tune a Llama on the slice this notebook was spawned with.

The end-to-end in-notebook workflow the whole platform exists to serve
(SURVEY.md §7's final conformance artifact), usable as a script or
pasted cell-by-cell into a jupyter-jax notebook:

1. join the slice — the webhook-injected rendezvous env
   (``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES``) becomes one
   ``jax.distributed`` job;
2. build the mesh (fsdp × tp over however many chips showed up);
3. load weights — an HF checkpoint via ``from_hf_llama``, or a preset;
4. stream packed batches from jsonl shards, host-disjoint;
5. ``fit()`` with gradient accumulation, orbax checkpointing, live MFU;
6. sample a continuation and (optionally) export back to HF format.

Tiny smoke (CPU mesh, synthetic data — what tests/test_examples.py
runs):   python examples/finetune_llama.py --preset tiny --steps 4
Real slice (v5p-8 north star):
    python examples/finetune_llama.py --preset llama2_7b \
        --hf-model meta-llama/Llama-2-7b-hf --data 'gs://bucket/*.jsonl' \
        --batch 8 --grad-accum 4 --seq-len 4096 --fsdp 4 --tp 2
"""

from __future__ import annotations

import argparse
import glob
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="tiny",
                    help="LlamaConfig preset (tiny/bench_1b/llama2_7b/...)")
    ap.add_argument("--hf-model", default=None,
                    help="HF model id/path to load weights from")
    ap.add_argument("--data", default=None,
                    help="glob of pre-tokenized jsonl shards "
                         "(default: synthetic)")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--grad-accum", type=int, default=1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--fsdp", type=int, default=0,
                    help="0 = all local devices")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--lora-rank", type=int, default=0,
                    help="train rank-r adapters instead of full "
                         "fine-tuning (frozen base: no grads/moments)")
    qbase = ap.add_mutually_exclusive_group()
    qbase.add_argument("--int8-base", action="store_true",
                       help="with --lora-rank: quantize the frozen "
                            "base to int8 (the 7B-on-one-v5e recipe)")
    qbase.add_argument("--int4-base", action="store_true",
                       help="with --lora-rank: pack the frozen base "
                            "to int4 (~3.6 GB for 7B — the "
                            "QLoRA-style maximum-headroom recipe)")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--tb-logdir", default=None,
                    help="write tensorboard events here (point a "
                         "Tensorboard CR at the same pvc:// path)")
    ap.add_argument("--export-hf", default=None,
                    help="write the tuned weights as an HF state_dict "
                         "(.npz) here")
    ap.add_argument("--sample", default=True, action=argparse.
                    BooleanOptionalAction,
                    help="greedy-decode a continuation at the end")
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from kubeflow_rm_tpu.models import LlamaConfig, generate_fused
    from kubeflow_rm_tpu.parallel import MeshConfig, make_mesh
    from kubeflow_rm_tpu.parallel.distributed import initialize
    from kubeflow_rm_tpu.training import TrainConfig
    from kubeflow_rm_tpu.training.optim import OptimConfig
    from kubeflow_rm_tpu.training.data import (
        device_prefetch, jsonl_documents, packed_batches,
        synthetic_batches,
    )
    from kubeflow_rm_tpu.training.loop import LoopConfig, fit
    from kubeflow_rm_tpu.training.train import TrainState

    # 1. the slice: no-op on single-host; multi-host pods all run this
    env = initialize()
    devices = jax.devices()
    fsdp = args.fsdp or max(1, len(devices) // (args.dp * args.tp))
    mesh = make_mesh(MeshConfig(dp=args.dp, fsdp=fsdp, tp=args.tp),
                     devices[:args.dp * fsdp * args.tp])
    print(f"process {env.process_id}/{env.num_hosts} "
          f"mesh {dict(mesh.shape)}")

    # 2. the model
    if args.hf_model:
        import transformers

        from kubeflow_rm_tpu.models import from_hf_llama
        hf = transformers.LlamaForCausalLM.from_pretrained(args.hf_model)
        model_cfg, params = from_hf_llama(hf)
    else:
        model_cfg = getattr(LlamaConfig, args.preset)()
        params = None
    optim = OptimConfig(train_only="lora" if args.lora_rank else None)
    cfg = TrainConfig(model=model_cfg, optim=optim)
    state = None  # built below once params are final
    if args.lora_rank:
        from kubeflow_rm_tpu.models import add_lora, init_params
        bits = 4 if args.int4_base else 8
        if params is None and (args.int8_base or args.int4_base):
            # no checkpoint: build the base DIRECTLY in quantized form,
            # leaf by leaf — a 7B's full-precision copy never fits next
            # to its quantized one on a 16 GiB chip
            from kubeflow_rm_tpu.models.quantize import (
                init_params_quantized,
            )
            params = init_params_quantized(model_cfg, jax.random.key(0),
                                           bits=bits)
        else:
            if params is None:
                params = init_params(model_cfg, jax.random.key(0))
            if args.int8_base or args.int4_base:
                from kubeflow_rm_tpu.models import quantize_params
                params = quantize_params(params, bits=bits)
        params = add_lora(params, args.lora_rank, key=jax.random.key(1))

    # 3. the data
    if args.data:
        paths = sorted(glob.glob(args.data))
        docs = jsonl_documents(paths, process_id=env.process_id,
                               num_processes=env.num_hosts, seed=0)
        batches = device_prefetch(
            packed_batches(docs, args.batch, args.seq_len), mesh)
        batch_keys = ("tokens", "labels", "positions", "segments")
    else:
        batches = synthetic_batches(args.batch, args.seq_len,
                                    cfg.model.vocab_size)
        batch_keys = ("tokens", "labels")

    # 4. train (fit restores from checkpoint_dir when present)
    if params is not None:
        from kubeflow_rm_tpu.training.train import init_train_state
        state = init_train_state(cfg, jax.random.key(0), params=params)
    loop = LoopConfig(total_steps=args.steps,
                      log_every=max(1, args.steps // 10),
                      checkpoint_dir=args.checkpoint_dir,
                      grad_accum=args.grad_accum)
    callbacks = ()
    if args.tb_logdir and env.process_id == 0:
        from kubeflow_rm_tpu.utils.tensorboard import TensorboardCallback
        callbacks = (TensorboardCallback(args.tb_logdir),)
    state, history = fit(cfg, mesh, batches, loop, state=state,
                         batch_keys=batch_keys, callbacks=callbacks)
    if history:
        last = history[-1]
        print(f"final: step {last.step} loss {last.loss:.4f} "
              f"{last.tokens_per_sec:.0f} tok/s mfu {last.mfu_pct:.1f}%")

    # 5. sample — decode applies adapters and int8 bases directly
    if args.sample and env.process_id == 0:
        prompt = np.ones((1, 4), np.int32)
        out = generate_fused(state.params, cfg.model,
                             jax.numpy.asarray(prompt), max_new_tokens=8)
        print("sample token ids:", np.asarray(out)[0].tolist())

    # 6. export
    if args.export_hf and env.process_id == 0:
        from kubeflow_rm_tpu.models.convert import to_hf_llama
        np.savez(args.export_hf, **to_hf_llama(cfg.model, state.params))
        print(f"exported HF state_dict -> {args.export_hf}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
