#!/usr/bin/env python3
"""Serve a Llama from the slice this notebook was spawned with.

The inference-side counterpart of ``finetune_llama.py``: a small HTTP
server around the single-program decode path (``generate_fused`` /
``make_generate_step``), meant to run inside a jupyter-jax notebook or
as the command of a spawned serving pod. The reference platform ships
no model runtime at all (SURVEY.md §2.6) — serving is capability the
TPU image adds on top.

TPU-shaped choices:

- **Micro-batching.** Requests arriving within a batching window are
  padded into one fixed-shape ``generate_fused`` call — decode is
  HBM-bandwidth-bound, so tokens/sec scales nearly free with batch.
- **Shape buckets.** Prompts pad up to power-of-two buckets and
  ``max_new_tokens`` is server-fixed, so XLA compiles a handful of
  programs once instead of one per request shape.
- **Token ids in/out.** The API speaks token ids (JSON lists);
  tokenization happens client-side (or pass ``--hf-tokenizer`` to
  decode text server-side when the files are available).

API: ``POST /generate {"prompt": [ids...], "temperature"?: t,
"top_k"?: k}`` → ``{"tokens": [ids...]}``; ``GET /healthz``.
Generation length is server-fixed (``--max-new-tokens``); sampling
params are compile-shape keys, so temperature snaps to a 0.05 grid
and top_k snaps to a small allowed set — both documented below.

Speculative decode — where it lives and what gates it:

| Surface | Knob | Gate |
|---|---|---|
| this server | ``--speculative`` (process-wide) | solo greedy batch-1 requests only; batched/sampled requests fall back to plain fused decode |
| engine / gateway | ``POST /generate {"speculative": true}`` per request | ``slo_class`` must be ``batch`` or ``best_effort`` (interactive keeps the paged continuous-batching path), greedy only, prompt > 3 tokens |
| fleet front door | same per-request field, any replica | disaggregated fleets run it decode-side and skip prefix staging (the drafter needs the whole prompt locally) |

All three run ``generate_speculative_fused`` (prompt-lookup n-gram
drafting + one fused verify pass per round) and are exactness-
preserving: output is token-for-token what plain greedy decode
produces, never an approximation — wins show up as fewer model calls
on repetitive continuations, worst case is one extra verify call.

Tiny smoke (CPU, what tests/test_examples.py runs):
    python examples/serve_llama.py --preset tiny --selftest
Real chip:
    python examples/serve_llama.py --preset llama2_7b \
        --hf-model meta-llama/Llama-2-7b-hf --int8 --port 8000
"""

from __future__ import annotations

import argparse
import json
import queue
import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


# top_k values the API serves; requests snap to the nearest member
# (top_k is a static compile key — see make_app)
TOP_K_CHOICES = (1, 5, 10, 20, 50, 100)


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class Batcher:
    """Collects concurrent generate requests into fixed-shape batches.

    One background thread drains the queue: it waits for the first
    request, then up to ``window_ms`` for stragglers (bounded by
    ``max_batch``), pads all prompts (left-pad with ``pad_id``, which
    doubles as a "begin" token) into the smallest power-of-two bucket,
    and runs ONE fused generation for the whole batch. Each waiter
    gets its row back, trimmed of padding.
    """

    def __init__(self, step_fn, *, max_new_tokens: int, pad_id: int = 0,
                 window_ms: float = 5.0, max_batch: int = 8,
                 rows_multiple: int = 1, exact_solo: bool = False):
        # step_fn: (ids (B,T), pad_counts (B,), temperature, top_k)
        #          -> (B, T+new)
        self.step_fn = step_fn
        self.max_new_tokens = max_new_tokens
        self.pad_id = pad_id
        self.window_ms = window_ms
        self.max_batch = max_batch
        # sharded batches must divide the mesh's data axes: dummy rows
        # (copies of row 0) round B up, and only real rows are returned
        self.rows_multiple = rows_multiple
        # speculative solo requests need the exact prompt (no pads) —
        # costs one compile per distinct prompt length instead of per
        # bucket, the price of the lookup decoder's prefix semantics.
        # The length set is capped: beyond it, solo requests fall back
        # to bucketing so cycling lengths can't accumulate compiles.
        self.exact_solo = exact_solo
        self._exact_lens: set = set()
        self.q: queue.Queue = queue.Queue()
        self.batches_run = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def submit(self, prompt: list[int], temperature: float = 0.0,
               top_k: int | None = None) -> list[int]:
        """Blocking: returns prompt + continuation token ids."""
        if self._stop.is_set():
            raise RuntimeError("batcher is closed")
        done = threading.Event()
        box: dict = {"prompt": prompt, "temperature": temperature,
                     "top_k": top_k, "done": done}
        self.q.put(box)
        # wake periodically: if close() killed the drain thread while
        # this request sat queued, nobody will ever set done — an
        # in-flight batch still completes (the thread finishes its
        # current batch before exiting), so only stop+dead-thread is
        # a guaranteed-orphan condition
        while not done.wait(timeout=1.0):
            if self._stop.is_set() and not self._thread.is_alive():
                # the drain thread may have finished this very box
                # between the wait timing out and the checks above
                if done.is_set():
                    break
                raise RuntimeError("batcher closed with request "
                                   "pending")
        if "error" in box:
            raise RuntimeError(box["error"])
        return box["result"]

    def close(self):
        self._stop.set()
        self.q.put(None)
        self._thread.join(timeout=5)
        # fail anything still queued (the drain thread can exit on the
        # sentinel while real requests remain behind it)
        while True:
            try:
                box = self.q.get_nowait()
            except queue.Empty:
                break
            if box is not None:
                box["error"] = "batcher closed"
                box["done"].set()

    def _run(self):
        import numpy as np

        while not self._stop.is_set():
            first = self.q.get()
            if first is None:
                continue
            batch = [first]
            # sampling params are per-BATCH shape keys: only coalesce
            # requests that share them (others wait for the next cycle)
            deadline = time.monotonic() + self.window_ms / 1e3
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self.q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    break
                if (nxt["temperature"] == first["temperature"]
                        and nxt["top_k"] == first["top_k"]):
                    batch.append(nxt)
                else:
                    self.q.put(nxt)
                    break

            # EVERYTHING per-batch lives under try: an assembly error
            # (e.g. an int that overflows int32) must fail the batch's
            # waiters, never kill this thread — a dead drain thread
            # would hang every future request forever
            try:
                lens = [len(b["prompt"]) for b in batch]
                if (self.exact_solo and len(batch) == 1
                        and first["temperature"] <= 0
                        and (lens[0] in self._exact_lens
                             or len(self._exact_lens) < 16)):
                    self._exact_lens.add(lens[0])
                    T = lens[0]
                else:
                    T = _bucket(max(lens))
                # batch size is a compile shape too: bucket the batch
                # in UNITS of rows_multiple (power-of-two unit counts)
                # so varying coalesce counts reuse log2(max_batch)
                # programs AND B stays divisible by the mesh's data
                # axes even when dp*fsdp is not a power of two
                units = -(-len(batch) // self.rows_multiple)
                B = _bucket(units, lo=1) * self.rows_multiple
                ids = np.full((B, T), self.pad_id, np.int32)
                for i, b in enumerate(batch):
                    ids[i, T - lens[i]:] = b["prompt"]   # left-pad
                for i in range(len(batch), B):           # dummy rows
                    ids[i] = ids[0]
                pads = np.asarray(
                    [T - ln for ln in lens] +
                    [T - lens[0]] * (B - len(batch)), np.int32)
                out = np.asarray(self.step_fn(
                    ids, pads, first["temperature"], first["top_k"]))
                self.batches_run += 1
                for i, b in enumerate(batch):
                    row = out[i, T - lens[i]:].tolist()
                    b["result"] = row
                    b["done"].set()
            except Exception as e:  # propagate to every waiter
                for b in batch:
                    b["error"] = repr(e)
                    b["done"].set()


def make_app(cfg, params, *, max_new_tokens: int = 64, mesh=None,
             window_ms: float = 5.0, max_batch: int = 8,
             speculative: bool = False, tokenizer=None,
             fused_int4: bool = True):
    """werkzeug WSGI app + its Batcher. ``mesh`` switches the backend
    to the sharded ``make_generate_step`` program; ``speculative``
    routes solo greedy requests through the single-program
    prompt-lookup decoder (repetitive text decodes in fewer model
    passes; see ``generate_speculative_fused``).

    int4 weights take the fused program by DEFAULT: the fused decode
    loop now unpacks nibbles once per generation instead of once per
    step (``quantize.unpack_int4_params``, hoisted ahead of the scan),
    which removed the 612.77-vs-137.07 ms/tok regression that made PR 4
    route int4 to the per-token loop (``BENCH_SWEEP_r05.json``
    ``decode_7b``; re-measured in ``SERVE_r01.json`` ``decode_int4``).
    ``fused_int4=False`` (``--loop-int4``) keeps the per-token loop as
    the measured A/B baseline arm."""
    import jax
    import numpy as np
    from werkzeug.exceptions import BadRequest, HTTPException
    from werkzeug.routing import Map, Rule
    from werkzeug.wrappers import Request, Response

    from kubeflow_rm_tpu.models import (
        generate, generate_fused, generate_speculative_fused,
        make_generate_step,
    )

    int4_params = any(
        isinstance(leaf, dict) and "q4" in leaf
        for leaf in jax.tree_util.tree_leaves(
            params,
            is_leaf=lambda x: isinstance(x, dict) and "q4" in x))
    loop_decode = int4_params and not fused_int4 and mesh is None

    steps = {}  # (total_len, temperature, top_k) -> sharded step
    LOOKUP_N = 3      # kept in ONE place: guard below + the call
    app_stats = {"speculative_requests": 0}

    def step_fn(ids, pad_counts, temperature, top_k):
        B, T = ids.shape
        S = T + max_new_tokens
        key = jax.random.key(0) if temperature <= 0 else \
            jax.random.key(np.random.randint(0, 2**31 - 1))
        if mesh is None:
            # pad==0 means the batcher granted exact-solo (its length
            # set bounds compiles); anything bucketed/padded verifies
            # on the fused path
            if (speculative and B == 1 and temperature <= 0
                    and int(pad_counts[0]) == 0 and T > LOOKUP_N):
                app_stats["speculative_requests"] += 1
                return generate_speculative_fused(
                    params, cfg, ids, max_new_tokens=max_new_tokens,
                    lookup_n=LOOKUP_N)
            if loop_decode:
                return generate(
                    params, cfg, ids, max_new_tokens=max_new_tokens,
                    key=key, temperature=temperature, top_k=top_k,
                    max_len=S, pad_counts=pad_counts)
            return generate_fused(
                params, cfg, ids, max_new_tokens=max_new_tokens,
                key=key, temperature=temperature, top_k=top_k,
                max_len=S, pad_counts=pad_counts)
        if (S, temperature, top_k) not in steps:
            if len(steps) >= 16:   # bound compile accumulation
                steps.pop(next(iter(steps)))
            steps[(S, temperature, top_k)] = make_generate_step(
                params, cfg, mesh, max_new_tokens=max_new_tokens,
                total_len=S, temperature=temperature, top_k=top_k)
        return steps[(S, temperature, top_k)](params, ids, key,
                                              pad_counts)

    rows = 1
    if mesh is not None:
        rows = int(mesh.shape["dp"] * mesh.shape["fsdp"])
    batcher = Batcher(step_fn, max_new_tokens=max_new_tokens,
                      window_ms=window_ms, max_batch=max_batch,
                      rows_multiple=rows,
                      exact_solo=speculative and mesh is None)

    urls = Map([Rule("/generate", endpoint="generate",
                     methods=["POST"]),
                Rule("/healthz", endpoint="healthz")])

    def app(environ, start_response):
        req = Request(environ)
        try:
            endpoint, _ = urls.bind_to_environ(environ).match()
            if endpoint == "healthz":
                resp = Response(json.dumps({"ok": True}),
                                content_type="application/json")
                return resp(environ, start_response)
            body = req.get_json(force=True)
            if not isinstance(body, dict):
                raise BadRequest("body must be a JSON object")
            if tokenizer is not None and "text" in body:
                if not isinstance(body["text"], str):
                    raise BadRequest("text must be a string")
                prompt = list(tokenizer.encode(body["text"]))
            else:
                prompt = body.get("prompt")
            if (not isinstance(prompt, list) or not prompt
                    or not all(isinstance(t, int)
                               and 0 <= t < cfg.vocab_size
                               for t in prompt)):
                raise BadRequest("prompt must be a non-empty list of "
                                 f"token ids in [0, {cfg.vocab_size}) "
                                 "(or pass text with a server-side "
                                 "tokenizer)")
            if len(prompt) > cfg.max_seq_len - max_new_tokens:
                raise BadRequest(f"prompt too long ({len(prompt)}); "
                                 f"limit {cfg.max_seq_len - max_new_tokens}")
            temp = body.get("temperature", 0.0)
            if not isinstance(temp, (int, float)) or not 0 <= temp <= 10:
                raise BadRequest("temperature must be a number in "
                                 "[0, 10]")
            # sampling params are compile keys (static in the fused
            # program): snap temperature to a 0.05 grid so hostile or
            # chatty clients can't force one XLA compile per request
            temp = round(float(temp) * 20) / 20
            top_k = body.get("top_k")
            if top_k is not None and (
                    not isinstance(top_k, int)
                    or not 1 <= top_k <= cfg.vocab_size):
                raise BadRequest("top_k must be an int in "
                                 f"[1, {cfg.vocab_size}]")
            # top_k is a compile key too (static in the fused program
            # and part of the sharded steps cache key): snap it to a
            # small allowed set so a client cycling values can't
            # accumulate one compiled program per distinct k
            if top_k is not None:
                choices = [c for c in TOP_K_CHOICES
                           if c <= cfg.vocab_size] or [1]
                top_k = min(choices, key=lambda c: abs(c - top_k))
            tokens = batcher.submit(prompt, temp, top_k)
            out = {"tokens": tokens}
            if tokenizer is not None:
                try:  # HF tokenizers: strip <s>/</s> markers
                    out["text"] = tokenizer.decode(
                        tokens, skip_special_tokens=True)
                except TypeError:  # minimal tokenizers (tests)
                    out["text"] = tokenizer.decode(tokens)
            resp = Response(json.dumps(out),
                            content_type="application/json")
        except HTTPException as e:
            resp = e
        return resp(environ, start_response)

    app.batcher = batcher
    app.stats = app_stats
    return app


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="tiny")
    ap.add_argument("--hf-model", default=None)
    quant = ap.add_mutually_exclusive_group()
    quant.add_argument("--int8", action="store_true",
                       help="weight-only int8 quantize before serving")
    quant.add_argument("--int4", action="store_true",
                       help="weight-only packed-int4 quantize "
                            "(smallest HBM footprint; per-group "
                            "scales)")
    ap.add_argument("--max-new-tokens", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--hf-tokenizer", default=None,
                    help="HF tokenizer id/path: lets clients pass "
                         '{"text": ...} and get text back')
    ap.add_argument("--speculative", action="store_true",
                    help="route solo greedy requests through the "
                         "prompt-lookup speculative decoder "
                         "(repetitive text decodes in fewer model "
                         "passes; one compile per distinct prompt "
                         "length)")
    ap.add_argument("--loop-int4", action="store_true",
                    help="serve int4 weights via the per-token "
                         "generate loop instead of the fused program "
                         "(A/B baseline arm; fused is the default now "
                         "that the nibble unpack is hoisted out of "
                         "the decode scan — SERVE_r01.json "
                         "decode_int4)")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--fsdp", type=int, default=0,
                    help="0 = all local devices (with --tp 1 ⇒ "
                         "single-device fused path when 1 device)")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--selftest", action="store_true",
                    help="serve in-process, run one batched round "
                         "trip, exit")
    args = ap.parse_args(argv)

    import jax

    from kubeflow_rm_tpu.models import (
        LlamaConfig, from_hf_llama, init_params, quantize_params,
    )
    from kubeflow_rm_tpu.parallel import MeshConfig, make_mesh

    cfg = getattr(LlamaConfig, args.preset)()
    if args.hf_model:
        cfg, params = from_hf_llama(args.hf_model, cfg)
    else:
        params = init_params(cfg, jax.random.key(0))
    if args.int8 or args.int4:
        params = quantize_params(params, bits=4 if args.int4 else 8)

    n_dev = len(jax.devices())
    mesh = None
    if n_dev > 1 or args.tp > 1:
        fsdp = args.fsdp or max(1, n_dev // args.tp)
        mesh = make_mesh(MeshConfig(fsdp=fsdp, tp=args.tp))
        if args.speculative:
            print("warning: --speculative is single-device only "
                  "(batch-1 lookup decoding); sharded requests take "
                  "the fused path", flush=True)

    tokenizer = None
    if args.hf_tokenizer:
        from transformers import AutoTokenizer
        tokenizer = AutoTokenizer.from_pretrained(args.hf_tokenizer)
        if len(tokenizer) > cfg.vocab_size:
            print(f"warning: tokenizer vocab ({len(tokenizer)}) exceeds "
                  f"model vocab_size ({cfg.vocab_size}) — text requests "
                  "producing out-of-range ids will be rejected",
                  flush=True)

    app = make_app(cfg, params, max_new_tokens=args.max_new_tokens,
                   mesh=mesh, max_batch=args.max_batch,
                   speculative=args.speculative, tokenizer=tokenizer,
                   fused_int4=not args.loop_int4)

    if args.selftest:
        from werkzeug.test import Client
        c = Client(app)
        r = c.post("/generate", json={"prompt": [1, 2, 3]})
        assert r.status_code == 200, r.get_data()
        toks = r.get_json()["tokens"]
        assert len(toks) == 3 + args.max_new_tokens
        print(f"selftest ok: {len(toks)} tokens, "
              f"{app.batcher.batches_run} batch(es)")
        app.batcher.close()
        return 0

    from werkzeug.serving import make_server
    httpd = make_server("0.0.0.0", args.port, app, threaded=True)
    print(f"serving {args.preset} on :{args.port} "
          f"(mesh={'1 device' if mesh is None else dict(zip(mesh.axis_names, mesh.devices.shape))})",
          flush=True)
    httpd.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
