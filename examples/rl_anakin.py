#!/usr/bin/env python3
"""Anakin-style actor–learner RL driven through the platform as a TPUJob.

Podracer (arxiv 2104.06272) describes two TPU RL architectures; Anakin
is the one where the learner owns the accelerator and actors are cheap
CPU processes feeding it trajectories. This example runs that shape
END-TO-END through the control plane — not as a hand-wired script:

1. boot the in-process platform (``make_control_plane`` + a small TPU
   node fleet) — the same stack the conformance walks drive;
2. submit a ``TPUJob`` CR: one ``learner`` role on a TPU slice plus N
   CPU-only ``actors`` — the whole gang binds all-or-nothing through
   ``SchedulerCache.gang_bind``;
3. verify the gang came up Running and every pod carries the role
   rendezvous env the webhook injected (``TPU_JOB_ROLE``,
   ``TPU_JOB_ROLE_INDEX``, ``TPU_JOB_LEARNER_ADDRESS``);
4. run the RL loop with the platform's API as the transport, the way
   the real pods would use the REST facade: the learner broadcasts
   params as a versioned ConfigMap, actors post trajectory ConfigMaps,
   the learner consumes them and applies a jitted REINFORCE update
   over a ``parallel/mesh.py`` mesh.

The toy problem is a 5-armed bandit: the exact expected loss
``-(softmax(logits) · rewards)`` is computable in closed form, so the
dryrun can assert learning happened (finite, decreasing loss) without
statistical slack.

Dryrun smoke (CPU mesh — what CI runs):
    JAX_PLATFORMS=cpu python examples/rl_anakin.py --dryrun --steps 20
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

#: per-arm expected rewards of the toy bandit; arm 2 is optimal, so a
#: learning policy drives the loss toward -0.9
TRUE_REWARDS = (0.1, 0.4, 0.9, 0.2, 0.5)


# ---- platform side ---------------------------------------------------

def boot_platform(num_nodes: int, accel: str):
    """The in-process stack: apiserver + every controller + webhook +
    a fleet of TPU nodes (one per host of ``num_nodes`` slices)."""
    from kubeflow_rm_tpu.controlplane import make_control_plane
    from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
    from kubeflow_rm_tpu.controlplane.controllers.statefulset import (
        make_tpu_node,
    )
    api, mgr = make_control_plane()
    api.ensure_namespace("rl")
    topo = tpu_api.lookup(accel)
    for i in range(num_nodes * topo.hosts):
        api.create(make_tpu_node(f"tpu-{i}", accel))
    return api, mgr


def submit_job(api, mgr, *, name: str, actors: int, accel: str) -> dict:
    """Create the TPUJob CR, reconcile to steady state, and assert the
    gang contract held: phase Running, every pod bound, role env on
    chip pods AND actors (TPU env only on chip pods)."""
    from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api
    job = tj_api.make_tpujob(name, "rl", roles=[
        {"name": "learner", "replicas": 1,
         "tpu": {"acceleratorType": accel}},
        {"name": "actors", "replicas": actors, "cpu": "1"},
    ])
    api.create(job)
    mgr.run_until_idle()
    live = api.get(tj_api.KIND, name, "rl")
    status = live.get("status") or {}
    if status.get("phase") != tj_api.RUNNING_PHASE:
        raise SystemExit(f"gang failed to assemble: status={status}")
    pods = api.list("Pod", "rl",
                    {"matchLabels": {tj_api.JOB_NAME_LABEL: name}})
    for p in pods:
        env = {e["name"]: e.get("value")
               for c in p["spec"]["containers"]
               for e in c.get("env", [])}
        role = env.get(tj_api.ENV_JOB_ROLE)
        assert role in ("learner", "actors"), p["metadata"]["name"]
        assert env.get(tj_api.ENV_LEARNER_ADDRESS), "no learner address"
        is_chip = "TPU_WORKER_ID" in env
        assert is_chip == (role == "learner"), (
            f"{p['metadata']['name']}: TPU env on a CPU actor (or "
            "missing on a chip pod)")
    return status


# ---- RL side (the toy Anakin loop) -----------------------------------

def _publish_params(api, logits, version: int) -> None:
    """Learner → actors broadcast, as the pods would do it: a versioned
    ConfigMap the actors poll (pull model — the in-memory apiserver
    and the REST facade serve the same verb)."""
    body = {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "anakin-params", "namespace": "rl"},
            "data": {"logits": json.dumps([float(x) for x in logits]),
                     "version": str(version)}}
    try:
        cur = api.get("ConfigMap", "anakin-params", "rl")
        cur["data"] = body["data"]
        api.update(cur)
    except Exception:
        api.create(body)


def _fetch_params(api):
    cm = api.get("ConfigMap", "anakin-params", "rl")
    import numpy as np
    return (np.asarray(json.loads(cm["data"]["logits"])),
            int(cm["data"]["version"]))


def _post_trajectory(api, actor: int, step: int, actions, rewards):
    api.create({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": f"anakin-traj-{actor}-{step}",
                     "namespace": "rl",
                     "labels": {"app": "anakin-traj",
                                "step": str(step)}},
        "data": {"actions": json.dumps([int(a) for a in actions]),
                 "rewards": json.dumps([float(r) for r in rewards])},
    })


def _drain_trajectories(api, step: int):
    out = []
    for cm in api.list("ConfigMap", "rl",
                       {"matchLabels": {"app": "anakin-traj",
                                        "step": str(step)}}):
        out.append((json.loads(cm["data"]["actions"]),
                    json.loads(cm["data"]["rewards"])))
        api.delete("ConfigMap", cm["metadata"]["name"], "rl")
    return out


def run_loop(api, *, actors: int, steps: int, batch: int,
             lr: float, seed: int) -> list[float]:
    """The Anakin cycle: broadcast → act → learn, ``steps`` times.

    The learner update is REINFORCE with a mean-reward baseline,
    jitted once over the framework mesh (dp×fsdp over however many
    devices the platform gave us — on CPU that is a 1×1 mesh, on a
    real slice the same code spans the chips)."""
    import jax
    import jax.numpy as jnp

    from kubeflow_rm_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig())
    n_arms = len(TRUE_REWARDS)
    true_r = jnp.asarray(TRUE_REWARDS)

    @jax.jit
    def update(logits, actions, rewards):
        def neg_score(lg):
            logp = jax.nn.log_softmax(lg)
            baseline = rewards.mean()
            return -jnp.mean((rewards - baseline) * logp[actions])
        grads = jax.grad(neg_score)(logits)
        return logits - lr * grads

    @jax.jit
    def exact_loss(logits):
        # closed-form expected negative reward of the current policy —
        # the assertable learning signal (no sampling noise)
        return -jnp.dot(jax.nn.softmax(logits), true_r)

    key = jax.random.PRNGKey(seed)
    logits = jnp.zeros(n_arms)
    _publish_params(api, logits, 0)
    losses: list[float] = []
    with mesh:
        for step in range(steps):
            # actors: pull params, sample a batch, post trajectories
            for a in range(actors):
                pulled, _ = _fetch_params(api)
                key, sub = jax.random.split(key)
                acts = jax.random.categorical(
                    sub, jnp.asarray(pulled), shape=(batch,))
                key, sub = jax.random.split(key)
                rews = (true_r[acts]
                        + 0.05 * jax.random.normal(sub, (batch,)))
                _post_trajectory(api, a, step, list(acts), list(rews))
            # learner: drain the step's trajectories, one fused update
            trajs = _drain_trajectories(api, step)
            assert len(trajs) == actors, "lost trajectories in flight"
            acts = jnp.asarray(sum((t[0] for t in trajs), []))
            rews = jnp.asarray(sum((t[1] for t in trajs), []))
            logits = update(logits, acts, rews)
            _publish_params(api, logits, step + 1)
            losses.append(float(exact_loss(logits)))
    return losses


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dryrun", action="store_true",
                    help="CPU smoke: assert the loss is finite and "
                         "decreasing, print a JSON summary")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--actors", type=int, default=4)
    ap.add_argument("--batch", type=int, default=64,
                    help="samples per actor per step")
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--accel", default="v5p-16")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    api, mgr = boot_platform(1, args.accel)
    status = submit_job(api, mgr, name="anakin", actors=args.actors,
                        accel=args.accel)
    print(f"gang Running: {status['readyPods']}/{status['totalPods']} "
          f"pods ({json.dumps(status['roles'])})")

    losses = run_loop(api, actors=args.actors, steps=args.steps,
                      batch=args.batch, lr=args.lr, seed=args.seed)
    import math
    summary = {
        "steps": args.steps,
        "actors": args.actors,
        "first_loss": losses[0],
        "last_loss": losses[-1],
        "optimal_loss": -max(TRUE_REWARDS),
        "finite": all(math.isfinite(x) for x in losses),
        "decreased": losses[-1] < losses[0],
    }
    print(json.dumps(summary))
    if args.dryrun:
        assert summary["finite"], "non-finite loss"
        assert summary["decreased"], (
            f"loss did not decrease: {losses[0]} -> {losses[-1]}")
        print("dryrun OK: loss", round(losses[0], 4), "->",
              round(losses[-1], 4))
    return 0


if __name__ == "__main__":
    sys.exit(main())
