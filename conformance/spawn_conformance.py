#!/usr/bin/env python3
"""Spawn-path conformance + load test.

The reference ships a load-test seed that mass-spawns notebook servers
(``notebook-controller/loadtest/start_notebooks.py`` +
``jupyter_test.yaml``) and a conformance harness shape
(``conformance/1.7``). This script is both for the TPU stack: it boots
the full control plane against a fake TPU fleet, drives the #1 call
stack (SURVEY.md §3.1) through the REAL web API N times — authn,
CSRF, authz, form→CR, webhook mutation, reconcile, scheduling,
rendezvous env — and asserts every slice comes up whole, printing
provisioning latency stats (reconcile counts stand in for wall time on
the in-memory apiserver).

Two modes:

- default: in-process (hermetic, deterministic; reconcile counts stand
  in for wall time on the in-memory apiserver);
- ``--wallclock``: the REAL process layout over sockets — the cluster
  (apiserver + admission + fake kubelet) behind the kube REST facade,
  the controller manager reconciling through the kube adapter with
  watch threads, the jupyter web app served by werkzeug over HTTP —
  and provisioning p50 measured in actual wall time, the
  BASELINE.json primary metric (VERDICT r2 next #8).

Usage:
    python conformance/spawn_conformance.py --slices v5p-16=2 --notebooks 3
    python conformance/spawn_conformance.py --wallclock --notebooks 5
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from kubeflow_rm_tpu.controlplane import make_control_plane  # noqa: E402
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api.profile import make_profile  # noqa: E402
from kubeflow_rm_tpu.controlplane.controllers.statefulset import (  # noqa: E402
    make_tpu_node,
)
from kubeflow_rm_tpu.controlplane.webapps import jupyter as jwa  # noqa: E402

USER = "conformance@corp.com"


def _run_meta(args, mode: str) -> dict:
    """The shared artifact header ``benchmarks/ratchet.py`` keys on:
    two artifacts are only comparable when these arm flags agree."""
    import os

    from kubeflow_rm_tpu.controlplane.obs.runmeta import build_run_meta
    interleave = os.environ.get("KFRM_RUN_INTERLEAVE")
    arms = {
        "mode": mode,
        "shards": args.shards,
        "wal": args.shards > 1 and not args.no_wal,
        "cache": "off" if args.no_cache else "on",
        "lock": "global" if args.global_lock else "sharded",
        "writes": "serial" if args.serial_writes else "batched",
        "schedule": "legacy" if args.legacy_schedule else "cache",
        "oversubscribe": not args.no_oversubscribe,
        "readiness": "poll" if args.poll_readiness else "push",
        "tracing": not args.no_tracing,
        "defrag": "active" if args.active_defrag else "off",
        "notebooks": args.notebooks,
        "concurrency": max(1, args.concurrency),
    }
    if mode == "diurnal":
        # elastic arms: two diurnal artifacts are only comparable when
        # the envelope and the chaos arm agree
        arms.update(max_shards=args.max_shards,
                    objects=args.diurnal_objects,
                    chaos_split=bool(args.chaos_split),
                    arrival="open" if args.arrival_rate > 0
                    else "closed",
                    arrival_rate=args.arrival_rate,
                    seed=args.seed)
    return build_run_meta(
        "spawn_conformance", arms,
        interleave_index=int(interleave) if interleave else None)


def wallclock_main(args) -> int:
    """Full process layout over sockets; wall-time p50 across
    ``--runs`` independent boots, with a per-phase breakdown computed
    from the apiserver write log (utils/profiling.PhaseRecorder)."""
    import statistics

    from kubeflow_rm_tpu.controlplane import tracing
    from kubeflow_rm_tpu.utils.profiling import PhaseRecorder

    if not args.no_tracing:
        # the harness is the trace ROOT process: every spawn opens a
        # client span around POST→Ready and propagates it over HTTP
        tracing.set_enabled(True)
        tracing.set_process("harness")
    phases = PhaseRecorder()
    runs = []
    throttled = {"calls": 0, "seconds": 0.0}
    readiness = {"status_gets": 0, "readiness_gets": 0}
    trace_reports = []
    once = _wallclock_once_sharded if args.shards > 1 else _wallclock_once
    for r in range(max(1, args.runs)):
        res = once(args, phases)
        tr = res.pop("_throttle", None)
        if tr:
            throttled["calls"] += tr["calls"]
            throttled["seconds"] += tr["seconds"]
        rd = res.pop("_readiness", None)
        if rd:
            readiness["status_gets"] += rd["status_gets"]
            readiness["readiness_gets"] += rd["readiness_gets"]
        rep = res.pop("_trace", None)
        if rep:
            trace_reports.append(rep)
        runs.append(res)
        print(f"run {r + 1}/{args.runs}: "
              f"p50={res['provision_p50_ms']}ms "
              f"p95={res['provision_p95_ms']}ms", file=sys.stderr)
    p50s = sorted(r["provision_p50_ms"] for r in runs)
    p95s = sorted(r["provision_p95_ms"] for r in runs)
    result = {
        "run_meta": _run_meta(args, "wallclock"),
        "mode": "wallclock",
        "shards": args.shards,
        "wal": args.shards > 1 and not args.no_wal,
        "cache": "off" if args.no_cache else "on",
        "lock": "global" if args.global_lock else "sharded",
        "writes": "serial" if args.serial_writes else "batched",
        "schedule": "legacy" if args.legacy_schedule else "cache",
        "oversubscribe": not args.no_oversubscribe,
        "readiness": {
            "mode": "poll" if args.poll_readiness else "push",
            "status_get_requests": readiness["status_gets"],
            "readiness_requests": readiness["readiness_gets"],
        },
        "notebooks": args.notebooks,
        "concurrency": max(1, args.concurrency),
        "slice": runs[0]["slice"],
        "hosts_per_slice": runs[0]["hosts_per_slice"],
        "runs": len(runs),
        "runs_p50_ms": [r["provision_p50_ms"] for r in runs],
        "provision_p50_ms": round(statistics.median(p50s), 1),
        "provision_p50_ms_best": p50s[0],
        "provision_p95_ms": round(statistics.median(p95s), 1),
        "total_s": round(sum(r["total_s"] for r in runs), 2),
        "phases": phases.summary(),
    }
    if args.qps:
        result["client_qps"] = args.qps
        result["client_burst"] = args.burst
        result["client_throttle"] = {
            "calls": throttled["calls"],
            "seconds": round(throttled["seconds"], 3),
        }
    result["tracing"] = not args.no_tracing
    if trace_reports:
        trace_section = _merge_trace_reports(trace_reports)
        # the slowest trace rides the printed result WITHOUT its full
        # span list (that lives in the --trace-out artifact)
        result["trace"] = {
            "count": trace_section["count"],
            "slowest": ({k: v for k, v in
                         trace_section["slowest"].items()
                         if k != "spans"}
                        if trace_section["slowest"] else None),
            "phase_exemplars": trace_section["phase_exemplars"],
        }
        if args.trace_out:
            artifact = {
                "run_meta": result["run_meta"],
                "mode": "wallclock",
                "shards": args.shards,
                "notebooks": args.notebooks,
                "concurrency": max(1, args.concurrency),
                "runs": len(runs),
                "provision_p50_ms": result["provision_p50_ms"],
                **trace_section,
            }
            with open(args.trace_out, "w") as f:
                json.dump(artifact, f, indent=1)
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print("CONFORMANCE OK (wallclock)")
    return 0


def _trace_report(spawn_traces, span_lists) -> dict:
    """Reduce one run's spans to per-spawn trace summaries.

    ``spawn_traces``: ``(name, trace_id, measured_s)`` per spawn;
    ``span_lists``: raw span-dict lists from every participating
    process (the harness collector + each shard's ``/debug/traces``).
    The slowest provision keeps its full span list and critical path —
    the TRACE artifact's centerpiece — others keep summaries."""
    from kubeflow_rm_tpu.controlplane import tracing

    spans = tracing.merge_spans(*span_lists)
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    traces = []
    for name, tid, measured_s in spawn_traces:
        tspans = sorted(by_trace.get(tid, []),
                        key=lambda s: s["start"])
        if not tspans:
            continue
        cp = tracing.critical_path(tspans)
        roots = [s for s in tspans if not s.get("parent_id")]
        dur = roots[0].get("duration_ms") if roots else None
        traces.append({
            "name": name,
            "trace_id": tid,
            "measured_ms": round(measured_s * 1e3, 1),
            "duration_ms": dur,
            # the critical-path invariant: these partition the root
            # interval, so the sum must track duration_ms (and thus
            # the measured wallclock) to within clock skew
            "self_ms_total": round(sum(h["self_ms"] for h in cp), 3),
            "hops": len(cp),
            "processes": sorted({s.get("process") or ""
                                 for s in tspans}),
            "critical_path": cp,
            "spans": tspans,
        })
    traces.sort(key=lambda t: -(t["duration_ms"] or 0))
    phase_exemplars: dict[str, dict] = {}
    for t in traces:
        for h in t["critical_path"]:
            ex = phase_exemplars.get(h["name"])
            if ex is None or h["self_ms"] > ex["self_ms"]:
                phase_exemplars[h["name"]] = {
                    "trace_id": t["trace_id"],
                    "self_ms": h["self_ms"]}
    return {
        "count": len(traces),
        "slowest": traces[0] if traces else None,
        "phase_exemplars": phase_exemplars,
        "traces": [{k: t[k] for k in
                    ("name", "trace_id", "measured_ms", "duration_ms",
                     "self_ms_total", "hops", "processes")}
                   for t in traces],
    }


def _merge_trace_reports(reports: list[dict]) -> dict:
    """Across --runs boots: overall slowest + per-phase maxima."""
    all_traces = [t for rep in reports for t in rep["traces"]]
    slowest = None
    for rep in reports:
        t = rep.get("slowest")
        if t and (slowest is None or
                  (t.get("duration_ms") or 0) >
                  (slowest.get("duration_ms") or 0)):
            slowest = t
    phase_exemplars: dict[str, dict] = {}
    for rep in reports:
        for name, ex in rep["phase_exemplars"].items():
            cur = phase_exemplars.get(name)
            if cur is None or ex["self_ms"] > cur["self_ms"]:
                phase_exemplars[name] = ex
    return {"count": len(all_traces), "slowest": slowest,
            "phase_exemplars": phase_exemplars, "traces": all_traces}


def _phases_from_write_log(write_log, prefix: str, hosts: int,
                           phases) -> None:
    """Per-notebook phase durations from the apiserver's attributed
    write log: CR create -> StatefulSet create -> last Pod create ->
    last status write. All timestamps come from one wall clock (the
    apiserver's), so the diffs are poll-free."""
    per_nb: dict[str, dict] = {}
    for e in write_log:
        name, kind, verb = e["name"], e["kind"], e["verb"]
        if kind == "Notebook" and name.startswith(prefix):
            nb = per_nb.setdefault(name, {})
            if verb == "CREATE":
                nb["cr"] = e["t"]
            elif verb == "UPDATE":
                nb["status"] = e["t"]  # last writer wins
        elif kind == "StatefulSet" and name.startswith(prefix):
            per_nb.setdefault(name, {}).setdefault("sts", e["t"])
        elif kind == "Pod" and name.startswith(prefix):
            nb_name = name.rsplit("-", 1)[0]
            nb = per_nb.setdefault(nb_name, {})
            nb["pod_last"] = max(nb.get("pod_last", 0.0), e["t"])
            nb["pods"] = nb.get("pods", 0) + 1
    for nb in per_nb.values():
        if {"cr", "sts"} <= nb.keys():
            phases.record("cr_to_statefulset", nb["sts"] - nb["cr"])
        if {"sts", "pod_last"} <= nb.keys() and nb.get("pods") >= hosts:
            phases.record("statefulset_to_pods",
                          nb["pod_last"] - nb["sts"])
        if {"pod_last", "status"} <= nb.keys():
            phases.record("pods_to_status_ready",
                          nb["status"] - nb["pod_last"])


def _wallclock_once(args, phases) -> dict:
    """One full boot + spawn storm + teardown; returns the run stats."""
    import secrets
    import threading

    import requests

    from kubeflow_rm_tpu.controlplane import (
        WATCHED_KINDS,
        make_cluster_manager,
        tracing,
    )
    from kubeflow_rm_tpu.controlplane.api import poddefault as pd_api
    from kubeflow_rm_tpu.controlplane.apiserver import APIServer
    from kubeflow_rm_tpu.controlplane.controllers.statefulset import (
        DeploymentController,
        StatefulSetController,
    )
    from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
        KubeAPIServer,
    )
    from kubeflow_rm_tpu.controlplane.deploy.restserver import RestServer
    from kubeflow_rm_tpu.controlplane.runtime import Manager
    from kubeflow_rm_tpu.controlplane.webapps.core import (
        CSRF_COOKIE,
        CSRF_HEADER,
        USER_HEADER,
        USER_PREFIX,
    )
    from kubeflow_rm_tpu.controlplane.webhook.notebook import (
        NotebookWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.poddefault import (
        PodDefaultWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.tpu_inject import (
        TpuInjectWebhook,
    )

    stop = threading.Event()
    if tracing.enabled():
        # per-run isolation: each --runs boot reports its own traces
        tracing.collector().clear()

    # -- the cluster: apiserver + admission + fake kubelet over REST --
    capi = APIServer(global_lock=args.global_lock)
    capi.register_validator(nb_api.KIND, nb_api.validate)
    capi.register_validator(pd_api.KIND, pd_api.validate)
    NotebookWebhook(capi).register()
    PodDefaultWebhook(capi).register()
    TpuInjectWebhook(capi).register()
    kubelet = Manager(capi)
    kubelet.add(StatefulSetController(auto_ready=True))
    kubelet.add(DeploymentController(auto_ready=True))
    accel = args.slices.split(",")[0].split("=")[0]
    topo = tpu_api.lookup(accel)
    # wallclock measures provisioning latency, so the fleet must cover
    # every spawn (fleet-exhaustion semantics are the in-process mode's
    # job); notebooks stay up for the whole run
    count = max(int(args.slices.split(",")[0].split("=")[1]),
                args.notebooks)
    for s in range(count):
        for h in range(topo.hosts):
            capi.create(make_tpu_node(f"{accel}-s{s}-h{h}", accel))
    rest = RestServer(capi)
    rest.start()
    threading.Thread(target=kubelet.run_forever,
                     args=(stop, 0.05), kwargs={"workers": 4},
                     daemon=True).start()

    # -- the platform: controller manager through the kube adapter --
    kapi = KubeAPIServer(rest.url, qps=args.qps or None,
                         burst=args.burst or None,
                         identity="conformance-manager",
                         cache_reads=not args.no_cache)
    mgr = make_cluster_manager(kapi, enable_culling=False)
    for kind in WATCHED_KINDS:
        threading.Thread(target=kapi.watch_kind,
                         args=(kind, None, stop, 60),
                         daemon=True).start()
    mgr.enqueue_all()
    threading.Thread(target=mgr.run_forever, args=(stop, 0.05),
                     kwargs={"workers": args.manager_workers},
                     daemon=True).start()

    # -- the web app: werkzeug HTTP server on its own adapter --
    from werkzeug.serving import make_server

    from kubeflow_rm_tpu.controlplane.webapps import jupyter as jwa
    japi = KubeAPIServer(rest.url, cache_reads=not args.no_cache)
    # the SPA polls notebook status: serve those reads from informers
    # exactly like the manager does (SARs stay live, behind the webapp
    # core's short-TTL decision cache)
    for kind in ("Notebook", "Event", "Pod", "PodDefault",
                 "PersistentVolumeClaim"):
        threading.Thread(target=japi.watch_kind,
                         args=(kind, None, stop, 60),
                         daemon=True).start()
    import logging as _logging
    _logging.getLogger("werkzeug").setLevel(_logging.ERROR)
    wsgi = jwa.create_app(japi)
    httpd = make_server("127.0.0.1", 0, wsgi, threaded=True)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    jwa_url = f"http://127.0.0.1:{httpd.server_port}"

    # namespace via the profile path (RBAC from the controller)
    kapi.create(make_profile("conformance", USER))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        # namespaceAdmin is the LAST rbac object the profile reconcile
        # writes before quota/plugins — once it exists the spawner's
        # SubjectAccessReview will pass
        if kapi.try_get("RoleBinding", "namespaceAdmin", "conformance"):
            break
        time.sleep(0.05)
    else:
        raise AssertionError("profile never reconciled over the wire")

    def spawn_one(i: int) -> dict:
        """POST the spawn form, then observe readiness through the web
        API until the slice is fully ready; returns the provision wall
        time plus the request counts of the readiness phase.

        Default path: the readiness long-poll (``.../readiness``) —
        each request parks on the server's ReadinessHub and returns at
        watch latency, so readiness is NOT quantized to a poll tick
        and the client issues zero fixed-interval status GETs.
        ``--poll-readiness`` restores the old 50ms status-GET loop as
        the A/B baseline arm. Each worker carries its own Session —
        requests Sessions are not thread-safe.

        The whole POST→Ready interval runs inside a ROOT client span
        whose traceparent rides every HTTP request of this spawn, so
        the provision trace covers exactly the latency being measured
        (no-op under --no-tracing)."""
        s = requests.Session()
        tok = secrets.token_urlsafe(16)
        s.cookies.set(CSRF_COOKIE, tok)
        s.headers[CSRF_HEADER] = tok
        s.headers[USER_HEADER] = USER_PREFIX + USER
        body = {
            "name": f"wc-{i}",
            "image": "ghcr.io/kubeflow-rm-tpu/jupyter-jax:latest",
            "imagePullPolicy": "IfNotPresent",
            "serverType": "jupyter", "cpu": "2", "memory": "8Gi",
            "tpu": {"acceleratorType": accel},
            "tolerationGroup": "none", "affinityConfig": "none",
            "configurations": [], "shm": True, "environment": {},
            "datavols": [],
        }
        t0 = time.perf_counter()
        with tracing.start_span(f"provision wc-{i}", kind="client",
                                root=True,
                                attrs={"notebook": f"wc-{i}"}) as root:
            tp = root.to_traceparent()
            if tp:
                s.headers[tracing.TRACE_HEADER] = tp
            for attempt in range(3):
                resp = s.post(
                    f"{jwa_url}/api/namespaces/conformance/notebooks",
                    json=body)
                if resp.status_code == 200:
                    break
                # a keep-alive reset mid-POST surfaces as a 500 with
                # the create possibly landed — poll for the CR like
                # the SPA would before re-submitting the form
                got = s.get(f"{jwa_url}/api/namespaces/conformance/"
                            f"notebooks/wc-{i}")
                if got.status_code == 200:
                    break
                time.sleep(0.1)
            else:
                raise AssertionError(f"wc-{i} POST failed: {resp.text}")
            phases.record("post_return", time.perf_counter() - t0)
            slice_deadline = time.monotonic() + 120
            status_gets = 0
            readiness_gets = 0
            if args.poll_readiness:
                while True:
                    # the list endpoint serves summaries without
                    # replica counts; the per-notebook GET returns the
                    # raw CR
                    resp = s.get(
                        f"{jwa_url}/api/namespaces/conformance/"
                        f"notebooks/wc-{i}")
                    status_gets += 1
                    nb = resp.json().get("notebook", {}) \
                        if resp.status_code == 200 else {}
                    if (nb.get("status") or {}).get(
                            "readyReplicas") == topo.hosts:
                        break
                    if time.monotonic() > slice_deadline:
                        raise AssertionError(
                            f"wc-{i} never ready: {nb.get('status')}")
                    # fixed 50ms poll: with the parallel manager the
                    # server side absorbs N pollers fine, and a
                    # concurrency-scaled interval would quantize the
                    # very latency being measured (20-way × 20ms =
                    # 400ms floor — the old r4 artifact's first ~fifth
                    # of its 2.05s p50 was the poll itself)
                    time.sleep(0.05)
            else:
                # push path: re-subscribe with the last observed
                # resourceVersion; the server blocks until the CR
                # moves, so there is no sleep anywhere in this loop
                known = ""
                while True:
                    resp = s.get(
                        f"{jwa_url}/api/namespaces/conformance/"
                        f"notebooks/wc-{i}/readiness",
                        params={"timeoutSeconds": 30,
                                "knownVersion": known})
                    readiness_gets += 1
                    if resp.status_code == 200:
                        nb = resp.json().get("notebook", {})
                        if (nb.get("status") or {}).get(
                                "readyReplicas") == topo.hosts:
                            break
                        known = str((nb.get("metadata") or {}).get(
                            "resourceVersion") or "")
                    else:
                        # 404 = long-poll expired before the CR became
                        # visible to the web app's informer — re-
                        # subscribe from scratch (still no fixed-
                        # interval sleep)
                        known = ""
                    if time.monotonic() > slice_deadline:
                        raise AssertionError(
                            f"wc-{i} never ready: "
                            f"{resp.status_code} {resp.text[:200]}")
        return {"latency": time.perf_counter() - t0,
                "status_gets": status_gets,
                "readiness_gets": readiness_gets,
                "trace_id": getattr(root, "trace_id", None)}

    t_start = time.perf_counter()
    try:
        from concurrent.futures import ThreadPoolExecutor

        workers = max(1, args.concurrency)
        with ThreadPoolExecutor(max_workers=workers) as pool:
            spawns = list(pool.map(spawn_one, range(args.notebooks)))
        latencies = [sp["latency"] for sp in spawns]
        total = time.perf_counter() - t_start
        _phases_from_write_log(list(capi.write_log), "wc-",
                               topo.hosts, phases)
        trace_report = None
        if tracing.enabled():
            # everything ran in THIS process (webapp, manager, cluster)
            # so the local collector holds the whole causal chain
            spawn_traces = [(f"wc-{i}", sp["trace_id"], sp["latency"])
                            for i, sp in enumerate(spawns)
                            if sp.get("trace_id")]
            trace_report = _trace_report(
                spawn_traces, [tracing.collector().spans()])
    finally:
        stop.set()
        # flush in-flight fanout deliveries before tearing the sockets
        # down — a watcher callback racing a closed RestServer would
        # log spurious errors into the next run's output
        capi.drain_watchers(timeout=10)
        httpd.shutdown()
        rest.stop()

    lat_sorted = sorted(latencies)
    result = {
        "notebooks": args.notebooks,
        "concurrency": workers,
        "slice": accel,
        "hosts_per_slice": topo.hosts,
        "provision_p50_ms": round(lat_sorted[len(latencies) // 2] * 1e3,
                                  1),
        "provision_p95_ms": round(
            lat_sorted[max(0, int(len(latencies) * 0.95) - 1)] * 1e3, 1),
        "total_s": round(total, 2),
        "_readiness": {
            "status_gets": sum(sp["status_gets"] for sp in spawns),
            "readiness_gets": sum(sp["readiness_gets"]
                                  for sp in spawns),
        },
    }
    if kapi.limiter is not None:
        result["_throttle"] = {
            "calls": kapi.limiter.throttled_calls,
            "seconds": kapi.limiter.throttled_seconds,
        }
    if trace_report is not None:
        result["_trace"] = trace_report
    return result


def _wallclock_once_sharded(args, phases) -> dict:
    """One boot of the SHARDED process layout: N shard processes
    (apiserver + WAL + manager each) under the consistent-hash ring,
    the jupyter web app served over the ``ShardedKubeAPIServer``
    router. The storm spreads notebooks across 2x-shards namespaces so
    every shard owns real traffic; nodes are name-salted onto the
    shard that schedules them (cluster-scoped objects route by name).

    ``--shards 1`` never reaches this function — the single-process
    arm (``_wallclock_once``) is preserved untouched."""
    import secrets
    import shutil
    import tempfile
    import threading
    import urllib.request
    from collections import Counter

    import requests

    from kubeflow_rm_tpu.controlplane import tracing
    from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
        ShardedKubeAPIServer,
    )
    from kubeflow_rm_tpu.controlplane.shard import ShardRunner
    from kubeflow_rm_tpu.controlplane.webapps.core import (
        CSRF_COOKIE,
        CSRF_HEADER,
        USER_HEADER,
        USER_PREFIX,
    )

    stop = threading.Event()
    if tracing.enabled():
        tracing.collector().clear()
    base_dir = tempfile.mkdtemp(prefix="conf-shards-")
    runner = ShardRunner(args.shards, base_dir=base_dir,
                         wal=not args.no_wal,
                         manager_workers=args.manager_workers,
                         hang_dump_s=args.hang_dump,
                         tracing=tracing.enabled())
    runner.start(timeout=120)

    router = ShardedKubeAPIServer(runner.urls, identity="conformance-web",
                                  qps=args.qps or None,
                                  burst=args.burst or None)
    # the web app reads through the router's merged informer cache —
    # same kinds the single-process arm streams into its adapter
    for kind in ("Notebook", "Event", "Pod", "PodDefault",
                 "PersistentVolumeClaim", "RoleBinding", "Namespace"):
        threading.Thread(target=router.watch_kind,
                         args=(kind, None, stop, 60),
                         daemon=True).start()
    if not router.wait_for_sync(["Notebook", "Pod"], timeout=30):
        raise AssertionError("router informers never synced")

    accel = args.slices.split(",")[0].split("=")[0]
    topo = tpu_api.lookup(accel)

    # 2x-shards namespaces via the profile path; notebook i lands in
    # conf-p{i % P}, so every shard owns live spawn traffic
    n_profiles = 2 * args.shards
    namespaces = [f"conf-p{p}" for p in range(n_profiles)]
    ns_of = [namespaces[i % n_profiles] for i in range(args.notebooks)]

    # salt the fleet: gang scheduling runs inside the shard that owns
    # the notebook's namespace, and it can only see nodes living on
    # that same shard (cluster-scoped -> routed by name)
    per_shard = Counter(router.shard_of("Notebook", None, ns)
                        for ns in ns_of)
    for shard, n_slices in per_shard.items():
        made, i = 0, 0
        while made < n_slices * topo.hosts:
            name = f"{accel}-{shard}-x{i}"
            i += 1
            if router.shard_of("Node", name, None) == shard:
                router.create(make_tpu_node(name, accel))
                made += 1

    for ns in namespaces:
        router.create(make_profile(ns, USER))
    deadline = time.monotonic() + 60
    for ns in namespaces:
        while time.monotonic() < deadline:
            if router.try_get("RoleBinding", "namespaceAdmin", ns):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(f"profile {ns} never reconciled")

    # -- the web app: werkzeug over the shard router --
    import logging as _logging

    from werkzeug.serving import make_server

    from kubeflow_rm_tpu.controlplane.webapps import jupyter as jwa
    _logging.getLogger("werkzeug").setLevel(_logging.ERROR)
    wsgi = jwa.create_app(router)
    httpd = make_server("127.0.0.1", 0, wsgi, threaded=True)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    jwa_url = f"http://127.0.0.1:{httpd.server_port}"

    def spawn_one(i: int) -> dict:
        """Same storm body as the single-process arm, parameterized by
        the notebook's ring namespace (see _wallclock_once for the
        readiness-path commentary)."""
        ns = ns_of[i]
        s = requests.Session()
        tok = secrets.token_urlsafe(16)
        s.cookies.set(CSRF_COOKIE, tok)
        s.headers[CSRF_HEADER] = tok
        s.headers[USER_HEADER] = USER_PREFIX + USER
        body = {
            "name": f"wc-{i}",
            "image": "ghcr.io/kubeflow-rm-tpu/jupyter-jax:latest",
            "imagePullPolicy": "IfNotPresent",
            "serverType": "jupyter", "cpu": "2", "memory": "8Gi",
            "tpu": {"acceleratorType": accel},
            "tolerationGroup": "none", "affinityConfig": "none",
            "configurations": [], "shm": True, "environment": {},
            "datavols": [],
        }
        t0 = time.perf_counter()
        with tracing.start_span(f"provision wc-{i}", kind="client",
                                root=True,
                                attrs={"notebook": f"wc-{i}",
                                       "namespace": ns}) as root:
            tp = root.to_traceparent()
            if tp:
                s.headers[tracing.TRACE_HEADER] = tp
            for attempt in range(3):
                resp = s.post(
                    f"{jwa_url}/api/namespaces/{ns}/notebooks",
                    json=body)
                if resp.status_code == 200:
                    break
                got = s.get(f"{jwa_url}/api/namespaces/{ns}/"
                            f"notebooks/wc-{i}")
                if got.status_code == 200:
                    break
                time.sleep(0.1)
            else:
                raise AssertionError(f"wc-{i} POST failed: {resp.text}")
            phases.record("post_return", time.perf_counter() - t0)
            slice_deadline = time.monotonic() + 180
            status_gets = 0
            readiness_gets = 0
            if args.poll_readiness:
                while True:
                    resp = s.get(f"{jwa_url}/api/namespaces/{ns}/"
                                 f"notebooks/wc-{i}")
                    status_gets += 1
                    nb = resp.json().get("notebook", {}) \
                        if resp.status_code == 200 else {}
                    if (nb.get("status") or {}).get(
                            "readyReplicas") == topo.hosts:
                        break
                    if time.monotonic() > slice_deadline:
                        raise AssertionError(
                            f"wc-{i} never ready: {nb.get('status')}")
                    time.sleep(0.05)
            else:
                known = ""
                while True:
                    resp = s.get(
                        f"{jwa_url}/api/namespaces/{ns}/"
                        f"notebooks/wc-{i}/readiness",
                        params={"timeoutSeconds": 30,
                                "knownVersion": known})
                    readiness_gets += 1
                    if resp.status_code == 200:
                        nb = resp.json().get("notebook", {})
                        if (nb.get("status") or {}).get(
                                "readyReplicas") == topo.hosts:
                            break
                        known = str((nb.get("metadata") or {}).get(
                            "resourceVersion") or "")
                    else:
                        known = ""
                    if time.monotonic() > slice_deadline:
                        raise AssertionError(
                            f"wc-{i} never ready: "
                            f"{resp.status_code} {resp.text[:200]}")
        return {"latency": time.perf_counter() - t0,
                "status_gets": status_gets,
                "readiness_gets": readiness_gets,
                "trace_id": getattr(root, "trace_id", None)}

    t_start = time.perf_counter()
    try:
        from concurrent.futures import ThreadPoolExecutor

        workers = max(1, args.concurrency)
        with ThreadPoolExecutor(max_workers=workers) as pool:
            spawns = list(pool.map(spawn_one, range(args.notebooks)))
        latencies = [sp["latency"] for sp in spawns]
        total = time.perf_counter() - t_start
        # phases from the UNION of the shards' write logs: every
        # shard stamps t from the same host clock, so cross-shard
        # diffs are as poll-free as the single-process ones
        merged: list[dict] = []
        for url in runner.urls.values():
            with urllib.request.urlopen(url + "/debug/writelog",
                                        timeout=10) as r:
                merged.extend(json.loads(r.read())["writes"])
        merged.sort(key=lambda e: e["t"])
        _phases_from_write_log(merged, "wc-", topo.hosts, phases)
        trace_report = None
        if tracing.enabled():
            # a trace's spans are SCATTERED: the harness holds the
            # client roots + webapp server spans, each shard process
            # holds its apiserver/reconcile/scheduler hops — pull every
            # shard's export and merge before the critical-path pass
            span_lists = [tracing.collector().spans()]
            for url in runner.urls.values():
                try:
                    with urllib.request.urlopen(
                            url + "/debug/traces", timeout=10) as r:
                        span_lists.append(
                            json.loads(r.read())["spans"])
                except OSError:
                    pass  # a chaos-killed shard loses its spans
            spawn_traces = [(f"wc-{i}", sp["trace_id"], sp["latency"])
                            for i, sp in enumerate(spawns)
                            if sp.get("trace_id")]
            trace_report = _trace_report(spawn_traces, span_lists)
    finally:
        stop.set()
        httpd.shutdown()
        runner.stop()
        shutil.rmtree(base_dir, ignore_errors=True)

    lat_sorted = sorted(latencies)
    result = {
        "notebooks": args.notebooks,
        "concurrency": workers,
        "slice": accel,
        "hosts_per_slice": topo.hosts,
        "provision_p50_ms": round(lat_sorted[len(latencies) // 2] * 1e3,
                                  1),
        "provision_p95_ms": round(
            lat_sorted[max(0, int(len(latencies) * 0.95) - 1)] * 1e3, 1),
        "total_s": round(total, 2),
        "_readiness": {
            "status_gets": sum(sp["status_gets"] for sp in spawns),
            "readiness_gets": sum(sp["readiness_gets"]
                                  for sp in spawns),
        },
    }
    limiters = [c.limiter for c in router._clients.values()
                if c.limiter is not None]
    if limiters:
        result["_throttle"] = {
            "calls": sum(lim.throttled_calls for lim in limiters),
            "seconds": sum(lim.throttled_seconds for lim in limiters),
        }
    if trace_report is not None:
        result["_trace"] = trace_report
    return result


def diurnal_main(args) -> int:
    """A simulated production day over the ELASTIC shard fleet:
    morning notebook rush -> midday TPUJob burst -> evening serving
    flood -> night idle, with the SLO/queue-depth autoscaler driving
    live split/merge (2 -> ``--max-shards`` -> 2) while the load is in
    flight. The zero-loss audit at the end re-reads every object the
    harness ever had acked — through the router AND from the shard the
    final ring says owns it.

    The autoscaler acts on real signals (federated ``workqueue_depth``
    + burn-rate criticals); if a phase ends before the signals carry
    the fleet to the envelope target, the harness forces the remaining
    split/merge steps through the same handoff path and records them
    as ``forced`` — CI asserts the envelope deterministically, the
    signal-driven decisions stay visible in the artifact."""
    import shutil
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from kubeflow_rm_tpu.controlplane import chaos, metrics, suspend
    from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api
    from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
        KubeAPIServer,
        ShardedKubeAPIServer,
    )
    from kubeflow_rm_tpu.controlplane.obs import Observer
    from kubeflow_rm_tpu.controlplane.shard import ShardRunner
    from kubeflow_rm_tpu.controlplane.shard.elastic import (
        ElasticShardManager,
        ShardAutoscaler,
    )

    min_shards = max(2, args.shards)
    if args.no_wal:
        raise SystemExit("--diurnal requires WAL-backed shards "
                         "(the handoff IS snapshot + WAL tail-replay)")
    suspend.set_active_defrag(args.active_defrag)
    plan = None
    if args.chaos_split:
        plan = chaos.install(chaos.FaultPlan(args.seed, [
            chaos.FaultSpec("shard_split", rate=1.0, limit=1)]))

    base_dir = tempfile.mkdtemp(prefix="conf-diurnal-")
    runner = ShardRunner(min_shards, base_dir=base_dir, wal=True,
                         manager_workers=args.manager_workers,
                         hang_dump_s=args.hang_dump, tracing=False)
    runner.start(timeout=120)
    router = ShardedKubeAPIServer(runner.urls,
                                  identity="diurnal-harness",
                                  retry_window_s=20.0)
    observer = Observer(interval_s=0.5, shard_urls=runner.urls,
                        liveness=runner.liveness,
                        run_meta=_run_meta(args, "diurnal"))
    runner.set_on_death(observer.on_shard_death)
    elastic = ElasticShardManager(runner, router, observer=observer)
    scaler = ShardAutoscaler(elastic, observer,
                             min_shards=min_shards,
                             max_shards=args.max_shards,
                             split_depth=args.split_depth,
                             merge_depth=args.merge_depth,
                             sustain=2, cooldown_s=2.0)

    from kubeflow_rm_tpu.analysis.lockgraph import make_lock
    created: list[tuple] = []
    created_lock = make_lock("harness.diurnal_results")
    errors: list[str] = []

    def track(obj: dict) -> None:
        try:
            router.create(obj)
        except Exception as e:  # noqa: BLE001 - audited, not raised
            errors.append(f"{obj.get('kind')}/"
                          f"{obj['metadata'].get('name')}: {e!r}")
            return
        meta = obj["metadata"]
        with created_lock:
            created.append((obj["kind"], meta["name"],
                            meta.get("namespace")))

    def pump(objs: list[dict], phase: str) -> float:
        """Run one load wave through the pool while the main thread
        ticks observer + autoscaler — splits/merges land DURING the
        wave, so the fence/remap window sees live writers.

        Two load models:

        - closed loop (default): every object is submitted at once and
          the pool's width throttles arrivals to completion rate — the
          legacy saturating wave;
        - open loop (``--arrival-rate R``): object *i* arrives at
          ``t0 + i/R`` whether or not earlier creates finished — the
          production shape, where demand does not politely wait for
          the fleet. Backlog (and so federated workqueue depth) builds
          whenever R outruns reconcile throughput, which is what lets
          the autoscaler reach the envelope on SIGNALS alone instead
          of needing the evening's forced-split floor.
        """
        t0 = time.monotonic()
        rate = args.arrival_rate
        # open loop needs headroom: in-flight creates must not cap the
        # arrival process, or it degenerates back into a closed loop
        width = max(4, args.concurrency) if rate <= 0 else \
            max(16, args.concurrency)
        with ThreadPoolExecutor(max_workers=width) as pool:
            futs = []
            if rate > 0:
                for i, o in enumerate(objs):
                    due = t0 + i / rate
                    while True:
                        now = time.monotonic()
                        if now >= due:
                            break
                        observer.tick()
                        scaler.tick()
                        time.sleep(min(0.15, due - now))
                    futs.append(pool.submit(track, o))
            else:
                futs = [pool.submit(track, o) for o in objs]
            while any(not f.done() for f in futs):
                observer.tick()
                scaler.tick()
                time.sleep(0.15)
            for f in futs:
                f.result()
        observer.tick()
        scaler.tick()
        print(f"  {phase}: {len(objs)} objects, "
              f"{len(router.ring)} shards "
              f"({time.monotonic() - t0:.1f}s)", file=sys.stderr)
        return time.monotonic() - t0

    total = max(60, args.diurnal_objects)
    n_morning = int(total * 0.45)
    n_midday = int(total * 0.25)
    n_evening = total - n_morning - n_midday
    namespaces = [f"day-{i}" for i in range(max(8, 3 * args.max_shards))]

    phases_out: list[dict] = []
    forced: list[dict] = []
    t_start = time.monotonic()
    try:
        for ns in namespaces:
            track({"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": ns}})

        # -- morning: the notebook rush --
        wave = [nb_api.make_notebook(f"rush-{i}",
                                     namespaces[i % len(namespaces)])
                for i in range(n_morning)]
        dt = pump(wave, "morning rush")
        phases_out.append({"phase": "morning", "objects": n_morning,
                           "shards_after": len(router.ring),
                           "duration_s": round(dt, 1)})

        # -- midday: the TPUJob burst --
        wave = [tj_api.make_tpujob(
                    f"burst-{i}", namespaces[i % len(namespaces)],
                    roles=[{"name": "learner", "replicas": 1,
                            "cpu": "2"},
                           {"name": "actors", "replicas": 2,
                            "cpu": "1"}])
                for i in range(n_midday)]
        dt = pump(wave, "midday burst")
        phases_out.append({"phase": "midday", "objects": n_midday,
                           "shards_after": len(router.ring),
                           "duration_s": round(dt, 1)})

        # -- evening: the serving flood --
        wave = [{"apiVersion": "apps/v1", "kind": "Deployment",
                 "metadata": {
                     "name": f"serve-{i}",
                     "namespace": namespaces[i % len(namespaces)],
                     "labels": {"app": "model-server"}},
                 "spec": {"replicas": 2, "template": {"spec": {
                     "containers": [{"name": "server",
                                     "image": "model-server:latest"}],
                 }}}}
                for i in range(n_evening)]
        dt = pump(wave, "evening flood")
        # the envelope floor: whatever the signals did not claim by
        # dusk is forced through the same handoff path. Closed loop
        # only — the open-loop arm must reach the envelope on pressure
        # alone (it asserts zero forced splits below, and the peak may
        # legitimately have come and gone mid-wave as backlog drained)
        if args.arrival_rate <= 0:
            while len(router.ring) < args.max_shards:
                name = elastic.split()
                forced.append({"op": "split", "shard": name})
        phases_out.append({"phase": "evening", "objects": n_evening,
                           "shards_after": len(router.ring),
                           "duration_s": round(dt, 1)})

        # -- night: idle; sustained quiet merges the fleet back --
        t0 = time.monotonic()
        deadline = t0 + 60
        while len(router.ring) > min_shards and \
                time.monotonic() < deadline:
            observer.tick()
            scaler.tick()
            time.sleep(0.2)
        while len(router.ring) > min_shards:
            name = elastic.merge()
            forced.append({"op": "merge", "shard": name})
        phases_out.append({"phase": "night", "objects": 0,
                           "shards_after": len(router.ring),
                           "duration_s": round(time.monotonic() - t0,
                                               1)})

        # -- the zero-loss audit --
        observer.tick()
        shard_clients = {n: KubeAPIServer(u, identity="auditor",
                                          cache_reads=False)
                         for n, u in runner.urls.items()}
        lost: list[str] = []
        misplaced: list[str] = []
        for kind, name, ns in created:
            if router.try_get(kind, name, ns) is None:
                lost.append(f"{kind} {ns}/{name}")
                continue
            owner = router.shard_of(kind, name, ns)
            if shard_clients[owner].try_get(kind, name, ns) is None:
                misplaced.append(f"{kind} {ns}/{name} -> {owner}")

        deaths = metrics.registry_value("shard_deaths_total")
        splits = metrics.registry_value("shard_splits_total")
        merges = metrics.registry_value("shard_merges_total")
        max_seen = max([min_shards]
                       + [len(e["members"]) for e in elastic.events]
                       + [d["shards"] for d in scaler.decisions])
        decision_counts: dict[str, int] = {}
        for d in scaler.decisions:
            decision_counts[d["decision"]] = \
                decision_counts.get(d["decision"], 0) + 1

        result = {
            "run_meta": _run_meta(args, "diurnal"),
            "mode": "diurnal",
            "objects_created": len(created),
            "create_errors": errors[:20],
            "lost": len(lost),
            "lost_sample": lost[:20],
            "misplaced": len(misplaced),
            "misplaced_sample": misplaced[:20],
            "envelope": {
                "min_shards": min_shards,
                "max_shards": args.max_shards,
                "max_reached": max_seen,
                "final_shards": len(router.ring),
            },
            "arrival": {"mode": "open" if args.arrival_rate > 0
                        else "closed",
                        "rate_per_s": args.arrival_rate},
            "splits_total": splits,
            "merges_total": merges,
            "forced_scale_steps": forced,
            "autoscaler_decisions": decision_counts,
            "decision_tail": [
                {k: d[k] for k in
                 ("decision", "shards", "mean_depth", "burning")}
                for d in scaler.decisions[-12:]],
            "scale_events": elastic.events,
            "handoff": {
                "objects_bulk": metrics.registry_value(
                    "shard_handoff_objects_total",
                    {"phase": "bulk"}),
                "objects_tail": metrics.registry_value(
                    "shard_handoff_objects_total",
                    {"phase": "tail"}),
            },
            "shard_deaths_total": deaths,
            "active_defrag": args.active_defrag,
            "phases": phases_out,
            "total_s": round(time.monotonic() - t_start, 1),
        }
        if plan is not None:
            result["chaos"] = plan.summary()
        try:
            result["slo_shard_deaths"] = \
                observer.engine.state_of("shard-deaths")
        except KeyError:
            result["slo_shard_deaths"] = "unconfigured"

        # -- the day's invariants --
        assert not errors, f"{len(errors)} creates errored: {errors[:5]}"
        assert not lost, f"{len(lost)} objects LOST: {lost[:10]}"
        assert not misplaced, \
            f"{len(misplaced)} objects misplaced: {misplaced[:10]}"
        assert splits >= 1 and merges >= 1, (splits, merges)
        assert max_seen >= args.max_shards, \
            f"never reached {args.max_shards} shards (peak {max_seen})"
        if args.arrival_rate > 0:
            # the open-loop contract: demand pressure alone must carry
            # the fleet to the envelope — the evening's forced-split
            # floor exists for the closed-loop arm, not this one
            forced_splits = [f for f in forced if f["op"] == "split"]
            assert not forced_splits, (
                f"open-loop run needed {len(forced_splits)} forced "
                f"split(s): the arrival rate never outran the fleet")
        assert len(router.ring) == min_shards
        if plan is None:
            # satellite: deliberate scale-downs are not deaths — the
            # whole day's merges must leave the counter and the
            # critical shard-death SLO untouched
            assert deaths == 0, f"shard_deaths_total={deaths}"
            assert result["slo_shard_deaths"] != "critical"
        else:
            assert plan.counts.get("shard_split", 0) >= 1, \
                "chaos arm never fired"
            assert deaths >= 1, "donor SIGKILL was not observed"
    finally:
        if plan is not None:
            chaos.uninstall()
        suspend.set_active_defrag(True)  # restore the library default
        runner.stop()
        shutil.rmtree(base_dir, ignore_errors=True)

    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print("CONFORMANCE OK (diurnal)")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slices", default="v5p-16=2",
                    help="comma list of acceleratorType=count node pools")
    ap.add_argument("--notebooks", type=int, default=3)
    ap.add_argument("--jobs", type=int, default=0,
                    help="in-process mode: also storm N multi-role "
                         "TPUJobs (learner slice + 4 CPU actors each) "
                         "over a dedicated node pool and assert every "
                         "gang assembles whole")
    ap.add_argument("--wallclock", action="store_true",
                    help="real sockets + watch threads; wall-time p50")
    ap.add_argument("--concurrency", type=int, default=1,
                    help="parallel spawn workers (wallclock mode): the "
                         "load shape that flushes watch/queue races")
    ap.add_argument("--manager-workers", type=int, default=8,
                    help="concurrent reconciles in the platform "
                         "manager (MaxConcurrentReconciles; 1 = the "
                         "pre-r5 serial drain)")
    ap.add_argument("--runs", type=int, default=1,
                    help="wallclock mode: independent boots to "
                         "aggregate (median-of-runs p50 + per-phase "
                         "breakdown)")
    ap.add_argument("--qps", type=float, default=0.0,
                    help="manager kube-client qps (0 = unthrottled); "
                         "the reference's --qps")
    ap.add_argument("--burst", type=int, default=0,
                    help="manager kube-client burst (with --qps); the "
                         "reference's --burst")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the shared informer read cache (all "
                         "reads live, no no-op write suppression) — "
                         "the A/B baseline arm for PROVISION_r{N}.json")
    ap.add_argument("--global-lock", action="store_true",
                    help="run the apiserver on the pre-r08 single "
                         "global RLock with synchronous watch delivery "
                         "— the sharded/async A/B baseline arm")
    ap.add_argument("--serial-writes", action="store_true",
                    help="restore the pre-r09 write path: sequential "
                         "child writes in reconcile_children and "
                         "per-object pod creates instead of bulk — the "
                         "batched-write A/B baseline arm")
    ap.add_argument("--legacy-schedule", action="store_true",
                    help="restore the pre-r10 scheduler: per-reconcile "
                         "full Pod scans under one global bind lock "
                         "instead of the incremental usage cache with "
                         "gang assume/bind — the scheduler A/B "
                         "baseline arm")
    ap.add_argument("--poll-readiness", action="store_true",
                    help="restore the pre-r10 readiness client: fixed "
                         "50ms status-GET polling instead of the "
                         "readiness long-poll — the push-readiness "
                         "A/B baseline arm (wallclock mode)")
    ap.add_argument("--no-oversubscribe", action="store_true",
                    help="pin-for-lifetime arm: disable idle "
                         "suspension and preemptive gang-bind (the "
                         "oversubscription A/B baseline — "
                         "oversub_conformance.py is the full proof)")
    ap.add_argument("--shards", type=int, default=1,
                    help="wallclock mode: run the control plane as N "
                         "shard PROCESSES under the consistent-hash "
                         "ring (apiserver + WAL + manager each) with "
                         "the web app over the shard router; 1 = the "
                         "single-process arm, byte-for-byte today's "
                         "path")
    ap.add_argument("--no-wal", action="store_true",
                    help="with --shards N>1: run the shards without "
                         "the durable write-ahead log (the durability "
                         "A/B baseline arm; --shards 1 never engages "
                         "the WAL)")
    ap.add_argument("--diurnal", action="store_true",
                    help="simulated production day over the ELASTIC "
                         "shard fleet: morning notebook rush, midday "
                         "TPUJob burst, evening serving flood, night "
                         "idle — the autoscaler live-splits/merges "
                         "min->--max-shards->min under load, and the "
                         "run fails on any lost or misplaced object "
                         "(ELASTIC_r{N}.json artifact)")
    ap.add_argument("--diurnal-objects", type=int, default=600,
                    help="total objects the simulated day creates "
                         "across its three waves (>=60)")
    ap.add_argument("--max-shards", type=int, default=6,
                    help="diurnal mode: the envelope ceiling the day "
                         "scales up to (floor is max(2, --shards))")
    ap.add_argument("--split-depth", type=float, default=6.0,
                    help="diurnal mode: mean per-shard workqueue depth "
                         "that counts as sustained pressure")
    ap.add_argument("--merge-depth", type=float, default=1.0,
                    help="diurnal mode: mean per-shard workqueue depth "
                         "at or below which the fleet is idle")
    ap.add_argument("--chaos-split", action="store_true",
                    help="diurnal mode: seeded chaos arm that SIGKILLs "
                         "the busiest donor mid-split (between bulk "
                         "copy and tail replay); the watchdog respawn "
                         "+ WAL tail-chase must still deliver zero "
                         "loss")
    ap.add_argument("--seed", type=int, default=1234,
                    help="chaos seed for --chaos-split")
    ap.add_argument("--arrival-rate", type=float, default=0.0,
                    metavar="R",
                    help="diurnal mode: OPEN-LOOP load — object i of "
                         "each wave arrives at t0 + i/R (objects/s) "
                         "whether or not earlier creates finished, so "
                         "backlog builds whenever R outruns the "
                         "fleet's reconcile throughput and the "
                         "autoscaler reaches the envelope on signals "
                         "alone (the run asserts ZERO forced splits); "
                         "0 = the legacy closed-loop saturating wave")
    ap.add_argument("--active-defrag",
                    action=argparse.BooleanOptionalAction,
                    default=True,
                    help="active fragmentation-driven placement "
                         "(scheduler idle passes migrate one victim "
                         "whenever doing so grows the largest free "
                         "contiguous block). Default ON since the "
                         "ratchet A/B proved the admission-latency "
                         "win; --no-active-defrag is the last-resort-"
                         "only baseline arm")
    ap.add_argument("--hang-dump", type=float, default=0.0, metavar="S",
                    help="arm faulthandler to dump every thread's "
                         "stack after S seconds (CI contention-stress "
                         "deadlock canary; 0 = off)")
    ap.add_argument("--no-tracing", action="store_true",
                    help="wallclock mode: disable distributed tracing "
                         "(the overhead A/B baseline arm; spans are "
                         "otherwise collected end-to-end from POST to "
                         "Ready across every process)")
    ap.add_argument("--trace-out", default="",
                    help="write the trace artifact JSON here "
                         "(TRACE_r{N}.json: slowest provision's full "
                         "span tree + critical path, per-phase "
                         "exemplars; wallclock mode with tracing on)")
    ap.add_argument("--out", default="",
                    help="also write the result JSON to this file "
                         "(PROVISION_r{N}.json artifact)")
    ap.add_argument("--lock-analysis", action="store_true",
                    help="run the storm under the instrumented lock "
                         "factory (analysis/lockgraph) and fail on "
                         "lock-order cycles, rank inversions, "
                         "hierarchy violations, or blocking calls "
                         "under hot locks; set KFRM_LOCK_ANALYSIS=1 "
                         "too so module-level locks are covered")
    ap.add_argument("--lockgraph-out", default="",
                    help="write the lockgraph report JSON here "
                         "(LOCKGRAPH_r{N}.json artifact)")
    args = ap.parse_args()
    if args.lock_analysis:
        from kubeflow_rm_tpu.analysis import lockgraph
        lockgraph.set_enabled(True)
    # module-level switch: covers every Manager in this process (the
    # platform manager AND the wallclock kubelet both import runtime)
    from kubeflow_rm_tpu.controlplane import runtime, scheduler, suspend
    runtime.set_serial_writes(args.serial_writes)
    scheduler.set_legacy_scan(args.legacy_schedule)
    suspend.set_oversubscribe(not args.no_oversubscribe)
    suspend.set_active_defrag(args.active_defrag)
    if args.hang_dump > 0:
        # a deadlock in the sharded locking scheme must fail CI with
        # stacks, not eat the job's timeout silently
        import faulthandler
        faulthandler.dump_traceback_later(args.hang_dump, exit=True)
    if args.diurnal:
        return diurnal_main(args) or _lockgraph_gate(args)
    if args.wallclock:
        return wallclock_main(args) or _lockgraph_gate(args)

    # suspend lifecycle controller on, idle parking off: explicit API
    # suspends work, spawn-path behavior is otherwise unchanged
    api, mgr = make_control_plane(cache=not args.no_cache,
                                  global_lock=args.global_lock,
                                  enable_suspend=True)

    # fake fleet: enough hosts for every requested slice
    pools = []
    for spec in args.slices.split(","):
        accel, count = spec.split("=")
        pools.append((accel, int(count)))
        topo = tpu_api.lookup(accel)
        for s in range(int(count)):
            for h in range(topo.hosts):
                api.create(make_tpu_node(f"{accel}-s{s}-h{h}", accel))

    # namespace via the profile path (RBAC comes from the controller)
    api.create(make_profile("conformance", USER))
    mgr.enqueue_all()
    mgr.run_until_idle()

    app = jwa.create_app(api)
    client = app.test_client(user=USER)
    accel = pools[0][0]
    topo = tpu_api.lookup(accel)

    latencies = []
    t_start = time.perf_counter()
    for i in range(args.notebooks):
        body = {
            "name": f"conf-{i}",
            "image": "ghcr.io/kubeflow-rm-tpu/jupyter-jax:latest",
            "imagePullPolicy": "IfNotPresent", "serverType": "jupyter",
            "cpu": "2", "memory": "8Gi",
            "tpu": {"acceleratorType": accel},
            "tolerationGroup": "none", "affinityConfig": "none",
            "configurations": [], "shm": True, "environment": {},
            "datavols": [],
        }
        t0 = time.perf_counter()
        resp = client.post(
            f"/api/namespaces/conformance/notebooks",
            data=json.dumps(body),
            headers=[("Content-Type", "application/json")])
        assert resp.status_code == 200, resp.get_data()
        reconciles = mgr.run_until_idle()
        latencies.append((time.perf_counter() - t0, reconciles))

        nb = api.get(nb_api.KIND, f"conf-{i}", "conformance")
        ready = nb.get("status", {}).get("readyReplicas", 0)
        pods = [p for p in api.list("Pod", "conformance")
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == f"conf-{i}"]
        if i * topo.hosts + topo.hosts <= sum(
                c * tpu_api.lookup(a).hosts for a, c in pools):
            assert ready == topo.hosts, (
                f"conf-{i}: {ready}/{topo.hosts} ready")
            envs = [
                {e["name"] for c in p["spec"]["containers"]
                 for e in c.get("env", [])}
                for p in pods
            ]
            for env in envs:
                assert {"TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES"} <= env
        else:
            # fleet exhausted: the slice must be Pending whole, not rump
            assert ready == 0, f"conf-{i}: rump slice with {ready} ready"

    total = time.perf_counter() - t_start

    # suspend->resume cycle: park each admitted slice through the API
    # arm (PATCH suspended) and measure request->Ready resume latency.
    # Skipped when the fleet is exhausted: a drained slice's chips are
    # immediately re-ganged by the Pending overflow (by design — the
    # oversubscription loop itself is proven in oversub_conformance.py),
    # so the resume would block on capacity, not on the lifecycle.
    resume_lat: list[float] = []
    admitted = [
        f"conf-{i}" for i in range(args.notebooks)
        if api.get(nb_api.KIND, f"conf-{i}", "conformance")
        .get("status", {}).get("readyReplicas", 0) == topo.hosts]
    if len(admitted) == args.notebooks:
        for name in admitted:
            url = f"/api/namespaces/conformance/notebooks/{name}"
            hdrs = [("Content-Type", "application/json")]
            resp = client.patch(url, data=json.dumps({"suspended": True}),
                                headers=hdrs)
            assert resp.status_code == 200, resp.get_data()
            mgr.run_until_idle()
            nb = api.get(nb_api.KIND, name, "conformance")
            assert nb.get("status", {}).get("phase") == \
                nb_api.SUSPENDED_PHASE, nb.get("status")
            t0 = time.perf_counter()
            resp = client.patch(url, data=json.dumps({"suspended": False}),
                                headers=hdrs)
            assert resp.status_code == 200, resp.get_data()
            for _ in range(20):
                mgr.run_until_idle()
                nb = api.get(nb_api.KIND, name, "conformance")
                if nb.get("status", {}).get(
                        "readyReplicas", 0) == topo.hosts:
                    break
            else:
                raise AssertionError(f"{name} never resumed")
            resume_lat.append(time.perf_counter() - t0)
    resume_lat.sort()
    suspend_resume = {"count": len(resume_lat)}
    if resume_lat:
        suspend_resume.update(
            resume_p50_ms=round(
                resume_lat[len(resume_lat) // 2] * 1e3, 1),
            resume_p95_ms=round(
                resume_lat[max(0, int(len(resume_lat) * 0.95) - 1)]
                * 1e3, 1))

    # multi-role gang jobs arm: storm N TPUJobs over a dedicated node
    # pool (the notebook fleet is sized for notebooks); every gang —
    # learner slice + CPU actors — must assemble whole, all-or-nothing
    jobs_section = None
    if args.jobs:
        from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api
        for s in range(args.jobs):
            for h in range(topo.hosts):
                api.create(make_tpu_node(f"{accel}-job{s}-h{h}", accel))
        t0 = time.perf_counter()
        for j in range(args.jobs):
            api.create(tj_api.make_tpujob(
                f"conf-job-{j}", "conformance", roles=[
                    {"name": "learner", "replicas": 1,
                     "tpu": {"acceleratorType": accel}},
                    {"name": "actors", "replicas": 4, "cpu": "1"},
                ]))
        mgr.run_until_idle()
        gang_pods = 0
        for j in range(args.jobs):
            job = api.get(tj_api.KIND, f"conf-job-{j}", "conformance")
            st = job.get("status") or {}
            assert st.get("phase") == tj_api.RUNNING_PHASE, (
                f"conf-job-{j} gang never assembled: {st}")
            assert st.get("readyPods") == st.get("totalPods"), st
            gang_pods += st["totalPods"]
        jobs_section = {
            "count": args.jobs,
            "actors_per_job": 4,
            "gang_pods": gang_pods,
            "wall_ms": round(1e3 * (time.perf_counter() - t0), 1),
        }

    p50 = sorted(t for t, _ in latencies)[len(latencies) // 2]
    result = {
        "run_meta": _run_meta(args, "in-process"),
        "notebooks": args.notebooks,
        "slice": accel,
        "hosts_per_slice": topo.hosts,
        "oversubscribe": not args.no_oversubscribe,
        "provision_p50_ms": round(p50 * 1e3, 1),
        "suspend_resume": suspend_resume,
        **({"jobs": jobs_section} if jobs_section else {}),
        "total_s": round(total, 2),
        "reconciles_per_spawn": [r for _, r in latencies],
    }
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print("CONFORMANCE OK")
    return _lockgraph_gate(args)


# locks on the spawn/reconcile hot path: a blocking syscall observed
# while one is held is a latency bug (the snapshot path's rotate under
# apiserver.write_log is the one documented, deliberate exception —
# see proposals/20260805-concurrency-analysis.md)
HOT_LOCK_PREFIXES = ("apiserver.kind", "scheduler.", "cache.store",
                     "runtime.", "workqueue", "readiness.")


def _lockgraph_gate(args) -> int:
    """When the storm ran under ``--lock-analysis``: dump the measured
    lock graph and fail the run on any concurrency-correctness
    violation the dynamic analysis can witness."""
    from kubeflow_rm_tpu.analysis import lockgraph
    from kubeflow_rm_tpu.analysis.hierarchy import check_edges
    if not lockgraph.enabled():
        return 0
    rep = lockgraph.report()
    if args.lockgraph_out:
        with open(args.lockgraph_out, "w") as f:
            json.dump(rep, f, indent=1)
    problems = []
    for c in rep["cycles"]:
        problems.append(
            "lock-order cycle: " + " <-> ".join(c["locks"]))
    for v in rep["order_violations"]:
        problems.append(
            f"rank inversion in {v['group']}: held {v['held_rank']} "
            f"then acquired {v['acquired_rank']} (x{v['count']})")
    problems.extend(check_edges(rep["edges"]))
    for b in rep["blocking_under_lock"]:
        if any(h.startswith(HOT_LOCK_PREFIXES) for h in b["held"]):
            problems.append(
                f"blocking {b['op']} under hot lock(s) "
                f"{','.join(b['held'])} (x{b['count']})\n"
                f"    {b['witness']}")
    if problems:
        print("LOCKGRAPH GATE FAILED:", file=sys.stderr)
        for p in problems:
            print("  " + p, file=sys.stderr)
        return 3
    print(f"LOCKGRAPH OK ({len(rep['locks'])} locks, "
          f"{len(rep['edges'])} edges, 0 cycles, 0 hot-lock blocking)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
