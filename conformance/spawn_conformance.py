#!/usr/bin/env python3
"""Spawn-path conformance + load test.

The reference ships a load-test seed that mass-spawns notebook servers
(``notebook-controller/loadtest/start_notebooks.py`` +
``jupyter_test.yaml``) and a conformance harness shape
(``conformance/1.7``). This script is both for the TPU stack: it boots
the full control plane against a fake TPU fleet, drives the #1 call
stack (SURVEY.md §3.1) through the REAL web API N times — authn,
CSRF, authz, form→CR, webhook mutation, reconcile, scheduling,
rendezvous env — and asserts every slice comes up whole, printing
provisioning latency stats (reconcile counts stand in for wall time on
the in-memory apiserver).

Usage:
    python conformance/spawn_conformance.py --slices v5p-16=2 --notebooks 3
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from kubeflow_rm_tpu.controlplane import make_control_plane  # noqa: E402
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api.profile import make_profile  # noqa: E402
from kubeflow_rm_tpu.controlplane.controllers.statefulset import (  # noqa: E402
    make_tpu_node,
)
from kubeflow_rm_tpu.controlplane.webapps import jupyter as jwa  # noqa: E402

USER = "conformance@corp.com"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slices", default="v5p-16=2",
                    help="comma list of acceleratorType=count node pools")
    ap.add_argument("--notebooks", type=int, default=3)
    args = ap.parse_args()

    api, mgr = make_control_plane()

    # fake fleet: enough hosts for every requested slice
    pools = []
    for spec in args.slices.split(","):
        accel, count = spec.split("=")
        pools.append((accel, int(count)))
        topo = tpu_api.lookup(accel)
        for s in range(int(count)):
            for h in range(topo.hosts):
                api.create(make_tpu_node(f"{accel}-s{s}-h{h}", accel))

    # namespace via the profile path (RBAC comes from the controller)
    api.create(make_profile("conformance", USER))
    mgr.enqueue_all()
    mgr.run_until_idle()

    app = jwa.create_app(api)
    client = app.test_client(user=USER)
    accel = pools[0][0]
    topo = tpu_api.lookup(accel)

    latencies = []
    t_start = time.perf_counter()
    for i in range(args.notebooks):
        body = {
            "name": f"conf-{i}",
            "image": "ghcr.io/kubeflow-rm-tpu/jupyter-jax:latest",
            "imagePullPolicy": "IfNotPresent", "serverType": "jupyter",
            "cpu": "2", "memory": "8Gi",
            "tpu": {"acceleratorType": accel},
            "tolerationGroup": "none", "affinityConfig": "none",
            "configurations": [], "shm": True, "environment": {},
            "datavols": [],
        }
        t0 = time.perf_counter()
        resp = client.post(
            f"/api/namespaces/conformance/notebooks",
            data=json.dumps(body),
            headers=[("Content-Type", "application/json")])
        assert resp.status_code == 200, resp.get_data()
        reconciles = mgr.run_until_idle()
        latencies.append((time.perf_counter() - t0, reconciles))

        nb = api.get(nb_api.KIND, f"conf-{i}", "conformance")
        ready = nb.get("status", {}).get("readyReplicas", 0)
        pods = [p for p in api.list("Pod", "conformance")
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == f"conf-{i}"]
        if i * topo.hosts + topo.hosts <= sum(
                c * tpu_api.lookup(a).hosts for a, c in pools):
            assert ready == topo.hosts, (
                f"conf-{i}: {ready}/{topo.hosts} ready")
            envs = [
                {e["name"] for c in p["spec"]["containers"]
                 for e in c.get("env", [])}
                for p in pods
            ]
            for env in envs:
                assert {"TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES"} <= env
        else:
            # fleet exhausted: the slice must be Pending whole, not rump
            assert ready == 0, f"conf-{i}: rump slice with {ready} ready"

    total = time.perf_counter() - t_start
    p50 = sorted(t for t, _ in latencies)[len(latencies) // 2]
    print(json.dumps({
        "notebooks": args.notebooks,
        "slice": accel,
        "hosts_per_slice": topo.hosts,
        "provision_p50_ms": round(p50 * 1e3, 1),
        "total_s": round(total, 2),
        "reconciles_per_spawn": [r for _, r in latencies],
    }))
    print("CONFORMANCE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
