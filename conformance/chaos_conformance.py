#!/usr/bin/env python3
"""Seeded fault-matrix storm: chaos across every choke point at once,
zero lost notebooks.

The chaos engine (``controlplane/chaos.py``) injects faults one choke
point at a time in unit tests; this harness is the integration claim —
a FULL fault matrix armed simultaneously over the wall-clock socket
stack (in-memory apiserver + admission + fake kubelet behind the REST
facade, an elected controller manager over the kube adapter with watch
threads), while a threaded client storm provisions a fleet of notebooks
and drives suspend/resume cycles through the real lifecycle verbs:

- ``reconcile_stall``    latency inside every controller's reconcile
- ``api_error``          synthetic 503s on the kube adapter's verbs
- ``api_timeout``        injected client timeouts on the same path
- ``watch_drop``         lost watch events (surfaced as TOO_OLD gaps)
- ``watch_dup``          duplicated watch deliveries
- ``checkpoint_fail``    checkpoint-store write failures mid-suspend
- ``pod_kill``           kubelet-level pod kills under running slices

Every arm heals through the platform's OWN recovery ladders (requeue
with backoff, relist on TOO_OLD, level-triggered convergence, slice
restart, lifecycle retry) — no harness-side cleanup. The claims in the
artifact (``CHAOS_r{N}.json``):

- **zero lost notebooks**: every spawned notebook reaches full slice
  readiness after the plan is uninstalled, none disappears;
- **exactness through chaos**: every suspend→resume cycle restores the
  checkpointed training step exactly, even with checkpoint writes
  failing underneath;
- **full attribution**: a fixed seed reproduces the fault mix; every
  enabled fault kind fired ≥1× and is itemized (counts, opportunities,
  ledger) in the artifact, with rate-limited flight-recorder bundles
  per injected incident (``--flight-out``).

``--no-chaos`` is the control arm for CI's perf ratchet: the identical
storm with no plan installed, asserting zero injections — so latency
baselines are never polluted by injected faults.

Usage:
    python conformance/chaos_conformance.py --out CHAOS_r01.json \\
        --flight-out FLIGHT_ci.json
    python conformance/chaos_conformance.py --no-chaos
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from kubeflow_rm_tpu.controlplane import (  # noqa: E402
    WATCHED_KINDS, chaos, make_cluster_manager, metrics, suspend,
)
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api import poddefault as pd_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api.meta import deep_get  # noqa: E402
from kubeflow_rm_tpu.controlplane.api.notebook import (  # noqa: E402
    make_notebook,
)
from kubeflow_rm_tpu.controlplane.api.profile import make_profile  # noqa: E402
from kubeflow_rm_tpu.controlplane.apiserver import (  # noqa: E402
    APIError, APIServer, Conflict,
)
from kubeflow_rm_tpu.controlplane.obs.flight import (  # noqa: E402
    FlightRecorder,
)
from kubeflow_rm_tpu.controlplane.obs.runmeta import (  # noqa: E402
    build_run_meta,
)

NS = "chaos"
USER = "chaos@corp.com"
ACCEL = "v5p-8"          # single-host slices: one node per notebook

# transient surfaces of the armed plan (plus CAS races the storm's
# threads cause on their own) — everything a client-side retry heals
_TRANSIENT = (APIError, Conflict, TimeoutError, OSError)


def _retry(fn, *, attempts=40, what="op"):
    """Client-side retry loop: injected 503s/timeouts and checkpoint
    write failures surface HERE (the harness is the client); a real
    notebook user's SDK retries exactly like this."""
    for attempt in range(attempts):
        try:
            return fn()
        except _TRANSIENT:
            if attempt == attempts - 1:
                raise
            time.sleep(0.05)


def default_plan(seed: int, flight) -> chaos.FaultPlan:
    """The CI fault matrix: all seven one-process fault kinds armed at
    once. Rates are tuned so high-opportunity sites (api verbs, watch
    fanout, reconciles) fire a handful of times over the storm, while
    low-opportunity sites (checkpoint writes, running-slice kills) are
    near-certain per opportunity but capped so convergence is never
    starved."""
    return chaos.FaultPlan(seed, [
        chaos.FaultSpec("reconcile_stall", rate=0.05, stall_ms=5.0),
        chaos.FaultSpec("api_error", rate=0.03),
        chaos.FaultSpec("api_timeout", rate=0.02),
        chaos.FaultSpec("watch_drop", rate=0.03),
        chaos.FaultSpec("watch_dup", rate=0.03),
        chaos.FaultSpec("checkpoint_fail", rate=0.75, limit=2),
        chaos.FaultSpec("pod_kill", rate=0.5, limit=2,
                        match=f"{NS}/"),
    ], flight=flight)


def local_stack(stop, *, nodes: int):
    """The e2e_walk local backend, storm-shaped: one elected-manager
    process layout (apiserver + webhooks + fake kubelet + REST facade +
    cluster manager over the kube adapter), suspend lifecycle on, no
    idle culler, short SyncPeriod so dropped watch events heal in ~2s
    instead of stalling a wait."""
    from kubeflow_rm_tpu.controlplane.controllers.statefulset import (
        DeploymentController, StatefulSetController, make_tpu_node,
    )
    from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
        KubeAPIServer,
    )
    from kubeflow_rm_tpu.controlplane.deploy.restserver import RestServer
    from kubeflow_rm_tpu.controlplane.runtime import Manager
    from kubeflow_rm_tpu.controlplane.webhook.notebook import (
        NotebookWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.poddefault import (
        PodDefaultWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.tpu_inject import (
        TpuInjectWebhook,
    )

    capi = APIServer()
    capi.register_validator(nb_api.KIND, nb_api.validate)
    capi.register_validator(pd_api.KIND, pd_api.validate)
    capi.register_validator(tj_api.KIND, tj_api.validate)
    NotebookWebhook(capi).register()
    PodDefaultWebhook(capi).register()
    TpuInjectWebhook(capi).register()
    kubelet = Manager(capi)
    kubelet.add(StatefulSetController(auto_ready=True))
    kubelet.add(DeploymentController(auto_ready=True))
    for i in range(nodes):
        capi.create(make_tpu_node(f"{ACCEL}-n{i}", ACCEL))
    rest = RestServer(capi)
    rest.start()
    threading.Thread(target=kubelet.run_forever, args=(stop, 0.05),
                     kwargs={"resync_interval_s": 2.0},
                     daemon=True).start()

    mapi = KubeAPIServer(rest.url, identity="chaos-mgr")
    mgr = make_cluster_manager(mapi, enable_culling=False,
                               enable_suspend=True)
    for kind in WATCHED_KINDS:
        threading.Thread(target=mapi.watch_kind,
                         args=(kind, None, stop, 60),
                         daemon=True).start()
    mgr.enqueue_all()
    threading.Thread(target=mgr.run_forever, args=(stop, 0.05),
                     kwargs={"workers": 8,
                             "resync_interval_s": 2.0},
                     daemon=True).start()
    # the storm's own client: live (uncached) reads, so every harness
    # verb crosses the injected request path like real user traffic
    return KubeAPIServer(rest.url, identity="chaos-client"), rest


class Storm:
    def __init__(self, api, n: int):
        self.api = api
        self.n = n
        self.hosts = tpu_api.lookup(ACCEL).hosts
        self.names = [f"chaos-{i}" for i in range(n)]

    def wait(self, cond, timeout=120, what="condition"):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = _retry(cond, what=what)
            if v:
                return v
            time.sleep(0.05)
        raise AssertionError(f"timed out waiting for {what}")

    def ready(self, name: str) -> bool:
        nb = self.api.try_get("Notebook", name, NS)
        return bool(nb and (nb.get("status") or {}).get(
            "readyReplicas") == self.hosts)

    def onboard(self):
        _retry(lambda: self.api.create(make_profile(NS, USER)),
               what="profile create")
        self.wait(lambda: self.api.try_get(
            "RoleBinding", "namespaceAdmin", NS), what="profile ready")

    def spawn(self):
        """Threaded provision storm: every create crosses the injected
        verb path; every readiness wait rides the chaos-laced watch and
        reconcile machinery."""
        from concurrent.futures import ThreadPoolExecutor

        def one(name):
            _retry(lambda: self.api.create(make_notebook(
                name, NS, accelerator_type=ACCEL,
                annotations={nb_api.CULLING_EXCLUDE_ANNOTATION:
                             "true"})), what=f"create {name}")
            self.wait(lambda name=name: self.ready(name),
                      what=f"{name} ready under chaos")

        with ThreadPoolExecutor(max_workers=4) as pool:
            list(pool.map(one, self.names))

    def lifecycle_cycles(self, count: int) -> list[dict]:
        """Suspend→resume cycles through the real verbs while the plan
        is armed: checkpoint writes fail underneath (the injected
        OSError surfaces to this client, which retries), drains race
        stalled reconciles, and the restored step must still be EXACT.
        Sequential on purpose: the checkpoint_fail stream then draws in
        a deterministic order for a fixed seed."""
        cycles = []
        for i, name in enumerate(self.names[:count]):
            step = str(10 + i)

            def stamp(name=name, step=step):
                nb = self.api.get("Notebook", name, NS)
                nb["metadata"].setdefault("annotations", {})[
                    nb_api.TRAINING_STEP_ANNOTATION] = step
                self.api.update(nb)
            _retry(stamp, what=f"stamp {name}")

            _retry(lambda name=name: suspend.initiate_suspend(
                self.api, self.api.get("Notebook", name, NS),
                reason="api"), what=f"suspend {name}")
            self.wait(lambda name=name: (
                (self.api.get("Notebook", name, NS).get("status") or {})
                .get("phase") == nb_api.SUSPENDED_PHASE),
                what=f"{name} suspended")

            _retry(lambda name=name: suspend.request_resume(
                self.api, self.api.get("Notebook", name, NS),
                source="api"), what=f"resume {name}")
            self.wait(lambda name=name: self.ready(name),
                      what=f"{name} resumed")
            restored = self.wait(
                lambda name=name: (self.api.get(
                    "Notebook", name, NS)["metadata"]
                    .get("annotations") or {}).get(
                    nb_api.RESTORED_STEP_ANNOTATION),
                what=f"{name} restored step")
            assert restored == step, \
                f"{name}: restored {restored} != checkpointed {step}"
            cycles.append({"notebook": name, "step": int(step),
                           "restored": int(restored)})
        return cycles

    def assert_zero_lost(self):
        """After the plan is gone the fleet must converge whole: every
        notebook still exists and reaches full slice readiness, every
        slice runs with exactly ``hosts`` Running pods."""
        for name in self.names:
            self.wait(lambda name=name: self.ready(name),
                      what=f"{name} ready post-chaos")
        pods = _retry(lambda: self.api.list("Pod", NS))
        by_nb: dict[str, int] = {}
        for p in pods:
            owner = (p["metadata"].get("labels") or {}).get(
                nb_api.NOTEBOOK_NAME_LABEL)
            if owner and deep_get(p, "status", "phase") == "Running":
                by_nb[owner] = by_nb.get(owner, 0) + 1
        for name in self.names:
            assert by_nb.get(name) == self.hosts, \
                f"{name}: {by_nb.get(name)} running pods != {self.hosts}"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=20260805,
                    help="FaultPlan seed (fixed in CI for a "
                         "reproducible fault mix)")
    ap.add_argument("--notebooks", type=int, default=8)
    ap.add_argument("--cycles", type=int, default=4,
                    help="suspend->resume cycles driven under chaos")
    ap.add_argument("--faults", default="",
                    help="override the fault matrix "
                         "(fault[:rate[:stall_ms]],... — see "
                         "chaos.plan_from_args); default: all seven "
                         "one-process kinds at CI rates")
    ap.add_argument("--no-chaos", action="store_true",
                    help="control arm: identical storm, no plan "
                         "installed, zero injections asserted (keeps "
                         "the perf ratchet unpolluted)")
    ap.add_argument("--flight-out", default="",
                    help="write the flight-recorder bundles (one per "
                         "non-rate-limited injected incident) to this "
                         "JSON file")
    ap.add_argument("--out", default="",
                    help="write the result JSON (CHAOS_r{N}.json)")
    args = ap.parse_args()

    t0 = time.perf_counter()
    injected_before = metrics.registry_value(
        "chaos_faults_injected_total")
    suspend.set_state_store(suspend.InMemoryStateStore())
    stop = threading.Event()
    api, rest = local_stack(stop, nodes=args.notebooks)
    storm = Storm(api, args.notebooks)
    flight = FlightRecorder(
        min_interval_s=1.0,
        run_meta=build_run_meta(
            "chaos_conformance",
            {"arm": "no-chaos" if args.no_chaos else "chaos",
             "seed": args.seed, "notebooks": args.notebooks}))

    plan = None
    if not args.no_chaos:
        plan = (chaos.plan_from_args(args.seed, args.faults,
                                     flight=flight)
                if args.faults else default_plan(args.seed, flight))
        chaos.install(plan)
    try:
        storm.onboard()
        storm.spawn()
        cycles = storm.lifecycle_cycles(args.cycles)
    finally:
        plan = chaos.uninstall() or plan
        stop_late = stop  # keep the stack up for convergence checks
    storm.assert_zero_lost()
    if plan is not None:
        plan.flush_flight()
    stop_late.set()

    result: dict = {
        "run_meta": flight.run_meta,
        "arm": "no-chaos" if args.no_chaos else "chaos",
        "seed": args.seed,
        "accelerator": ACCEL,
        "notebooks": args.notebooks,
        "suspend_resume_cycles": cycles,
        "zero_lost_notebooks": True,      # asserted above
        "restored_steps_exact": True,     # asserted per cycle
        "total_s": round(time.perf_counter() - t0, 2),
    }
    if args.no_chaos:
        injected = metrics.registry_value(
            "chaos_faults_injected_total") - injected_before
        assert injected == 0, \
            f"{injected} faults injected in the no-chaos arm"
        result["faults"] = {}
        result["injections_total"] = 0
    else:
        summary = plan.summary()
        missing = [s.fault for s in plan.specs
                   if summary["faults"].get(s.fault, 0) < 1]
        assert not missing, \
            f"fault kinds never fired: {missing} " \
            f"(opportunities: {summary['opportunities']})"
        result["faults"] = summary["faults"]
        result["fault_opportunities"] = summary["opportunities"]
        result["injections_total"] = sum(summary["faults"].values())
        result["ledger"] = plan.ledger()
        result["flight"] = {
            "bundles": flight.triggered_total,
            "suppressed_rate_limited": flight.suppressed_total,
        }
    if args.flight_out:
        with open(args.flight_out, "w") as f:
            json.dump({"run_meta": flight.run_meta,
                       "bundles": flight.bundles(),
                       "triggered_total": flight.triggered_total,
                       "suppressed_total": flight.suppressed_total},
                      f, indent=1, default=str)
        result["flight_out"] = args.flight_out

    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print(f"CHAOS CONFORMANCE OK ({result['arm']}: "
          f"{result['injections_total']} injections, "
          f"0 lost notebooks)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
