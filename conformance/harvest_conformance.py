#!/usr/bin/env python3
"""Chip-harvesting conformance: one diurnal day, measured A/B.

The r20 claim is pure utilization: during an evening serving flood the
chips under idle/suspended notebooks are dead weight unless the
serving fleet can borrow them — and borrowing is only safe if every
chip comes back the moment its notebook wants it, inside the r15
failover SLO, with the training step restored bit-exact.

This harness plays one compressed "day" per segment on the in-process
stack (fake clock, real web-of-controllers, real tiny-Llama decode on
CPU):

1. **morning** — donor notebooks spawn, gang-bind, train (their
   durable ``TRAINING_STEP`` advances);
2. **evening** — the donors idle out and the SuspendController parks
   them (checkpoint -> drain -> release); serving demand floods: an
   unmeasured pressure wave deepens the decode queue, and in the
   harvest arm the :class:`ChipHarvestController` grants leases on the
   freed slices and registers borrowed replicas with the fleet;
3. **flood (measured)** — a fixed burst of prompts hits the fleet at
   once; useful tok/s = tokens of requests actually served within a
   fixed window / the window. Per-replica queues are bounded (an
   unbounded queue is an OOM, not a policy choice), so the baseline's
   lone replica sheds most of the burst — shed demand is decode
   capacity lost forever, which is precisely what idle notebook chips
   cost. Every served output is compared against the solo
   ``generate_fused`` oracle — the SAME oracle for both arms, so
   "harvest serves more" can never hide "harvest serves different";
4. **morning after** — each donor demand-resumes. The harvest arm must
   reclaim its lease (drain the borrowed replica, release the charge)
   and re-gang the notebook with ``RESTORED_STEP`` exactly equal to
   the step that went in; per-reclaim latency is asserted against
   ``harvest.FAILOVER_SLO_S``.

Invariants on every sample, both arms: zero chip overcommit (ground
truth read from the scheduler's node ledger, which is where synthetic
harvest charges live — pods alone cannot see a lease), zero lost
notebooks.

A/B is interleaved on the same host: baseline segment, harvest
segment, repeated ``--interleaves`` times, each stamped with
``run_meta`` (``interleave_index`` increments across segments) so the
ratchet can refuse mismatched comparisons. The headline assert is
per-pair AND aggregate: the harvest arm's useful tok/s strictly beats
the baseline it interleaved with.

Usage:
    python conformance/harvest_conformance.py --out HARVEST_r01.json
    python conformance/harvest_conformance.py --no-harvest
"""

from __future__ import annotations

import argparse
import datetime
import json
import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from kubeflow_rm_tpu.controlplane import (  # noqa: E402
    harvest, make_control_plane, metrics, scheduler, suspend,
)
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api.meta import (  # noqa: E402
    annotations_of, set_annotation,
)
from kubeflow_rm_tpu.controlplane.api.notebook import (  # noqa: E402
    make_notebook,
)
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.controllers.statefulset import (  # noqa: E402
    make_tpu_node,
)
from kubeflow_rm_tpu.controlplane.obs.runmeta import (  # noqa: E402
    build_run_meta,
)
from kubeflow_rm_tpu.controlplane.serving_fleet import (  # noqa: E402
    ServingFleet,
)
from kubeflow_rm_tpu.controlplane.webapps.serving import (  # noqa: E402
    ServingGateway,
)

NS = "serve-day"

#: the measured flood: fixed prompts, fixed budget — identical in both
#: arms so the useful-tok/s delta is capacity, not workload. 16
#: near-simultaneous requests against a per-replica absorb capacity of
#: slots(2) + max_queue(4) = 6: the baseline's lone replica MUST shed
#: most of the flood (the queue cap is real — an unbounded queue is an
#: OOM, not a policy choice), while the harvest arm's 3 replicas
#: absorb all of it. Shed demand is capacity lost forever: its tokens
#: are never decoded, which is exactly what idle notebook chips cost.
FLOOD_PROMPTS = [[i + 1, 7, 3, (i % 5) + 2] for i in range(16)]
#: the fixed measurement window useful tok/s is normalized over (both
#: arms identically); every served request must complete inside it
FLOOD_WINDOW_S = 3.0
#: per-gateway queue cap (shared by base and harvest replicas)
MAX_QUEUE = 4
SPREAD_PROMPTS = [[60 + i, 4, 8] for i in range(4)]


class FakeClock:
    """Manually-advanced clock: idle windows elapse in fake minutes so
    a day runs in CI seconds (decode throughput and reclaim latency are
    real wall time, untouched by this clock)."""

    def __init__(self, start: str = "2026-01-01T07:00:00+00:00"):
        self.now = datetime.datetime.fromisoformat(start)

    def __call__(self) -> datetime.datetime:
        return self.now

    def advance(self, **timedelta_kwargs) -> None:
        self.now = self.now + datetime.timedelta(**timedelta_kwargs)


class _Model:
    """Process-wide tiny model + the solo-decode oracle, shared by
    every segment of every arm (identical weights = comparable arms)."""

    _instance = None

    def __init__(self):
        import jax
        from kubeflow_rm_tpu.models import LlamaConfig, init_params
        self.cfg = LlamaConfig.tiny()
        self.params = init_params(self.cfg, jax.random.key(0))
        self._oracle: dict[tuple, list] = {}

    @classmethod
    def get(cls) -> "_Model":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def gateway(self) -> ServingGateway:
        from kubeflow_rm_tpu.models.generate import (
            ContinuousBatchingEngine,
        )
        eng = ContinuousBatchingEngine(self.params, self.cfg, slots=2,
                                       slot_len=32, block_size=4)
        return ServingGateway(eng, admission=False,
                              max_queue=MAX_QUEUE)

    def solo(self, prompt: list, budget: int) -> list:
        """The bit-exactness oracle: single-program fused decode."""
        key = (tuple(prompt), budget)
        if key not in self._oracle:
            import jax.numpy as jnp
            import numpy as np
            from kubeflow_rm_tpu.models.generate import generate_fused
            ref = generate_fused(self.params, self.cfg,
                                 jnp.asarray([prompt], jnp.int32),
                                 max_new_tokens=budget, max_len=32)
            self._oracle[key] = np.asarray(
                ref)[0, len(prompt):].tolist()
        return self._oracle[key]


def _counter(name: str, labels=None) -> float:
    return metrics.registry_value(name, labels) or 0.0


class Day:
    """One diurnal day for one arm."""

    def __init__(self, args, arm: str, interleave_index: int):
        self.args = args
        self.arm = arm
        self.idx = interleave_index
        self.model = _Model.get()
        accel, count = args.slices.split(",")[0].split("=")
        self.accel, self.slices = accel, int(count)
        self.topo = tpu_api.lookup(accel)
        self.clock = FakeClock()
        suspend.set_oversubscribe(True)
        suspend.set_state_store(suspend.InMemoryStateStore())
        self.api, self.mgr = make_control_plane(
            clock=self.clock, enable_suspend=True,
            suspend_config={"suspend_idle_minutes": args.idle_minutes,
                            "check_period_minutes": 1.0})
        self.api.ensure_namespace(NS)
        self.node_cap: dict[str, float] = {}
        for s in range(self.slices):
            for h in range(self.topo.hosts):
                node = f"{accel}-s{s}-h{h}"
                self.api.create(make_tpu_node(node, accel))
                self.node_cap[node] = float(self.topo.chips_per_host)
        self.capacity = sum(self.node_cap.values())
        self.donors = [f"donor-{i}" for i in range(self.slices)]
        self.steps = {n: str(37 + 11 * i)
                      for i, n in enumerate(self.donors)}
        self.base_gw = self.model.gateway()
        self.fleet = ServingFleet({"base": self.base_gw})
        self.ctl = None
        if arm == "harvest":
            self.ctl = harvest.ChipHarvestController(
                self.api, self.fleet,
                gateway_factory=lambda name: self.model.gateway(),
                pressure_depth=1.0, sustain=1, idle_minutes=15.0)
        self.samples: list[dict] = []
        self.mismatches = 0

    # ---- invariants --------------------------------------------------
    def check_overcommit(self) -> float:
        """Ground truth from the scheduler's node ledger — the only
        place synthetic harvest charges exist. Bound chips (pods AND
        leases) never exceed any node's capacity."""
        sched = scheduler.cache_for(self.api)
        total = 0.0
        with sched._nlock:
            nodes = list(sched._nodes.values())
        for node in nodes:
            with node.lock:
                assert node.used <= node.capacity + 1e-9, \
                    f"OVERCOMMIT: {node.name} {node.used}/{node.capacity}"
                total += node.used
        return total

    def sample(self, tag: str) -> None:
        bound = self.check_overcommit()
        sched = scheduler.cache_for(self.api)
        st = sched.stats()
        ph = {"ready": 0, "suspended": 0, "pending": 0}
        for name in self.donors:
            nb = self.api.get(nb_api.KIND, name, NS)
            if (nb.get("status") or {}).get(
                    "readyReplicas") == self.topo.hosts:
                ph["ready"] += 1
            elif nb_api.SUSPEND_ANNOTATION in annotations_of(nb):
                ph["suspended"] += 1
            else:
                ph["pending"] += 1
        self.samples.append({
            "t": self.clock().isoformat(), "tag": tag,
            "bound_chips": bound, "capacity_chips": self.capacity,
            "free_chips": st["free_chips"],
            "harvested_chips": sched.harvested_chips(),
            "serving_replicas": sum(
                1 for s in self.fleet.states().values()
                if s == "ready"),
            **ph,
        })

    def ready(self, name: str) -> bool:
        nb = self.api.get(nb_api.KIND, name, NS)
        return (nb.get("status") or {}).get(
            "readyReplicas") == self.topo.hosts

    def drive_until_ready(self, name: str, ticks: int = 30) -> None:
        for _ in range(ticks):
            if self.ready(name):
                return
            self.check_overcommit()
            self.clock.advance(minutes=1.0)
            self.mgr.run_until_idle()
        raise AssertionError(f"{name} never became ready")

    # ---- the day -----------------------------------------------------
    def morning(self) -> None:
        for name in self.donors:
            nb = make_notebook(name, NS, accelerator_type=self.accel)
            set_annotation(nb, nb_api.TRAINING_STEP_ANNOTATION,
                           self.steps[name])
            self.api.create(nb)
            self.mgr.run_until_idle()
        for name in self.donors:
            self.drive_until_ready(name)
        self.sample("morning")

    def evening_idle(self) -> None:
        """The donors idle past the culler's window and park: their
        slices drain and the chips go free (both arms identically)."""
        self.clock.advance(minutes=self.args.idle_minutes + 1.1)
        self.mgr.run_until_idle()
        for name in self.donors:
            ann = annotations_of(self.api.get(nb_api.KIND, name, NS))
            assert nb_api.SUSPEND_DRAINED_ANNOTATION in ann, \
                f"{name} did not drain for the evening"
        self.sample("evening-idle")

    def _wait_fleet_idle(self, timeout_s: float = 60.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            busy = any(gw.engine.queue_depth or gw.engine.active_slots
                       for gw in self.fleet.gateways.values())
            if not busy:
                return
            time.sleep(0.01)
        raise AssertionError("fleet never drained")

    def _decode_wave(self, prompts, budget, stagger_s=0.05):
        """Unmeasured helper wave through the fleet; returns outputs
        (None for a shed request)."""
        outputs: dict[int, list | None] = {}

        def run(i, p):
            outputs[i] = self.fleet.submit_and_wait(
                "warm", list(p), max_new_tokens=budget)[0]

        threads = [threading.Thread(target=run, args=(i, p))
                   for i, p in enumerate(prompts)]
        for t in threads:
            t.start()
            time.sleep(stagger_s)
        for t in threads:
            t.join(timeout=300)
        return [outputs[i] for i in range(len(prompts))]

    def _pressure_and_grant(self) -> int:
        """Deepen the base replica's queue with blocker decodes (real
        demand: the controller's pressure signal is queue depth, not a
        forced constant) and tick the controller until every idle
        slice is granted. The baseline arm runs the identical blocker
        load, just with nobody to answer it."""
        def blockers(n):
            admitted = 0
            for j in range(n):
                pend, _ = self.base_gw.try_submit(
                    "press", [90 + j, 2, 9],
                    max_new_tokens=self.args.budget)
                admitted += pend is not None
            return admitted

        assert blockers(2 + MAX_QUEUE) >= MAX_QUEUE, \
            "pressure blockers did not queue"
        grants = 0
        if self.ctl is not None:
            deadline = time.monotonic() + 30.0
            while grants < self.slices and time.monotonic() < deadline:
                d = self.ctl.tick()
                if d == "grant":
                    grants += 1
                elif d == "hold":
                    blockers(2)   # keep the queue visibly deep
                    time.sleep(0.02)
            assert grants == self.slices, \
                f"only {grants}/{self.slices} harvest grants landed"
            sched = scheduler.cache_for(self.api)
            assert sched.harvested_chips() == self.capacity, \
                "harvest did not absorb the whole idle pool"
        self._wait_fleet_idle()
        return grants

    def evening_flood(self) -> dict:
        """Pressure blockers (the harvest arm grants during them), a
        spread wave (warms every replica outside the measured window),
        then the measured flood: a near-simultaneous burst of
        ``FLOOD_PROMPTS``, useful tok/s = tokens of requests served
        within the fixed ``FLOOD_WINDOW_S`` / the window. Shed demand
        contributes zero useful tokens — that capacity is what the
        idle notebook chips were worth."""
        grants = self._pressure_and_grant()
        self.sample("evening-pressure")

        self._decode_wave(SPREAD_PROMPTS, self.args.budget)
        self._wait_fleet_idle()

        results: dict[int, tuple[list | None, float]] = {}

        def run(i, p):
            out, _info = self.fleet.submit_and_wait(
                "flood", list(p), max_new_tokens=self.args.budget)
            results[i] = (out, time.perf_counter() - t0)

        threads = [threading.Thread(target=run, args=(i, p))
                   for i, p in enumerate(FLOOD_PROMPTS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        served = shed = tokens = 0
        for i, p in enumerate(FLOOD_PROMPTS):
            out, t_done = results[i]
            if out is None:
                shed += 1
                continue
            assert t_done <= FLOOD_WINDOW_S, \
                f"request {i} finished at {t_done:.2f}s, outside the " \
                f"{FLOOD_WINDOW_S}s window"
            if out != self.model.solo(p, self.args.budget):
                self.mismatches += 1
            served += 1
            tokens += len(out)
        assert self.mismatches == 0, \
            f"{self.mismatches} flood outputs diverged from the oracle"
        assert served, "the flood served nothing at all"
        self.sample("evening-flood")
        return {"offered": len(FLOOD_PROMPTS), "served": served,
                "shed": shed, "tokens": tokens,
                "window_s": FLOOD_WINDOW_S,
                "useful_tok_s": round(tokens / FLOOD_WINDOW_S, 2),
                "harvest_grants": grants,
                "replicas_serving": self.samples[-1][
                    "serving_replicas"],
                "bit_exact": True}

    def morning_after(self) -> dict:
        """Each donor demand-resumes; the harvest arm reclaims the
        lease first. Reclaim latency (the serving side's give-back) is
        measured around the synchronous release, resume wall time
        around the whole re-gang."""
        resumes = []
        for name in self.donors:
            t0 = time.perf_counter()
            suspend.request_resume(
                self.api, self.api.get(nb_api.KIND, name, NS))
            reclaim_s = None
            if self.ctl is not None:
                r0 = time.perf_counter()
                decision = self.ctl.tick()
                reclaim_s = time.perf_counter() - r0
                assert decision == "reclaim", \
                    f"{name}: tick chose {decision}, not reclaim"
                assert reclaim_s <= harvest.FAILOVER_SLO_S, \
                    f"{name}: reclaim took {reclaim_s:.3f}s " \
                    f"> {harvest.FAILOVER_SLO_S}s failover SLO"
            self.mgr.run_until_idle()
            self.drive_until_ready(name)
            resume_wall = time.perf_counter() - t0
            nb = self.api.get(nb_api.KIND, name, NS)
            restored = annotations_of(nb).get(
                nb_api.RESTORED_STEP_ANNOTATION)
            assert restored == self.steps[name], \
                f"{name}: restored step {restored!r} != " \
                f"{self.steps[name]!r}"
            resumes.append({"notebook": name,
                            "restored_step": restored,
                            "step_exact": True,
                            "reclaim_s": (None if reclaim_s is None
                                          else round(reclaim_s, 4)),
                            "resume_wall_s": round(resume_wall, 3)})
        self.sample("morning-after")
        return {"resumes": resumes}

    def run(self) -> dict:
        before = {
            "grants": _counter("harvest_grants_total"),
            "reclaims_resume": _counter("harvest_reclaims_total",
                                        {"trigger": "resume"}),
            "reclaim_count": _counter("harvest_reclaim_seconds_count"),
            "reclaim_in_slo": _counter(
                "harvest_reclaim_seconds_bucket",
                {"le": str(harvest.FAILOVER_SLO_S)}),
        }
        self.morning()
        self.evening_idle()
        flood = self.evening_flood()
        night = self.morning_after()

        # zero lost notebooks: every donor is back, ready, exact
        lost = [n for n in self.donors if not self.ready(n)]
        assert not lost, f"lost notebooks: {lost}"
        sched = scheduler.cache_for(self.api)
        assert sched.harvested_chips() == 0.0, \
            "chips still on loan after the day ended"
        if self.ctl is not None:
            assert self.ctl.lease_count() == 0
            reclaimed = _counter("harvest_reclaims_total",
                                 {"trigger": "resume"}) \
                - before["reclaims_resume"]
            assert reclaimed >= self.slices, \
                f"only {reclaimed} resume-reclaims recorded"
            # every reclaim this segment landed in the <=SLO bucket
            n_new = _counter("harvest_reclaim_seconds_count") \
                - before["reclaim_count"]
            in_slo = _counter("harvest_reclaim_seconds_bucket",
                              {"le": str(harvest.FAILOVER_SLO_S)}) \
                - before["reclaim_in_slo"]
            assert in_slo == n_new, \
                f"{n_new - in_slo} reclaims blew the failover SLO"
        else:
            assert _counter("harvest_grants_total") \
                == before["grants"], \
                "baseline arm recorded a harvest grant"
        self.ctl and self.ctl.close()
        self.fleet.close()
        reclaims = [r["reclaim_s"] for r in night["resumes"]
                    if r["reclaim_s"] is not None]
        reclaims.sort()
        return {
            "arm": self.arm,
            "run_meta": build_run_meta(
                "harvest_conformance",
                {"arm": self.arm, "slices": self.args.slices,
                 "model": "tiny", "flood": len(FLOOD_PROMPTS),
                 "budget": self.args.budget},
                interleave_index=self.idx),
            **flood,
            **night,
            "reclaim_p95_s": (
                reclaims[max(0, int(len(reclaims) * 0.95) - 1)]
                if reclaims else None),
            "lost_notebooks": 0,
            "zero_overcommit": True,   # asserted on every sample
            "utilization": self.samples,
        }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slices", default="v5p-16=2",
                    help="acceleratorType=count donor fleet")
    ap.add_argument("--budget", type=int, default=24,
                    help="max_new_tokens per flood request")
    ap.add_argument("--idle-minutes", type=float, default=30.0,
                    help="culler idle window (fake minutes)")
    ap.add_argument("--interleaves", type=int, default=2,
                    help="A/B pairs to run (baseline, harvest, ...)")
    ap.add_argument("--no-harvest", action="store_true",
                    help="run ONLY the baseline arm once (CI's "
                         "standalone baseline leg)")
    ap.add_argument("--out", default="",
                    help="write the composed artifact JSON here")
    args = ap.parse_args()

    t0 = time.perf_counter()
    segments: list[dict] = []
    if args.no_harvest:
        plan = [("no-harvest", 0)]
    else:
        plan = []
        for i in range(args.interleaves):
            plan.append(("no-harvest", 2 * i))
            plan.append(("harvest", 2 * i + 1))
    for arm, idx in plan:
        print(f"== segment {idx}: {arm}", file=sys.stderr)
        segments.append(Day(args, arm, idx).run())
        print(f"   {segments[-1]['useful_tok_s']} tok/s "
              f"({segments[-1]['replicas_serving']} replicas)",
              file=sys.stderr)

    base = [s for s in segments if s["arm"] == "no-harvest"]
    harv = [s for s in segments if s["arm"] == "harvest"]
    result = {
        "artifact": "HARVEST_r01",
        "scenario": "diurnal evening flood: donors idle out, serving "
                    "floods, donors demand-resume at dawn",
        "run_meta": build_run_meta(
            "harvest_conformance",
            {"arm": "ab" if not args.no_harvest else "no-harvest",
             "slices": args.slices, "model": "tiny",
             "flood": len(FLOOD_PROMPTS), "budget": args.budget}),
        "failover_slo_s": harvest.FAILOVER_SLO_S,
        "segments": segments,
        "baseline_tok_s": [s["useful_tok_s"] for s in base],
        "harvest_tok_s": [s["useful_tok_s"] for s in harv],
        "bit_exact": all(s["bit_exact"] for s in segments),
        "zero_overcommit": all(s["zero_overcommit"] for s in segments),
        "lost_notebooks": sum(s["lost_notebooks"] for s in segments),
        "total_s": round(time.perf_counter() - t0, 2),
    }
    if harv:
        # the headline: every interleaved pair, harvest strictly wins
        for b, h in zip(base, harv):
            assert h["useful_tok_s"] > b["useful_tok_s"], \
                f"harvest arm ({h['useful_tok_s']} tok/s) did not " \
                f"beat its paired baseline ({b['useful_tok_s']})"
        mean = lambda xs: sum(xs) / len(xs)  # noqa: E731
        result["speedup"] = round(
            mean(result["harvest_tok_s"])
            / mean(result["baseline_tok_s"]), 3)
        reclaims = sorted(
            r["reclaim_s"] for s in harv for r in s["resumes"]
            if r["reclaim_s"] is not None)
        result["reclaim_p95_s"] = reclaims[
            max(0, int(len(reclaims) * 0.95) - 1)]
        assert result["reclaim_p95_s"] <= harvest.FAILOVER_SLO_S
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    print(f"HARVEST CONFORMANCE OK "
          f"({'A/B' if harv else 'baseline-only'}"
          f"{', speedup ' + str(result.get('speedup')) + 'x' if harv else ''})",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
