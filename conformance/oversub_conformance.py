#!/usr/bin/env python3
"""Chip-oversubscription conformance: 2x notebooks vs chips, all progress.

NotebookOS's core claim is oversubscription through transparent
suspend/resume: more notebooks than accelerators, with idle slices
checkpointed and parked so every workload still makes progress. This
harness proves that loop end-to-end on the in-process stack (the
deterministic mode of ``spawn_conformance``): a fake TPU fleet, 2x as
many notebooks as it has chips, all spawned through the REAL web API,
then a demand storm — each round one notebook is "touched" (the
readiness long-poll, i.e. real client demand), the rest idle out and
the SuspendController parks them, freed chips re-gang waiting slices,
and the touched notebook resumes with its checkpointed step restored
exactly.

Invariants asserted every round, on the backing store (not the cache):

- **zero overcommit**: bound chips never exceed any node's capacity
  (oversubscription is of *notebooks*, never of chips);
- **progress**: every notebook becomes Ready repeatedly and its
  training step advances (the bump stands in for the launcher agent);
- **exactness**: after each resume ``RESTORED_STEP_ANNOTATION`` equals
  the step the suspend-time snapshot recorded;
- **priority**: the one high-priority notebook — spawned into a full
  fleet — binds immediately by preempting exactly one victim.

The artifact (``OVERSUB_r{N}.json``) carries suspend->resume latency
percentiles (client wall time, in-process standin like
``spawn_conformance``'s default mode) plus the server-side per-phase
histogram and a chip-utilization-over-time series.

``--no-oversubscribe`` is the A/B baseline arm: pin-for-lifetime.
Notebooks beyond the fleet stay Pending forever, nobody is ever
suspended or preempted, and the harness asserts exactly that.

``--migration`` is the fragmentation arm: a packed v6e fleet with free
chips stranded across nodes rejects a whole-gang waiter under static
placement, then admits it once fragmentation-triggered live migration
(checkpoint -> drain -> re-bind elsewhere) defragments a node.

Usage:
    python conformance/oversub_conformance.py --out OVERSUB_r01.json
    python conformance/oversub_conformance.py --no-oversubscribe
    python conformance/oversub_conformance.py --migration \\
        --slices v6e-4=3 --out OVERSUB_MIGRATION_r01.json
"""

from __future__ import annotations

import argparse
import datetime
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from kubeflow_rm_tpu.controlplane import (  # noqa: E402
    make_control_plane, metrics, scheduler, suspend,
)
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api.meta import (  # noqa: E402
    annotations_of, deep_get, set_annotation,
)
from kubeflow_rm_tpu.controlplane.api.profile import make_profile  # noqa: E402
from kubeflow_rm_tpu.controlplane.apiserver import Conflict  # noqa: E402
from kubeflow_rm_tpu.controlplane.controllers.statefulset import (  # noqa: E402
    make_tpu_node,
)
from kubeflow_rm_tpu.controlplane.webapps import jupyter as jwa  # noqa: E402

NS = "oversub"
USER = "oversub@corp.com"


class FakeClock:
    """Manually-advanced clock: idle windows elapse in fake minutes,
    so the storm runs in CI seconds (suspend latency itself is measured
    in client wall time, which the fake clock does not touch)."""

    def __init__(self, start: str = "2026-01-01T00:00:00+00:00"):
        self.now = datetime.datetime.fromisoformat(start)

    def __call__(self) -> datetime.datetime:
        return self.now

    def advance(self, **timedelta_kwargs) -> None:
        self.now = self.now + datetime.timedelta(**timedelta_kwargs)


def _update_annotations(api, name, mutate):
    """Read-modify-write a notebook's annotations with Conflict retry
    (the storm races the controllers on the same map)."""
    for attempt in range(8):
        nb = api.get(nb_api.KIND, name, NS)
        mutate(nb)
        try:
            return api.update(nb)
        except Conflict:
            if attempt == 7:
                raise


class Storm:
    def __init__(self, args):
        self.args = args
        accel, count = args.slices.split(",")[0].split("=")
        self.accel, self.slices = accel, int(count)
        self.topo = tpu_api.lookup(accel)
        self.n = args.notebooks or 2 * self.slices
        self.clock = FakeClock()
        suspend.set_oversubscribe(not args.no_oversubscribe)
        suspend.set_state_store(suspend.InMemoryStateStore())
        self.api, self.mgr = make_control_plane(
            clock=self.clock, enable_suspend=True,
            suspend_config={
                "suspend_idle_minutes": args.idle_minutes,
                "check_period_minutes": 1.0,
            })
        self.node_cap: dict[str, float] = {}
        for s in range(self.slices):
            for h in range(self.topo.hosts):
                node = f"{accel}-s{s}-h{h}"
                self.api.create(make_tpu_node(node, accel))
                self.node_cap[node] = float(self.topo.chips_per_host)
        self.capacity = sum(self.node_cap.values())
        self.api.create(make_profile(NS, USER))
        self.mgr.enqueue_all()
        self.mgr.run_until_idle()
        self.client = jwa.create_app(self.api).test_client(user=USER)
        self.names = [f"ov-{i}" for i in range(self.n)]
        self.high = self.names[-1]  # spawned last, into a full fleet
        self.samples: list[dict] = []
        self.resume_lat: list[float] = []
        self.resumes_ok = 0

    # ---- invariants ----------------------------------------------------
    def check_overcommit(self):
        """Ground truth from the backing store: per-node bound chips
        never exceed the node's capacity. The whole point of the design
        is oversubscribing notebooks, never chips."""
        per_node: dict[str, float] = {}
        for p in self.api.list("Pod", NS):
            node = deep_get(p, "spec", "nodeName")
            phase = deep_get(p, "status", "phase")
            if not node or phase in scheduler.TERMINAL_PHASES:
                continue
            per_node[node] = per_node.get(node, 0.0) + \
                scheduler._pod_chips(p)
        for node, used in per_node.items():
            cap = self.node_cap.get(node, 0.0)
            assert used <= cap + 1e-9, \
                f"OVERCOMMIT: node {node} has {used} chips bound, " \
                f"capacity {cap}"
        return sum(per_node.values())

    def phases(self) -> dict[str, int]:
        out = {"ready": 0, "suspended": 0, "pending": 0}
        for name in self.names:
            nb = self.api.try_get(nb_api.KIND, name, NS)
            if nb is None:  # arm-specific fleets (e.g. --migration)
                continue
            ann = annotations_of(nb)
            if deep_get(nb, "status", "readyReplicas",
                        default=0) == self.topo.hosts:
                out["ready"] += 1
            elif nb_api.SUSPEND_ANNOTATION in ann:
                out["suspended"] += 1
            else:
                out["pending"] += 1
        return out

    def sample(self, tag: str):
        bound = self.check_overcommit()
        st = scheduler.cache_for(self.mgr.api).stats()
        self.samples.append({
            "t": self.clock().isoformat(),
            "tag": tag,
            "bound_chips": bound,
            "capacity_chips": self.capacity,
            "free_chips": st["free_chips"],
            "largest_free_gang": st["largest_free_gang"],
            "fragmentation": st["fragmentation"],
            **self.phases(),
        })

    def ready(self, name: str) -> bool:
        nb = self.api.get(nb_api.KIND, name, NS)
        return deep_get(nb, "status", "readyReplicas",
                        default=0) == self.topo.hosts

    def drive_until_ready(self, name: str, ticks: int = 30):
        for _ in range(ticks):
            if self.ready(name):
                return
            self.check_overcommit()
            self.clock.advance(minutes=1.0)
            self.mgr.run_until_idle()
        raise AssertionError(
            f"{name} never became ready; phases={self.phases()}")

    def bump_steps(self):
        """Every Ready notebook trains: advance its durable step (the
        launcher agent's TRAINING_STEP_ANNOTATION) by one."""
        for name in self.names:
            if not self.ready(name):
                continue

            def bump(nb):
                ann = annotations_of(nb)
                step = int(ann.get(
                    nb_api.TRAINING_STEP_ANNOTATION) or 0) + 1
                set_annotation(nb, nb_api.TRAINING_STEP_ANNOTATION,
                               str(step))
            _update_annotations(self.api, name, bump)

    # ---- the storm -----------------------------------------------------
    def spawn(self):
        for name in self.names:
            body = {
                "name": name,
                "image": "ghcr.io/kubeflow-rm-tpu/jupyter-jax:latest",
                "imagePullPolicy": "IfNotPresent",
                "serverType": "jupyter", "cpu": "2", "memory": "8Gi",
                "tpu": {"acceleratorType": self.accel},
                "tolerationGroup": "none", "affinityConfig": "none",
                "configurations": [], "shm": True, "environment": {},
                "datavols": [],
            }
            if name == self.high:
                body["priorityClassName"] = "high"
            resp = self.client.post(
                f"/api/namespaces/{NS}/notebooks",
                data=json.dumps(body),
                headers=[("Content-Type", "application/json")])
            assert resp.status_code == 200, resp.get_data()
            self.mgr.run_until_idle()
        self.sample("spawn")

    def wake(self, name: str):
        """Client demand on a suspended notebook: the readiness
        long-poll's wake side effect (timeoutSeconds=0 so the in-process
        client never blocks)."""
        self.client.get(f"/api/namespaces/{NS}/notebooks/{name}"
                        f"/readiness?timeoutSeconds=0")

    def round(self, r: int):
        target = self.names[r % self.n]
        # the idle window elapses for everyone...
        self.clock.advance(minutes=self.args.idle_minutes + 1.1)
        nb = self.api.get(nb_api.KIND, target, NS)
        ann = annotations_of(nb)
        waking = (nb_api.SUSPEND_ANNOTATION in ann
                  or nb_api.RESUME_REQUESTED_ANNOTATION in ann)
        t0 = time.perf_counter()
        if waking:
            self.wake(target)
        elif self.ready(target):
            # ...except the touched one: fresh demand resets its clock
            _update_annotations(
                self.api, target,
                lambda n: set_annotation(
                    n, nb_api.LAST_ACTIVITY_ANNOTATION,
                    self.clock().isoformat()))
        self.mgr.run_until_idle()
        self.drive_until_ready(target)
        if waking:
            self.resume_lat.append(time.perf_counter() - t0)
            live = self.api.get(nb_api.KIND, target, NS)
            a = annotations_of(live)
            restored = a.get(nb_api.RESTORED_STEP_ANNOTATION)
            trained = a.get(nb_api.TRAINING_STEP_ANNOTATION) or "0"
            assert restored is not None, \
                f"{target} resumed without a restored step"
            assert int(restored) == int(trained), \
                f"{target}: restored step {restored} != " \
                f"pre-suspend step {trained}"
            self.resumes_ok += 1
        self.bump_steps()
        self.sample(f"round-{r}")

    def run_oversubscribed(self) -> dict:
        self.spawn()
        # the high-priority notebook hit a full fleet and must have
        # preempted its way in: exactly one victim, all-or-nothing
        assert self.ready(self.high), \
            "high-priority notebook did not preempt into the full fleet"
        preempts = metrics.registry_value("notebook_preempt_total")
        assert preempts >= 1, f"no preemption recorded: {preempts}"
        for r in range(self.args.rounds):
            self.round(r)
            print(f"round {r + 1}/{self.args.rounds}: "
                  f"{self.samples[-1]['tag']} phases="
                  f"{ {k: self.samples[-1][k] for k in ('ready', 'suspended', 'pending')} }",
                  file=sys.stderr)
        # every notebook made progress, repeatedly
        steps = {}
        for name in self.names:
            nb = self.api.get(nb_api.KIND, name, NS)
            steps[name] = int(annotations_of(nb).get(
                nb_api.TRAINING_STEP_ANNOTATION) or 0)
            assert steps[name] >= 2, \
                f"{name} made no progress: step {steps[name]}"
        assert self.resumes_ok >= self.n // 2, \
            f"only {self.resumes_ok} suspend->resume cycles observed"
        lat = sorted(self.resume_lat)
        phase_hist = {}
        for phase in ("drain", "rebind", "restore"):
            phase_hist[phase] = {
                "count": metrics.registry_value(
                    "suspend_resume_phase_seconds_count",
                    {"phase": phase}),
                "sum_s": round(metrics.registry_value(
                    "suspend_resume_phase_seconds_sum",
                    {"phase": phase}), 4),
            }
        return {
            "suspend_resume_ms": {
                "count": len(lat),
                "p50": round(lat[len(lat) // 2] * 1e3, 1),
                "p95": round(
                    lat[max(0, int(len(lat) * 0.95) - 1)] * 1e3, 1),
                "max": round(lat[-1] * 1e3, 1),
            },
            "phase_seconds": phase_hist,
            "progress_steps": steps,
            "resumes_observed": self.resumes_ok,
            "suspends_total": metrics.registry_value(
                "notebook_suspend_total"),
            "preemptions_total": metrics.registry_value(
                "notebook_preempt_total"),
        }

    def run_migration(self) -> dict:
        """--migration: fragmentation-triggered live migration admits a
        gang that static placement rejects.

        Six 1-chip kernels and one 4-chip kernel pack a 3-node v6e
        fleet; suspending two smalls on DIFFERENT nodes strands enough
        free chips in total (4) with no node holding the gang whole
        (largest free run = 3). A 4-chip waiter then:

        - static arm (auto-migration off): FailedScheduling forever —
          the chips exist, placement can't use them;
        - migration arm (auto-migration on): the compactor picks the
          ONE victim whose chips defragment a node, checkpoints it,
          re-binds it across the fleet, and the waiter admits. Exactly
          one migration, zero chip overcommit throughout, and the
          migrated kernel itself comes back with its step restored.
        """
        from kubeflow_rm_tpu.controlplane.api.notebook import (
            make_notebook,
        )

        assert self.accel == "v6e-4" and self.slices == 3, \
            "--migration expects --slices v6e-4=3"
        api, mgr = self.api, self.mgr

        def drive(name, ticks=30):
            for _ in range(ticks):
                if self.ready_hosts(name):
                    return
                self.check_overcommit()
                self.clock.advance(minutes=1.0)
                mgr.run_until_idle()
            raise AssertionError(f"{name} never became ready")

        # pack: s0-s3 fill node 0 (least-free-first + name tiebreak),
        # the 4-chip big kernel fills node 1, s4-s5 land on node 2
        smalls = [f"frag-s{i}" for i in range(6)]
        for nm in smalls[:4]:
            api.create(make_notebook(nm, NS, accelerator_type="v6e-1"))
            mgr.run_until_idle()
        api.create(make_notebook("frag-big", NS,
                                 accelerator_type="v6e-4"))
        mgr.run_until_idle()
        for nm in smalls[4:]:
            api.create(make_notebook(nm, NS, accelerator_type="v6e-1"))
            mgr.run_until_idle()
        for nm in smalls + ["frag-big"]:
            drive(nm)

        # strand chips across nodes: park one small on node 0 and one
        # on node 2 through the real lifecycle verbs
        for nm in ("frag-s0", "frag-s4"):
            _update_annotations(
                api, nm, lambda n: set_annotation(
                    n, nb_api.TRAINING_STEP_ANNOTATION, "5"))
            suspend.initiate_suspend(
                api, api.get(nb_api.KIND, nm, NS), reason="api")
            mgr.run_until_idle()
            self.clock.advance(minutes=2.0)
            mgr.run_until_idle()
        st = scheduler.cache_for(mgr.api).stats()
        assert st["free_chips"] >= 4.0, st
        assert st["largest_free_gang"] < 4.0, st
        assert st["fragmentation"] > 0, st

        # static placement rejects the waiter: enough chips in total,
        # no node holds the gang — FailedScheduling, zero rump
        api.create(make_notebook("frag-waiter", NS,
                                 accelerator_type="v6e-4"))
        for _ in range(5):
            self.clock.advance(minutes=1.0)
            mgr.run_until_idle()
        assert not self.ready_hosts("frag-waiter"), \
            "static placement admitted the fragmented gang"
        waiter_pods = [p for p in api.list("Pod", NS)
                       if (p["metadata"].get("labels") or {}).get(
                           nb_api.NOTEBOOK_NAME_LABEL) == "frag-waiter"]
        assert waiter_pods and all(
            not deep_get(p, "spec", "nodeName")
            and any(e["reason"] == "FailedScheduling"
                    for e in api.events_for(p))
            for p in waiter_pods), "waiter not refused whole"
        static_stats = {k: st[k] for k in
                        ("free_chips", "largest_free_gang",
                         "fragmentation")}

        # flip auto-migration on: the SAME fleet, the SAME waiter
        suspend.set_auto_migration(True)
        try:
            mgr.enqueue_all()
            drive("frag-waiter")
        finally:
            suspend.set_auto_migration(False)
        self.check_overcommit()
        migs = metrics.registry_value(
            "notebook_migration_total", {"trigger": "fragmentation"})
        assert migs == 1, f"expected exactly one migration, got {migs}"
        movable = smalls[1:4] + smalls[5:] + ["frag-big"]
        migrated = [nm for nm in movable if any(
            e["reason"] == "Migrated"
            for e in api.events_for(api.get(nb_api.KIND, nm, NS)))]
        assert len(migrated) == 1, f"migrated: {migrated}"
        drive(migrated[0])  # the displaced kernel itself recovered
        restored = annotations_of(api.get(
            nb_api.KIND, migrated[0], NS)).get(
            nb_api.RESTORED_STEP_ANNOTATION)
        assert restored is not None, \
            f"{migrated[0]} re-bound without a checkpoint restore"
        return {
            "suspend_resume_ms": {"count": 0},
            "progress_steps": {},
            "resumes_observed": 0,
            "static_arm": {**static_stats,
                           "waiter_admitted": False},
            "migration_arm": {"waiter_admitted": True,
                              "migrated": migrated[0],
                              "migrations_total": migs,
                              "restored_step": restored},
            "suspends_total": metrics.registry_value(
                "notebook_suspend_total"),
            "preemptions_total": metrics.registry_value(
                "notebook_preempt_total"),
        }

    def ready_hosts(self, name: str) -> bool:
        """Readiness against the notebook's OWN topology (the migration
        fleet mixes 1-chip and 4-chip types; ``ready()`` assumes the
        storm's single type)."""
        nb = self.api.get(nb_api.KIND, name, NS)
        accel = deep_get(nb, "spec", "tpu", "acceleratorType")
        hosts = tpu_api.lookup(accel).hosts if accel else 1
        return deep_get(nb, "status", "readyReplicas",
                        default=0) == hosts

    def run_baseline(self) -> dict:
        """--no-oversubscribe: pin-for-lifetime preserved. The fleet
        admits exactly its capacity, the overflow stays Pending whole,
        and nobody is ever suspended or preempted no matter how idle."""
        self.spawn()
        ph = self.phases()
        assert ph["ready"] == self.slices, \
            f"baseline arm admitted {ph['ready']} != fleet {self.slices}"
        assert not self.ready(self.high), \
            "baseline arm let the high-priority notebook preempt"
        for r in range(self.args.rounds):
            self.clock.advance(minutes=10 * self.args.idle_minutes)
            self.mgr.run_until_idle()
            self.sample(f"round-{r}")
        for name in self.names:
            nb = self.api.get(nb_api.KIND, name, NS)
            ann = annotations_of(nb)
            assert nb_api.SUSPEND_ANNOTATION not in ann, \
                f"{name} suspended in the no-oversubscribe arm"
        ph = self.phases()
        assert ph["ready"] == self.slices and ph["suspended"] == 0
        assert metrics.registry_value("notebook_suspend_total") == 0
        assert metrics.registry_value("notebook_preempt_total") == 0
        return {
            "suspend_resume_ms": {"count": 0},
            "progress_steps": {},
            "resumes_observed": 0,
            "suspends_total": 0,
            "preemptions_total": 0,
            "pending_for_lifetime": ph["pending"],
        }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slices", default="v5p-16=2",
                    help="acceleratorType=count fleet (first entry used)")
    ap.add_argument("--notebooks", type=int, default=0,
                    help="0 = 2x the fleet's slice capacity")
    ap.add_argument("--rounds", type=int, default=12,
                    help="demand-storm rounds (each touches one "
                         "notebook and idles the rest out)")
    ap.add_argument("--idle-minutes", type=float, default=5.0,
                    help="SuspendController idle window (fake minutes)")
    ap.add_argument("--no-oversubscribe", action="store_true",
                    help="A/B baseline arm: pin-for-lifetime — no idle "
                         "suspension, no preemption; overflow notebooks "
                         "stay Pending")
    ap.add_argument("--migration", action="store_true",
                    help="fragmentation arm: prove auto live-migration "
                         "admits a gang static placement rejects "
                         "(expects --slices v6e-4=3)")
    ap.add_argument("--out", default="",
                    help="also write the result JSON to this file "
                         "(OVERSUB_r{N}.json artifact)")
    args = ap.parse_args()

    t0 = time.perf_counter()
    if args.migration:
        # explicit lifecycle verbs drive every suspend in this arm; a
        # huge idle window keeps the fake-clock ticks from idle-parking
        # the packed fleet mid-scenario
        args.idle_minutes = 1e6
    storm = Storm(args)
    if args.migration:
        detail = storm.run_migration()
    elif args.no_oversubscribe:
        detail = storm.run_baseline()
    else:
        detail = storm.run_oversubscribed()
    storm.sample("final")

    result = {
        "arm": ("migration" if args.migration
                else "no-oversubscribe" if args.no_oversubscribe
                else "oversubscribe"),
        "slice": storm.accel,
        "fleet_slices": storm.slices,
        "hosts_per_slice": storm.topo.hosts,
        "capacity_chips": storm.capacity,
        "notebooks": storm.n,
        "oversubscription_ratio": round(
            storm.n / max(1, storm.slices), 2),
        "rounds": args.rounds,
        **detail,
        "zero_overcommit": True,  # asserted per-node on every sample
        "utilization": storm.samples,
        "total_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print(f"OVERSUB CONFORMANCE OK ({result['arm']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
