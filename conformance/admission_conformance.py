#!/usr/bin/env python3
"""Predictive-admission conformance: the jaxcheck pricer in the
webhook path, end to end, plus the HBM-aware packing A/B storm.

Phase A — admission e2e. A Notebook declaring a provably-OOM training
config (``tpu.kubeflow.org/declared-workload``) is created through the
real control plane: the webhook prices the declaration with the
memplan walker and the CR is **rejected before placement** — verdict
and priced explanation in ``status.admission``, an ``AdmissionRejected``
Warning event, zero pods rendered. The advisor's cheapest passing
ladder rung is then pasted back via UPDATE and the same CR admits AND
schedules to Running. No TPU ever saw the OOM config.

Phase B — packing A/B storm. The SAME mix of declared slices (equal
chip totals per arm) is spawned twice: once with chip-count-only
admission (the baseline arm), once with ``--hbm-packing``
(``scheduler.set_hbm_packing``) where predicted HBM is the second
packing axis and declared slices may share a node's chips (bounded)
because HBM — the axis that actually OOMs — is never overcommitted.
The HBM arm must admit strictly more of the mix, and every node must
end the storm with ``hbm_used <= hbm_capacity``.

The artifact (``ADMIT_r01.json`` / ``ADMIT_ci.json``) carries both
phases plus the shared run_meta header benchmarks/ratchet.py keys on.

Usage:
    python conformance/admission_conformance.py --out ADMIT_r01.json
    python conformance/admission_conformance.py --arm hbm --nodes 4
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from kubeflow_rm_tpu.controlplane import (  # noqa: E402
    make_control_plane,
    scheduler,
)
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api.meta import (  # noqa: E402
    deep_get,
    set_annotation,
)
from kubeflow_rm_tpu.controlplane.api.notebook import (  # noqa: E402
    make_notebook,
)
from kubeflow_rm_tpu.controlplane.controllers.statefulset import (  # noqa: E402
    make_tpu_node,
)

NS = "admit"

#: phase A: a real 1.3B bench preset that provably OOMs a v5litepod-8
#: (22.85 predicted GB/chip vs the 16.91 GB usable budget — the
#: microbatch-32 logits+workspace bind); the advisor's grad_accum=2
#: rung fits the same slice
OOM_DECL = {"preset": "bench_1b", "optim": "adamw", "seq": 4096,
            "batch": 32, "grad_accum": 1, "tenant": "teamA"}

#: phase B: tiny-model declarations (sub-second traces) whose LOGITS
#: dominate — heavy ~50 GB and light ~25 GB predicted slice peaks, so
#: a 128-GiB v5e host packs 2 heavy or a heavy+light+light, while
#: chip-count-only admission packs exactly one 8-chip slice per node
_TINY = {"model": {"dim": 64, "n_layers": 2, "n_heads": 4,
                   "n_kv_heads": 4, "hidden_dim": 256,
                   "vocab_size": 32000},
         "seq": 4096, "batch": 256, "optim": "adamw", "remat": "full"}
HEAVY_DECL = {**_TINY, "grad_accum": 8, "tenant": "teamB"}
LIGHT_DECL = {**_TINY, "grad_accum": 16, "tenant": "teamC"}


def _run_meta(args, arms_extra: dict) -> dict:
    from kubeflow_rm_tpu.controlplane.obs.runmeta import build_run_meta
    arms = {"accelerator": args.accelerator, "nodes": args.nodes,
            "heavy": args.heavy, "light": args.light}
    arms.update(arms_extra)
    return build_run_meta("admission_conformance", arms)


def _stack(args):
    api, mgr = make_control_plane()
    api.ensure_namespace(NS)
    for i in range(args.nodes):
        api.create(make_tpu_node(f"tpu-{i}", args.accelerator))
    return api, mgr


# ---- phase A: the admission e2e --------------------------------------

def e2e_main(args) -> dict:
    api, mgr = _stack(args)
    t0 = time.perf_counter()
    api.create(make_notebook(
        "oom", NS, accelerator_type=args.accelerator,
        annotations={tpu_api.DECLARED_WORKLOAD_ANNOTATION:
                     json.dumps(OOM_DECL)}))
    mgr.run_until_idle()
    reject_ms = (time.perf_counter() - t0) * 1000
    nb = api.get("Notebook", "oom", NS)
    adm = deep_get(nb, "status", "admission") or {}
    assert adm.get("verdict") == "rejected", \
        f"OOM declaration not rejected: {adm}"
    pods = api.list("Pod", NS)
    assert pods == [], f"rejected CR rendered {len(pods)} pods"
    events = [e["reason"] for e in api.events_for(nb)]
    assert "AdmissionRejected" in events, events
    advice = adm.get("advisor")
    assert advice, "rejection carries no advisor rung"

    # paste the advisor's rung back: the SAME CR admits and schedules
    t1 = time.perf_counter()
    set_annotation(nb, tpu_api.DECLARED_WORKLOAD_ANNOTATION,
                   json.dumps(advice["workload"]))
    api.update(nb)
    mgr.run_until_idle()
    admit_ms = (time.perf_counter() - t1) * 1000
    nb = api.get("Notebook", "oom", NS)
    assert deep_get(nb, "status", "admission", "verdict") == "fit"
    pods = api.list("Pod", NS)
    assert pods and all(
        deep_get(p, "status", "phase") == "Running" for p in pods), \
        "advisor rung did not schedule"
    print(f"phase A: rejected in {reject_ms:.0f}ms "
          f"({adm['predicted_peak_per_chip_gb']} GB/chip vs "
          f"{adm['budget_per_chip_gb']} budget, {adm['binds']} binds); "
          f"advisor rung admitted+Running in {admit_ms:.0f}ms",
          file=sys.stderr)
    return {
        "declared": OOM_DECL,
        "verdict": adm["verdict"],
        "explanation": adm["explanation"],
        "predicted_peak_per_chip_gb": adm["predicted_peak_per_chip_gb"],
        "budget_per_chip_gb": adm["budget_per_chip_gb"],
        "binds": adm["binds"],
        "pods_rendered_while_rejected": 0,
        "advisor_rung": advice["workload"],
        "advisor_note": advice["note"],
        "rung_running_pods": len(pods),
        "reject_ms": round(reject_ms, 1),
        "rung_admit_ms": round(admit_ms, 1),
    }


# ---- phase B: the packing A/B storm ----------------------------------

def _storm_arm(args, hbm: bool) -> dict:
    """Spawn the declared mix on a fresh fleet under one packing arm."""
    scheduler.set_hbm_packing(hbm)
    try:
        api, mgr = _stack(args)
        mix = ([("heavy", HEAVY_DECL)] * args.heavy
               + [("light", LIGHT_DECL)] * args.light)
        t0 = time.perf_counter()
        for i, (kind, decl) in enumerate(mix):
            api.create(make_notebook(
                f"{kind}-{i}", NS, accelerator_type=args.accelerator,
                annotations={tpu_api.DECLARED_WORKLOAD_ANNOTATION:
                             json.dumps(decl)}))
        reconciles = mgr.run_until_idle()
        wall_ms = (time.perf_counter() - t0) * 1000
        running = pending = 0
        for i, (kind, _) in enumerate(mix):
            nb = api.get("Notebook", f"{kind}-{i}", NS)
            hosts = deep_get(nb, "status", "desiredReplicas", default=1)
            ready = deep_get(nb, "status", "readyReplicas", default=0)
            if hosts and ready >= hosts:
                running += 1
            else:
                pending += 1
        by_node = scheduler.cache_for(api).hbm_by_node()
        overcommitted = [n for n, (used, cap) in by_node.items()
                        if cap > 0 and used > cap + 1e-3]
        chips_admitted = sum(
            scheduler.cache_for(api).node_used(n) for n in by_node)
        return {
            "hbm_packing": hbm,
            "slices_in_mix": len(mix),
            "admitted_running": running,
            "refused_pending": pending,
            "chips_bound": chips_admitted,
            "hbm_by_node_gib": {n: [round(u, 1), round(c, 1)]
                                for n, (u, c) in sorted(by_node.items())},
            "overcommitted_nodes": overcommitted,
            "reconciles": reconciles,
            "wall_ms": round(wall_ms, 1),
        }
    finally:
        scheduler.set_hbm_packing(False)


def storm_main(args) -> dict:
    arms = {}
    if args.arm in ("both", "chip"):
        arms["chip"] = _storm_arm(args, hbm=False)
    if args.arm in ("both", "hbm"):
        arms["hbm"] = _storm_arm(args, hbm=True)
    for name, arm in arms.items():
        assert arm["overcommitted_nodes"] == [], \
            f"{name} arm overcommitted HBM on {arm['overcommitted_nodes']}"
        print(f"phase B [{name}]: {arm['admitted_running']}/"
              f"{arm['slices_in_mix']} slices Running, "
              f"hbm_by_node={arm['hbm_by_node_gib']}", file=sys.stderr)
    if args.arm == "both":
        # the tentpole claim: same chip totals offered, the HBM arm
        # admits a mix the chip-count arm refuses — with zero
        # predicted-HBM overcommit anywhere
        assert arms["hbm"]["admitted_running"] > \
            arms["chip"]["admitted_running"], (
                "HBM arm admitted no more than the chip arm: "
                f"{arms['hbm']['admitted_running']} vs "
                f"{arms['chip']['admitted_running']}")
    return arms


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--accelerator", default="v5litepod-8",
                    help="slice type for every spawned notebook")
    ap.add_argument("--nodes", type=int, default=2,
                    help="fake TPU nodes in the fleet")
    ap.add_argument("--heavy", type=int, default=4,
                    help="slices declaring the ~50 GB workload")
    ap.add_argument("--light", type=int, default=4,
                    help="slices declaring the ~25 GB workload")
    ap.add_argument("--arm", choices=("both", "chip", "hbm"),
                    default="both",
                    help="packing arm(s) for the phase-B storm")
    ap.add_argument("--skip-e2e", action="store_true",
                    help="phase B only (skip the priced-rejection e2e)")
    ap.add_argument("--skip-storm", action="store_true",
                    help="phase A only")
    ap.add_argument("--out", default="",
                    help="write the ADMIT artifact JSON here")
    args = ap.parse_args()

    result: dict = {
        "run_meta": _run_meta(args, {"arm": args.arm,
                                     "hbm_packing": "ab"}),
        "harness": "admission_conformance",
    }
    if not args.skip_e2e:
        result["e2e"] = e2e_main(args)
    if not args.skip_storm:
        result["packing_storm"] = storm_main(args)
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
