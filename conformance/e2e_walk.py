#!/usr/bin/env python3
"""Deep e2e scenario walk — the odh e2e matrix for this platform.

The reference carries a ~1,100-LoC real-cluster e2e that walks
creation/update/deletion across deployment modes and asserts
Routes/NetworkPolicies/OAuth objects
(``odh-notebook-controller/e2e/notebook_controller_setup_test.go:54-80``,
``run-e2e-test.sh:1-40``). This harness walks the same matrix — and
the TPU-specific scenarios the reference never had — against either
backend:

- ``--backend local`` (default): the full fake-cluster process layout
  over sockets (in-memory apiserver + admission + fake kubelet behind
  the REST facade; controller manager over the kube adapter with watch
  threads) — runnable anywhere, CI included.
- ``--backend cluster --server URL [--token T]``: a live apiserver
  (KinD lane: ``kubectl proxy`` + ``--server http://127.0.0.1:8001``)
  with the platform deployed; kubelet-dependent scenarios adapt,
  clock-dependent ones self-skip.

Scenarios (each emits ok/skip + wall ms into the JSON artifact):

  profile_onboarding   Profile → ns, SAs, RBAC, owner policy
  spawn_oauth          Notebook+oauth → STS/Services/VS/Routes/
                       NetworkPolicies/OAuth SA+Secret, slice Ready
  no_restart_guard     live spec change denied; restart annotation
                       opt-in applies it (webhook ``_guard_restart``)
  stop_start           stop drains ALL hosts; start recovers
  culling              idle slice gets the stop annotation whole
  slice_restart        one Failed pod → whole-slice teardown+rebuild
                       with a SliceRestart event
  quota_denial         quota that can't fit the slice → all-or-nothing
                       rejection, zero rump pods
  conversion           v1beta1 (annotation-shaped) create converts to
                       stored v1 spec.tpu and back on read
  ha_failover          two elected managers; kill the leader without
                       lease release — the standby takes over within
                       the lease window and recreates a deleted
                       StatefulSet; the apiserver write log proves no
                       dead-leader write lands after takeover
  oversubscription     more slices than the fleet: suspend parks one
                       (chips re-gang the waiter), a high-priority
                       resume preempts exactly one victim, the pinned
                       notebook is never chosen
  replicated           R=2 kernel: kill the active slice mid-session —
                       the parked CPU standby promotes by demand-resume
                       during think-time; first-execute-after-failover
                       p50 beats cold provision (329 ms) by >=10x
  multirole            TPUJob gang (learner slice + CPU actors) binds
                       all-or-nothing; every pod gets role rendezvous
                       env (TPU vars on chip pods only); an oversize
                       gang binds ZERO pods
  delete_cascade       deleting the CR garbage-collects every
                       satellite object
  shard_chaos          4 shard PROCESSES (apiserver + WAL + manager
                       each) under the consistent-hash ring; SIGKILL
                       one mid-storm — WAL replay + watchdog respawn
                       + router retry-with-remap lose ZERO notebooks,
                       and the aggregated watch stream recovers
                       (TOO_OLD -> relist) without intervention

Usage:
    python conformance/e2e_walk.py --out E2E_WALK_r05.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api  # noqa: E402
from kubeflow_rm_tpu.controlplane.api.conversion import (  # noqa: E402
    TPU_ACCELERATOR_ANNOTATION,
)
from kubeflow_rm_tpu.controlplane.api.meta import deep_get  # noqa: E402
from kubeflow_rm_tpu.controlplane.api.notebook import (  # noqa: E402
    make_notebook,
)
from kubeflow_rm_tpu.controlplane.api.profile import make_profile  # noqa: E402
from kubeflow_rm_tpu.controlplane.apiserver import (  # noqa: E402
    AdmissionDenied, APIError, Invalid, NotFound,
)
from kubeflow_rm_tpu.controlplane.controllers.authcompanion import (  # noqa: E402
    OAUTH_INJECT_ANNOTATION,
)
from kubeflow_rm_tpu.controlplane.controllers.notebook import (  # noqa: E402
    headless_name,
)

NS = "e2e-walk"
USER = "e2e@corp.com"
ACCEL = "v5p-16"


class Walk:
    """One scenario list over one backend."""

    def __init__(self, api, *, has_fake_kubelet: bool,
                 fast_culling: bool, rest_url: str | None = None,
                 image: str = "jupyter-jax:latest", ha=None,
                 only: set | None = None, flight_out: str = ""):
        self.api = api
        self.has_fake_kubelet = has_fake_kubelet
        self.fast_culling = fast_culling
        self.rest_url = rest_url
        self.image = image
        self.ha = ha
        self.only = only
        self.flight_out = flight_out
        self.results: list[dict] = []
        self.hosts = tpu_api.lookup(ACCEL).hosts

    def available(self, kind: str) -> bool:
        """Is this kind's API group installed? (A KinD lane has no
        route.openshift.io or networking.istio.io CRDs — the odh e2e
        similarly parameterizes by DeploymentMode.)"""
        try:
            self.api.list(kind, NS)
            return True
        except (NotFound, APIError):
            return False

    # ---- plumbing ----------------------------------------------------
    def wait(self, cond, timeout=60, what="condition"):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = cond()
            if v:
                return v
            time.sleep(0.05)
        raise AssertionError(f"timed out waiting for {what}")

    def run(self, name, fn, skip: str | None = None):
        from kubeflow_rm_tpu.controlplane import tracing
        if self.only is not None and name not in self.only:
            skip = skip or "filtered by --scenarios"
        t0 = time.perf_counter()
        rec = {"scenario": name}
        if skip:
            rec.update(ok=None, skipped=skip)
            self.results.append(rec)
            print(f"  ~ {name}: skipped ({skip})", flush=True)
            return
        try:
            # each scenario is one root trace: its kube calls carry the
            # context, so the artifact can show the blocking chain of a
            # slow scenario (no-op unless --tracing)
            with tracing.start_span(f"scenario {name}", kind="client",
                                    root=True) as root:
                detail = fn() or {}
            tid = getattr(root, "trace_id", None)
            if tid:
                rec["trace_id"] = tid
            rec.update(ok=True, ms=round(1e3 * (time.perf_counter() - t0),
                                         1), **detail)
            print(f"  ✓ {name} ({rec['ms']} ms)", flush=True)
        except Exception as e:  # noqa: BLE001 - recorded, not fatal
            rec.update(ok=False, error=f"{type(e).__name__}: {e}")
            print(f"  ✗ {name}: {rec['error']}", flush=True)
        self.results.append(rec)

    def nb_ready(self, name, hosts=None):
        def check():
            nb = self.api.try_get("Notebook", name, NS)
            return nb and (nb.get("status") or {}).get(
                "readyReplicas") == (hosts or self.hosts) and nb
        return self.wait(check, what=f"{name} ready")

    # ---- scenarios ---------------------------------------------------
    def profile_onboarding(self):
        self.api.create(make_profile(NS, USER))
        for kind, n in (("Namespace", NS),
                        ("ServiceAccount", "default-editor"),
                        ("ServiceAccount", "default-viewer"),
                        ("RoleBinding", "namespaceAdmin")):
            ns = None if kind == "Namespace" else NS
            self.wait(lambda k=kind, nm=n, s=ns:
                      self.api.try_get(k, nm, s), what=f"{kind}/{n}")
        return {"objects": 4}

    def spawn_oauth(self):
        nb = make_notebook(
            "walk", NS, accelerator_type=ACCEL, image=self.image,
            annotations={OAUTH_INJECT_ANNOTATION: "true"})
        self.api.create(nb)
        self.nb_ready("walk")
        must = [("StatefulSet", "walk"), ("Service", "walk"),
                ("Service", headless_name("walk")),
                ("NetworkPolicy", "walk-ctrl-np"),
                ("NetworkPolicy", "walk-slice-np"),
                ("NetworkPolicy", "walk-oauth-np"),
                ("ServiceAccount", "walk"),
                ("Service", "walk-tls"),
                ("Secret", "walk-oauth-config")]
        # mesh/openshift satellites only where their API groups exist
        # (the odh e2e parameterizes the same way by DeploymentMode)
        skipped_kinds = []
        for kind, n in (("VirtualService", f"notebook-{NS}-walk"),
                        ("Route", "walk")):
            if self.available(kind):
                must.append((kind, n))
            else:
                skipped_kinds.append(kind)
        for kind, n in must:
            self.wait(lambda k=kind, nm=n: self.api.try_get(k, nm, NS),
                      what=f"{kind}/{n}")
        sts = self.api.get("StatefulSet", "walk", NS)
        assert deep_get(sts, "spec", "replicas") == self.hosts
        assert deep_get(sts, "spec", "podManagementPolicy") == "Parallel"
        assert deep_get(sts, "spec", "serviceName") == \
            headless_name("walk")
        if ("Route", "walk") in must:
            route = self.api.get("Route", "walk", NS)
            assert deep_get(route, "spec", "to", "name") == "walk-tls"
        out = {"objects": len(must), "hosts": self.hosts}
        if skipped_kinds:
            out["unavailable_groups"] = skipped_kinds
        return out

    def _update_retrying(self, mutate, name="walk"):
        """Cached reads can carry a stale resourceVersion for a beat;
        retry the CAS like every controller does."""
        from kubeflow_rm_tpu.controlplane.apiserver import Conflict
        for attempt in range(10):
            nb = self.api.get("Notebook", name, NS)
            mutate(nb)
            try:
                return self.api.update(nb)
            except Conflict:
                if attempt == 9:
                    raise
                time.sleep(0.05)

    def no_restart_guard(self):
        def bump(nb):
            nb["spec"]["template"]["spec"]["containers"][0]["image"] = \
                "jupyter-jax:v2"
        denied = False
        try:
            self._update_retrying(bump)
        except (AdmissionDenied, Invalid, APIError) as e:
            denied = "restart" in str(e).lower()
        assert denied, "live spec change must be denied"

        # explicit opt-in applies it
        def bump_optin(nb):
            bump(nb)
            nb["metadata"].setdefault("annotations", {})[
                nb_api.RESTART_ANNOTATION] = "true"
        self._update_retrying(bump_optin)
        if self.has_fake_kubelet:
            self.wait(lambda: deep_get(
                self.api.get("StatefulSet", "walk", NS),
                "spec", "template", "spec", "containers")[0]["image"]
                == "jupyter-jax:v2", what="image rollout")
        return {}

    def stop_start(self):
        self.api.patch("Notebook", "walk", {"metadata": {"annotations": {
            nb_api.STOP_ANNOTATION: "2026-01-01T00:00:00Z"}}}, NS)
        self.wait(lambda: deep_get(
            self.api.get("StatefulSet", "walk", NS),
            "spec", "replicas") == 0, what="scale to 0")
        if self.has_fake_kubelet:
            self.wait(lambda: not [
                p for p in self.api.list("Pod", NS)
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == "walk"],
                what="pods drained")
        self.api.patch("Notebook", "walk", {"metadata": {"annotations": {
            nb_api.STOP_ANNOTATION: None}}}, NS)
        if self.has_fake_kubelet:
            self.nb_ready("walk")
        return {}

    def culling(self):
        # the culler stamps last-activity on first sight; with the
        # walk's tiny idle window the slice must acquire the stop
        # annotation (whole-slice: replicas -> 0) without any client
        # traffic
        nb = self.wait(lambda: (
            nb_api.STOP_ANNOTATION in
            ((self.api.get("Notebook", "walk", NS)["metadata"]
              .get("annotations")) or {})
            and self.api.get("Notebook", "walk", NS)),
            timeout=90, what="culling stop annotation")
        self.wait(lambda: deep_get(
            self.api.get("StatefulSet", "walk", NS),
            "spec", "replicas") == 0, what="culled scale-down")
        # wait for the drain to actually land before restarting:
        # removing the stop annotation while old pods still exist lets
        # nb_ready pass on the stale readyReplicas and hands the next
        # scenario a half-torn-down slice
        self.wait(lambda: not [
            p for p in self.api.list("Pod", NS)
            if (p["metadata"].get("labels") or {}).get(
                nb_api.NOTEBOOK_NAME_LABEL) == "walk"],
            what="culled pods drained")
        # ... and the controller must have SEEN the park land in status
        # (status.parked). The restart below then waits for the epoch
        # bump: unparking zeroes readyReplicas in the SAME status write
        # it increments restartEpoch, so a stale ready count carried
        # across the restart can never satisfy nb_ready and hand
        # slice_restart a half-drained slice
        st = self.wait(lambda: (lambda s: s if s.get("parked") else
                                None)((self.api.get(
                                    "Notebook", "walk", NS)
                                    .get("status")) or {}),
                       what="parked status mirrored")
        epoch0 = st.get("restartEpoch", 0)
        # restart for the following scenarios
        self.api.patch("Notebook", "walk", {"metadata": {"annotations": {
            nb_api.STOP_ANNOTATION: None,
            nb_api.CULLING_EXCLUDE_ANNOTATION: "true"}}}, NS)
        self.wait(lambda: ((self.api.get("Notebook", "walk", NS)
                            .get("status")) or {}).get(
            "restartEpoch", 0) > epoch0, what="restart epoch bump")
        self.nb_ready("walk")
        last = (nb["metadata"]["annotations"] or {}).get(
            nb_api.LAST_ACTIVITY_ANNOTATION)
        return {"last_activity": last}

    def slice_restart(self):
        def full_slice():
            cur = [p for p in self.api.list("Pod", NS)
                   if (p["metadata"].get("labels") or {}).get(
                       nb_api.NOTEBOOK_NAME_LABEL) == "walk"]
            return cur if len(cur) == self.hosts else None
        pods = self.wait(full_slice, what="full walk slice")
        victim = pods[0]
        old_uids = {p["metadata"]["uid"] for p in pods}
        victim["status"] = {"phase": "Failed"}
        self.api.update_status(victim)
        self.wait(lambda: any(
            e["reason"] == "SliceRestart"
            for e in self.api.events_for(
                self.api.get("Notebook", "walk", NS))),
            what="SliceRestart event")
        # the whole slice comes back with fresh pods
        def rebuilt():
            cur = [p for p in self.api.list("Pod", NS)
                   if (p["metadata"].get("labels") or {}).get(
                       nb_api.NOTEBOOK_NAME_LABEL) == "walk"]
            return (len(cur) == self.hosts
                    and not ({p["metadata"]["uid"] for p in cur}
                             & old_uids)
                    and all(deep_get(p, "status", "phase") == "Running"
                            for p in cur))
        self.wait(rebuilt, what="whole-slice rebuild")
        return {"hosts_restarted": self.hosts}

    def quota_denial(self):
        chips = tpu_api.lookup(ACCEL).chips_per_host
        self.api.create({
            "apiVersion": "v1", "kind": "ResourceQuota",
            "metadata": {"name": "tiny-quota", "namespace": NS},
            "spec": {"hard": {
                f"requests.{tpu_api.GOOGLE_TPU_RESOURCE}": str(chips)}},
        })
        try:
            self.api.create(make_notebook("denied", NS,
                                          accelerator_type=ACCEL))
            self.wait(lambda: any(
                e["reason"] == "SliceAdmissionFailed"
                for e in self.api.events_for(
                    self.api.get("Notebook", "denied", NS))),
                what="SliceAdmissionFailed event")
            rump = [p for p in self.api.list("Pod", NS)
                    if (p["metadata"].get("labels") or {}).get(
                        nb_api.NOTEBOOK_NAME_LABEL) == "denied"]
            assert not rump, f"rump slice of {len(rump)} pods admitted"
        finally:
            try:
                self.api.delete("Notebook", "denied", NS)
            except NotFound:
                pass  # admission may have rejected the create outright
            self.api.delete("ResourceQuota", "tiny-quota", NS)
        return {"quota_chips": chips,
                "slice_chips": chips * self.hosts}

    def conversion(self):
        import urllib.request
        beta = {
            "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
            "metadata": {"name": "legacy", "namespace": NS,
                         "annotations": {
                             TPU_ACCELERATOR_ANNOTATION: ACCEL}},
            "spec": {"template": {"spec": {"containers": [
                {"name": "legacy", "image": "jupyter-jax:latest"}]}}},
        }
        req = urllib.request.Request(
            f"{self.rest_url}/apis/kubeflow.org/v1beta1/namespaces/"
            f"{NS}/notebooks", data=json.dumps(beta).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req)
        stored = self.wait(
            lambda: self.api.try_get("Notebook", "legacy", NS),
            what="converted object")
        assert deep_get(stored, "spec", "tpu", "acceleratorType") == ACCEL
        back = json.loads(urllib.request.urlopen(
            f"{self.rest_url}/apis/kubeflow.org/v1beta1/namespaces/"
            f"{NS}/notebooks/legacy").read())
        assert "tpu" not in back["spec"]
        self.api.delete("Notebook", "legacy", NS)
        return {}

    def ha_failover(self):
        """Crash failover between two lease-elected managers.

        The leader provisions a slice, then dies WITHOUT releasing its
        Lease (crash semantics: ``release_on_exit=False``). The standby
        must steal the expired lease within the lease window and prove
        it reconciles by recreating a StatefulSet deleted out from
        under the notebook. The apiserver write log (writer attribution
        via X-Writer-Identity) then shows a clean hand-over: not a
        single dead-leader write sequenced after the standby's first.
        """
        capi = self.ha["capi"]
        mgrs = self.ha["managers"]

        def sole_leader():
            leaders = [m for m in mgrs if m["elector"].is_leader]
            return leaders[0] if len(leaders) == 1 else None
        lead = self.wait(sole_leader, timeout=15,
                         what="exactly one elected leader")
        standby = next(m for m in mgrs if m is not lead)

        self.api.create(make_notebook(
            "failover", NS, accelerator_type=ACCEL, image=self.image,
            annotations={nb_api.CULLING_EXCLUDE_ANNOTATION: "true"}))
        self.nb_ready("failover")

        # crash the leader: stop its workers/watches/elector mid-term;
        # the lease stays held until it expires on the wall clock
        t_kill = time.perf_counter()
        lead["stop"].set()
        self.wait(lambda: standby["elector"].is_leader, timeout=15,
                  what="standby takeover")
        takeover_ms = round(1e3 * (time.perf_counter() - t_kill), 1)
        el = lead["elector"]
        bound_ms = 1e3 * (el.lease_duration_s + el.renew_deadline_s
                          + 2 * el.retry_period_s)
        assert takeover_ms <= bound_ms, \
            f"takeover {takeover_ms}ms > bound {bound_ms}ms"

        # the new leader must do real work: recreate a deleted slice
        self.api.delete("StatefulSet", "failover", NS)
        self.wait(lambda: self.api.try_get("StatefulSet", "failover",
                                           NS),
                  what="standby recreates StatefulSet")
        self.nb_ready("failover")

        log = list(capi.write_log)
        standby_writes = [w["seq"] for w in log
                          if w.get("writer") == standby["identity"]]
        assert standby_writes, "standby never wrote"
        first_standby = min(standby_writes)
        dead_after = [w for w in log
                      if w.get("writer") == lead["identity"]
                      and w["seq"] > first_standby]
        assert not dead_after, \
            f"dead leader wrote after takeover: {dead_after[:3]}"
        sts_creates = [w for w in log
                       if w["kind"] == "StatefulSet"
                       and w["verb"] == "CREATE"
                       and w["name"] == "failover"]
        # one per legitimate leader term — duplicates would mean an
        # overlapping reconcile
        assert len(sts_creates) == 2, sts_creates
        assert {w.get("writer") for w in sts_creates} == \
            {lead["identity"], standby["identity"]}, sts_creates
        self.api.delete("Notebook", "failover", NS)
        return {"takeover_ms": takeover_ms,
                "takeover_bound_ms": round(bound_ms, 1),
                "lease_duration_ms": round(1e3 * el.lease_duration_s),
                "old_leader": lead["identity"],
                "new_leader": standby["identity"],
                "dead_writes_after_takeover": 0}

    def oversubscription(self):
        """The NotebookOS loop over the socket stack: more slices than
        the fleet holds; suspending one parks it (phase Suspended, chips
        freed), the waiting gang binds into the freed slice, and a
        high-priority resume preempts its way back all-or-nothing —
        while the pinned main notebook is never chosen as a victim."""
        from kubeflow_rm_tpu.controlplane import suspend as suspend_mod

        # pin the walk's notebook: do-not-suspend for its lifetime
        self.api.patch("Notebook", "walk", {"metadata": {"annotations": {
            nb_api.PIN_ANNOTATION: "true"}}}, NS)
        names = ("ov-a", "ov-b", "ov-c")
        # fleet: 3 slices, walk holds one -> ov-a and ov-b gang, ov-c
        # must wait whole (no rump). Stagger the creates: racing all
        # three lets the reconcile workers bind ov-b/ov-c first, and
        # high-priority ov-a then (correctly) preempts ov-b — a valid
        # outcome, but not the placement this scenario asserts about.
        for name in names:
            self.api.create(make_notebook(
                name, NS, accelerator_type=ACCEL, image=self.image,
                priority_class="high" if name == "ov-a" else None,
                annotations={
                    nb_api.CULLING_EXCLUDE_ANNOTATION: "true"}))
            if name != "ov-c":
                self.nb_ready(name)

        # deterministic negative check (formerly a 0.5s wall-clock
        # sleep, which raced the gang binds): the scheduler must have
        # actually CONSIDERED ov-c against the full fleet and refused
        # it whole — every host pod carries a FailedScheduling event
        # and stays unbound
        def ovc_refused():
            pods = [p for p in self.api.list("Pod", NS)
                    if (p["metadata"].get("labels") or {}).get(
                        nb_api.NOTEBOOK_NAME_LABEL) == "ov-c"]
            return (len(pods) == self.hosts and all(
                not deep_get(p, "spec", "nodeName")
                and any(e["reason"] == "FailedScheduling"
                        for e in self.api.events_for(p))
                for p in pods))
        self.wait(ovc_refused, what="ov-c refused whole (no rump)")
        pending = self.api.get("Notebook", "ov-c", NS)
        assert (pending.get("status") or {}).get(
            "readyReplicas", 0) == 0, "ov-c bound past a full fleet"

        # suspend ov-a through the lifecycle verbs (snapshot -> stamp ->
        # drain); its chips must re-gang the waiting ov-c
        self.api.patch("Notebook", "ov-a", {"metadata": {"annotations": {
            nb_api.TRAINING_STEP_ANNOTATION: "41"}}}, NS)
        suspend_mod.initiate_suspend(
            self.api, self.api.get("Notebook", "ov-a", NS), reason="api")
        self.wait(lambda: (self.api.get("Notebook", "ov-a", NS)
                           .get("status") or {}).get("phase")
                  == nb_api.SUSPENDED_PHASE, what="ov-a Suspended")
        t0 = time.perf_counter()
        self.nb_ready("ov-c")
        backfill_ms = round(1e3 * (time.perf_counter() - t0), 1)

        # resume ov-a into a full fleet: high priority preempts exactly
        # one default victim; the pinned walk is never selected
        suspend_mod.request_resume(
            self.api, self.api.get("Notebook", "ov-a", NS), source="api")
        t0 = time.perf_counter()
        self.nb_ready("ov-a")
        resume_ms = round(1e3 * (time.perf_counter() - t0), 1)
        restored = self.wait(
            lambda: ((self.api.get("Notebook", "ov-a", NS)["metadata"]
                      .get("annotations")) or {}).get(
                nb_api.RESTORED_STEP_ANNOTATION),
            what="ov-a restored step")
        assert restored == "41", f"restored step {restored} != 41"
        victims = [n for n in ("ov-b", "ov-c") if nb_api.SUSPEND_ANNOTATION
                   in ((self.api.get("Notebook", n, NS)["metadata"]
                        .get("annotations")) or {})]
        assert len(victims) == 1, f"expected one victim, got {victims}"
        walk_ann = (self.api.get("Notebook", "walk", NS)["metadata"]
                    .get("annotations")) or {}
        assert nb_api.SUSPEND_ANNOTATION not in walk_ann, \
            "pinned notebook was preempted"
        self.nb_ready("walk")
        for name in names:
            self.api.delete("Notebook", name, NS)
        self.wait(lambda: not [
            p for p in self.api.list("Pod", NS)
            if (p["metadata"].get("labels") or {}).get(
                nb_api.NOTEBOOK_NAME_LABEL) in names],
            what="oversub pods swept")
        return {"backfill_ms": backfill_ms, "resume_ms": resume_ms,
                "victim": victims[0]}

    def replicated(self):
        """NotebookOS replicated kernels over the socket stack: R=2, the
        active replica holds the slice while a parked CPU-only standby
        keeps warm state through the checkpoint store. Kill the active's
        slice mid-"session" and measure the user-visible wait at the
        NEXT execute: the warm standby promotes by demand-resume during
        think-time, so the first-execute-after-failover wait must beat
        a cold 100-way provision (PROVISION_r11 p50 = 329 ms) by >=10x
        at the median."""
        from kubeflow_rm_tpu.controlplane.controllers.notebook import (
            standby_name,
        )

        cold_provision_p50_ms = 329.0   # PROVISION_r11, 100-way storm
        iterations, think_s = 5, 0.25
        self.api.create(make_notebook(
            "rep", NS, accelerator_type=ACCEL, image=self.image,
            replicas=2,
            annotations={nb_api.CULLING_EXCLUDE_ANNOTATION: "true",
                         nb_api.TRAINING_STEP_ANNOTATION: "17"}))
        self.nb_ready("rep")
        # the standby fleet parks next to it (R-1 CPU kernels), the
        # failover controller publishes the replica state machine, and
        # the warm checkpoint seeds before any failure happens
        self.wait(lambda: deep_get(
            self.api.try_get("StatefulSet", standby_name("rep"), NS)
            or {}, "spec", "replicas") == 1, what="standby fleet")
        self.wait(lambda: (self.api.get("Notebook", "rep", NS)
                           ["metadata"].get("annotations") or {}).get(
            nb_api.WARM_CHECKPOINT_ANNOTATION),
            what="warm checkpoint seeded")

        def slice_pods():
            return [p for p in self.api.list("Pod", NS)
                    if (p["metadata"].get("labels") or {}).get(
                        nb_api.NOTEBOOK_NAME_LABEL) == "rep"]

        waits_ms, active = [], "0"
        for i in range(iterations):
            pods = self.wait(
                lambda: (lambda c: c if len(c) == self.hosts and all(
                    deep_get(p, "status", "phase") == "Running"
                    for p in c) else None)(slice_pods()),
                what=f"iter {i}: full active slice")
            victim = pods[0]
            victim["status"] = {"phase": "Failed"}
            self.api.update_status(victim)
            time.sleep(think_s)          # the user is typing
            flipped = "1" if active == "0" else "0"
            t0 = time.perf_counter()

            def promoted(flipped=flipped):
                nb = self.api.get("Notebook", "rep", NS)
                ann = nb["metadata"].get("annotations") or {}
                states = json.loads(
                    ann.get(nb_api.REPLICA_STATES_ANNOTATION) or "{}")
                return (ann.get(nb_api.ACTIVE_REPLICA_ANNOTATION)
                        == flipped
                        and states.get(flipped) == "active"
                        and nb_api.RESUME_REQUESTED_ANNOTATION
                        not in ann
                        and (nb.get("status") or {}).get(
                            "readyReplicas") == self.hosts)
            self.wait(promoted, what=f"iter {i}: standby promoted")
            waits_ms.append(round(
                1e3 * (time.perf_counter() - t0), 1))
            active = flipped
        p50 = sorted(waits_ms)[len(waits_ms) // 2]
        assert p50 * 10 <= cold_provision_p50_ms, (
            f"first-execute-after-failover p50 {p50}ms not >=10x "
            f"better than cold provision {cold_provision_p50_ms}ms")
        ann = (self.api.get("Notebook", "rep", NS)["metadata"]
               .get("annotations")) or {}
        restored = ann.get(nb_api.RESTORED_STEP_ANNOTATION)
        assert restored == "17", f"restored step {restored} != 17"
        failovers = [e for e in self.api.events_for(
            self.api.get("Notebook", "rep", NS))
            if e["reason"] == "FailedOver"]
        assert len(failovers) >= iterations, \
            f"{len(failovers)} FailedOver events < {iterations}"
        self.api.delete("Notebook", "rep", NS)
        self.wait(lambda: not slice_pods(), what="rep slice swept")
        return {"iterations": iterations,
                "failover_waits_ms": waits_ms,
                "first_execute_p50_ms": p50,
                "cold_provision_p50_ms": cold_provision_p50_ms,
                "speedup_vs_cold": round(
                    cold_provision_p50_ms / max(p50, 0.1), 1)}

    def shard_chaos(self):
        """Kill-a-shard chaos over the REAL sharded process topology.

        Boots its own 4-process shard fleet (each shard: apiserver +
        durable WAL + admission + kubelet + elected manager), storms
        notebooks across 2x-shards namespaces through the router, and
        SIGKILLs the busiest shard mid-storm. The claim under test:

        - writes aimed at the dead shard block in retry-with-remap
          until the watchdog respawns it (same port, same WAL dir);
        - the respawned shard REPLAYS its WAL — every notebook created
          before the kill is still there and finishes provisioning;
        - the router's per-shard watch stream reconnects, gets TOO_OLD
          for its stale rv (the shard's rv sequence resumed past its
          backlog floor) and relists — post-recovery events flow.
        """
        import shutil
        import tempfile
        import threading
        from collections import Counter
        from concurrent.futures import ThreadPoolExecutor

        from kubeflow_rm_tpu.controlplane import obs, tracing
        from kubeflow_rm_tpu.controlplane.controllers.statefulset import (
            make_tpu_node,
        )
        from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
            ShardedKubeAPIServer,
        )
        from kubeflow_rm_tpu.controlplane.shard import ShardRunner

        n_shards, n_notebooks = 4, 12
        base = tempfile.mkdtemp(prefix="e2e-shards-")
        runner = ShardRunner(n_shards, base_dir=base, manager_workers=4,
                             tracing=tracing.enabled())
        # the black box: TSDB federating every shard's /metrics, the
        # SLO engine (shard-deaths pages critical), and the flight
        # recorder — armed on the watchdog's death hook AND on any
        # alert transition to critical
        observer = obs.Observer(
            interval_s=0.5, shard_urls=runner.urls,
            liveness=runner.liveness,
            run_meta=obs.build_run_meta(
                "e2e_walk", {"scenario": "shard_chaos",
                             "shards": n_shards,
                             "notebooks": n_notebooks,
                             "tracing": tracing.enabled()}))
        runner.set_on_death(observer.on_shard_death)
        stop = threading.Event()
        try:
            runner.start(timeout=120)
            observer.tick()      # baseline sample before the storm
            observer.start()
            router = ShardedKubeAPIServer(
                runner.urls, identity="e2e-chaos", retry_window_s=30.0)
            events: list[tuple] = []
            router.add_watcher(
                lambda et, obj, old=None: events.append(
                    (et, obj.get("kind"), obj["metadata"]["name"])),
                name="chaos-observer")
            for kind in ("Notebook", "Pod", "RoleBinding"):
                threading.Thread(target=router.watch_kind,
                                 args=(kind, None, stop, 60),
                                 daemon=True).start()
            if not router.wait_for_sync(["Notebook", "Pod"],
                                        timeout=30):
                raise AssertionError("router informers never synced")

            namespaces = [f"chaos-p{i}" for i in range(2 * n_shards)]
            ns_of = [namespaces[i % len(namespaces)]
                     for i in range(n_notebooks)]
            per_shard = Counter(router.shard_of("Notebook", None, ns)
                                for ns in ns_of)
            # salted fleet: nodes must live on the shard that gangs them
            for shard, n in per_shard.items():
                made, i = 0, 0
                while made < n * self.hosts:
                    nm = f"{ACCEL}-{shard}-x{i}"
                    i += 1
                    if router.shard_of("Node", nm, None) == shard:
                        router.create(make_tpu_node(nm, ACCEL))
                        made += 1
            for ns in namespaces:
                router.create(make_profile(ns, USER))
            for ns in namespaces:
                self.wait(lambda ns=ns: router.try_get(
                    "RoleBinding", "namespaceAdmin", ns),
                    what=f"profile {ns}")

            victim = per_shard.most_common(1)[0][0]
            killed: dict = {}

            def spawn(i: int) -> None:
                if i == n_notebooks // 2:
                    killed["pid"] = runner.kill(victim)
                    killed["t"] = time.monotonic()
                # one root trace per provision (create -> full slice
                # readiness): spawns that straddle the outage come out
                # slow, land in the collector's tail sample, and give
                # the flight bundle its critical paths
                with tracing.start_span(f"provision chaos-{i}",
                                        kind="client", root=True):
                    router.create(make_notebook(
                        f"chaos-{i}", ns_of[i], accelerator_type=ACCEL,
                        image=self.image,
                        annotations={
                            nb_api.CULLING_EXCLUDE_ANNOTATION: "true"}))
                    self.wait(
                        lambda: (lambda nb: nb and (
                            nb.get("status") or {}).get(
                            "readyReplicas") == self.hosts)(
                            router.try_get("Notebook", f"chaos-{i}",
                                           ns_of[i])),
                        timeout=120, what=f"chaos-{i} ready in-span")

            with ThreadPoolExecutor(max_workers=4) as pool:
                list(pool.map(spawn, range(n_notebooks)))
            assert killed, "the chaos kill never fired"
            # the watchdog respawns it in place: same port, same WAL
            runner.wait_ready(timeout=60, names=[victim])
            respawn_ms = round(
                1e3 * (time.monotonic() - killed["t"]), 1)

            # ZERO lost notebooks: every spawn — before the kill (WAL
            # replay), during the outage (retry-with-remap) and after —
            # reaches full slice readiness
            for i in range(n_notebooks):
                self.wait(
                    lambda i=i: (lambda nb: nb and (
                        nb.get("status") or {}).get(
                        "readyReplicas") == self.hosts and nb)(
                        router.try_get("Notebook", f"chaos-{i}",
                                       ns_of[i])),
                    timeout=120, what=f"chaos-{i} ready after chaos")

            # watch recovery: a FRESH write on the revived shard must
            # reach the aggregated stream (reconnect -> TOO_OLD ->
            # relist happened under the hood)
            probe_ns = next(ns for ns in ns_of
                            if router.shard_of("Notebook", None, ns)
                            == victim)
            router.create(make_notebook(
                "chaos-probe", probe_ns, accelerator_type=ACCEL,
                image=self.image,
                annotations={
                    nb_api.CULLING_EXCLUDE_ANNOTATION: "true"}))
            self.wait(lambda: any(
                name == "chaos-probe" and kind == "Notebook"
                for _, kind, name in list(events)),
                what="post-recovery watch event from revived shard")

            on_victim = sum(1 for ns in ns_of
                            if router.shard_of("Notebook", None, ns)
                            == victim)
            detail = {"shards": n_shards, "notebooks": n_notebooks,
                      "killed_shard": victim,
                      "killed_pid": killed["pid"],
                      "notebooks_on_killed_shard": on_victim,
                      "respawn_ms": respawn_ms,
                      "lost_notebooks": 0,
                      "watch_recovered": True}
            # explicit chaos-scenario trigger: freeze the post-recovery
            # state (trailing metric window, slow traces + critical
            # paths, the shard-deaths alert, liveness, lockgraph) into
            # one bundle while the shards are still up to scrape
            observer.tick()
            bundle = observer.flight.trigger("shard_chaos_complete",
                                             detail=detail)
            detail["flight"] = {
                "slow_traces": len(bundle["slow_traces"]),
                "metric_series": len(bundle.get("metrics") or []),
                "active_alerts": [a["slo"] for a in
                                  bundle["alerts"]["active"]],
                "bundles": observer.flight.triggered_total,
            }
            if self.flight_out:
                observer.flight.dump_json(self.flight_out, bundle)
                detail["flight"]["path"] = self.flight_out
            return detail
        finally:
            observer.stop()
            stop.set()
            runner.stop()
            shutil.rmtree(base, ignore_errors=True)

    def multirole(self):
        """Podracer-style actor–learner gang over the socket stack: a
        TPUJob with one learner slice + 4 CPU actors must bind
        all-or-nothing, every pod carries the role rendezvous env (and
        TPU vars stay off the chipless actors); an oversize gang must
        schedule ZERO pods (no rump)."""
        from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api

        actors = 4
        self.api.create(tj_api.make_tpujob("podracer", NS, roles=[
            {"name": "learner", "replicas": 1,
             "tpu": {"acceleratorType": ACCEL}},
            {"name": "actors", "replicas": actors, "cpu": "500m"},
        ], image=self.image))
        self.wait(lambda: ((self.api.try_get("TPUJob", "podracer", NS)
                            or {}).get("status") or {}).get("phase")
                  == "Running", what="podracer gang Running")

        def gang_pods(job):
            return [p for p in self.api.list("Pod", NS)
                    if (p["metadata"].get("labels") or {}).get(
                        tj_api.JOB_NAME_LABEL) == job]
        pods = gang_pods("podracer")
        assert len(pods) == self.hosts + actors, \
            f"expected {self.hosts + actors} gang pods, got {len(pods)}"
        for p in pods:
            env = {e["name"]: e.get("value")
                   for c in p["spec"]["containers"]
                   for e in c.get("env", [])}
            role = env.get(tj_api.ENV_JOB_ROLE)
            assert role in ("learner", "actors"), p["metadata"]["name"]
            assert env.get(tj_api.ENV_JOB_ROLE_INDEX) is not None
            assert env.get(tj_api.ENV_LEARNER_ADDRESS, "").startswith(
                "podracer-learner-0."), env.get(
                    tj_api.ENV_LEARNER_ADDRESS)
            if role == "learner":
                assert "TPU_WORKER_ID" in env, \
                    f"chip pod {p['metadata']['name']} missing TPU env"
            else:
                assert "TPU_WORKER_ID" not in env \
                    and "TPU_WORKER_HOSTNAMES" not in env, \
                    f"TPU env leaked onto actor {p['metadata']['name']}"

        # all-or-nothing: 3 more slices can't fit next to walk+learner
        # on a 3-slice fleet — nothing may bind, not even one host
        self.api.create(tj_api.make_tpujob("podracer-big", NS, roles=[
            {"name": "learner", "replicas": 3,
             "tpu": {"acceleratorType": ACCEL}},
        ], image=self.image))
        self.wait(lambda: any(
            e["reason"] == "FailedScheduling"
            for e in self.api.events_for(
                self.api.get("TPUJob", "podracer-big", NS))),
            what="oversize gang FailedScheduling")
        bound = [p for p in gang_pods("podracer-big")
                 if deep_get(p, "spec", "nodeName")]
        assert not bound, f"rump gang of {len(bound)} pods bound"

        for nm in ("podracer-big", "podracer"):
            self.api.delete("TPUJob", nm, NS)
        self.wait(lambda: not (gang_pods("podracer")
                               + gang_pods("podracer-big")),
                  what="gang pods swept")
        return {"gang_pods": len(pods), "actors": actors,
                "learner_hosts": self.hosts}

    def delete_cascade(self):
        self.api.delete("Notebook", "walk", NS)
        gone = [("StatefulSet", "walk"), ("Service", "walk"),
                ("Service", headless_name("walk")),
                ("Secret", "walk-oauth-config"),
                ("NetworkPolicy", "walk-ctrl-np")]
        for kind, n in (("VirtualService", f"notebook-{NS}-walk"),
                        ("Route", "walk")):
            if self.available(kind):
                gone.append((kind, n))
        for kind, n in gone:
            self.wait(lambda k=kind, nm=n:
                      self.api.try_get(k, nm, NS) is None,
                      what=f"{kind}/{n} gone")
        if self.has_fake_kubelet:
            self.wait(lambda: not [
                p for p in self.api.list("Pod", NS)
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == "walk"],
                what="pods garbage-collected")
        return {"objects_swept": len(gone)}

    # ---- driver ------------------------------------------------------
    def walk(self):
        k = self.has_fake_kubelet
        self.run("profile_onboarding", self.profile_onboarding)
        self.run("spawn_oauth", self.spawn_oauth)
        self.run("no_restart_guard", self.no_restart_guard)
        self.run("stop_start", self.stop_start)
        self.run("culling", self.culling,
                 skip=None if self.fast_culling else
                 "needs the fast-culling config (local backend)")
        self.run("slice_restart", self.slice_restart,
                 skip=None if k else
                 "needs pod-status control (fake kubelet)")
        self.run("quota_denial", self.quota_denial,
                 skip=None if k else
                 "needs admission-visible pod creation (fake kubelet)")
        self.run("conversion", self.conversion,
                 skip=None if self.rest_url else
                 "needs the multi-version REST facade URL")
        self.run("ha_failover", self.ha_failover,
                 skip=None if self.ha else
                 "needs the two-manager local backend")
        self.run("oversubscription", self.oversubscription,
                 skip=None if k else
                 "needs the local backend (suspend controller + "
                 "pod-status control)")
        self.run("replicated", self.replicated,
                 skip=None if k else
                 "needs the local backend (failover controller + "
                 "pod-status control)")
        self.run("multirole", self.multirole,
                 skip=None if k else
                 "needs gang pod-status control (fake kubelet)")
        self.run("delete_cascade", self.delete_cascade)
        self.run("shard_chaos", self.shard_chaos,
                 skip=None if self.ha else
                 "needs the local backend (spawns shard processes)")
        return self.results


def local_backend(stop):
    """The wallclock process layout (spawn_conformance's, plus fast
    culling and the null probe — fake pods serve no Jupyter API) —
    with the manager deployed the way manifests.py ships it: TWO
    replicas behind lease-based leader election, each with its own
    client identity, watch threads and stop event so one can be
    crashed independently (the ha_failover scenario)."""
    import threading

    from kubeflow_rm_tpu.controlplane import (
        WATCHED_KINDS, make_cluster_manager,
    )
    from kubeflow_rm_tpu.controlplane.api import poddefault as pd_api
    from kubeflow_rm_tpu.controlplane.apiserver import APIServer
    from kubeflow_rm_tpu.controlplane.controllers.statefulset import (
        DeploymentController, StatefulSetController, make_tpu_node,
    )
    from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
        KubeAPIServer,
    )
    from kubeflow_rm_tpu.controlplane.deploy.restserver import RestServer
    from kubeflow_rm_tpu.controlplane.ha.leases import LeaderElector
    from kubeflow_rm_tpu.controlplane.runtime import Manager
    from kubeflow_rm_tpu.controlplane.webhook.notebook import (
        NotebookWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.poddefault import (
        PodDefaultWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.tpu_inject import (
        TpuInjectWebhook,
    )

    from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api

    capi = APIServer()
    capi.register_validator(nb_api.KIND, nb_api.validate)
    capi.register_validator(pd_api.KIND, pd_api.validate)
    capi.register_validator(tj_api.KIND, tj_api.validate)
    NotebookWebhook(capi).register()
    PodDefaultWebhook(capi).register()
    TpuInjectWebhook(capi).register()
    kubelet = Manager(capi)
    kubelet.add(StatefulSetController(auto_ready=True))
    kubelet.add(DeploymentController(auto_ready=True))
    topo = tpu_api.lookup(ACCEL)
    for s in range(3):
        for h in range(topo.hosts):
            capi.create(make_tpu_node(f"{ACCEL}-s{s}-h{h}", ACCEL))
    rest = RestServer(capi)
    rest.start()
    # short SyncPeriod: the walk's waits assert convergence, so bound
    # the staleness a lost watch event can cause to ~2s instead of "the
    # next stream restart" (the ~1min stalls behind the old flakes)
    threading.Thread(target=kubelet.run_forever, args=(stop, 0.05),
                     kwargs={"resync_interval_s": 2.0},
                     daemon=True).start()
    # the Lease namespace (deployment-wise: the manager's own ns)
    capi.ensure_namespace("kubeflow")

    culler_config = {
        # idle after ~1.8s of no activity, checked every ~0.6s;
        # the null probe models fake pods with no Jupyter API
        "cull_idle_minutes": 0.03,
        "check_period_minutes": 0.01,
        "probe_fn": lambda nb, pod0: None,
    }

    def elected_manager(identity: str) -> dict:
        mstop = threading.Event()
        kapi = KubeAPIServer(rest.url, identity=identity)
        # suspend lifecycle on, idle parking off: the oversubscription
        # scenario drives suspends explicitly (the fast culler would
        # otherwise race every idle window)
        mgr = make_cluster_manager(kapi, culler_config=culler_config,
                                   enable_suspend=True)
        elector = LeaderElector(
            kapi, identity,
            # scaled-down from the 15s/10s/2s production defaults so
            # the walk's failover completes in seconds; crash-oriented
            # (release_on_exit stays False)
            lease_duration_s=1.5, renew_deadline_s=0.5,
            retry_period_s=0.1)
        for kind in WATCHED_KINDS:
            threading.Thread(target=kapi.watch_kind,
                             args=(kind, None, mstop, 60),
                             daemon=True).start()
        mgr.enqueue_all()
        threading.Thread(target=mgr.run_forever, args=(mstop, 0.05),
                         kwargs={"workers": 8, "elector": elector,
                                 "resync_interval_s": 2.0},
                         daemon=True).start()
        return {"identity": identity, "stop": mstop,
                "elector": elector, "kapi": kapi}

    managers = [elected_manager("mgr-a"), elected_manager("mgr-b")]

    # the walk reads through its own client so its informer caches
    # survive a leader kill
    kapi = KubeAPIServer(rest.url, identity="e2e-client")
    for kind in WATCHED_KINDS:
        threading.Thread(target=kapi.watch_kind,
                         args=(kind, None, stop, 60),
                         daemon=True).start()
    return kapi, rest, {"capi": capi, "managers": managers}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", choices=["local", "cluster"],
                    default="local")
    ap.add_argument("--server", default=None,
                    help="cluster backend: apiserver URL "
                         "(e.g. kubectl proxy at :8001)")
    ap.add_argument("--token", default=None)
    ap.add_argument("--image", default=None,
                    help="notebook container image (cluster backend: "
                         "something the nodes can pull, e.g. "
                         "busybox:stable)")
    ap.add_argument("--scenarios", default="",
                    help="comma-separated subset to run (others are "
                         "recorded as skipped); scenarios share state "
                         "— pick prefixes of the full walk order")
    ap.add_argument("--tracing", action="store_true",
                    help="local backend: collect a distributed trace "
                         "per scenario (root span around each, spans "
                         "from every control-plane hop)")
    ap.add_argument("--trace-out", default="",
                    help="write per-scenario traces + critical paths "
                         "to this JSON file (with --tracing)")
    ap.add_argument("--flight-out", default="",
                    help="shard_chaos: write the flight-recorder "
                         "bundle (trailing metric window, slow traces "
                         "+ critical paths, alerts, shard liveness) "
                         "to this JSON file")
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    from kubeflow_rm_tpu.controlplane import tracing
    if args.tracing and args.backend == "local":
        tracing.set_enabled(True)
        tracing.set_process("e2e")

    import threading
    stop = threading.Event()
    only = set(filter(None, args.scenarios.split(","))) or None
    t0 = time.time()
    ha = None
    if args.backend == "local":
        api, rest, ha = local_backend(stop)
        walk = Walk(api, has_fake_kubelet=True, fast_culling=True,
                    rest_url=rest.url,
                    image=args.image or "jupyter-jax:latest",
                    ha=ha, only=only, flight_out=args.flight_out)
    else:
        from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
            KubeAPIServer,
        )
        api = KubeAPIServer(args.server, token=args.token)
        walk = Walk(api, has_fake_kubelet=False, fast_culling=False,
                    rest_url=args.server,
                    image=args.image or "busybox:stable", only=only)

    print(f"e2e walk ({args.backend}):", flush=True)
    results = walk.walk()
    stop.set()
    for m in (ha or {}).get("managers", []):
        m["stop"].set()
    ran = [r for r in results if r.get("ok") is not None]
    passed = [r for r in ran if r["ok"]]
    import os

    from kubeflow_rm_tpu.controlplane.obs.runmeta import build_run_meta
    interleave = os.environ.get("KFRM_RUN_INTERLEAVE")
    artifact = {
        "run_meta": build_run_meta(
            "e2e_walk",
            {"backend": args.backend,
             "scenarios": args.scenarios or "all",
             "tracing": bool(args.tracing)},
            interleave_index=int(interleave) if interleave else None),
        "backend": args.backend,
        "scenarios": results,
        "passed": len(passed),
        "ran": len(ran),
        "skipped": len(results) - len(ran),
        "total_s": round(time.time() - t0, 2),
    }
    if tracing.enabled():
        spans = tracing.collector().spans()
        by_trace: dict[str, list] = {}
        for s in spans:
            by_trace.setdefault(s["trace_id"], []).append(s)
        traces = []
        for rec in results:
            tid = rec.get("trace_id")
            tspans = sorted(by_trace.get(tid, []),
                            key=lambda s: s["start"]) if tid else []
            if not tspans:
                continue
            cp = tracing.critical_path(tspans)
            traces.append({
                "scenario": rec["scenario"],
                "trace_id": tid,
                "measured_ms": rec.get("ms"),
                "self_ms_total": round(
                    sum(h["self_ms"] for h in cp), 3),
                "hops": len(cp),
                "critical_path": cp,
            })
        artifact["trace"] = {"count": len(traces), "scenarios": traces}
        if args.trace_out:
            with open(args.trace_out, "w") as f:
                json.dump(artifact["trace"], f, indent=1)
    print(json.dumps(artifact))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
    ok = len(passed) == len(ran)
    print("E2E WALK", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
