import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_rm_tpu.models import LlamaConfig, forward, init_params
from kubeflow_rm_tpu.ops import dot_product_attention
from kubeflow_rm_tpu.parallel import (
    MeshConfig,
    make_mesh,
    param_pspecs,
    param_shardings,
    ring_attention,
)
from kubeflow_rm_tpu.parallel.ring_attention import ring_self_attention


def test_mesh_config_resolution(devices8):
    assert MeshConfig(dp=2, fsdp=2, sp=1, tp=2).resolve(8) == (2, 1, 2, 1, 1, 2)
    assert MeshConfig(dp=1, fsdp=-1, sp=1, tp=2).resolve(8) == (1, 1, 4, 1, 1, 2)
    with pytest.raises(ValueError):
        MeshConfig(dp=3, fsdp=1, sp=1, tp=1).resolve(8)
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, sp=1, tp=2), devices8)
    assert mesh.shape == {"dp": 2, "pp": 1, "fsdp": 2, "ep": 1, "sp": 1, "tp": 2}


def test_param_pspecs_cover_llama_tree():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    specs = param_pspecs(params)
    assert jax.tree_util.tree_structure(specs, is_leaf=lambda x: isinstance(x, P)) \
        == jax.tree_util.tree_structure(params)
    assert specs["blocks"]["wq"] == P("pp", "fsdp", "tp")


def test_sharded_forward_matches_single_device(devices8):
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
    ref = forward(params, tokens, cfg)

    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, sp=1, tp=2), devices8)
    pshard = param_shardings(params, mesh)
    params_s = jax.device_put(params, pshard)
    tokens_s = jax.device_put(
        tokens, NamedSharding(mesh, P(("dp", "fsdp"), None)))
    out = jax.jit(lambda p, t: forward(p, t, cfg))(params_s, tokens_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-3)


def test_ring_attention_matches_dense(devices8):
    B, T, H, D = 2, 32, 4, 8
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    ref = dot_product_attention(q, k, v, causal=True)

    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1), devices8)
    out = ring_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)


def test_ring_attention_noncausal_matches_dense(devices8):
    B, T, H, D = 1, 16, 2, 4
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    ref = dot_product_attention(q, k, v, causal=False)
    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1), devices8)
    out = ring_self_attention(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)


def test_ring_attention_gqa(devices8):
    B, T, H, KVH, D = 1, 16, 4, 2, 8
    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, KVH, D))
    v = jax.random.normal(ks[2], (B, T, KVH, D))
    ref = dot_product_attention(q, k, v, causal=True)
    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1), devices8)
    out = ring_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)


def test_ring_attention_segments_match_dense(devices8):
    # packed sequences across sequence shards: the ADVICE r1 'medium'
    # finding — a query row whose first ring block is fully masked must
    # not silently accumulate masked V. Segment layout here guarantees
    # some (q-chunk, kv-chunk) ring steps are fully masked.
    B, T, H, D = 1, 32, 2, 4
    ks = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    # two docs of 12 + 8 tokens of pad (segment 0), positions restart
    pos = jnp.concatenate([jnp.arange(12), jnp.arange(12), jnp.arange(8)])[None, :]
    seg = jnp.concatenate([jnp.full((12,), 1), jnp.full((12,), 2),
                           jnp.zeros((8,), jnp.int32)])[None, :]
    pos = pos.astype(jnp.int32)
    seg = seg.astype(jnp.int32)
    ref = dot_product_attention(q, k, v, causal=True,
                                positions_q=pos, positions_kv=pos,
                                segment_ids_q=seg, segment_ids_kv=seg)
    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1), devices8)
    out = ring_self_attention(q, k, v, mesh, causal=True,
                              positions=pos, segments=seg)
    # doc tokens must match the dense segment-aware reference exactly
    np.testing.assert_allclose(np.asarray(out[:, :24]), np.asarray(ref[:, :24]),
                               atol=1e-5, rtol=1e-4)


def test_ring_attention_differentiable(devices8):
    B, T, H, D = 1, 16, 2, 4
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1), devices8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   atol=1e-4, rtol=1e-3)


def test_make_hybrid_mesh_cpu_fallback(devices8):
    """Hybrid multislice mesh on virtual CPU devices (no slice_index):
    dp spans the slices, per-slice blocks are contiguous, and a sharded
    computation over the mesh matches single-device numerics."""
    from kubeflow_rm_tpu.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh(
        MeshConfig(dp=2, fsdp=2, sp=1, tp=2), n_slices=2, devices=devices8
    )
    assert mesh.shape == {"dp": 2, "pp": 1, "fsdp": 2, "ep": 1, "sp": 1, "tp": 2}
    # slice-major: the first dp block is exactly the first 4 devices
    grid = np.asarray(mesh.devices)
    assert [d.id for d in grid[0].flatten()] == [d.id for d in devices8[:4]]
    assert [d.id for d in grid[1].flatten()] == [d.id for d in devices8[4:]]

    x = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"), "tp")))
    out = jax.jit(lambda a: jnp.sum(a * a, axis=-1))(xs)
    np.testing.assert_allclose(np.asarray(out), (x * x).sum(-1), rtol=1e-6)


def test_make_hybrid_mesh_dp_must_match_slices(devices8):
    from kubeflow_rm_tpu.parallel.mesh import make_hybrid_mesh

    with pytest.raises(ValueError, match="must equal n_slices"):
        make_hybrid_mesh(
            MeshConfig(dp=4, fsdp=2, sp=1, tp=1), n_slices=2, devices=devices8
        )


def test_make_hybrid_mesh_dp_wildcard(devices8):
    from kubeflow_rm_tpu.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh(
        MeshConfig(dp=-1, fsdp=4, sp=1, tp=1), n_slices=2, devices=devices8
    )
    assert mesh.shape["dp"] == 2
