import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_rm_tpu.models import LlamaConfig, forward, init_params
from kubeflow_rm_tpu.ops import dot_product_attention
from kubeflow_rm_tpu.parallel import (
    MeshConfig,
    make_mesh,
    param_pspecs,
    param_shardings,
    ring_attention,
)
from kubeflow_rm_tpu.parallel.ring_attention import ring_self_attention


def test_mesh_config_resolution(devices8):
    assert MeshConfig(dp=2, fsdp=2, sp=1, tp=2).resolve(8) == (2, 2, 1, 2)
    assert MeshConfig(dp=1, fsdp=-1, sp=1, tp=2).resolve(8) == (1, 4, 1, 2)
    with pytest.raises(ValueError):
        MeshConfig(dp=3, fsdp=1, sp=1, tp=1).resolve(8)
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, sp=1, tp=2), devices8)
    assert mesh.shape == {"dp": 2, "fsdp": 2, "sp": 1, "tp": 2}


def test_param_pspecs_cover_llama_tree():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    specs = param_pspecs(params)
    assert jax.tree_util.tree_structure(specs, is_leaf=lambda x: isinstance(x, P)) \
        == jax.tree_util.tree_structure(params)
    assert specs["blocks"]["wq"] == P(None, "fsdp", "tp")


def test_sharded_forward_matches_single_device(devices8):
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
    ref = forward(params, tokens, cfg)

    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, sp=1, tp=2), devices8)
    pshard = param_shardings(params, mesh)
    params_s = jax.device_put(params, pshard)
    tokens_s = jax.device_put(
        tokens, NamedSharding(mesh, P(("dp", "fsdp"), None)))
    out = jax.jit(lambda p, t: forward(p, t, cfg))(params_s, tokens_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-3)


def test_ring_attention_matches_dense(devices8):
    B, T, H, D = 2, 32, 4, 8
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    ref = dot_product_attention(q, k, v, causal=True)

    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1), devices8)
    out = ring_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)


def test_ring_attention_noncausal_matches_dense(devices8):
    B, T, H, D = 1, 16, 2, 4
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    ref = dot_product_attention(q, k, v, causal=False)
    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1), devices8)
    out = ring_self_attention(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)


def test_ring_attention_gqa(devices8):
    B, T, H, KVH, D = 1, 16, 4, 2, 8
    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, KVH, D))
    v = jax.random.normal(ks[2], (B, T, KVH, D))
    ref = dot_product_attention(q, k, v, causal=True)
    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1), devices8)
    out = ring_self_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)


def test_ring_attention_differentiable(devices8):
    B, T, H, D = 1, 16, 2, 4
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, H, D))
    v = jax.random.normal(ks[2], (B, T, H, D))
    mesh = make_mesh(MeshConfig(dp=1, fsdp=1, sp=8, tp=1), devices8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   atol=1e-4, rtol=1e-3)
