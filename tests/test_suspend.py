"""Suspend/resume lifecycle + priority-preemptive gang scheduling
(controlplane/suspend.py): idle slices checkpoint and release their
chips, any incoming request resumes them, and a higher-priority gang
that cannot fit suspends lower-priority victims all-or-nothing."""

import json

import pytest

from kubeflow_rm_tpu.controlplane import (
    make_control_plane, metrics, scheduler, suspend,
)
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of, set_annotation,
)
from kubeflow_rm_tpu.controlplane.api.notebook import make_notebook
from kubeflow_rm_tpu.controlplane.controllers.statefulset import (
    make_tpu_node,
)
from kubeflow_rm_tpu.controlplane.webapps import status as status_mod
from kubeflow_rm_tpu.controlplane.webapps.jupyter import create_app
from tests.cp_fixtures import FakeClock


@pytest.fixture(autouse=True)
def _fresh_store():
    suspend.set_state_store(suspend.InMemoryStateStore())
    suspend.set_oversubscribe(True)
    yield
    suspend.set_oversubscribe(True)


@pytest.fixture
def stack():
    """Two v5p-16 nodes = capacity for exactly one 2-host slice's
    worth of notebooks at a time (each v5p-16 slice takes both)."""
    clock = FakeClock()
    api, mgr = make_control_plane(
        clock=clock, enable_suspend=True,
        suspend_config={"suspend_idle_minutes": 30.0,
                        "check_period_minutes": 1.0})
    api.ensure_namespace("u")
    for i in range(2):
        api.create(make_tpu_node(f"n{i}", "v5p-16"))
    return api, mgr, clock


def _ready(api, name, ns="u"):
    return (api.get(nb_api.KIND, name, ns).get("status") or {}).get(
        "readyReplicas", 0)


# ---- idle suspension -------------------------------------------------

def test_idle_notebook_suspends_and_releases_chips(stack):
    api, mgr, clock = stack
    nb = make_notebook("idle", "u", accelerator_type="v5p-16")
    set_annotation(nb, nb_api.TRAINING_STEP_ANNOTATION, "7")
    api.create(nb)
    mgr.run_until_idle()
    assert len(api.list("Pod", "u")) == 2

    clock.advance(minutes=31)
    mgr.run_until_idle()

    nb = api.get(nb_api.KIND, "idle", "u")
    ann = annotations_of(nb)
    assert nb_api.SUSPEND_ANNOTATION in ann
    assert ann[nb_api.SUSPEND_REASON_ANNOTATION] == "idle"
    assert nb_api.SUSPEND_DRAINED_ANNOTATION in ann
    # the checkpoint token recorded the workload's durable step
    assert json.loads(ann[nb_api.SUSPEND_CHECKPOINT_ANNOTATION]) == {
        "step": 7}
    # whole slice drained, chips back in the pool
    assert api.list("Pod", "u") == []
    assert api.get("StatefulSet", "idle", "u")["spec"]["replicas"] == 0
    assert nb["status"]["phase"] == nb_api.SUSPENDED_PHASE
    stats = scheduler.cache_for(api).stats()
    # both v5p-16 hosts (4 chips each) back in the pool
    assert stats["free_chips"] == 8.0
    assert stats["largest_free_gang"] == 8.0
    assert stats["fragmentation"] == 0.0


def test_resume_restores_checkpointed_step(stack):
    api, mgr, clock = stack
    nb = make_notebook("nb", "u", accelerator_type="v5p-16")
    set_annotation(nb, nb_api.TRAINING_STEP_ANNOTATION, "42")
    api.create(nb)
    mgr.run_until_idle()
    clock.advance(minutes=31)
    mgr.run_until_idle()
    assert api.list("Pod", "u") == []

    suspend.request_resume(api, api.get(nb_api.KIND, "nb", "u"))
    mgr.run_until_idle()

    nb = api.get(nb_api.KIND, "nb", "u")
    ann = annotations_of(nb)
    assert _ready(api, "nb") == 2
    # restored exactly at the pre-suspend checkpoint step
    assert ann[nb_api.RESTORED_STEP_ANNOTATION] == "42"
    # cycle annotations cleared — ready for the next suspend
    assert nb_api.SUSPEND_ANNOTATION not in ann
    assert nb_api.RESUME_REQUESTED_ANNOTATION not in ann
    assert nb_api.SUSPEND_CHECKPOINT_ANNOTATION not in ann
    assert any(e["reason"] == "Resumed" for e in api.events_for(nb))


def test_pinned_notebook_never_idle_suspended(stack):
    api, mgr, clock = stack
    nb = make_notebook("pinned", "u", accelerator_type="v5p-16",
                       annotations={nb_api.PIN_ANNOTATION: "true"})
    api.create(nb)
    mgr.run_until_idle()
    clock.advance(minutes=120)
    mgr.run_until_idle()
    ann = annotations_of(api.get(nb_api.KIND, "pinned", "u"))
    assert nb_api.SUSPEND_ANNOTATION not in ann
    assert len(api.list("Pod", "u")) == 2


def test_no_oversubscribe_arm_disables_idle_suspension(stack):
    api, mgr, clock = stack
    suspend.set_oversubscribe(False)
    api.create(make_notebook("nb", "u", accelerator_type="v5p-16"))
    mgr.run_until_idle()
    clock.advance(minutes=120)
    mgr.run_until_idle()
    ann = annotations_of(api.get(nb_api.KIND, "nb", "u"))
    assert nb_api.SUSPEND_ANNOTATION not in ann
    assert len(api.list("Pod", "u")) == 2


def test_resumed_notebook_gets_fresh_idle_window(stack):
    api, mgr, clock = stack
    api.create(make_notebook("nb", "u", accelerator_type="v5p-16"))
    mgr.run_until_idle()
    clock.advance(minutes=31)
    mgr.run_until_idle()
    assert nb_api.SUSPEND_ANNOTATION in annotations_of(
        api.get(nb_api.KIND, "nb", "u"))

    suspend.request_resume(api, api.get(nb_api.KIND, "nb", "u"))
    mgr.run_until_idle()
    assert _ready(api, "nb") == 2
    # 20 more minutes < 30: the idle clock restarted at resume
    clock.advance(minutes=20)
    mgr.run_until_idle()
    assert nb_api.SUSPEND_ANNOTATION not in annotations_of(
        api.get(nb_api.KIND, "nb", "u"))
    assert _ready(api, "nb") == 2


# ---- preemption ------------------------------------------------------

def test_higher_priority_gang_displaces_one_victim(stack):
    api, mgr, _clock = stack
    api.create(make_notebook("low", "u", accelerator_type="v5p-16",
                             priority_class="low"))
    mgr.run_until_idle()
    assert _ready(api, "low") == 2

    api.create(make_notebook("high", "u", accelerator_type="v5p-16",
                             priority_class="high"))
    mgr.run_until_idle()

    low = api.get(nb_api.KIND, "low", "u")
    ann = annotations_of(low)
    assert ann.get(nb_api.SUSPEND_REASON_ANNOTATION) == "preempted"
    assert nb_api.SUSPEND_DRAINED_ANNOTATION in ann
    # the newcomer bound all-or-nothing; exactly one victim suspended
    assert _ready(api, "high") == 2
    names = {p["metadata"]["name"] for p in api.list("Pod", "u")}
    assert names == {"high-0", "high-1"}
    high_sts = api.get("StatefulSet", "high", "u")
    assert any(e["reason"] == "Preempted"
               for e in api.events_for(high_sts))


def test_pinned_victim_never_selected(stack):
    api, mgr, _clock = stack
    api.create(make_notebook(
        "pinned-low", "u", accelerator_type="v5p-16",
        priority_class="low",
        annotations={nb_api.PIN_ANNOTATION: "true"}))
    mgr.run_until_idle()
    api.create(make_notebook("high", "u", accelerator_type="v5p-16",
                             priority_class="high"))
    mgr.run_until_idle()

    # the pinned slice kept its chips; the high gang waits
    assert _ready(api, "pinned-low") == 2
    assert _ready(api, "high") == 0
    ann = annotations_of(api.get(nb_api.KIND, "pinned-low", "u"))
    assert nb_api.SUSPEND_ANNOTATION not in ann


def test_equal_priority_never_preempts(stack):
    api, mgr, _clock = stack
    api.create(make_notebook("first", "u", accelerator_type="v5p-16"))
    mgr.run_until_idle()
    api.create(make_notebook("second", "u", accelerator_type="v5p-16"))
    mgr.run_until_idle()
    # default vs default: first-come-first-served preserved
    assert _ready(api, "first") == 2
    assert _ready(api, "second") == 0


def test_no_oversubscribe_arm_disables_preemption(stack):
    api, mgr, _clock = stack
    suspend.set_oversubscribe(False)
    api.create(make_notebook("low", "u", accelerator_type="v5p-16",
                             priority_class="low"))
    mgr.run_until_idle()
    api.create(make_notebook("high", "u", accelerator_type="v5p-16",
                             priority_class="high"))
    mgr.run_until_idle()
    assert _ready(api, "low") == 2
    assert _ready(api, "high") == 0


def test_preempted_victim_regangs_when_capacity_frees(stack):
    api, mgr, _clock = stack
    api.create(make_notebook("low", "u", accelerator_type="v5p-16",
                             priority_class="low"))
    mgr.run_until_idle()
    api.create(make_notebook("high", "u", accelerator_type="v5p-16",
                             priority_class="high"))
    mgr.run_until_idle()
    assert _ready(api, "high") == 2

    # victim expresses demand while the fleet is full: stays parked
    suspend.request_resume(api, api.get(nb_api.KIND, "low", "u"))
    mgr.run_until_idle()
    assert _ready(api, "low") == 0
    assert _ready(api, "high") == 2  # a lower priority never preempts

    # the high slice suspends -> freed chips flow to the waiter
    suspend.initiate_suspend(
        api, api.get(nb_api.KIND, "high", "u"), reason="api")
    mgr.run_until_idle()
    assert _ready(api, "low") == 2
    assert api.get(nb_api.KIND, "high", "u")["status"]["phase"] == \
        nb_api.SUSPENDED_PHASE


# ---- priority API ----------------------------------------------------

def test_priority_resolution_and_validation():
    nb = make_notebook("a", "u", priority_class="high")
    assert nb_api.priority_of(nb) == nb_api.PRIORITY_CLASSES["high"]
    nb["spec"]["priority"] = 5
    assert nb_api.priority_of(nb) == 5  # explicit integer wins
    assert nb_api.priority_of(make_notebook("b", "u")) == \
        nb_api.DEFAULT_PRIORITY
    with pytest.raises(ValueError):
        nb_api.validate(make_notebook("c", "u",
                                      priority_class="platinum"))
    bad = make_notebook("d", "u")
    bad["spec"]["priority"] = "urgent"
    with pytest.raises(ValueError):
        nb_api.validate(bad)


# ---- web app surface -------------------------------------------------

def test_patch_suspended_and_status_ladder(stack):
    api, mgr, _clock = stack
    api.create(make_notebook("nb", "u", accelerator_type="v5p-16"))
    mgr.run_until_idle()
    app = create_app(api, disable_auth=True)
    client = app.test_client()

    r = client.patch("/api/namespaces/u/notebooks/nb",
                     data=json.dumps({"suspended": True}),
                     headers=[("Content-Type", "application/json")])
    assert r.status_code == 200
    mgr.run_until_idle()
    nb = api.get(nb_api.KIND, "nb", "u")
    st = status_mod.process_status(nb, api.events_for(nb))
    assert st.phase == status_mod.PHASE_SUSPENDED

    r = client.patch("/api/namespaces/u/notebooks/nb",
                     data=json.dumps({"suspended": False}),
                     headers=[("Content-Type", "application/json")])
    assert r.status_code == 200
    mgr.run_until_idle()
    assert _ready(api, "nb") == 2


def test_readiness_longpoll_auto_resumes(stack):
    api, mgr, _clock = stack
    api.create(make_notebook("nb", "u", accelerator_type="v5p-16"))
    mgr.run_until_idle()
    suspend.initiate_suspend(
        api, api.get(nb_api.KIND, "nb", "u"), reason="api")
    mgr.run_until_idle()
    assert api.list("Pod", "u") == []

    app = create_app(api, disable_auth=True)
    client = app.test_client()
    # the long-poll itself is the demand signal: it flips the notebook
    # back toward Running before blocking (timeoutSeconds=0 returns
    # immediately; the controllers run after)
    client.get("/api/namespaces/u/notebooks/nb/readiness?timeoutSeconds=0")
    mgr.run_until_idle()
    assert _ready(api, "nb") == 2

    # wake=false observes without resuming
    suspend.initiate_suspend(
        api, api.get(nb_api.KIND, "nb", "u"), reason="api")
    mgr.run_until_idle()
    client.get("/api/namespaces/u/notebooks/nb/readiness"
               "?timeoutSeconds=0&wake=false")
    mgr.run_until_idle()
    assert nb_api.SUSPEND_ANNOTATION in annotations_of(
        api.get(nb_api.KIND, "nb", "u"))


# ---- state stores ----------------------------------------------------

def test_checkpointer_state_store_bridges_latest_step():
    class FakeManager:
        def __init__(self):
            self.step = 1234
            self.waited = False

        def wait(self):
            self.waited = True

        def latest_step(self):
            return self.step

    mgr = FakeManager()
    store = suspend.CheckpointerStateStore(lambda ns, name: mgr)
    nb = make_notebook("nb", "u")
    token = store.snapshot(nb)
    assert token == {"step": 1234}
    assert mgr.waited  # pending async saves flushed before teardown
    assert store.restore(nb, token) == {"step": 1234}
    # a regressed checkpoint reports the degradation
    mgr.step = 1000
    out = store.restore(nb, token)
    assert out["step"] == 1000 and out["degraded_from"] == 1234


def test_suspend_metrics_observed(stack):
    api, mgr, clock = stack
    before = metrics.registry_value("notebook_suspend_total",
                                    {"reason": "idle"})
    api.create(make_notebook("nb", "u", accelerator_type="v5p-16"))
    mgr.run_until_idle()
    clock.advance(minutes=31)
    mgr.run_until_idle()
    assert metrics.registry_value(
        "notebook_suspend_total", {"reason": "idle"}) == before + 1
    drains = metrics.registry_value(
        "suspend_resume_phase_seconds_count", {"phase": "drain"})
    assert drains >= 1
    suspend.request_resume(api, api.get(nb_api.KIND, "nb", "u"))
    mgr.run_until_idle()
    assert metrics.registry_value(
        "suspend_resume_phase_seconds_count", {"phase": "rebind"}) >= 1
    assert metrics.registry_value(
        "suspend_resume_phase_seconds_count", {"phase": "restore"}) >= 1
