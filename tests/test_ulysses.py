"""Ulysses all-to-all sequence parallelism vs the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_rm_tpu.ops import dot_product_attention
from kubeflow_rm_tpu.parallel import MeshConfig, make_mesh
from kubeflow_rm_tpu.parallel.ulysses import ulysses_self_attention
from kubeflow_rm_tpu.training.data import pack_documents


@pytest.fixture(scope="module")
def sp_mesh(devices8):
    return make_mesh(MeshConfig(sp=8), devices8)


def _qkv(B, T, H, D, KVH=None, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, KVH or H, D))
    v = jax.random.normal(ks[2], (B, T, KVH or H, D))
    return q, k, v


def test_matches_dense_causal(sp_mesh):
    q, k, v = _qkv(2, 64, 8, 16)
    out = ulysses_self_attention(q, k, v, sp_mesh, causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6)


def test_matches_dense_bidirectional(sp_mesh):
    q, k, v = _qkv(1, 32, 8, 8, seed=3)
    out = ulysses_self_attention(q, k, v, sp_mesh, causal=False)
    ref = dot_product_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6)


def test_gqa_kv_heads_below_sp(sp_mesh):
    """KVH=2 < sp=8: KV broadcast path — correctness must hold even
    when GQA's memory saving can't survive the head scatter."""
    q, k, v = _qkv(2, 64, 8, 16, KVH=2, seed=5)
    out = ulysses_self_attention(q, k, v, sp_mesh, causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6)


def test_packed_segments_match_dense(sp_mesh):
    """Packed documents: segment isolation + per-doc causal positions
    flow through the all-to-all layout unchanged."""
    rng = np.random.default_rng(0)
    docs = [rng.integers(1, 100, size=n).tolist() for n in (30, 20, 30, 14)]
    packed = pack_documents(docs, seq_len=64)
    pos = jnp.asarray(packed["positions"][:1])
    seg = jnp.asarray(packed["segments"][:1])
    q, k, v = _qkv(1, 64, 8, 16, seed=7)
    out = ulysses_self_attention(q, k, v, sp_mesh, causal=True,
                                 positions=pos, segments=seg)
    ref = dot_product_attention(
        q, k, v, causal=True, positions_q=pos, positions_kv=pos,
        segment_ids_q=seg, segment_ids_kv=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6)


def test_indivisible_heads_rejected(sp_mesh):
    q, k, v = _qkv(1, 32, 4, 8)  # 4 heads on sp=8
    with pytest.raises(ValueError, match="divide n_heads"):
        ulysses_self_attention(q, k, v, sp_mesh, causal=True)


def test_grad_flows(sp_mesh):
    """The schedule differentiates: all-to-all transposes are exact."""
    q, k, v = _qkv(1, 32, 8, 8, seed=9)

    def loss_ulysses(q):
        return jnp.sum(ulysses_self_attention(q, k, v, sp_mesh) ** 2)

    def loss_dense(q):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    gu = jax.grad(loss_ulysses)(q)
    gd = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(np.asarray(gu), np.asarray(gd), atol=1e-5)
