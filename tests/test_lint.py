"""The KFRM static lint: every rule catches its seeded fixture
violation, the escape hatches work, and the shipped tree is clean
(the same invariant the CI gate enforces)."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from kubeflow_rm_tpu.analysis.lint import (
    ALL_RULES,
    lint_paths,
    lint_source,
)

FIXTURES = Path(__file__).parent / "lint_fixtures"
REPO = Path(__file__).parent.parent


def _lint_fixture(name: str):
    path = FIXTURES / name
    return lint_source(path.read_text(), str(path))


# (fixture, rule, expected violation lines)
SEEDED = [
    ("kfrm001_raw_lock.py", "KFRM001", {5, 6, 11}),
    ("kfrm002_blocking_under_lock.py", "KFRM002", {15, 16}),
    ("kfrm003_acquire_no_finally.py", "KFRM003", {10}),
    ("kfrm004_write_under_lock.py", "KFRM004", {14}),
    ("kfrm005_silent_swallow.py", "KFRM005", {8}),
    ("kfrm006_scalar_sync_in_loop.py", "KFRM006", {21, 28}),
    ("kfrm007_jit_in_loop.py", "KFRM007", {12, 20}),
    ("kfrm008_nondonated_state.py", "KFRM008", {11, 16, 24}),
]


@pytest.mark.parametrize("fixture,rule,lines",
                         SEEDED, ids=[s[1] for s in SEEDED])
def test_seeded_violation_detected(fixture, rule, lines):
    findings = _lint_fixture(fixture)
    assert {f.rule for f in findings} == {rule}, findings
    assert {f.line for f in findings} == lines, findings


def test_clean_fixture_has_no_findings():
    assert _lint_fixture("clean.py") == []


def test_inline_and_file_wide_disables():
    # raw lock silenced file-wide, sleep-under-lock silenced inline
    assert _lint_fixture("disabled.py") == []


def test_syntax_error_reports_kfrm000():
    findings = lint_source("def broken(:\n", "bad.py")
    assert [f.rule for f in findings] == ["KFRM000"]


def test_lockgraph_factory_is_allowlisted_for_kfrm001():
    path = REPO / "kubeflow_rm_tpu" / "analysis" / "lockgraph.py"
    findings = lint_paths([str(path)])
    assert not any(f.rule == "KFRM001" for f in findings), findings


def test_shipped_tree_is_clean():
    """The invariant the CI lint gate enforces: zero findings over the
    package and the conformance harness."""
    findings = lint_paths([str(REPO / "kubeflow_rm_tpu"),
                           str(REPO / "conformance")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_rule_ids_are_unique_and_documented():
    ids = [cls.rule_id for cls in ALL_RULES]
    assert len(ids) == len(set(ids))
    assert ids == sorted(ids)
    for cls in ALL_RULES:
        assert cls.__doc__, f"{cls.rule_id} has no docstring"


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "kubeflow_rm_tpu.analysis.lint", *args],
        capture_output=True, text=True, cwd=str(REPO))


def test_cli_exit_one_on_findings_and_json_output():
    proc = _run_cli("--json", str(FIXTURES / "kfrm001_raw_lock.py"))
    assert proc.returncode == 1, proc.stderr
    payload = json.loads(proc.stdout)
    assert all(f["rule"] == "KFRM001" for f in payload)
    assert {"rule", "path", "line", "col", "message"} <= set(payload[0])


def test_cli_exit_zero_on_clean_file():
    proc = _run_cli(str(FIXTURES / "clean.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_rule_filter():
    # restricting to KFRM005 makes the KFRM001 fixture pass
    proc = _run_cli("--rules", "KFRM005",
                    str(FIXTURES / "kfrm001_raw_lock.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
