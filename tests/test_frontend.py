"""Frontend layer (VERDICT r2 missing #1): the SPA shell + static
assets served by the dashboard, the single-origin gateway, and a
JS↔backend contract check so the SPA cannot drift from the route
maps."""

import json
import re
import secrets
from pathlib import Path

import pytest

from kubeflow_rm_tpu.controlplane import make_control_plane
from kubeflow_rm_tpu.controlplane.api.meta import make_object
from kubeflow_rm_tpu.controlplane.api.profile import make_profile
from kubeflow_rm_tpu.controlplane.controllers.statefulset import (
    make_tpu_node,
)
from kubeflow_rm_tpu.controlplane.webapps import dashboard as dashboard_mod
from kubeflow_rm_tpu.controlplane.webapps.core import (
    CSRF_COOKIE,
    CSRF_HEADER,
    USER_HEADER,
    USER_PREFIX,
)
from kubeflow_rm_tpu.controlplane.webapps.gateway import make_gateway

USER = "alice@corp.com"
STATIC = Path(__file__).parent.parent / \
    "kubeflow_rm_tpu/controlplane/webapps/static"


@pytest.fixture
def stack():
    api, mgr = make_control_plane()
    api.create(make_profile("team", USER))
    for i in range(2):
        api.create(make_tpu_node(f"n{i}", "v5p-16"))
    mgr.enqueue_all()
    mgr.run_until_idle()
    return api, mgr


def gateway_client(api, user=USER):
    from werkzeug.test import Client
    client = Client(make_gateway(api, secure_cookies=False))
    headers = []
    if user:
        headers.append((USER_HEADER, USER_PREFIX + user))
    token = secrets.token_urlsafe(16)
    client.set_cookie(CSRF_COOKIE, token, path="/")
    headers.append((CSRF_HEADER, token))

    class C:
        def open(self, *a, **kw):
            hs = list(kw.pop("headers", []) or []) + headers
            return client.open(*a, headers=hs, **kw)

        def get(self, *a, **kw):
            return self.open(*a, method="GET", **kw)

        def post(self, *a, **kw):
            return self.open(*a, method="POST", **kw)

    return C()


def _js_structure_check(src: str) -> None:
    """Bracket-balance lexer for app.js: string/template/comment/regex
    aware. No JS engine ships in this image (the browser e2e lane runs
    in CI only), so this is the strongest static guard against an edit
    that unbalances a brace and takes down the whole SPA."""
    stack = []            # open brackets (char, offset) + "${" markers
    mode = ["code"]       # code | template
    last_sig = ""         # last significant char (regex-vs-divide)
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if mode[-1] == "template":
            if c == "\\":
                i += 2
                continue
            if c == "`":
                mode.pop()
                i += 1
                continue
            if c == "$" and i + 1 < n and src[i + 1] == "{":
                stack.append(("${", i))
                mode.append("code")
                i += 2
                continue
            i += 1
            continue
        if c == "/" and src.startswith("//", i):
            nl = src.find("\n", i)
            i = n if nl < 0 else nl
            continue
        if c == "/" and src.startswith("/*", i):
            end = src.find("*/", i)
            assert end > 0, f"unterminated block comment at {i}"
            i = end + 2
            continue
        if c in "'\"":
            j = i + 1
            while j < n and src[j] != c:
                j += 2 if src[j] == "\\" else 1
            assert j < n, f"unterminated string at {i}"
            i, last_sig = j + 1, c
            continue
        if c == "`":
            mode.append("template")
            i += 1
            continue
        if c == "/" and (last_sig in "(,=:[!&|?{};>+-*%~^" or not last_sig
                         or re.search(r"\b(return|typeof|case|in|of|new|"
                                      r"delete|void|instanceof|yield|"
                                      r"await|do|else)$",
                                      src[:i].rstrip())):
            # try a regex literal; if no closing "/" before the newline
            # this was division after all — fall through, consuming
            # only the one "/" (heuristic must never fail valid code)
            j, in_class = i + 1, False
            while j < n and src[j] != "\n" and (in_class or src[j] != "/"):
                if src[j] == "\\":
                    j += 1
                elif src[j] == "[":
                    in_class = True
                elif src[j] == "]":
                    in_class = False
                j += 1
            if j < n and src[j] == "/":
                i, last_sig = j + 1, "/"
                continue
        if c in "([{":
            stack.append((c, i))
        elif c in ")]}":
            if c == "}" and stack and stack[-1][0] == "${":
                stack.pop()
                assert mode.pop() == "code"
            else:
                assert stack, f"unmatched {c!r} at {i}"
                o, at = stack.pop()
                pairs = {"(": ")", "[": "]", "{": "}"}
                assert pairs[o] == c, (
                    f"{o!r} at {at} closed by {c!r} at {i}")
        if not c.isspace():
            last_sig = c
        i += 1
    assert not stack, f"unclosed {stack[-1][0]!r} at {stack[-1][1]}"
    assert mode == ["code"], "unterminated template literal"


def test_app_js_brackets_balanced():
    # negative controls: the checker must actually catch breakage
    for bad in ("function f() { if (x) { g(); }",
                "const s = `a ${b ? 'x' : 'y'`;",
                "f(]"):
        with pytest.raises(AssertionError):
            _js_structure_check(bad)
    _js_structure_check((STATIC / "app.js").read_text())


# ---- SPA shell -------------------------------------------------------

def test_index_serves_spa_and_sets_csrf_cookie(stack):
    api, _ = stack
    app = dashboard_mod.create_app(api, secure_cookies=False)
    resp = app.test_client(user=None).get("/")
    assert resp.status_code == 200
    assert resp.mimetype == "text/html"
    assert b'src="/static/app.js"' in resp.get_data()
    cookie = resp.headers.get("Set-Cookie", "")
    assert CSRF_COOKIE in cookie


def test_static_assets_served_with_mimetypes(stack):
    api, _ = stack
    app = dashboard_mod.create_app(api)
    client = app.test_client(user=None)
    assert client.get("/static/app.js").mimetype in (
        "text/javascript", "application/javascript")
    assert client.get("/static/style.css").mimetype == "text/css"
    assert client.get("/static/nope.js").status_code == 404


def test_static_path_traversal_blocked(stack):
    api, _ = stack
    app = dashboard_mod.create_app(api)
    resp = app.test_client(user=None).get(
        "/static/../../apiserver.py")
    assert resp.status_code == 404


# ---- gateway ---------------------------------------------------------

def test_gateway_path_routes_every_webapp(stack):
    api, _ = stack
    c = gateway_client(api)
    assert json.loads(c.get("/jupyter/api/config").get_data())["config"]
    assert "tpus" in json.loads(c.get("/jupyter/api/tpus").get_data())
    assert "pvcs" in json.loads(
        c.get("/volumes/api/namespaces/team/pvcs").get_data())
    assert "tensorboards" in json.loads(
        c.get("/tensorboards/api/namespaces/team/tensorboards").get_data())
    assert "bindings" in json.loads(
        c.get("/kfam/kfam/v1/bindings?namespace=team").get_data())
    assert "namespaces" in json.loads(c.get("/api/namespaces").get_data())


def test_gateway_spawn_through_browser_contract(stack):
    """The exact request sequence app.js makes to spawn a notebook."""
    api, mgr = stack
    c = gateway_client(api)
    tpus = json.loads(c.get("/jupyter/api/tpus").get_data())["tpus"]
    accel = tpus[0]["acceleratorType"]
    body = {
        "name": "from-spa", "image": "ghcr.io/kubeflow-rm-tpu/jupyter-jax:latest",
        "imagePullPolicy": "IfNotPresent", "serverType": "jupyter",
        "cpu": "4", "memory": "16Gi",
        "tpu": {"acceleratorType": accel},
        "tolerationGroup": "none", "affinityConfig": "none",
        "configurations": [], "shm": True, "environment": {},
        "datavols": [],
    }
    resp = c.post("/jupyter/api/namespaces/team/notebooks",
                  data=json.dumps(body),
                  headers=[("Content-Type", "application/json")])
    assert resp.status_code == 200, resp.get_data()
    mgr.run_until_idle()
    nbs = json.loads(c.get(
        "/jupyter/api/namespaces/team/notebooks").get_data())["notebooks"]
    assert nbs[0]["status"]["phase"] == "ready"
    # per-ordinal logs through the gateway, as the detail view fetches
    logs = json.loads(c.get(
        "/jupyter/api/namespaces/team/notebooks/from-spa/pods/0/logs"
    ).get_data())["logs"]
    assert any("TPU_WORKER_ID=0" in line for line in logs)


def test_gateway_spawn_with_advanced_options(stack):
    """The advanced form section's body shape: PodDefault
    configurations, data volumes (existing + new PVC), toleration
    group, env vars — each must land on the rendered pods."""
    from kubeflow_rm_tpu.controlplane.api.meta import (
        deep_get, make_object,
    )

    api, mgr = stack
    # a PodDefault + an existing PVC to attach
    pd = make_object("kubeflow.org/v1alpha1", "PodDefault", "gcs-creds",
                     "team")
    pd["spec"] = {
        "desc": "GCS credentials",
        "selector": {"matchLabels": {"use-gcs-creds": "true"}},
        "env": [{"name": "GOOGLE_CLOUD_PROJECT", "value": "proj"}],
    }
    api.create(pd)
    pvc = make_object("v1", "PersistentVolumeClaim", "datasets", "team")
    pvc["spec"] = {"resources": {"requests": {"storage": "10Gi"}},
                   "accessModes": ["ReadWriteOnce"]}
    api.create(pvc)

    c = gateway_client(api)
    pds = json.loads(c.get(
        "/jupyter/api/namespaces/team/poddefaults").get_data())["poddefaults"]
    label_key = list(pds[0]["label"])[0]
    body = {
        "name": "adv", "image": "ghcr.io/kubeflow-rm-tpu/jupyter-jax:latest",
        "imagePullPolicy": "IfNotPresent", "serverType": "jupyter",
        "cpu": "4", "memory": "16Gi",
        "tpu": {"acceleratorType": "v5p-16"},
        "tolerationGroup": "tpu-preemptible", "affinityConfig": "none",
        "configurations": [label_key], "shm": True,
        "environment": {"HF_HOME": "/home/jovyan/.cache"},
        "datavols": [
            {"mount": "/data", "existingSource": {
                "persistentVolumeClaim": {"claimName": "datasets"}}},
            {"mount": "/scratch", "newPvc": {
                "metadata": {"name": "{notebook-name}-scratch"},
                "spec": {"resources": {"requests": {"storage": "5Gi"}},
                         "accessModes": ["ReadWriteOnce"]}}},
        ],
    }
    resp = c.post("/jupyter/api/namespaces/team/notebooks",
                  data=json.dumps(body),
                  headers=[("Content-Type", "application/json")])
    assert resp.status_code == 200, resp.get_data()
    mgr.run_until_idle()

    pods = [p for p in api.list("Pod", "team")
            if p["metadata"]["name"].startswith("adv-")]
    assert len(pods) == 2
    for pod in pods:
        env = {e["name"]: e.get("value")
               for cont in pod["spec"]["containers"]
               for e in cont.get("env", [])}
        assert env["HF_HOME"] == "/home/jovyan/.cache"
        assert env["GOOGLE_CLOUD_PROJECT"] == "proj"  # PodDefault merged
        mounts = {m["mountPath"] for cont in pod["spec"]["containers"]
                  for m in cont.get("volumeMounts", [])}
        assert {"/data", "/scratch"} <= mounts
        tol = deep_get(pod, "spec", "tolerations", default=[]) or []
        assert any(t.get("key") == "cloud.google.com/gke-preemptible"
                   for t in tol)
    assert api.try_get("PersistentVolumeClaim", "adv-scratch", "team")


def test_gateway_csrf_enforced(stack):
    api, _ = stack
    from werkzeug.test import Client
    raw = Client(make_gateway(api))
    resp = raw.post("/jupyter/api/namespaces/team/notebooks",
                    headers=[(USER_HEADER, USER_PREFIX + USER)])
    assert resp.status_code == 403  # no CSRF cookie/header pair


def test_gateway_dev_user_injects_identity(stack):
    api, _ = stack
    from werkzeug.test import Client
    client = Client(make_gateway(api, dev_user=USER, secure_cookies=False))
    resp = client.get("/jupyter/api/namespaces")
    data = json.loads(resp.get_data())
    assert data["user"] == USER


# ---- JS <-> backend contract ----------------------------------------

def _routes_of(app):
    return {rule.rule for rule in app._map.iter_rules()}


def test_spa_urls_exist_in_backends(stack):
    """Every literal API path referenced in app.js must match a route
    in the web app it targets (template params normalized)."""
    api, _ = stack
    from kubeflow_rm_tpu.controlplane.webapps import (
        jupyter as jwa, kfam, tensorboards as twa, volumes as vwa,
    )
    route_maps = {
        "/jupyter": _routes_of(jwa.create_app(api)),
        "/volumes": _routes_of(vwa.create_app(api)),
        "/tensorboards": _routes_of(twa.create_app(api)),
        "/kfam": _routes_of(kfam.create_app(api)),
        "": _routes_of(dashboard_mod.create_app(api)),
    }
    js = (STATIC / "app.js").read_text()
    called = re.findall(r'["`](/(?:jupyter|volumes|tensorboards|kfam|api)'
                        r'[^"`\s?]*)["`?]', js)
    assert called, "no API calls found in app.js — regex drift?"
    for url in called:
        prefix = ""
        for p in ("/jupyter", "/volumes", "/tensorboards", "/kfam"):
            if url.startswith(p):
                prefix, url = p, url[len(p):]
                break
        # normalize JS template holes (${...}) to a wildcard segment
        pattern = "^" + re.escape(url).replace(
            re.escape("${"), "X").replace(re.escape("}"), "X") + "$"
        pattern = re.sub(r"X[^/]*X", "[^/]+", pattern)
        routes = route_maps[prefix]
        normalized = {re.sub(r"<[^>]+>", "[^/]+", r) for r in routes}
        assert any(re.fullmatch(n, url) for n in normalized), (
            f"app.js calls {prefix}{url} but no backend route matches")
