"""The sharded train step must partition cleanly.

XLA's SPMD partitioner logs "Involuntary full rematerialization" when
it cannot move a tensor between two shardings without replicating it —
a silent per-step all-gather tax on a real slice (VERDICT r3 weak-#1
caught exactly this in the pp=2 pipeline schedule). The partitioner
warns on C++ stderr, so ``capfd`` (OS-level capture) sees it; these
tests compile the step fresh with caching disabled and assert the log
stays clean.
"""

import jax
import jax.numpy as jnp
import pytest

from kubeflow_rm_tpu.models import LlamaConfig
from kubeflow_rm_tpu.parallel import MeshConfig, make_mesh
from kubeflow_rm_tpu.training.train import (
    TrainConfig,
    init_train_state,
    make_train_step,
)

BAD = "Involuntary full rematerialization"


def _compile_step(mcfg, devices, **kw):
    cfg = TrainConfig(model=LlamaConfig.tiny())
    mesh = make_mesh(mcfg, devices)
    state = jax.eval_shape(
        lambda k: init_train_state(cfg, k), jax.random.key(0))
    step = make_train_step(
        cfg, mesh, state,
        batch_keys=("tokens", "labels", "positions", "segments"), **kw)
    batch = {k: jax.ShapeDtypeStruct((8, 32), jnp.int32)
             for k in ("tokens", "labels", "positions", "segments")}
    step.lower(state, batch).compile()


@pytest.mark.parametrize("mcfg,kw", [
    (MeshConfig(dp=1, fsdp=2, sp=2, tp=2), {}),
    (MeshConfig(dp=2, fsdp=4), {}),
    (MeshConfig(pp=2, fsdp=4), {"n_microbatches": 2}),
    (MeshConfig(pp=2, fsdp=2, tp=2), {"n_microbatches": 4}),
], ids=["flat", "dp2", "pp2-fsdp4", "pp2-fsdp2-tp2"])
def test_train_step_partitions_without_remat(devices8, mcfg, kw, capfd):
    _compile_step(mcfg, devices8, **kw)
    err = capfd.readouterr().err
    assert BAD not in err, (
        f"SPMD partitioner fell back to full remat:\n"
        f"{[l for l in err.splitlines() if BAD in l]}")
